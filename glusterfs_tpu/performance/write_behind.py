"""performance/write-behind — async write aggregation.

Reference: xlators/performance/write-behind (3.3k LoC; doc
doc/developer-guide/write-behind.md): acknowledge writes immediately,
coalesce adjacent ones in a per-fd window, flush on fsync/flush/read
overlap or window pressure, surface deferred errors on the next fop.
"""

from __future__ import annotations

import asyncio

from ..core.fops import FopError
from ..core.layer import FdObj, Layer, register
from ..core.options import Option


class _WbFd:
    def __init__(self):
        self.chunks: list[tuple[int, bytearray]] = []  # (offset, data)
        self.bytes = 0
        self.error: FopError | None = None
        self.lock = asyncio.Lock()
        self.last_iatt = None


@register("performance/write-behind")
class WriteBehindLayer(Layer):
    OPTIONS = (
        Option("window-size", "size", default="1MB", min=512),
        Option("flush-behind", "bool", default="on"),
        Option("trickling-writes", "bool", default="on"),
    )

    def _ctx(self, fd: FdObj) -> _WbFd:
        ctx = fd.ctx_get(self)
        if ctx is None:
            ctx = _WbFd()
            fd.ctx_set(self, ctx)
        return ctx

    def _absorb(self, ctx: _WbFd, data: bytes, offset: int) -> None:
        """Coalesce with an adjacent/overlapping chunk when possible."""
        end = offset + len(data)
        for i, (coff, cbuf) in enumerate(ctx.chunks):
            cend = coff + len(cbuf)
            if offset <= cend and end >= coff:  # overlap or adjacent
                start = min(coff, offset)
                merged = bytearray(max(cend, end) - start)
                merged[coff - start: cend - start] = cbuf
                merged[offset - start: end - start] = data
                ctx.bytes += len(merged) - len(cbuf)
                ctx.chunks[i] = (start, merged)
                return
        ctx.chunks.append((offset, bytearray(data)))
        ctx.bytes += len(data)

    async def _drain(self, fd: FdObj, ctx: _WbFd) -> None:
        async with ctx.lock:
            chunks, ctx.chunks, ctx.bytes = ctx.chunks, [], 0
            for off, buf in sorted(chunks):
                try:
                    ctx.last_iatt = await self.children[0].writev(
                        fd, bytes(buf), off)
                except FopError as e:
                    ctx.error = e  # deferred error (wb_fd error analog)
                    break

    def _raise_deferred(self, ctx: _WbFd) -> None:
        if ctx.error is not None:
            err, ctx.error = ctx.error, None
            raise err

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        ctx = self._ctx(fd)
        self._raise_deferred(ctx)
        async with ctx.lock:
            self._absorb(ctx, bytes(data), offset)
        if ctx.bytes >= self.opts["window-size"]:
            await self._drain(fd, ctx)
            self._raise_deferred(ctx)
        ia = ctx.last_iatt
        if ia is None:
            ia = await self.children[0].fstat(fd)
        return ia

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        ctx = self._ctx(fd)
        if ctx.chunks:  # read sees pending writes: flush first
            await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].readv(fd, size, offset, xdata)

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        ctx = self._ctx(fd)
        await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].flush(fd, xdata)

    async def fsync(self, fd: FdObj, datasync: int = 0,
                    xdata: dict | None = None):
        ctx = self._ctx(fd)
        await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].fsync(fd, datasync, xdata)

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        ctx = self._ctx(fd)
        if ctx.chunks:
            await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].fstat(fd, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        ctx = self._ctx(fd)
        await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].ftruncate(fd, size, xdata)

    async def release(self, fd: FdObj):
        ctx: _WbFd | None = fd.ctx_get(self)
        if ctx is not None and ctx.chunks:
            await self._drain(fd, ctx)
        fd.ctx_del(self)
        await super().release(fd)

    def dump_private(self) -> dict:
        return {"window_size": self.opts["window-size"]}
