"""performance/write-behind — async write aggregation.

Reference: xlators/performance/write-behind (3.3k LoC; doc
doc/developer-guide/write-behind.md): acknowledge writes immediately,
coalesce adjacent ones in a per-fd window, flush on fsync/flush/read
overlap or window pressure, surface deferred errors on the next fop.
"""

from __future__ import annotations

import asyncio

from ..core.fops import FopError
from ..core.layer import FdObj, Layer, register
from ..core.options import Option


class _WbFd:
    def __init__(self):
        self.chunks: list[tuple[int, bytearray]] = []  # (offset, data)
        self.bytes = 0
        self.error: FopError | None = None
        self.lock = asyncio.Lock()
        self.last_iatt = None
        self.logical_end = 0  # high-water mark incl. absorbed writes


@register("performance/write-behind")
class WriteBehindLayer(Layer):
    OPTIONS = (
        Option("window-size", "size", default="1MB", min=512),
        Option("flush-behind", "bool", default="on"),
        Option("trickling-writes", "bool", default="on"),
        Option("aggregate-size", "size", default="0", min=0,
               description="flush once a single coalesced chunk reaches "
                           "this size (performance.aggregate-size; "
                           "reference default 128KB): bounds how large "
                           "one merged child writev grows.  0 = only "
                           "the window bounds it (this framework's "
                           "historical behavior — EC mounts want whole "
                           "stripes aggregated)"),
        Option("strict-o-direct", "bool", default="off",
               description="O_DIRECT fds bypass the window entirely "
                           "(performance.strict-o-direct): the app asked "
                           "for unbuffered semantics"),
        Option("strict-write-ordering", "bool", default="off",
               description="never acknowledge a write before every "
                           "prior one reached the child: each write "
                           "drains the window first "
                           "(performance.strict-write-ordering)"),
    )

    def _ctx(self, fd: FdObj) -> _WbFd:
        ctx = fd.ctx_get(self)
        if ctx is None:
            ctx = _WbFd()
            fd.ctx_set(self, ctx)
        return ctx

    def _absorb(self, ctx: _WbFd, data: bytes, offset: int) -> None:
        """Coalesce every overlapping/adjacent chunk into one, newest data
        last.  Merging ALL touching chunks (not just the first) keeps the
        chunk list disjoint, so drain order can never replay stale bytes
        over newer ones.  The union is gap-free because each absorbed
        chunk touches the new write's interval."""
        end = offset + len(data)
        touching, rest = [], []
        for coff, cbuf in ctx.chunks:
            if offset <= coff + len(cbuf) and end >= coff:
                touching.append((coff, cbuf))
            else:
                rest.append((coff, cbuf))
        start = min([offset] + [c for c, _ in touching])
        stop = max([end] + [c + len(b) for c, b in touching])
        merged = bytearray(stop - start)
        for coff, cbuf in touching:  # disjoint among themselves
            merged[coff - start: coff - start + len(cbuf)] = cbuf
        merged[offset - start: end - start] = data
        rest.append((start, merged))
        ctx.chunks = rest
        ctx.bytes = sum(len(b) for _, b in ctx.chunks)

    async def _drain(self, fd: FdObj, ctx: _WbFd) -> None:
        async with ctx.lock:
            chunks, ctx.chunks, ctx.bytes = ctx.chunks, [], 0
            for off, buf in sorted(chunks):
                try:
                    ctx.last_iatt = await self.children[0].writev(
                        fd, bytes(buf), off)
                except FopError as e:
                    ctx.error = e  # deferred error (wb_fd error analog)
                    break

    def _raise_deferred(self, ctx: _WbFd) -> None:
        if ctx.error is not None:
            err, ctx.error = ctx.error, None
            raise err

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        import os as _os

        ctx = self._ctx(fd)
        self._raise_deferred(ctx)
        if self.opts["strict-o-direct"] and \
                getattr(fd, "flags", 0) & getattr(_os, "O_DIRECT", 0):
            # unbuffered semantics: drain anything pending, then write
            # through (wb_enqueue bypass on O_DIRECT)
            if ctx.chunks:
                await self._drain(fd, ctx)
                self._raise_deferred(ctx)
            return await self.children[0].writev(fd, data, offset, xdata)
        if self.opts["strict-write-ordering"] and ctx.chunks:
            await self._drain(fd, ctx)
            self._raise_deferred(ctx)
        async with ctx.lock:
            self._absorb(ctx, bytes(data), offset)
            ctx.logical_end = max(ctx.logical_end, offset + len(data))
        agg = self.opts["aggregate-size"]
        if ctx.bytes >= self.opts["window-size"] or \
                (agg and any(len(b) >= agg for _, b in ctx.chunks)):
            await self._drain(fd, ctx)
            self._raise_deferred(ctx)
        ia = ctx.last_iatt
        if ia is None:
            ia = await self.children[0].fstat(fd)
        # the postbuf must reflect absorbed-but-unflushed bytes too:
        # upper caches (md-cache) absorb this iatt, and a stale size
        # there would corrupt a stat-after-write
        if hasattr(ia, "size") and ia.size < ctx.logical_end:
            from ..core.iatt import Iatt

            ia = Iatt(**{**ia.__dict__})
            ia.size = ctx.logical_end
        return ia

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        ctx = self._ctx(fd)
        if ctx.chunks:  # read sees pending writes: flush first
            await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].readv(fd, size, offset, xdata)

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        ctx = self._ctx(fd)
        await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].flush(fd, xdata)

    async def fsync(self, fd: FdObj, datasync: int = 0,
                    xdata: dict | None = None):
        ctx = self._ctx(fd)
        await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].fsync(fd, datasync, xdata)

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        ctx = self._ctx(fd)
        if ctx.chunks:
            await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].fstat(fd, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        ctx = self._ctx(fd)
        await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        ctx.logical_end = size
        return await self.children[0].ftruncate(fd, size, xdata)

    async def release(self, fd: FdObj):
        ctx: _WbFd | None = fd.ctx_get(self)
        if ctx is not None and ctx.chunks:
            await self._drain(fd, ctx)
        fd.ctx_del(self)
        await super().release(fd)

    def dump_private(self) -> dict:
        return {"window_size": self.opts["window-size"]}
