"""performance/write-behind — async write aggregation.

Reference: xlators/performance/write-behind (3.3k LoC; doc
doc/developer-guide/write-behind.md): acknowledge writes immediately,
coalesce adjacent ones in a per-fd window, flush on fsync/flush/read
overlap or window pressure, surface deferred errors on the next fop.
"""

from __future__ import annotations

import asyncio

from ..core.fops import FopError
from ..core.layer import FdObj, Layer, register
from ..core.options import Option
from ..core import metrics as _metrics

#: live write-behind layers, scraped by the unified registry
_LIVE_WB_LAYERS = _metrics.REGISTRY.register_objects(
    "gftpu_write_behind_window_bytes", "gauge",
    "bytes absorbed into write-behind windows and not yet drained",
    lambda l: [({"layer": l.name}, l.window_bytes)])


class _WbFd:
    def __init__(self):
        self.chunks: list[tuple[int, bytearray]] = []  # (offset, data)
        self.bytes = 0
        self.error: FopError | None = None
        self.lock = asyncio.Lock()
        self.last_iatt = None
        self.logical_end = 0  # high-water mark incl. absorbed writes


@register("performance/write-behind")
class WriteBehindLayer(Layer):
    OPTIONS = (
        Option("window-size", "size", default="1MB", min=512),
        Option("flush-behind", "bool", default="on"),
        Option("trickling-writes", "bool", default="on"),
        Option("aggregate-size", "size", default="0", min=0,
               description="flush once a single coalesced chunk reaches "
                           "this size (performance.aggregate-size; "
                           "reference default 128KB): bounds how large "
                           "one merged child writev grows.  0 = only "
                           "the window bounds it (this framework's "
                           "historical behavior — EC mounts want whole "
                           "stripes aggregated)"),
        Option("strict-o-direct", "bool", default="off",
               description="O_DIRECT fds bypass the window entirely "
                           "(performance.strict-o-direct): the app asked "
                           "for unbuffered semantics"),
        Option("strict-write-ordering", "bool", default="off",
               description="never acknowledge a write before every "
                           "prior one reached the child: each write "
                           "drains the window first "
                           "(performance.strict-write-ordering)"),
        Option("compound-fops", "bool", default="off",
               description="emit flushed windows as compound chains "
                           "(cluster.use-compound-fops): a multi-chunk "
                           "drain is one fused writev chain, and flush "
                           "rides the same frame as the final drain "
                           "instead of its own round trip"),
        Option("stripe-size", "int", default=0, min=0,
               description="align window flush cut points to this "
                           "stripe size (volgen sets the EC stripe "
                           "when the window sits above a disperse "
                           "graph): PRESSURE drains cut at the last "
                           "stripe boundary and keep the sub-stripe "
                           "TAIL absorbed, so a streamed writer (the "
                           "gateway's chunked PUT) hits the aligned "
                           "encode path instead of paying a tail "
                           "read-modify-write per chunk.  A stream "
                           "that STARTS unaligned still pays its one "
                           "intrinsic head partial on the first drain "
                           "(holding the head back could never align "
                           "it).  flush/fsync/read/release still "
                           "drain everything; 0 = cut anywhere"),
    )

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        # window occupancy across all fds (registry gauge + statedump):
        # maintained by delta in _absorb/_drain, never recomputed by
        # walking fd contexts
        self.window_bytes = 0
        _LIVE_WB_LAYERS.add(self)

    def _ctx(self, fd: FdObj) -> _WbFd:
        ctx = fd.ctx_get(self)
        if ctx is None:
            ctx = _WbFd()
            fd.ctx_set(self, ctx)
        return ctx

    def _absorb(self, ctx: _WbFd, data: bytes, offset: int) -> None:
        """Coalesce every overlapping/adjacent chunk into one, newest data
        last.  Merging ALL touching chunks (not just the first) keeps the
        chunk list disjoint, so drain order can never replay stale bytes
        over newer ones.  The union is gap-free because each absorbed
        chunk touches the new write's interval."""
        end = offset + len(data)
        touching, rest = [], []
        for coff, cbuf in ctx.chunks:
            if offset <= coff + len(cbuf) and end >= coff:
                touching.append((coff, cbuf))
            else:
                rest.append((coff, cbuf))
        start = min([offset] + [c for c, _ in touching])
        stop = max([end] + [c + len(b) for c, b in touching])
        merged = bytearray(stop - start)
        for coff, cbuf in touching:  # disjoint among themselves
            merged[coff - start: coff - start + len(cbuf)] = cbuf
        merged[offset - start: end - start] = data
        rest.append((start, merged))
        ctx.chunks = rest
        before = ctx.bytes
        ctx.bytes = sum(len(b) for _, b in ctx.chunks)
        self.window_bytes += ctx.bytes - before

    async def _drain(self, fd: FdObj, ctx: _WbFd,
                     tail: tuple = (), partial: bool = False) -> list | None:
        """Flush the window.  With compound-fops on, a multi-chunk
        window (or any window with a ``tail`` of extra links, e.g. the
        flush that triggered the drain) goes down as ONE fused chain;
        otherwise the historical per-chunk writev loop runs and the
        tail is the caller's business.  Returns the tail's reply
        entries when a chain carried them, else None.

        ``partial`` (pressure drains only) with ``stripe-size`` set:
        the flush cuts at the last stripe boundary of each chunk and
        RETAINS the sub-stripe tail in the window — the next absorbed
        write extends it, so a streamed sequential writer below a
        disperse graph pays no TAIL partial per chunk (every retained
        cut is stripe-aligned, so all drains after a stream's first
        start aligned too; an unaligned stream START keeps its one
        intrinsic head partial — holding it back could never align
        it).  Ordering is safe: the retained tail stays newest-data
        in the window, and every full-drain site (flush/fsync/read/
        fstat/release/compound) still empties it."""
        async with ctx.lock:
            chunks = ctx.chunks
            keep: list[tuple[int, bytearray]] = []
            s = self.opts["stripe-size"]
            if partial and s:
                flushable = []
                for off, buf in chunks:
                    cut = (off + len(buf)) // s * s
                    if cut <= off:
                        keep.append((off, buf))  # all sub-stripe: hold
                        continue
                    flushable.append((off, buf[: cut - off]))
                    if cut - off < len(buf):
                        keep.append((cut, buf[cut - off:]))
                if flushable:
                    chunks = flushable
                else:
                    keep = []  # nothing aligned: flush everything —
                    # the window must stay bounded even for pathological
                    # all-sub-stripe patterns
            ctx.chunks = keep
            before = ctx.bytes
            ctx.bytes = sum(len(b) for _, b in keep)
            self.window_bytes -= before - ctx.bytes
            if self.opts["compound-fops"] and chunks and \
                    (len(chunks) + len(tail)) > 1:
                links = [("writev", (fd, bytes(buf), off), {})
                         for off, buf in sorted(chunks)]
                try:
                    replies = await self.children[0].compound(
                        links + list(tail))
                except FopError as e:
                    # transport-level failure (ENOTCONN mid-drain): the
                    # window is already popped — defer like the singles
                    # loop would, never let it escape an absorbing
                    # writev as a spurious hard error
                    ctx.error = e
                    return [("err", e)] if tail else None
                for st, val in replies[:len(links)]:
                    if st == "ok" and val is not None:
                        ctx.last_iatt = val
                    elif st == "err":
                        ctx.error = val  # deferred (wb_fd error analog)
                return replies[len(links):]
            for off, buf in sorted(chunks):
                try:
                    ctx.last_iatt = await self.children[0].writev(
                        fd, bytes(buf), off)
                except FopError as e:
                    ctx.error = e  # deferred error (wb_fd error analog)
                    break
            return None

    def _raise_deferred(self, ctx: _WbFd) -> None:
        if ctx.error is not None:
            err, ctx.error = ctx.error, None
            raise err

    async def create(self, loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        fd, ia = await self.children[0].create(loc, flags, mode, xdata)
        # seed the window's postbuf with the create iatt: without it,
        # EVERY write absorbed on a fresh fd pays a wire fstat just to
        # fabricate its reply iatt (a streaming writer — the object
        # gateway's chunked PUT — burned one round trip per chunk,
        # which is exactly what the window exists to avoid)
        self._ctx(fd).last_iatt = ia
        return fd, ia

    async def writev(self, fd: FdObj, data, offset: int,
                     xdata: dict | None = None):
        import os as _os

        ctx = self._ctx(fd)
        self._raise_deferred(ctx)
        if self.opts["strict-o-direct"] and \
                getattr(fd, "flags", 0) & getattr(_os, "O_DIRECT", 0):
            # unbuffered semantics: drain anything pending, then write
            # through (wb_enqueue bypass on O_DIRECT)
            if ctx.chunks:
                await self._drain(fd, ctx)
                self._raise_deferred(ctx)
            return await self.children[0].writev(fd, data, offset, xdata)
        if self.opts["strict-write-ordering"] and ctx.chunks:
            await self._drain(fd, ctx)
            self._raise_deferred(ctx)
        async with ctx.lock:
            self._absorb(ctx, bytes(data), offset)
            ctx.logical_end = max(ctx.logical_end, offset + len(data))
        agg = self.opts["aggregate-size"]
        if ctx.bytes >= self.opts["window-size"] or \
                (agg and any(len(b) >= agg for _, b in ctx.chunks)):
            # pressure drain: stripe-aligned cut points (the sub-stripe
            # tail stays absorbed for the next write to extend)
            await self._drain(fd, ctx, partial=True)
            self._raise_deferred(ctx)
        ia = ctx.last_iatt
        if ia is None:
            ia = await self.children[0].fstat(fd)
        # the postbuf must reflect absorbed-but-unflushed bytes too:
        # upper caches (md-cache) absorb this iatt, and a stale size
        # there would corrupt a stat-after-write
        if hasattr(ia, "size") and ia.size < ctx.logical_end:
            from ..core.iatt import Iatt

            ia = Iatt(**{**ia.__dict__})
            ia.size = ctx.logical_end
        return ia

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        ctx = self._ctx(fd)
        if ctx.chunks:  # read sees pending writes: flush first
            await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].readv(fd, size, offset, xdata)

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        ctx = self._ctx(fd)
        if self.opts["compound-fops"] and ctx.chunks:
            # the flush rides the drain's frame: window + flush is one
            # chain (one round trip) instead of N writevs + a flush
            tail = await self._drain(
                fd, ctx, tail=(("flush", (fd,),
                                {"xdata": xdata} if xdata else {}),))
            self._raise_deferred(ctx)
            if tail:  # ("ok", ret) | ("skip", None) — err raised above
                st, val = tail[0]
                if st == "err":
                    raise val
                return val
            return await self.children[0].flush(fd, xdata)
        await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].flush(fd, xdata)

    async def fsync(self, fd: FdObj, datasync: int = 0,
                    xdata: dict | None = None):
        ctx = self._ctx(fd)
        await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].fsync(fd, datasync, xdata)

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        ctx = self._ctx(fd)
        if ctx.chunks:
            await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        return await self.children[0].fstat(fd, xdata)

    async def ftruncate(self, fd: FdObj, size: int,
                        xdata: dict | None = None):
        ctx = self._ctx(fd)
        await self._drain(fd, ctx)
        self._raise_deferred(ctx)
        ctx.logical_end = size
        ia = await self.children[0].ftruncate(fd, size, xdata)
        # refresh the cached postbuf: the drain's predates the truncate
        # and a later absorbed write would reply with the stale size
        ctx.last_iatt = ia if hasattr(ia, "size") else None
        return ia

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Chains pass through write-through: any involved fd's pending
        window drains first (ordering), its deferred error surfaces,
        then the chain forwards INTACT — the point of a fused
        create+writev is that it skips the window entirely.  FdRef
        links (fds the chain itself creates) have no window by
        definition."""
        for _fop, args, kwargs in links:
            for a in list(args) + list((kwargs or {}).values()):
                if isinstance(a, FdObj):
                    ctx: _WbFd | None = a.ctx_get(self)
                    if ctx is not None:
                        if ctx.chunks:
                            await self._drain(a, ctx)
                        self._raise_deferred(ctx)
        replies = await self.children[0].compound(links, xdata)
        # replay the per-fop bookkeeping the forwarded links skipped:
        # a fused ftruncate must reset the absorbed-bytes high-water
        # mark or later write replies inflate a shrunk file's size
        for (fop, args, _kw), (st, val) in zip(links, replies):
            if fop == "ftruncate" and st == "ok" and \
                    isinstance(args[0], FdObj) and len(args) > 1:
                ctx = args[0].ctx_get(self)
                if ctx is not None:
                    ctx.logical_end = args[1]
                    # the drain's postbuf predates the truncate: keep
                    # the truncated iatt or later writes reply stale
                    ctx.last_iatt = val if hasattr(val, "size") else None
        return replies

    async def release(self, fd: FdObj):
        ctx: _WbFd | None = fd.ctx_get(self)
        if ctx is not None and ctx.chunks:
            await self._drain(fd, ctx)
        fd.ctx_del(self)
        await super().release(fd)

    def dump_private(self) -> dict:
        return {"window_size": self.opts["window-size"],
                "window_bytes": self.window_bytes}
