"""Volfile-spec builders shared by benches and tests.

The analog of the reference's volgen templates for the common shapes
(reference xlators/mgmt/glusterd/src/glusterd-volgen.c); tests and
bench.py previously each hand-rolled the same brick+disperse string.
"""

from __future__ import annotations


def brick_volumes(base, n: int, layers: list[tuple[str, dict]] | None = None,
                  name: str = "b") -> tuple[list[str], list[str]]:
    """N posix bricks under ``base``; each optionally wrapped bottom-up by
    ``layers`` [(type, options), ...].  The top volume of brick i is named
    ``<name><i>``.  Returns (volfile chunks, top names)."""
    out, tops = [], []
    layers = list(layers or [])
    for i in range(n):
        stack = [("storage/posix", {"directory": f"{base}/brick{i}"})] + layers
        prev = None
        for j, (ltype, opts) in enumerate(stack):
            vname = f"{name}{i}" if j == len(stack) - 1 else f"{name}{i}_{j}"
            body = "".join(f"    option {k} {v}\n" for k, v in opts.items())
            subs = f"    subvolumes {prev}\n" if prev else ""
            out.append(f"volume {vname}\n    type {ltype}\n{body}{subs}"
                       f"end-volume\n")
            prev = vname
        tops.append(prev)
    return out, tops


def ec_volfile(base, n: int, r: int, options: dict | None = None,
               brick_layers: list[tuple[str, dict]] | None = None,
               top: str = "disp", groups: int = 1) -> str:
    """A disperse (n = k+r) volume over n local posix bricks; with
    ``groups`` > 1, a distributed-disperse volume of ``groups``
    (n, r) groups under a dht top (the 2x(4+2) bench shape)."""
    chunks, tops = brick_volumes(base, n * groups, brick_layers)
    body = "".join(f"    option {k} {v}\n"
                   for k, v in (options or {}).items())
    if groups == 1:
        chunks.append(f"volume {top}\n    type cluster/disperse\n"
                      f"    option redundancy {r}\n{body}"
                      f"    subvolumes {' '.join(tops)}\nend-volume\n")
    else:
        subs = []
        for g in range(groups):
            gname = f"{top}-g{g}"
            gt = tops[g * n:(g + 1) * n]
            chunks.append(f"volume {gname}\n    type cluster/disperse\n"
                          f"    option redundancy {r}\n{body}"
                          f"    subvolumes {' '.join(gt)}\nend-volume\n")
            subs.append(gname)
        chunks.append(f"volume {top}\n    type cluster/distribute\n"
                      f"    subvolumes {' '.join(subs)}\nend-volume\n")
    return "\n".join(chunks)
