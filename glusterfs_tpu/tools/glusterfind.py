"""glusterfind — incremental "what changed since session X" file lists.

Reference: tools/glusterfind (main.py subcommands create/pre/post/
list/delete/query) driven by the changelog history API
(changelog/lib/src/gf-history-changelog.c).  Sessions persist a
timestamp; ``pre`` emits every namespace/data/metadata change recorded
by the bricks' changelog journals since that timestamp, coalesced per
path into NEW / MODIFY / DELETE / RENAME lines; ``post`` commits the
new timestamp so the next ``pre`` is incremental.

TPU-build mechanisms: the brick journals are JSON-line segments
(features/changelog); sessions live under ``<session-dir>/<session>/
<volume>/status`` holding the committed timestamp, with a ``pending``
file between pre and post (the reference keeps the same split under
/var/lib/glusterd/glusterfind).  Brick locations come from glusterd's
volume-info; ``create`` force-enables changelog exactly like the
reference does.

Usage:
    gftpu-find create  SESSION VOLUME [--server H:P]
    gftpu-find pre     SESSION VOLUME OUTFILE
    gftpu-find post    SESSION VOLUME
    gftpu-find list
    gftpu-find delete  SESSION VOLUME
    gftpu-find query   VOLUME OUTFILE --since-time TS
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

DEFAULT_SESSION_DIR = os.path.expanduser("~/.gftpu/glusterfind")

# ops -> emitted change class (the reference's NEW/MODIFY/DELETE split)
_NEW_OPS = {"create", "mknod", "mkdir", "symlink", "link", "icreate",
            "put"}
_DEL_OPS = {"unlink", "rmdir"}


def _session_path(base: str, session: str, volume: str) -> str:
    return os.path.join(base, session, volume)


def _read_ts(path: str) -> float | None:
    try:
        with open(path) as f:
            return float(f.read().strip())
    except (FileNotFoundError, ValueError):
        return None


def _write_ts(path: str, ts: float) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(repr(ts))
    os.replace(tmp, path)


async def _volinfo(server: str, volume: str) -> dict:
    from ..mgmt.glusterd import MgmtClient

    host, _, port = server.partition(":")
    async with MgmtClient(host, int(port or 24007)) as c:
        info = await c.call("volume-info", name=volume)
    if volume not in info:
        raise SystemExit(f"no volume {volume!r}")
    return info[volume]


def _brick_journal_dirs(vol: dict) -> list[str]:
    out = []
    for b in vol.get("bricks", []):
        d = os.path.join(b["path"], ".glusterfs_tpu", "changelog")
        if os.path.isdir(d):
            out.append(d)
    return out


async def _brick_history(vol: dict, brick: dict, since: float,
                         until: float) -> dict | None:
    """Query one brick's changelog history over its RPC (the
    gf-history-changelog.c consumer contract served by
    changelog-rpc.c): handshake with the volume's generated
    credentials, call ``changelog_history``, return its payload.
    None when the brick is unreachable (caller falls back to reading
    the journal directory locally, if it can)."""
    from ..rpc import wire

    port = brick.get("port")
    if not port:
        return None
    host = brick.get("host", "127.0.0.1")
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), 5)
    except (OSError, asyncio.TimeoutError):
        return None
    try:
        auth = vol.get("auth") or {}
        creds = {"username": auth.get("mgmt-username",
                                      auth.get("username", "")),
                 "password": auth.get("mgmt-password",
                                      auth.get("password", ""))}
        writer.write(wire.pack(1, wire.MT_CALL, [
            "__handshake__", [b"glusterfind", brick.get("name", ""),
                              creds], {}]))
        await writer.drain()
        rec = await asyncio.wait_for(wire.read_frame(reader), 5)
        _, mtype, payload = wire.unpack(rec)
        if mtype != wire.MT_REPLY or not payload.get("ok"):
            return None
        writer.write(wire.pack(2, wire.MT_CALL, [
            "changelog_history", [since, until], {}]))
        await writer.drain()
        rec = await asyncio.wait_for(wire.read_frame(reader), 30)
        _, mtype, payload = wire.unpack(rec)
        if mtype != wire.MT_REPLY:
            return None
        return payload
    except (OSError, asyncio.TimeoutError, wire.WireError):
        return None
    finally:
        try:
            writer.close()
        except Exception:
            pass


async def _collect(server: str, vol: dict, since: float,
                   until: float) -> tuple[list[dict], bool]:
    """(records, covered): per-brick history via RPC first — a brick on
    another node is reachable over the wire only — falling back to
    reading its journal directory when the brick process is down but
    its path is local.  ``covered`` is False when any brick's journal
    epoch postdates ``since`` (window not fully recorded: the caller
    must full-crawl, reference brickfind.py)."""
    recs: list[dict] = []
    covered = True
    for b in vol.get("bricks", []):
        payload = await _brick_history(vol, b, since, until)
        if payload is not None:
            recs.extend(payload.get("records", ()))
            start = payload.get("start_ts")
            if start is None or start > since:
                covered = False
            while payload.get("truncated"):
                last = payload["records"][-1]["ts"]
                payload = await _brick_history(vol, b, last, until)
                if payload is None:
                    break
                recs.extend(payload.get("records", ()))
            continue
        d = os.path.join(b["path"], ".glusterfs_tpu", "changelog")
        if os.path.isdir(d):
            recs.extend(_scan([d], since, until))
            htime = os.path.join(d, "HTIME")
            try:
                with open(htime) as f:
                    if float(f.read().strip() or 0) > since:
                        covered = False
            except (OSError, ValueError):
                covered = False
        else:
            covered = False
    recs.sort(key=lambda r: r.get("ts", 0))
    return recs, covered


async def _full_crawl(server: str, volume: str) -> list[tuple[str, ...]]:
    """Namespace walk emitting NEW for every entry (the brickfind.py
    fallback for sessions/windows predating changelogs) — done through
    a mounted client so distribution/EC layouts are walked exactly
    once, not once per brick."""
    from ..mgmt.glusterd import mount_volume

    host, _, port = server.partition(":")
    client = await mount_volume(host or "127.0.0.1", int(port or 24007),
                                volume)
    out: list[tuple[str, ...]] = []
    try:
        stack = ["/"]
        while stack:
            d = stack.pop()
            for name, ia in await client.listdir_with_stat(d):
                path = (d if d != "/" else "") + "/" + name
                out.append(("NEW", path))
                if getattr(ia.ia_type, "name", "") == "DIR":
                    stack.append(path)
    finally:
        await client.unmount()
    return out


def _scan(dirs: list[str], since: float, until: float) -> list[dict]:
    """All journal records with since < ts <= until, time-ordered."""
    recs: list[dict] = []
    for d in dirs:
        for name in sorted(os.listdir(d)):
            if not name.startswith("CHANGELOG."):
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    for line in f:
                        try:
                            r = json.loads(line)
                        except ValueError:
                            continue
                        if since < r.get("ts", 0) <= until:
                            recs.append(r)
            except OSError:
                continue
    recs.sort(key=lambda r: r.get("ts", 0))
    return recs


def coalesce(recs: list[dict]) -> list[tuple[str, ...]]:
    """Per-path final outcome, reference glusterfind semantics:
    NEW+changes = NEW, NEW+DELETE = nothing, changes+DELETE = DELETE,
    RENAME tracked to the final name (a NEW file renamed stays NEW at
    its final path).  Replica bricks journal the same logical op;
    identical outcomes dedupe naturally."""
    # path -> NEW | MODIFY | DELETE | DROPPED (born-and-died tombstone:
    # replica bricks echo every op, so a second unlink of a dropped
    # path must not resurrect it as DELETE)
    state: dict[str, str] = {}
    renames: dict[str, str] = {}  # final path -> original path
    applied_renames: set[tuple[str, str]] = set()  # replica-echo filter
    order: list[str] = []

    def touch(path: str, kind: str) -> None:
        cur = state.get(path)
        if cur is None:
            order.append(path)
        if kind == "NEW":
            # replica echo of a create we saw, or re-create after
            # delete: re-created files are NEW again
            if cur in (None, "NEW", "DELETE", "DROPPED"):
                state[path] = "NEW"
            if cur == "DELETE":
                renames.pop(path, None)
        elif kind == "MODIFY":
            if cur in (None, "MODIFY"):
                state[path] = "MODIFY"
            # NEW + modify stays NEW; DROPPED is an echo, keep dropped
        elif kind == "DELETE":
            if cur == "DROPPED":
                return  # replica echo of the delete we already folded
            if cur == "NEW" and path not in renames:
                state[path] = "DROPPED"  # born and died in the window
            else:
                state[path] = "DELETE"

    for r in recs:
        op = r.get("op", "")
        path = r.get("path", "")
        if not path:
            continue
        if op == "rename":
            dst = r.get("path2", "")
            if not dst:
                continue
            if (path, dst) in applied_renames and path not in state:
                continue  # a replica's echo of a rename already folded
            applied_renames.add((path, dst))
            prev = state.pop(path, None)
            if path in order:
                order.remove(path)
            origin = renames.pop(path, path)
            if prev == "NEW":
                touch(dst, "NEW")
            else:
                if dst not in state:
                    order.append(dst)
                state[dst] = "RENAME"
                renames[dst] = origin
        elif op in _NEW_OPS:
            touch(path, "NEW")
        elif op in _DEL_OPS:
            touch(path, "DELETE")
        else:
            touch(path, "MODIFY")

    out = []
    for path in order:
        kind = state.get(path)
        if kind in (None, "DROPPED"):
            continue
        if kind == "RENAME":
            out.append(("RENAME", renames.get(path, path), path))
        else:
            out.append((kind, path))
    return out


def _emit(outfile: str, changes: list[tuple[str, ...]]) -> None:
    with open(outfile, "w") as f:
        for c in changes:
            f.write(" ".join(c) + "\n")


async def cmd_create(args) -> dict:
    from ..mgmt.glusterd import MgmtClient

    await _volinfo(args.server, args.volume)  # existence check
    host, _, port = args.server.partition(":")
    async with MgmtClient(host, int(port or 24007)) as c:
        # the reference's create also force-enables changelog
        await c.call("volume-set", name=args.volume,
                     key="changelog.changelog", value="on")
    sp = _session_path(args.session_dir, args.session, args.volume)
    _write_ts(os.path.join(sp, "status"), time.time())
    return {"created": args.session, "volume": args.volume}


async def cmd_pre(args) -> dict:
    vol = await _volinfo(args.server, args.volume)
    sp = _session_path(args.session_dir, args.session, args.volume)
    since = _read_ts(os.path.join(sp, "status"))
    if since is None:
        raise SystemExit(f"session {args.session!r} not created for "
                         f"{args.volume!r} (run create first)")
    now = time.time()
    recs, covered = await _collect(args.server, vol, since, now)
    if covered:
        changes = coalesce(recs)
        mode = "changelog"
    else:
        # window predates the journals (session created after data
        # already existed, or changelog enabled late): full namespace
        # crawl, everything NEW (reference brickfind fallback)
        changes = await _full_crawl(args.server, args.volume)
        mode = "full-crawl"
    _emit(args.outfile, changes)
    _write_ts(os.path.join(sp, "pending"), now)
    return {"changes": len(changes), "outfile": args.outfile,
            "since": since, "mode": mode}


async def cmd_post(args) -> dict:
    sp = _session_path(args.session_dir, args.session, args.volume)
    pend = _read_ts(os.path.join(sp, "pending"))
    if pend is None:
        raise SystemExit("no pending pre to commit (run pre first)")
    _write_ts(os.path.join(sp, "status"), pend)
    os.unlink(os.path.join(sp, "pending"))
    return {"committed": pend}


async def cmd_query(args) -> dict:
    vol = await _volinfo(args.server, args.volume)
    recs, covered = await _collect(args.server, vol, args.since_time,
                                   time.time())
    if covered or not args.full_fallback:
        changes = coalesce(recs)
        mode = "changelog"
    else:
        changes = await _full_crawl(args.server, args.volume)
        mode = "full-crawl"
    _emit(args.outfile, changes)
    return {"changes": len(changes), "outfile": args.outfile,
            "mode": mode}


async def cmd_list(args) -> dict:
    out = {}
    base = args.session_dir
    if not os.path.isdir(base):
        return out
    for session in sorted(os.listdir(base)):
        for volume in sorted(os.listdir(os.path.join(base, session))):
            ts = _read_ts(os.path.join(base, session, volume, "status"))
            if ts is not None:
                out.setdefault(session, {})[volume] = ts
    return out


async def cmd_delete(args) -> dict:
    import shutil

    sp = _session_path(args.session_dir, args.session, args.volume)
    shutil.rmtree(sp, ignore_errors=True)
    return {"deleted": args.session}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-find")
    p.add_argument("--server", default="127.0.0.1:24007")
    p.add_argument("--session-dir", default=DEFAULT_SESSION_DIR)
    sub = p.add_subparsers(dest="cmd", required=True)
    for name, need in (("create", "sv"), ("pre", "svo"), ("post", "sv"),
                       ("delete", "sv"), ("list", ""), ("query", "vo")):
        sp = sub.add_parser(name)
        if "s" in need:
            sp.add_argument("session")
        if "v" in need:
            sp.add_argument("volume")
        if "o" in need:
            sp.add_argument("outfile")
        if name == "query":
            sp.add_argument("--since-time", type=float, required=True)
            sp.add_argument("--full-fallback", action="store_true",
                            help="namespace-crawl when the window "
                                 "predates the changelogs")
    args = p.parse_args(argv)
    fn = globals()[f"cmd_{args.cmd}"]
    out = asyncio.run(fn(args))
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
