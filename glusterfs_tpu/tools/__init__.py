"""Ops tooling (reference tools/: glusterfind, gfind_missing_files)."""
