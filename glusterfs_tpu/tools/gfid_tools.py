"""Brick identity ops tools: setgfid2path + gfind_missing_files.

Reference: tools/setgfid2path (main.c — stamp the gfid2path metadata
onto pre-existing brick files so gfid-keyed consumers can resolve
them) and tools/gfind_missing_files (gfind_missing_files.sh +
gcrawler.c — crawl a brick, emit files absent on a geo-rep secondary
so an out-of-band sync can repair the gap).

TPU-build mechanisms: a brick's identity lives in the
``.glusterfs_tpu`` sidecar store (gfid records + dev:ino bindings +
handle hardlinks, storage/posix.py) instead of on-file xattrs, so

* ``setgfid2path`` walks the data tree, mints bindings for files the
  store does not know (legacy/side-loaded data), repairs records whose
  dev:ino went stale, and prunes records whose object is gone;
* ``gfind_missing_files`` walks the brick's files and looks each path
  up on a mounted secondary volume, writing the missing ones to the
  output file (one path per line, newline-escaped like the
  reference's output encoding).

Usage:
    gftpu-gfid-tool setgfid2path BRICKPATH
    gftpu-gfid-tool gfind-missing BRICKPATH OUTFILE \\
        --server H:P --volume SECONDARY
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

from ..core.iatt import gfid_new
from ..storage.posix import META_DIR, split_gfid_record


def _walk_data(root: str):
    """Yield brick-relative paths of every data object (files,
    symlinks, dirs), skipping the sidecar store."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != META_DIR]
        rel = os.path.relpath(dirpath, root)
        rel = "" if rel == "." else rel
        for d in dirnames:
            yield "/" + os.path.join(rel, d) if rel else "/" + d
        for f in filenames:
            yield "/" + os.path.join(rel, f) if rel else "/" + f


def setgfid2path(root: str) -> dict:
    """Repair/complete the identity store of a brick in place."""
    root = os.path.abspath(root)
    meta = os.path.join(root, META_DIR)
    gfid_dir = os.path.join(meta, "gfid")
    xattr_dir = os.path.join(meta, "xattr")
    handle_dir = os.path.join(meta, "handle")
    for d in (gfid_dir, xattr_dir, handle_dir):
        os.makedirs(d, exist_ok=True)

    known: dict[str, str] = {}  # relpath -> gfid hex
    pruned = 0
    for hexg in os.listdir(gfid_dir):
        if hexg.endswith(".tmp"):
            continue
        rec = os.path.join(gfid_dir, hexg)
        try:
            with open(rec) as f:
                _, relpath = split_gfid_record(f.read())
        except OSError:
            continue
        ap = os.path.join(root, relpath.lstrip("/"))
        if not os.path.lexists(ap):
            # object gone: prune the orphan identity (the reference
            # tool skips these; stale records would shadow reuse)
            for p in (rec, os.path.join(xattr_dir, hexg + ".json"),
                      os.path.join(handle_dir, hexg)):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            pruned += 1
            continue
        known[relpath if relpath.startswith("/") else "/" + relpath] \
            = hexg

    stamped = rebound = 0
    for rel in _walk_data(root):
        ap = os.path.join(root, rel.lstrip("/"))
        try:
            st = os.lstat(ap)
        except OSError:
            continue
        key = f"{st.st_dev}:{st.st_ino}"
        binding = os.path.join(xattr_dir, "ino-" + key)
        hexg = known.get(rel)
        if hexg is None:
            # side-loaded object: mint identity (posix_gfid_set heal,
            # done offline)
            hexg = gfid_new().hex()
            with open(os.path.join(gfid_dir, hexg), "w") as f:
                f.write(key + "\n" + rel)
            stamped += 1
        elif not os.path.exists(binding):
            # record exists but dev:ino binding is stale/missing
            with open(os.path.join(gfid_dir, hexg), "w") as f:
                f.write(key + "\n" + rel)
            rebound += 1
        else:
            continue
        with open(binding + ".tmp", "wb") as f:
            f.write(bytes.fromhex(hexg))
        os.replace(binding + ".tmp", binding)
        hp = os.path.join(handle_dir, hexg)
        if not os.path.isdir(ap) and not os.path.lexists(hp):
            try:
                os.link(ap, hp, follow_symlinks=False)
            except OSError:
                pass
    return {"stamped": stamped, "rebound": rebound, "pruned": pruned,
            "known": len(known)}


async def gfind_missing_paths(root: str, top) -> tuple[int, list[str]]:
    """Crawl brick files; return (scanned, paths absent on `top`, a
    mounted secondary volume's top layer)."""
    from ..core.fops import FopError
    from ..core.layer import Loc

    missing = []
    scanned = 0
    for rel in _walk_data(os.path.abspath(root)):
        ap = os.path.join(root, rel.lstrip("/"))
        if os.path.isdir(ap):
            continue
        scanned += 1
        try:
            await top.lookup(Loc(rel))
        except FopError:
            missing.append(rel)
    return scanned, missing


def write_missing(outfile: str, missing: list[str]) -> None:
    with open(outfile, "w") as f:
        for p in missing:
            # newline-escape: paths are the one field per line
            f.write(p.replace("\\", "\\\\").replace("\n", "\\n") + "\n")


async def gfind_missing(root: str, server: str, volume: str,
                        outfile: str) -> dict:
    """CLI surface: mount the secondary via glusterd, crawl, write."""
    from ..mgmt.glusterd import mount_volume

    host, _, port = server.partition(":")
    client = await mount_volume(host, int(port or 24007), volume)
    try:
        scanned, missing = await gfind_missing_paths(root,
                                                     client.graph.top)
    finally:
        await client.unmount()
    write_missing(outfile, missing)
    return {"scanned": scanned, "missing": len(missing),
            "outfile": outfile}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-gfid-tool")
    sp = p.add_subparsers(dest="cmd", required=True)

    s1 = sp.add_parser("setgfid2path")
    s1.add_argument("brick")

    s2 = sp.add_parser("gfind-missing")
    s2.add_argument("brick")
    s2.add_argument("outfile")
    s2.add_argument("--server", default="127.0.0.1:24007")
    s2.add_argument("--volume", required=True)

    args = p.parse_args(argv)
    if args.cmd == "setgfid2path":
        out = setgfid2path(args.brick)
    else:
        out = asyncio.run(gfind_missing(args.brick, args.server,
                                        args.volume, args.outfile))
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
