"""JAX/XLA backends for the GF(256) erasure codec.

Two formulations of the same math (see ops/gf256.py for layout semantics;
reference: xlators/cluster/ec/src/ec-method.c:393-433):

* ``matmul``: unpack chunk bytes to GF(2) bits and contract with the
  (R*8, C*8) binary bit-matrix on the MXU (int8 dot, mod 2), then repack.
  One matmul per stripe batch — the TPU-native replacement for the
  reference's JIT-emitted XOR chains (ec-code.c).
* ``xor``: keep bytes packed and XOR-accumulate plane words on the VPU,
  unrolling the CSE'd straight-line XOR program (gf256.build_xor_program)
  into the trace — shared subexpressions are computed once per batch
  instead of once per output plane (the analog of the reference's AVX XOR
  chains, but ~2-3x fewer XORs and traded for XLA fusion instead of
  hand JIT).

``matmul`` takes the coefficient bit-matrix as a traced argument, so decode
does not retrace per surviving-fragment mask; ``xor`` bakes the program
into the trace (one compile per mask, like the reference's per-matrix
JIT).  Decode programs come from the shared per-mask compiled-program LRU
(gf256.DECODE_PROGRAMS), the jitted fns from a cache keyed the same way.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256

_BIT_SHIFTS = tuple(1 << t for t in range(8))


def _unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """(..., W) uint8 -> (..., W*8) uint8 bits, little-endian within bytes."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(*x.shape[:-1], x.shape[-1] * 8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    """(..., W*8) uint8 bits -> (..., W) uint8 bytes."""
    w8 = bits.shape[-1]
    b = bits.reshape(*bits.shape[:-1], w8 // 8, 8)
    weights = jnp.array(_BIT_SHIFTS, dtype=jnp.uint8)
    return (b * weights).sum(axis=-1, dtype=jnp.uint8)


def _apply_matmul(abits: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """y[s,i,:] = (sum_j abits[i,j] * bits(x)[s,j,:]) mod 2, repacked.

    x: (S, C, 64) uint8 plane words; abits: (R, C) int8 in {0,1}.
    Returns (S, R, 64) uint8.
    """
    bits = _unpack_bits(x).astype(jnp.int8)  # (S, C, 512)
    y = jax.lax.dot_general(
        abits.astype(jnp.int8),
        bits,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (R, S, 512)
    y = jnp.transpose(y, (1, 0, 2))
    return _pack_bits((y & 1).astype(jnp.uint8))


def _apply_program(prog: gf256.XorProgram, x: jnp.ndarray) -> jnp.ndarray:
    """Same contraction, packed bytes on the VPU, via the CSE'd
    straight-line program: each op is one (S, 64) XOR shared by every
    output row that references it."""
    t = [x[:, j, :] for j in range(prog.n_inputs)]
    for _dst, a, b in prog.ops:
        t.append(t[a] ^ t[b])
    zero = jnp.zeros(x.shape[::2], dtype=jnp.uint8)  # (S, 64)
    outs = []
    for o in prog.outs:
        if not o:
            outs.append(zero)
            continue
        acc = t[o[0]]
        for v in o[1:]:
            acc = acc ^ t[v]
        outs.append(acc)
    return jnp.stack(outs, axis=1)  # (S, R, 64)


@functools.lru_cache(maxsize=64)
def _encode_fn(k: int, n: int, formulation: str, systematic: bool = False):
    if formulation == "xor":
        prog = gf256.encode_program(k, n, systematic)
        abits_np = None
    else:
        abits_np = gf256.expand_bitmatrix(gf256.generator_matrix(
            k, n, systematic))

    def run(data: jnp.ndarray) -> jnp.ndarray:
        s = data.shape[0] // (k * gf256.CHUNK_SIZE)
        x = data.reshape(s, k * 8, gf256.WORD_SIZE)
        if formulation == "xor":
            y = _apply_program(prog, x)
        else:
            y = _apply_matmul(jnp.asarray(abits_np), x)
        # (S, n*8, 64) -> fragment-major (n, S*512)
        return (
            y.reshape(s, n, gf256.CHUNK_SIZE)
            .transpose(1, 0, 2)
            .reshape(n, s * gf256.CHUNK_SIZE)
        )

    return jax.jit(run)


@functools.lru_cache(maxsize=256)
def _decode_fn(k: int, formulation: str, rows: tuple[int, ...] | None,
               systematic: bool = False):
    """One jitted decoder per surviving mask for the static ``xor``
    form (keyed exactly like gf256.DECODE_PROGRAMS, whose compiled
    program it unrolls); ``matmul`` passes rows=None — its bit-matrix
    is a traced operand, one compile serves every mask."""
    prog = gf256.decode_program(k, rows, systematic) \
        if formulation == "xor" else None

    def run(frags: jnp.ndarray, bbits: jnp.ndarray | None) -> jnp.ndarray:
        s = frags.shape[1] // gf256.CHUNK_SIZE
        x = (
            frags.reshape(k, s, 8, gf256.WORD_SIZE)
            .transpose(1, 0, 2, 3)
            .reshape(s, k * 8, gf256.WORD_SIZE)
        )
        if formulation == "xor":
            y = _apply_program(prog, x)
        else:
            y = _apply_matmul(bbits, x)
        return y.reshape(s * k * gf256.CHUNK_SIZE)

    return jax.jit(run)


@functools.lru_cache(maxsize=64)
def _parity_fn(k: int, n: int, formulation: str):
    """jitted: stripe-major bytes -> parity fragments ONLY
    ((n-k), S*512) of the systematic code — the delta-encode of the
    parity-delta write plane (only the generator's parity submatrix is
    applied; the data rows of a delta are shipped verbatim)."""
    if formulation == "xor":
        prog = gf256.parity_program(k, n)
        pbits_np = None
    else:
        pbits_np = gf256.parity_bits_cached(k, n)
    m = n - k

    def run(data: jnp.ndarray) -> jnp.ndarray:
        s = data.shape[0] // (k * gf256.CHUNK_SIZE)
        x = data.reshape(s, k * 8, gf256.WORD_SIZE)
        if formulation == "xor":
            y = _apply_program(prog, x)
        else:
            y = _apply_matmul(jnp.asarray(pbits_np), x)
        return (
            y.reshape(s, m, gf256.CHUNK_SIZE)
            .transpose(1, 0, 2)
            .reshape(m, s * gf256.CHUNK_SIZE)
        )

    return jax.jit(run)


def parity(data: np.ndarray, k: int, n: int,
           formulation: str = "matmul") -> np.ndarray:
    """Systematic parity rows ((n-k), S*512) for stripe-major bytes."""
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    if data.size % (k * gf256.CHUNK_SIZE):
        raise ValueError("data length must be a multiple of k*512")
    out = _parity_fn(k, n, formulation)(jnp.asarray(data))
    return np.asarray(out)


def encode(data: np.ndarray, k: int, n: int, formulation: str = "matmul",
           systematic: bool = False) -> np.ndarray:
    """Encode bytes (len multiple of k*512) -> (n, S*512) fragments."""
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    if data.size % (k * gf256.CHUNK_SIZE):
        raise ValueError("data length must be a multiple of k*512")
    out = _encode_fn(k, n, formulation, systematic)(jnp.asarray(data))
    return np.asarray(out)


def decode(
    frags: np.ndarray, rows, k: int, formulation: str = "matmul",
    systematic: bool = False
) -> np.ndarray:
    """Decode k fragments (k, S*512) with indices `rows` -> original bytes."""
    frags = np.ascontiguousarray(frags, dtype=np.uint8)
    rows = tuple(int(x) for x in rows)
    if formulation == "xor":
        fn = _decode_fn(k, "xor", rows, systematic)
        out = fn(jnp.asarray(frags), None)
    else:
        bbits_np = gf256.decode_bits_cached(k, rows, systematic)
        fn = _decode_fn(k, "matmul", None)
        out = fn(jnp.asarray(frags), jnp.asarray(bbits_np))
    return np.asarray(out)
