"""Batching codec: coalesce concurrent fop codec work into one device batch.

The reference amortizes per-write stripe work with a stripe-cache
(reference xlators/cluster/ec/src/ec.c:286 option ``stripe-cache``); the
TPU analog — and the north star's "stripe fragments from concurrent fops
coalesced into HBM-resident batches" — is a batching window:

* concurrent ``encode_async``/``decode_async`` calls within one event-loop
  tick (plus ``window`` seconds) queue into a pending list;
* one flush concatenates the queued stripe-aligned payloads and makes ONE
  kernel launch for the whole batch (encode; decodes group by surviving
  mask — one launch per mask, same keying as the reference's LRU of
  inverted matrices);
* a latency cutoff keeps small/straggler batches off the device: below
  ``min_batch`` bytes the flush runs on the native/CPU ladder instead, so
  a lone metadata-sized write never pays a device dispatch.

Correctness leans on fragment-stream concatenation: fragment ``f`` of
``concat(stripes_a, stripes_b)`` is ``concat(frag_f(a), frag_f(b))`` —
stripes are independent (ec-method.c:393-408 loops stripes).
"""

from __future__ import annotations

import asyncio

import numpy as np

from . import gf256
from .codec import Codec

_DEVICE_BACKENDS = ("pallas-xor", "pallas-mxu", "xla", "xla-xor")


class BatchingCodec(Codec):
    """Codec with an async batching window for the served data path.

    The sync ``encode``/``decode`` API stays available (heal tooling,
    tests); the data path awaits ``encode_async``/``decode_async``.

    Stats: ``launches`` counts device batch launches, ``cpu_launches``
    counts small-batch fallbacks, ``batched_fops`` total fops served,
    ``max_batch`` the largest coalesced batch in fops.
    """

    def __init__(self, k: int, r: int, backend: str = "auto", *,
                 window: float = 0.0003, min_batch: int = 256 * 1024,
                 max_batch_bytes: int = 256 << 20):
        super().__init__(k, r, backend)
        self.window = window
        self.min_batch = min_batch
        self.max_batch_bytes = max_batch_bytes
        self._enc_q: list[tuple[np.ndarray, asyncio.Future]] = []
        self._enc_task: asyncio.Task | None = None
        self._dec_q: dict[tuple[int, ...],
                          list[tuple[np.ndarray, asyncio.Future]]] = {}
        self._dec_task: asyncio.Task | None = None
        self._cpu = None  # lazy small-batch codec
        self.launches = 0
        self.cpu_launches = 0
        self.batched_fops = 0
        self.max_batch = 0

    # -- stats hooks (count every device launch, sync path included) ------

    def encode(self, data: np.ndarray) -> np.ndarray:
        self.launches += 1
        return super().encode(data)

    def decode(self, frags: np.ndarray, rows) -> np.ndarray:
        self.launches += 1
        return super().decode(frags, rows)

    def _small(self) -> Codec:
        if self._cpu is None:
            if self.backend in _DEVICE_BACKENDS:
                try:
                    self._cpu = Codec(self.k, self.r, "native")
                except RuntimeError:
                    self._cpu = Codec(self.k, self.r, "ref")
            else:
                self._cpu = self  # already a CPU ladder backend
        return self._cpu

    # -- encode ------------------------------------------------------------

    async def encode_async(self, data: np.ndarray) -> np.ndarray:
        """Encode stripe-aligned bytes; coalesced with concurrent calls."""
        data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
        if data.size % self.stripe_size:
            raise ValueError("data length not a multiple of the stripe")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._enc_q.append((data, fut))
        if sum(d.size for d, _ in self._enc_q) >= self.max_batch_bytes:
            self._flush_encodes()
        elif self._enc_task is None:
            self._enc_task = asyncio.ensure_future(self._enc_timer())
        return await fut

    async def _enc_timer(self):
        await asyncio.sleep(self.window)
        self._flush_encodes()

    def _flush_encodes(self) -> None:
        if self._enc_task is not None:
            self._enc_task.cancel()
            self._enc_task = None
        batch, self._enc_q = self._enc_q, []
        if not batch:
            return
        self.batched_fops += len(batch)
        self.max_batch = max(self.max_batch, len(batch))
        total = sum(d.size for d, _ in batch)
        codec: Codec = self
        if total < self.min_batch and self._small() is not self:
            codec = self._small()
            self.cpu_launches += 1
        try:
            if len(batch) == 1:
                frags = codec.encode(batch[0][0])
                batch[0][1].set_result(frags)
                return
            cat = np.concatenate([d for d, _ in batch])
            frags = codec.encode(cat)  # ONE launch for the whole batch
            off = 0
            for d, fut in batch:
                flen = d.size // self.k
                if not fut.cancelled():
                    fut.set_result(frags[:, off:off + flen].copy())
                off += flen
        except Exception as e:  # pragma: no cover - propagate to callers
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)

    # -- decode ------------------------------------------------------------

    async def decode_async(self, frags: np.ndarray, rows) -> np.ndarray:
        """Decode k fragments; coalesced with concurrent same-mask calls."""
        rows = tuple(int(x) for x in rows)
        frags = np.ascontiguousarray(frags, dtype=np.uint8)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        q = self._dec_q.setdefault(rows, [])
        q.append((frags, fut))
        if sum(f.size for f, _ in q) >= self.max_batch_bytes:
            self._flush_decodes()  # same blow-up guard as the encode path
        elif self._dec_task is None:
            self._dec_task = asyncio.ensure_future(self._dec_timer())
        return await fut

    async def _dec_timer(self):
        await asyncio.sleep(self.window)
        self._flush_decodes()

    def _flush_decodes(self) -> None:
        if self._dec_task is not None:
            self._dec_task.cancel()
            self._dec_task = None
        queues, self._dec_q = self._dec_q, {}
        for rows, batch in queues.items():
            self.batched_fops += len(batch)
            self.max_batch = max(self.max_batch, len(batch))
            total = sum(f.size for f, _ in batch)
            codec: Codec = self
            if total < self.min_batch and self._small() is not self:
                codec = self._small()
                self.cpu_launches += 1
            try:
                if len(batch) == 1:
                    batch[0][1].set_result(codec.decode(batch[0][0], rows))
                    continue
                cat = np.concatenate([f for f, _ in batch], axis=1)
                out = codec.decode(cat, rows)  # one launch per mask
                off = 0
                for f, fut in batch:
                    nbytes = f.shape[1] * self.k
                    if not fut.cancelled():
                        fut.set_result(out[off:off + nbytes].copy())
                    off += nbytes
            except Exception as e:  # pragma: no cover
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)

    def dump_stats(self) -> dict:
        return {
            "backend": self.backend,
            "launches": self.launches,
            "cpu_launches": self.cpu_launches,
            "batched_fops": self.batched_fops,
            "max_batch": self.max_batch,
            "window_s": self.window,
            "min_batch_bytes": self.min_batch,
        }
