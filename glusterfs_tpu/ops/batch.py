"""Batching codec: coalesce concurrent fop codec work into one device batch.

The reference amortizes per-write stripe work with a stripe-cache
(reference xlators/cluster/ec/src/ec.c:286 option ``stripe-cache``); the
TPU analog — and the north star's "stripe fragments from concurrent fops
coalesced into HBM-resident batches" — is a batching window:

* concurrent ``encode_async``/``decode_async`` calls within one event-loop
  tick (plus ``window`` seconds) queue into a pending list;
* one flush concatenates the queued stripe-aligned payloads and makes ONE
  kernel launch for the whole batch (encode; decodes group by surviving
  mask — one launch per mask, the same ``(k, rows)`` keying as the
  per-mask compiled-program LRU every backend decodes through
  (gf256.DECODE_PROGRAMS), so a flush group always lands on one cached
  program/kernel);
* flushes run OFF the event loop in a small thread pool, so batch N+1
  keeps filling (and can dispatch) while batch N is on the device — fop
  latency never serializes on a device round trip;
* device launches are shape-bucketed: the concatenated batch is padded
  with zero stripes up to the next power-of-two stripe count, so the
  jitted kernel cache sees a bounded set of shapes instead of recompiling
  for every distinct batch size (correct because stripes are independent,
  ec-method.c:393-408, and the codec is linear so zero stripes encode to
  zero fragments that we slice off);
* routing between the device and the CPU ladder is MEASURED, not assumed:
  a background calibration times the device at two bucket sizes (fitting
  ``t = overhead + bytes/rate``) and the native ladder on the same data;
  each flush then goes to whichever path predicts faster for its size.
  Until calibration completes, flushes run on the CPU ladder — a served
  volume is never slower than the native path while the device warms up.
  Production flush timings keep updating the models (EMA), so a drifting
  transfer latency (e.g. a congested tunnel) re-routes automatically.

* the **mesh tier** (ISSUE 8, ``cluster.mesh-codec``): when the volume
  key is on and the wedge-safe device probe saw >1 jax device, flushes
  at/above ``stripe-cache-min-batch`` skip the single-device ladder and
  land in ONE pjit'd ``NamedSharding(Mesh(dp, frag))`` launch
  (parallel/mesh_codec) — many concurrent fops' stripes sharded over
  ``dp``, the fragment dimension over ``frag``, so the encode IS the
  scatter.  Decodes past ``MESH_RING_DECODE_BYTES`` ride the
  ring-pipelined ppermute reduce instead of the all-gather plane.
  Systematic volumes joined the tier in ISSUE 12: encodes (and
  parity deltas) take the PARITY-ROWS-ONLY sharded program — the k
  data fragments are host reshapes, the mesh computes just the r
  parity rows — while degraded decodes keep the single-device
  ladder (healthy systematic reads never decode at all).
  Launches are counted per (op, origin) on the
  ``gftpu_mesh_{launches,batch_stripes}_total`` families ("serve" =
  fop traffic, "heal" = shd re-encode) and each opens a ``mesh-codec``
  span joined to the first queued fop's trace.

Correctness leans on fragment-stream concatenation: fragment ``f`` of
``concat(stripes_a, stripes_b)`` is ``concat(frag_f(a), frag_f(b))`` —
stripes are independent (ec-method.c:393-408 loops stripes).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
import time

import numpy as np

from ..core import metrics as _metrics
from ..core import tracing as _tracing
from . import gf256
from .codec import Codec

_DEVICE_BACKENDS = ("pallas-xor", "pallas-mxu", "xla", "xla-xor", "mesh")

#: live BatchingCodecs, scraped (not owned) by the unified registry —
#: the mesh data-plane families (ISSUE 8): launches prove coalesced
#: traffic really lands on the (dp, frag) mesh, batch_stripes sizes it,
#: and the origin label separates the serving path from shd heal
_LIVE_BATCHERS = _metrics.REGISTRY.register_objects(
    "gftpu_mesh_launches_total", "counter",
    "pjit'd (dp, frag) mesh codec launches by owning codec, op, and "
    "traffic origin (serve = BatchingCodec flushes from fops, heal = "
    "shd re-encode)",
    lambda c: [({"codec": c.name, "op": op, "origin": o}, v)
               for (op, o), v in list(c.mesh_launches.items())])
_metrics.REGISTRY.register_objects(
    "gftpu_mesh_batch_stripes_total", "counter",
    "stripes carried by mesh codec launches (post-bucket-padding) by "
    "owning codec, op, and origin",
    lambda c: [({"codec": c.name, "op": op, "origin": o}, v)
               for (op, o), v in list(c.mesh_stripes.items())],
    live=_LIVE_BATCHERS)

# Shape buckets: power-of-two stripe counts with this floor.  Bounded
# distinct shapes -> bounded jit compiles per (k, n) / (k, mask).
_BUCKET_FLOOR_STRIPES = 16

# Calibration bucket sizes (in stripes): a small and a large point to fit
# t(n) = overhead + n / rate.  The large point also warms the kernel cache
# for the bucket real traffic most often lands in.
_CAL_SMALL = 64
_CAL_LARGE = 2048

_EMA = 0.3  # weight of a new production sample in the online models


def _bucket_stripes(s: int) -> int:
    b = _BUCKET_FLOOR_STRIPES
    while b < s:
        b <<= 1
    return b


class _PathModel:
    """Online ``t(bytes) = overhead + bytes / rate`` timing model."""

    def __init__(self) -> None:
        self.overhead = 0.0
        self.rate = 0.0  # bytes/s; 0 -> uncalibrated
        self.samples = 0

    @property
    def ready(self) -> bool:
        return self.rate > 0.0

    def fit_two_points(self, n1: int, t1: float, n2: int, t2: float) -> None:
        """Exact fit from calibration at two sizes (n2 > n1)."""
        slope = max((t2 - t1) / max(n2 - n1, 1), 1e-15)
        self.rate = 1.0 / slope
        self.overhead = max(t1 - n1 * slope, 0.0)
        self.samples = 2

    def observe(self, nbytes: int, secs: float) -> None:
        """EMA update from a production flush (overhead held, rate tracked)."""
        if not self.ready:
            return
        span = secs - self.overhead
        if span <= 0:
            # faster than the modeled overhead: overhead was overestimated
            self.overhead = (1 - _EMA) * self.overhead + _EMA * secs * 0.5
            span = max(secs - self.overhead, 1e-9)
        implied = nbytes / span
        self.rate = (1 - _EMA) * self.rate + _EMA * implied
        self.samples += 1

    def predict(self, nbytes: int) -> float:
        return self.overhead + nbytes / self.rate if self.ready else float("inf")


class BatchingCodec(Codec):
    """Codec with an async batching window for the served data path.

    The sync ``encode``/``decode`` API stays available (heal tooling,
    tests); the data path awaits ``encode_async``/``decode_async``.

    Stats: ``launches`` counts device batch launches, ``cpu_launches``
    counts flushes routed to the CPU ladder, ``batched_fops`` total fops
    served, ``max_batch`` the largest coalesced batch in fops.

    ``min_batch`` is a hard floor below which flushes never go to the
    device; ``min_batch=0`` disables routing entirely (every flush takes
    the device path — tests and kernel benches use this to pin the path).
    Between the floor and the measured break-even, the calibrated models
    decide per flush.
    """

    def __init__(self, k: int, r: int, backend: str = "auto", *,
                 window: float = 0.0, min_batch: int = 256 * 1024,
                 max_batch_bytes: int = 256 << 20,
                 systematic: bool = False, mesh: bool = False,
                 name: str = ""):
        super().__init__(k, r, backend, systematic=systematic)
        # instance label on the mesh families: the owning layer's name
        # (a distribute-over-disperse volume has one codec PER group —
        # identical label sets would collide in the exposition)
        self.name = name or f"{k}+{r}"
        self.window = window
        self.min_batch = min_batch
        self.max_batch_bytes = max_batch_bytes
        self._enc_q: list[tuple] = []  # (data, fut, origin, trace_id)
        self._enc_task: asyncio.Task | None = None
        self._dec_q: dict[tuple[int, ...], list[tuple]] = {}
        self._dec_task: asyncio.Task | None = None
        # parity-delta queue (ISSUE 10): coalesced sub-stripe write
        # deltas ride the same flush ladder as full encodes — one
        # parity-rows-only launch per flush
        self._delta_q: list[tuple] = []
        self._delta_task: asyncio.Task | None = None
        # lazy small-batch codec; CPU-ladder backends alias self HERE
        # (pre-publication, against self.backend as RESOLVED by the
        # base init) so _small()'s lazy build is the only
        # cross-context write left — and that one is lock-serialized
        self._cpu = None if self.backend in _DEVICE_BACKENDS else self
        self.launches = 0
        self.cpu_launches = 0
        self.batched_fops = 0
        self.max_batch = 0
        # two workers: batch N's device round trip overlaps batch N+1's
        # dispatch/host work (jax serializes on-device execution itself)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix=f"ec-codec-{k}+{r}")
        self._lock = threading.Lock()
        self._dev = _PathModel()
        self._nat = _PathModel()
        self._cal_state = "idle"  # idle -> running -> done/failed
        # mesh data plane (ISSUE 8, cluster.mesh-codec): when the key is
        # on AND >1 device is visible, flushes at/above min_batch land
        # in ONE pjit'd NamedSharding(Mesh(dp, frag)) launch.  The
        # device-count probe can block 45 s on a wedged transport, so it
        # warms OFF the event loop; until it answers "ready", flushes
        # take the existing ladder unchanged.  Systematic volumes ride
        # the tier too (ISSUE 12): encodes take the parity-rows-only
        # sharded launch; degraded DECODES keep the single-device
        # ladder (healthy systematic reads never decode at all).
        self.mesh_requested = mesh
        self._mesh = None
        self._mesh_state = "off"  # off -> warming -> ready/unavailable
        self._mesh_stop = False   # close() retires a retrying warm loop
        self.mesh_launches: dict[tuple[str, str], int] = {}
        self.mesh_stripes: dict[tuple[str, str], int] = {}
        if mesh:
            self._mesh_state = "warming"
            # a dedicated daemon thread, NOT the flush pool: on a
            # wedged transport the probe join holds its thread for the
            # full 45 s deadline, and with calibration on the other
            # pool worker that would queue production flushes behind
            # it — exactly the stall the ladder fallback promises away
            threading.Thread(target=self._mesh_warm, daemon=True,
                             name=f"gftpu-mesh-warm-{k}+{r}").start()
        _LIVE_BATCHERS.add(self)  # unified-registry scrape target
        # calibration is DEFERRED to an idle gap: the first device
        # encode pays jax imports + kernel compiles that monopolize the
        # GIL for seconds — run that while production flushes are
        # arriving and every in-flight fop (and the event loop's own
        # heartbeats) stalls behind it.  Flushes stamp _last_flush; a
        # debounce task starts calibrating only after _CAL_IDLE_S of
        # quiet.  ensure_calibrated() (benches) still forces it NOW.
        # Seeded with NOW, not 0: a zero seed would make the first
        # flush see an "infinite" idle gap and fire calibration under
        # the cold-start burst.
        self._last_flush = time.monotonic()
        self._cal_timer: asyncio.Task | None = None

    _CAL_IDLE_S = 0.3

    # -- stats hooks (count every device launch, sync path included) ------

    def encode(self, data: np.ndarray) -> np.ndarray:
        with self._lock:
            self.launches += 1
        return super().encode(data)

    def decode(self, frags: np.ndarray, rows) -> np.ndarray:
        with self._lock:
            self.launches += 1
        return super().decode(frags, rows)

    def encode_delta(self, delta: np.ndarray) -> np.ndarray:
        with self._lock:
            self.launches += 1
        return super().encode_delta(delta)

    def _small(self) -> Codec:
        # double-checked under the codec lock: _route (loop) and
        # _calibrate (flush-pool thread) race the first call, and an
        # unserialized lazy build constructs the native codec twice —
        # graft-race GL09 caught the unlocked cross-context write
        if self._cpu is None:
            with self._lock:
                if self._cpu is None:
                    try:
                        self._cpu = Codec(self.k, self.r, "native",
                                          systematic=self.systematic)
                    except RuntimeError:
                        self._cpu = Codec(self.k, self.r, "ref",
                                          systematic=self.systematic)
        return self._cpu

    # -- mesh data plane ---------------------------------------------------

    _MESH_WARM_RETRIES = 2

    def _mesh_warm(self) -> None:
        """Runs on its own daemon thread (NEVER the flush pool — see
        the spawn site in __init__): deadline device probe, then build
        (cache) the process mesh.  A single device parks the codec on
        the existing ladder; a RETRYABLE 0 (probe timeout / transient
        jax error, the window device_count caches for _COUNT_RETRY_S)
        re-probes up to _MESH_WARM_RETRIES times after the window —
        without this, a startup plugin-registration race would disable
        the mesh for the codec's whole lifetime despite the probe's
        own retry window."""
        try:
            from ..parallel import mesh_codec

            for attempt in range(1 + self._MESH_WARM_RETRIES):
                n = mesh_codec.device_count()
                if n > 1:
                    self._mesh = mesh_codec.default_mesh()
                    self._mesh_state = "ready"
                    return
                if not (n == 0 and mesh_codec.device_count_transient()
                        and attempt < self._MESH_WARM_RETRIES):
                    break
                wake = time.monotonic() + mesh_codec._COUNT_RETRY_S + 1.0
                while time.monotonic() < wake and not self._mesh_stop:
                    time.sleep(1.0)
                if self._mesh_stop:  # codec replaced/closed: stand down
                    break
            self._mesh_state = "unavailable"
        except Exception:
            self._mesh_state = "unavailable"

    async def ensure_mesh(self) -> bool:
        """Await the mesh warm probe (tests/benches/dryrun — daemons
        never wait); True when the mesh plane is routable."""
        while self._mesh_state == "warming":
            await asyncio.sleep(0.01)
        return self._mesh_state == "ready"

    def _mesh_launch(self, op: str, cat: np.ndarray, rows, batch):
        """ONE pjit'd NamedSharding launch over the (dp, frag) mesh for
        a whole coalesced flush (runs in the pool).  Pads to the stripe
        bucket so the jit cache stays bounded (zero stripes encode to
        zero fragments — sliced back off), records the launch on the
        mesh counters, and opens a ``mesh-codec`` span joined to the
        first queued fop's trace so slow-fop trees show the dispatch."""
        from . import codec as codec_mod
        from ..parallel import mesh_codec

        origins = {o for _d, _f, o, _t in batch}
        origin = origins.pop() if len(origins) == 1 else "mixed"
        tid = next((t for _d, _f, _o, t in batch if t), None)
        tok = _tracing.CURRENT.set((tid, 0)) \
            if (_tracing.ENABLED and tid) else None
        span = _tracing.enter("mesh-codec", op) if _tracing.ENABLED \
            else None
        t0 = time.perf_counter()
        err = False
        sb = 0
        try:
            if op in ("encode", "delta"):
                s = cat.size // self.stripe_size
                sb = _bucket_stripes(s)
                if sb != s:
                    cat = np.concatenate(
                        [cat, np.zeros((sb - s) * self.stripe_size,
                                       dtype=np.uint8)])
                if op == "delta":
                    out = mesh_codec.sharded_parity(
                        self.k, self.r, cat, self._mesh)
                else:
                    out = mesh_codec.sharded_encode(
                        self.k, self.r, cat, self._mesh,
                        systematic=self.systematic)
                out = out[:, : s * self.fragment_chunk]
            else:
                w = cat.shape[1]
                s = w // self.fragment_chunk
                sb = _bucket_stripes(s)
                if sb != s:
                    cat = np.concatenate(
                        [cat, np.zeros((cat.shape[0],
                                        (sb - s) * self.fragment_chunk),
                                       dtype=np.uint8)], axis=1)
                if cat.size > codec_mod.MESH_RING_DECODE_BYTES:
                    # the memory-bounded alternative: fragments stay
                    # ring-sharded, an XOR accumulator ppermutes
                    from ..parallel import ring_codec

                    out = ring_codec.ring_decode(
                        self.k, rows, cat, self._mesh)
                else:
                    out = mesh_codec.sharded_decode(
                        self.k, rows, cat, self._mesh)
                out = out[: w * self.k]
            return out
        except Exception:
            err = True
            raise
        finally:
            if span is not None:
                _tracing.exit_span(span, time.perf_counter() - t0, err)
            if tok is not None:
                _tracing.CURRENT.reset(tok)
            with self._lock:
                self.launches += 1
                key = (op, origin)
                self.mesh_launches[key] = \
                    self.mesh_launches.get(key, 0) + 1
                self.mesh_stripes[key] = \
                    self.mesh_stripes.get(key, 0) + sb

    # -- measured break-even routing --------------------------------------

    def _calibrate(self) -> None:
        """Time device + native at two bucket sizes; fit both models.

        Runs in the pool.  Each size gets a warmup launch (pays the jit
        compile, which production flushes to that bucket then reuse) and a
        timed launch.
        """
        try:
            small = self._small()
            pts_dev, pts_nat = [], []
            for stripes in (_CAL_SMALL, _CAL_LARGE):
                data = np.frombuffer(
                    np.random.default_rng(stripes).bytes(
                        stripes * self.stripe_size), dtype=np.uint8)
                super().encode(data)  # warmup: compile + cache
                t0 = time.perf_counter()
                super().encode(data)
                pts_dev.append((data.size, time.perf_counter() - t0))
                t0 = time.perf_counter()
                small.encode(data)
                pts_nat.append((data.size, time.perf_counter() - t0))
            with self._lock:
                self._dev.fit_two_points(*pts_dev[0], *pts_dev[1])
                self._nat.fit_two_points(*pts_nat[0], *pts_nat[1])
                self._cal_state = "done"
        except Exception:  # device unusable -> stay on the CPU ladder
            with self._lock:
                self._cal_state = "failed"

    def _maybe_start_calibration(self) -> None:
        with self._lock:
            if self._cal_state != "idle":
                return
            self._cal_state = "running"
        if self._cal_timer is not None:
            self._cal_timer.cancel()
            self._cal_timer = None
        self._pool.submit(self._calibrate)

    def _maybe_schedule_calibration(self) -> None:
        """Debounced: start calibration after an idle gap, not under load."""
        # _cal_state is written by the pool thread (_calibrate) under
        # the lock; this loop-side read takes it too (graft-race GL09:
        # an unlocked read beside a cross-context writer) — one
        # uncontended acquire on a path that already locks in _route
        with self._lock:
            if self._cal_state != "idle" or self._cal_timer is not None:
                return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return

        async def when_idle():
            while True:
                gap = time.monotonic() - self._last_flush
                if gap >= self._CAL_IDLE_S:
                    break
                await asyncio.sleep(self._CAL_IDLE_S - gap)
            self._cal_timer = None
            self._maybe_start_calibration()

        self._cal_timer = loop.create_task(when_idle())

    async def ensure_calibrated(self) -> bool:
        """Run (or await) calibration; True if the device model is ready.

        Benches call this so routing decisions in the measured window are
        model-driven rather than 'calibrating -> CPU'.  Daemons never wait.
        """
        if self._small() is self:
            return False
        self._maybe_start_calibration()
        while True:
            with self._lock:
                st = self._cal_state
            if st in ("done", "failed"):
                return st == "done"
            await asyncio.sleep(0.01)

    def _route(self, total: int) -> tuple[Codec, str]:
        """Pick the path for a flush of ``total`` bytes ->
        ``(codec, kind)`` with kind in {"mesh", "device", "cpu"}.

        The mesh tier outranks the calibrated single-device ladder when
        the volume key armed it AND the warm probe saw >1 device AND
        the flush clears min_batch (min_batch <= 0 pins the path for
        tests) — below that, the pre-mesh ladder is untouched."""
        if self._mesh_state == "ready" and \
                (self.min_batch <= 0 or total >= self.min_batch):
            return self, "mesh"
        small = self._small()
        if small is self:
            return self, "cpu"  # CPU-ladder backend: nothing to route
        if self.min_batch <= 0:
            return self, "device"  # routing disabled: force the device
        if total < self.min_batch:
            return small, "cpu"
        with self._lock:
            st, dev, nat = self._cal_state, self._dev, self._nat
            if st != "done":
                pass
            elif dev.predict(self._padded(total)) <= nat.predict(total):
                return self, "device"
            else:
                return small, "cpu"
        self._maybe_schedule_calibration()
        return small, "cpu"

    def _padded(self, total: int) -> int:
        return _bucket_stripes(total // self.stripe_size) * self.stripe_size

    def break_even_bytes(self) -> int | None:
        """Bytes past which the device model predicts a win (None if flat)."""
        with self._lock:
            if not (self._dev.ready and self._nat.ready):
                return None
            inv = 1.0 / self._nat.rate - 1.0 / self._dev.rate
            if inv <= 0:
                return None
            # 0 when the device model wins at every size (overhead
            # below native's): never report a negative byte count
            return max(0, int((self._dev.overhead - self._nat.overhead)
                              / inv))

    def _observe(self, device: bool, nbytes: int, secs: float) -> None:
        with self._lock:
            (self._dev if device else self._nat).observe(nbytes, secs)

    # -- bucketed device launches ------------------------------------------

    def _encode_bucketed(self, data: np.ndarray) -> np.ndarray:
        """Device encode with zero-stripe padding to a bucketed shape."""
        s = data.size // self.stripe_size
        sb = _bucket_stripes(s)
        if sb != s:
            data = np.concatenate(
                [data, np.zeros((sb - s) * self.stripe_size, dtype=np.uint8)])
        frags = self.encode(data)
        return frags[:, : s * self.fragment_chunk]

    def _delta_bucketed(self, delta: np.ndarray) -> np.ndarray:
        """Device parity-delta encode with zero-stripe bucket padding
        (zero stripes have zero parity deltas — sliced back off)."""
        s = delta.size // self.stripe_size
        sb = _bucket_stripes(s)
        if sb != s:
            delta = np.concatenate(
                [delta, np.zeros((sb - s) * self.stripe_size,
                                 dtype=np.uint8)])
        pds = self.encode_delta(delta)
        return pds[:, : s * self.fragment_chunk]

    def _decode_bucketed(self, frags: np.ndarray, rows) -> np.ndarray:
        w = frags.shape[1]
        s = w // self.fragment_chunk
        sb = _bucket_stripes(s)
        if sb != s:
            frags = np.concatenate(
                [frags,
                 np.zeros((frags.shape[0], (sb - s) * self.fragment_chunk),
                          dtype=np.uint8)], axis=1)
        return self.decode(frags, rows)[: w * self.k]

    # -- encode ------------------------------------------------------------

    async def encode_async(self, data: np.ndarray,
                           origin: str = "serve") -> np.ndarray:
        """Encode stripe-aligned bytes; coalesced with concurrent calls.

        ``origin`` labels the traffic source on the mesh counters
        ("serve" = fop data path, "heal" = shd re-encode) and rides the
        queue so a flush can attribute its launch."""
        data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
        if data.size % self.stripe_size:
            raise ValueError("data length not a multiple of the stripe")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._enc_q.append((data, fut, origin, _tracing.current_id()))
        if sum(d.size for d, *_ in self._enc_q) >= self.max_batch_bytes:
            self._flush_encodes()
        elif self._enc_task is None:
            self._enc_task = asyncio.ensure_future(self._enc_timer())
        return await fut

    async def _enc_timer(self):
        # window 0 = same-tick coalescing: sleep(0) runs after every
        # already-scheduled callback, so fops made concurrent in this
        # loop pass still land in one batch, while a lone sequential
        # writer pays no idle wait (a fixed window poll costs ~0.3 ms
        # of epoll timeout per flush on the smallfile path)
        await asyncio.sleep(self.window)
        self._flush_encodes()

    def _flush_encodes(self) -> None:
        if self._enc_task is not None:
            self._enc_task.cancel()
            self._enc_task = None
        batch, self._enc_q = self._enc_q, []
        if not batch:
            return
        self._last_flush = time.monotonic()
        self.batched_fops += len(batch)
        self.max_batch = max(self.max_batch, len(batch))
        total = sum(d.size for d, *_ in batch)
        codec, kind = self._route(total)
        if kind == "cpu" and codec is not self:
            self.cpu_launches += 1
        loop = asyncio.get_running_loop()
        self._submit(self._run_encode, loop, batch, codec, kind, total)

    def _submit(self, fn, loop, *args) -> None:
        """Pool submit with an inline fallback: a batch still pending in
        the window when close() shuts the pool (live reconfigure swaps
        the codec) must NOT strand its awaiting fops — run the flush on
        the loop thread instead."""
        try:
            self._pool.submit(fn, loop, *args)
        except RuntimeError:  # pool shut down after close()
            fn(loop, *args)

    def _run_encode(self, loop, batch, codec: Codec, kind: str,
                    total: int) -> None:
        """Executes in the pool: concatenate, launch, time, resolve."""
        try:
            t0 = time.perf_counter()
            if len(batch) == 1:
                cat = batch[0][0]
            else:
                cat = np.concatenate([d for d, *_ in batch])
            if kind == "mesh":
                frags = self._mesh_launch("encode", cat, None, batch)
            elif kind == "device":
                frags = self._encode_bucketed(cat)
            else:
                frags = codec.encode(cat)
            if kind != "mesh":
                # device samples observe the PADDED size — the launch
                # did that much work, and _route predicts padded too.
                # Mesh launches are key-routed, not model-routed: their
                # timings must not skew the single-device model.
                self._observe(kind == "device",
                              self._padded(total) if kind == "device"
                              else total,
                              time.perf_counter() - t0)
            results, off = [], 0
            for d, *_ in batch:
                flen = d.size // self.k
                results.append(frags[:, off:off + flen].copy()
                               if len(batch) > 1 else frags)
                off += flen
            loop.call_soon_threadsafe(self._resolve, batch, results, None)
        except Exception as e:
            loop.call_soon_threadsafe(self._resolve, batch, None, e)

    @staticmethod
    def _resolve(batch, results, err) -> None:
        for i, (_d, fut, *_rest) in enumerate(batch):
            if fut.done() or fut.cancelled():
                continue
            if err is not None:
                fut.set_exception(err)
            else:
                fut.set_result(results[i])

    # -- parity-delta encode (ISSUE 10) ------------------------------------

    async def encode_delta_async(self, delta: np.ndarray,
                                 origin: str = "serve") -> np.ndarray:
        """Parity deltas for a stripe-aligned XOR delta; coalesced with
        concurrent calls exactly like ``encode_async`` (fragment-stream
        concatenation holds for the parity submatrix too — stripes are
        independent).  Deltas ride the measured flush ladder, and on a
        mesh-armed codec a routed flush lands on the same
        parity-rows-only sharded program as the systematic mesh encode
        (``mesh_codec.sharded_parity``, a ``delta`` launch on the mesh
        counters)."""
        delta = np.ascontiguousarray(delta, dtype=np.uint8).ravel()
        if delta.size % self.stripe_size:
            raise ValueError("delta length not a multiple of the stripe")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._delta_q.append((delta, fut, origin, _tracing.current_id()))
        if sum(d.size for d, *_ in self._delta_q) >= self.max_batch_bytes:
            self._flush_deltas()
        elif self._delta_task is None:
            self._delta_task = asyncio.ensure_future(self._delta_timer())
        return await fut

    async def _delta_timer(self):
        await asyncio.sleep(self.window)
        self._flush_deltas()

    def _flush_deltas(self) -> None:
        if self._delta_task is not None:
            self._delta_task.cancel()
            self._delta_task = None
        batch, self._delta_q = self._delta_q, []
        if not batch:
            return
        self._last_flush = time.monotonic()
        self.batched_fops += len(batch)
        self.max_batch = max(self.max_batch, len(batch))
        total = sum(d.size for d, *_ in batch)
        codec, kind = self._route(total)
        if kind == "cpu" and codec is not self:
            self.cpu_launches += 1
        loop = asyncio.get_running_loop()
        self._submit(self._run_delta, loop, batch, codec, kind, total)

    def _run_delta(self, loop, batch, codec: Codec, kind: str,
                   total: int) -> None:
        try:
            t0 = time.perf_counter()
            if len(batch) == 1:
                cat = batch[0][0]
            else:
                cat = np.concatenate([d for d, *_ in batch])
            if kind == "mesh":
                # parity deltas ride the same parity-rows-only sharded
                # program as the systematic mesh encode (ISSUE 12)
                pds = self._mesh_launch("delta", cat, None, batch)
            elif kind == "device":
                pds = self._delta_bucketed(cat)
            else:
                pds = codec.encode_delta(cat)
            # the single-device models track full-generator encodes;
            # parity-only work would skew them low — don't observe
            results, off = [], 0
            for d, *_ in batch:
                flen = d.size // self.k
                results.append(pds[:, off:off + flen].copy()
                               if len(batch) > 1 else pds)
                off += flen
            loop.call_soon_threadsafe(self._resolve, batch, results, None)
        except Exception as e:
            loop.call_soon_threadsafe(self._resolve, batch, None, e)

    # -- decode ------------------------------------------------------------

    async def decode_async(self, frags: np.ndarray, rows,
                           origin: str = "serve") -> np.ndarray:
        """Decode k fragments; coalesced with concurrent same-mask calls."""
        rows = tuple(int(x) for x in rows)
        frags = np.ascontiguousarray(frags, dtype=np.uint8)
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        q = self._dec_q.setdefault(rows, [])
        q.append((frags, fut, origin, _tracing.current_id()))
        if sum(f.size for f, *_ in q) >= self.max_batch_bytes:
            self._flush_decodes()  # same blow-up guard as the encode path
        elif self._dec_task is None:
            self._dec_task = asyncio.ensure_future(self._dec_timer())
        return await fut

    async def _dec_timer(self):
        await asyncio.sleep(self.window)
        self._flush_decodes()

    def _flush_decodes(self) -> None:
        if self._dec_task is not None:
            self._dec_task.cancel()
            self._dec_task = None
        queues, self._dec_q = self._dec_q, {}
        if not queues:
            return
        self._last_flush = time.monotonic()
        loop = asyncio.get_running_loop()
        for rows, batch in queues.items():
            self.batched_fops += len(batch)
            self.max_batch = max(self.max_batch, len(batch))
            total = sum(f.size for f, *_ in batch)
            codec, kind = self._route(total)
            if kind == "mesh" and self.systematic:
                # the systematic mesh tier is encode-only (parity-rows
                # sharded launch): a degraded decode reconstructs
                # missing data rows on the single-device ladder
                codec, kind = self, "device"
            if kind == "cpu" and codec is not self:
                self.cpu_launches += 1
            self._submit(self._run_decode, loop, rows, batch, codec,
                         kind, total)

    def _run_decode(self, loop, rows, batch, codec: Codec, kind: str,
                    total: int) -> None:
        try:
            t0 = time.perf_counter()
            if len(batch) == 1:
                cat = batch[0][0]
            else:
                cat = np.concatenate([f for f, *_ in batch], axis=1)
            if kind == "mesh":
                out = self._mesh_launch("decode", cat, rows, batch)
            elif kind == "device":
                out = self._decode_bucketed(cat, rows)
            else:
                out = codec.decode(cat, rows)
            if kind != "mesh":
                self._observe(kind == "device",
                              self._padded(total) if kind == "device"
                              else total,
                              time.perf_counter() - t0)
            results, off = [], 0
            for f, *_ in batch:
                nbytes = f.shape[1] * self.k
                results.append(out[off:off + nbytes].copy()
                               if len(batch) > 1 else out)
                off += nbytes
            loop.call_soon_threadsafe(self._resolve, batch, results, None)
        except Exception as e:
            loop.call_soon_threadsafe(self._resolve, batch, None, e)

    def close(self) -> None:
        """Release the flush pool.  The EC layer calls this when a
        reconfigure replaces the codec and at graph fini — without it
        every rebuild leaks the two worker threads.  Queued flushes
        still run (their awaiters must resolve); threads exit after."""
        if self._cal_timer is not None:
            self._cal_timer.cancel()
            self._cal_timer = None
        self._mesh_stop = True  # a retrying warm loop stands down
        self._pool.shutdown(wait=False)

    def dump_stats(self) -> dict:
        with self._lock:
            dev_ready = self._dev.ready
            dev = {"overhead_s": round(self._dev.overhead, 6),
                   "rate_MiB_s": round(self._dev.rate / 2**20, 1),
                   "samples": self._dev.samples} if dev_ready else None
            nat = {"overhead_s": round(self._nat.overhead, 6),
                   "rate_MiB_s": round(self._nat.rate / 2**20, 1),
                   "samples": self._nat.samples} if self._nat.ready else None
            cal = self._cal_state
        return {
            "backend": self.backend,
            "launches": self.launches,
            "cpu_launches": self.cpu_launches,
            "batched_fops": self.batched_fops,
            "max_batch": self.max_batch,
            "window_s": self.window,
            "min_batch_bytes": self.min_batch,
            "calibration": cal,
            "device_model": dev,
            "native_model": nat,
            "break_even_bytes": self.break_even_bytes(),
            "mesh": {
                "requested": self.mesh_requested,
                "state": self._mesh_state,
                "launches": {f"{op}:{o}": v for (op, o), v
                             in self.mesh_launches.items()},
                "stripes": {f"{op}:{o}": v for (op, o), v
                            in self.mesh_stripes.items()},
            },
        }
