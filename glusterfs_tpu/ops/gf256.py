"""GF(2^8) arithmetic core for the disperse (erasure-coding) engine.

Semantics match the reference implementation's Galois field and matrix
construction (reference: ``xlators/cluster/ec/src/ec-galois.c``,
``ec-method.c:22-71``, ``doc/developer-guide/ec-implementation.md``):

* Field: GF(2^8) with primitive polynomial ``0x11D``, generator 2
  (``ec-method.h:17-18``).
* Encode matrix: non-systematic reverse Vandermonde. Row for value
  ``v = i + 1`` (i in 0..N-1) is ``[v^(K-1), v^(K-2), ..., v, 1]``
  (``ec-method.c:22-35`` builds exactly this via exp + repeated division).
* Decode matrix: the unique GF(256) inverse of the K surviving rows
  (``ec-method.c:38-71`` computes it by polynomial interpolation; we use
  Gauss-Jordan — the inverse is unique, parity is proven by golden vectors
  generated from the reference's own portable C kernel).

Data layout (bit-sliced chunks, ``ec-implementation.md:485-519``):
a chunk is ``EC_METHOD_CHUNK_SIZE = 512`` bytes = 8 bit-planes of
``EC_METHOD_WORD_SIZE = 64`` bytes.  Plane ``p`` holds bit ``p`` of each of
the 512 logical GF(256) elements of the chunk; element ``e``'s bit lives at
plane byte ``e >> 3``, bit ``e & 7``.  Multiplying every element of a chunk
by a constant ``c`` is therefore a fixed 8x8 GF(2) bit-matrix applied to the
planes — which makes a full encode a single binary matmul
``(N*8, K*8) @ (K*8, bits) mod 2``: MXU food.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import NamedTuple

import numpy as np

GF_BITS = 8
GF_MOD = 0x11D
GF_SIZE = 1 << GF_BITS

WORD_SIZE = 64  # bytes per bit-plane (EC_METHOD_WORD_SIZE)
CHUNK_SIZE = WORD_SIZE * GF_BITS  # 512 bytes (EC_METHOD_CHUNK_SIZE)
MAX_FRAGMENTS = 16  # EC_METHOD_MAX_FRAGMENTS


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """pow/log tables, generator 2 mod 0x11D (ec-galois.c:53-70 semantics)."""
    pow_t = np.zeros(512, dtype=np.int32)
    log_t = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        pow_t[i] = x
        pow_t[i + 255] = x
        log_t[x] = i
        x <<= 1
        if x >= 256:
            x ^= GF_MOD
    log_t[0] = -511  # sentinel: pow[log[0] + anything] never valid; callers mask
    return pow_t, log_t


POW, LOG = _build_tables()


def gf_mul(a, b):
    """Element-wise GF(256) multiply (vectorized)."""
    a = np.asarray(a, dtype=np.int32)
    b = np.asarray(b, dtype=np.int32)
    nz = (a != 0) & (b != 0)
    idx = np.where(nz, LOG[a] + LOG[b], 0)  # in [0, 508] when nz
    return np.where(nz, POW[idx], 0).astype(np.uint8)


def gf_div(a, b):
    a = np.asarray(a, dtype=np.int32)
    b = np.asarray(b, dtype=np.int32)
    if np.any(b == 0):
        raise ZeroDivisionError("GF(256) division by zero")
    nz = a != 0
    idx = np.where(nz, 255 + LOG[a] - LOG[b], 0)
    return np.where(nz, POW[idx], 0).astype(np.uint8)


def gf_pow(a: int, e: int) -> int:
    r = 1
    a = int(a)
    while e:
        if e & 1:
            r = int(gf_mul(r, a))
        a = int(gf_mul(a, a))
        e >>= 1
    return r


def gf_inv(a):
    return gf_div(1, a)


@functools.cache
def bitmatrices() -> np.ndarray:
    """(256, 8, 8) uint8: BITMAT[c][p][q] = bit p of (c * 2^q).

    Column q of BITMAT[c] is the image of basis element 2^q under
    multiplication by c — applying BITMAT[c] to the 8 bit-planes of a chunk
    multiplies all 512 elements by c (the linear map the reference's XOR-chain
    programs in ec-gf8.c implement).
    """
    c = np.arange(256, dtype=np.int32)[:, None]
    q = (1 << np.arange(8, dtype=np.int32))[None, :]
    prod = gf_mul(c, q).astype(np.int32)  # (256, 8): c * 2^q
    p = np.arange(8, dtype=np.int32)[None, :, None]
    return ((prod[:, None, :] >> p) & 1).astype(np.uint8)  # (256, p, q)


def encode_matrix(k: int, n: int) -> np.ndarray:
    """(n, k) non-systematic Vandermonde: A[i][j] = (i+1)^(k-1-j)."""
    if k > MAX_FRAGMENTS:
        raise ValueError(f"at most {MAX_FRAGMENTS} data fragments supported")
    if n > 255:
        raise ValueError("at most 255 fragments representable in GF(256)")
    v = np.arange(1, n + 1, dtype=np.int32)
    exps = np.arange(k - 1, -1, -1, dtype=np.int64)
    out = np.empty((n, k), dtype=np.uint8)
    for j, e in enumerate(exps):
        out[:, j] = [gf_pow(int(val), int(e)) for val in v]
    return out


@functools.lru_cache(maxsize=64)
def systematic_matrix(k: int, n: int) -> np.ndarray:
    """Systematic generator: ``V @ inv(V[:k])`` for the Vandermonde V of
    :func:`encode_matrix` — rows 0..k-1 are the identity (data fragments
    ARE the raw stripe chunks), rows k.. are parity.  Any k rows stay
    invertible (each is ``V[rows] @ inv(V[:k])`` with both factors
    invertible).

    The reference's code is non-systematic (every fragment is a codeword;
    reads always decode, ec-method.c:393-433) — fine when decode is a
    cheap local AVX pass.  On a TPU behind a bandwidth-bound link the
    systematic form is the tpu-first layout: healthy reads touch no
    device at all, encode ships back only the parity rows, degraded
    reads reconstruct only the missing rows.  Selected per volume via
    ``disperse.systematic``."""
    v = encode_matrix(k, n).astype(np.int64)
    inv = invert_matrix(encode_matrix(k, k)).astype(np.int64)
    out = np.zeros((n, k), dtype=np.uint8)
    for i in range(n):
        for j in range(k):
            acc = 0
            for t in range(k):
                acc ^= gf_mul(int(v[i, t]), int(inv[t, j]))
            out[i, j] = acc
    return out


def generator_matrix(k: int, n: int, systematic: bool = False) -> np.ndarray:
    return systematic_matrix(k, n) if systematic else encode_matrix(k, n)


def invert_matrix(a: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inverse over GF(256)."""
    a = a.astype(np.int32)
    k = a.shape[0]
    if a.shape != (k, k):
        raise ValueError("square matrix required")
    inv = np.eye(k, dtype=np.int32)
    for col in range(k):
        piv = col
        while piv < k and a[piv, col] == 0:
            piv += 1
        if piv == k:
            raise np.linalg.LinAlgError("singular matrix over GF(256)")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        d = int(a[col, col])
        a[col] = gf_div(a[col], d)
        inv[col] = gf_div(inv[col], d)
        for r in range(k):
            if r == col or a[r, col] == 0:
                continue
            f = int(a[r, col])
            a[r] ^= gf_mul(f, a[col]).astype(np.int32)
            inv[r] ^= gf_mul(f, inv[col]).astype(np.int32)
    return inv.astype(np.uint8)


def decode_matrix(k: int, rows: np.ndarray | list[int],
                  systematic: bool = False) -> np.ndarray:
    """Inverse of the generator-matrix rows `rows` (surviving indices)."""
    rows = np.asarray(rows, dtype=np.int64)
    if len(rows) != k:
        raise ValueError(f"need exactly {k} surviving fragments, got {len(rows)}")
    sub = generator_matrix(k, int(rows.max()) + 1, systematic)[rows]
    return invert_matrix(sub)


@functools.lru_cache(maxsize=256)
def decode_bits_cached(k: int, rows: tuple[int, ...],
                       systematic: bool = False) -> np.ndarray:
    """Per-surviving-mask cached decode bit-matrix — the one LRU shared by
    every backend (the reference keeps an equivalent LRU of inverted
    matrices keyed by fragment bitmask, ec-method.c:200-245)."""
    return expand_bitmatrix(decode_matrix(k, list(rows), systematic))


@functools.lru_cache(maxsize=64)
def parity_bits_cached(k: int, n: int) -> np.ndarray:
    """Bit-matrix of the systematic generator's parity rows only
    ((n-k)*8, k*8): the device work of a systematic encode."""
    return expand_bitmatrix(systematic_matrix(k, n)[k:])


@functools.lru_cache(maxsize=256)
def reconstruct_bits_cached(k: int, rows: tuple[int, ...],
                            wanted: tuple[int, ...]) -> np.ndarray:
    """Bit-matrix mapping k systematic survivors (indices ``rows``) to
    just the ``wanted`` data rows (len(wanted)*8, k*8): a degraded
    systematic read reconstructs ONLY what is missing."""
    m = decode_matrix(k, list(rows), systematic=True)
    return expand_bitmatrix(m[list(wanted)])


def expand_bitmatrix(coeff: np.ndarray) -> np.ndarray:
    """Expand an (R, C) GF(256) coefficient matrix into its (R*8, C*8) GF(2)
    bit-matrix: block (i, j) is BITMAT[coeff[i, j]].

    ``Y_bits = (Abits @ X_bits) % 2`` computes ``Y = coeff (*) X`` on
    bit-sliced chunk data.
    """
    bm = bitmatrices()[coeff.astype(np.int32)]  # (R, C, 8, 8)
    r, c = coeff.shape
    return bm.transpose(0, 2, 1, 3).reshape(r * 8, c * 8)


# ---------------------------------------------------------------------------
# Bit-exact NumPy reference codec (the `cpu-extensions=none` oracle).
# ---------------------------------------------------------------------------


def _to_planes(data: np.ndarray, k: int) -> np.ndarray:
    """(S*k*512,) bytes -> (S, k*8, 64) plane words (stripe-major)."""
    s = data.size // (k * CHUNK_SIZE)
    return data.reshape(s, k * GF_BITS, WORD_SIZE)


def _xor_matmul_planes(abits: np.ndarray, x: np.ndarray) -> np.ndarray:
    """XOR-matmul: y[s, i, :] = XOR_j { x[s, j, :] : abits[i, j] == 1 }.

    x: (S, C, 64) uint8 plane words; abits: (R, C) in {0,1}.
    Bitwise XOR accumulation over bytes == GF(2) matmul applied to each of
    the 8 bit positions in parallel (no unpacking needed host-side).
    """
    r = abits.shape[0]
    s = x.shape[0]
    out = np.zeros((s, r, WORD_SIZE), dtype=np.uint8)
    for i in range(r):
        sel = np.nonzero(abits[i])[0]
        if sel.size:
            out[:, i, :] = np.bitwise_xor.reduce(x[:, sel, :], axis=1)
    return out


def ref_encode(data: np.ndarray, k: int, n: int,
               systematic: bool = False) -> np.ndarray:
    """Encode `data` (length multiple of k*512) into n fragments.

    Returns (n, S*512) uint8 — fragment i is the concatenation of its chunk
    from every stripe (matching ec_method_encode's output layout,
    ec-method.c:393-408).
    """
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    if data.size % (k * CHUNK_SIZE):
        raise ValueError("data length must be a multiple of k*512")
    abits = expand_bitmatrix(generator_matrix(k, n, systematic))
    x = _to_planes(data, k)  # (S, k*8, 64)
    y = _xor_matmul_planes(abits, x)  # (S, n*8, 64)
    s = x.shape[0]
    # (S, n, 8, 64) -> fragment-major (n, S, 512)
    return (
        y.reshape(s, n, GF_BITS * WORD_SIZE)
        .transpose(1, 0, 2)
        .reshape(n, s * CHUNK_SIZE)
        .copy()
    )


def ref_parity(data: np.ndarray, k: int, n: int) -> np.ndarray:
    """Parity rows ONLY of the systematic code: ((n-k), S*512) for
    stripe-major bytes (length multiple of k*512).

    This is the delta-encode primitive of the parity-delta write plane
    (the classic RAID parity-logging result): the code is linear, so
    ``frag_i(old ⊕ Δ) = frag_i(old) ⊕ frag_i(Δ)`` — a sub-stripe write
    ships the overwritten data bytes verbatim (systematic data rows ARE
    the stripe chunks) plus ``parity(Δ)`` applied brick-side as an XOR
    (the ``xorv`` fop), never re-encoding the untouched rows."""
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    if data.size % (k * CHUNK_SIZE):
        raise ValueError("data length must be a multiple of k*512")
    pbits = parity_bits_cached(k, n)
    x = _to_planes(data, k)  # (S, k*8, 64)
    y = _xor_matmul_planes(pbits, x)  # (S, (n-k)*8, 64)
    m = n - k
    s = x.shape[0]
    return (
        y.reshape(s, m, GF_BITS * WORD_SIZE)
        .transpose(1, 0, 2)
        .reshape(m, s * CHUNK_SIZE)
        .copy()
    )


def frags_to_planes(frags: np.ndarray, k: int) -> np.ndarray:
    """Fragment-major (k, S*512) -> stripe-major plane words (S, k*8, 64)
    (inverse of ref_encode's output transform)."""
    frags = np.ascontiguousarray(frags, dtype=np.uint8)
    if frags.shape[0] != k:
        raise ValueError(f"need exactly {k} fragments, got {frags.shape[0]}")
    if frags.shape[1] % CHUNK_SIZE:
        raise ValueError("fragment length must be a multiple of 512")
    s = frags.shape[1] // CHUNK_SIZE
    return (
        frags.reshape(k, s, GF_BITS, WORD_SIZE)
        .transpose(1, 0, 2, 3)
        .reshape(s, k * GF_BITS, WORD_SIZE)
    )


def ref_decode(frags: np.ndarray, rows, k: int,
               systematic: bool = False) -> np.ndarray:
    """Decode k fragments (k, S*512) given their indices `rows` -> (S*k*512,)."""
    bbits = expand_bitmatrix(decode_matrix(k, rows, systematic))
    x = frags_to_planes(frags, k)  # (S, k*8, 64)
    y = _xor_matmul_planes(bbits, x)  # (S, k*8, 64)
    return y.reshape(x.shape[0] * k * CHUNK_SIZE).copy()


class XorProgram(NamedTuple):
    """A compiled straight-line XOR program computing ``y = abits @ x mod 2``.

    ``ops`` is a tuple of ``(dst, a, b)`` meaning ``t[dst] = t[a] ^ t[b]``
    (``t[0..n_inputs-1]`` are the input planes, new ids are dense from
    ``n_inputs`` up — ``dst == n_inputs + op_index`` always holds);
    ``outs[r]`` is the tuple of var ids whose XOR is output row r (often
    a single shared id).  This tuple IS the compiled artifact every
    backend consumes: the Pallas/XLA kernels unroll it into their traces
    and the native kernel walks it directly (gf_decode_prog).
    """

    ops: tuple[tuple[int, int, int], ...]
    outs: tuple[tuple[int, ...], ...]
    n_inputs: int

    @property
    def xor_count(self) -> int:
        """Total 64-byte-word XORs per stripe the program costs."""
        return len(self.ops) + sum(max(len(o) - 1, 0) for o in self.outs)


def schedule_program(prog: XorProgram) -> tuple[np.ndarray, int]:
    """Register-allocate a program for the native block walker: returns
    ``(code, n_slots)`` — a flat int32 instruction stream over a slab of
    ``n_slots`` reusable variable slots.

    The naive walk keeps every op's result live to the end, so the var
    slab at 16+4 is ~550 KiB per 8-stripe block — it thrashes L2 and
    LOSES to the row-select kernel despite 2.8x fewer word-XORs (the
    row-select scratch is 8 KiB and lives in L1).  Keeping results live
    until their output rows assemble doesn't fix it either (Paar's
    greedy op order finishes most rows late: peak live measured 874 of
    1067 vars at 16+4).  So the schedule is TRANSPOSED, like the fused
    TPU kernel's stripe-major walk: every output row gets a fixed
    accumulator slot, each value is scattered (XOR) into its rows'
    accumulators the moment it is computed and freed at its last use —
    the live set becomes accumulators + inputs + in-flight CSE chains,
    small enough to stay cache-resident.

    Instructions (opcode-first):
    ``[0, dst, a, b]``      slot dst = slot a ^ slot b
    ``[1, row, nv, v...]``  emit output row = XOR of nv slots (0 -> zeros)
    ``[2, slot, f, p]``     load plane p of input fragment f into slot
    ``[3, src, n, s...]``   acc: slot s_i ^= slot src, for n slots
    ``[4, src, n, s...]``   init: slot s_i = copy of slot src (first touch)

    Accumulator for output row r is slot r (ids below ``len(outs)`` are
    reserved); rows emit as ``[1, r, 1, r]`` at the end, empty rows as
    ``[1, r, 0]``.
    """
    c = prog.n_inputs
    n_rows = len(prog.outs)
    n_vars = c + len(prog.ops)
    rows_of: dict[int, list[int]] = {}
    for r, o in enumerate(prog.outs):
        for v in o:
            rows_of.setdefault(v, []).append(r)
    op_uses = [0] * n_vars  # uses as an OPERAND of later ops
    for _d, a, b in prog.ops:
        op_uses[a] += 1
        op_uses[b] += 1
    slot = [-1] * n_vars
    free: list[int] = []
    code: list[int] = []
    touched = [False] * n_rows
    n_slots = n_rows

    def alloc() -> int:
        nonlocal n_slots
        if free:
            return free.pop()
        n_slots += 1
        return n_slots - 1

    def scatter(v: int) -> None:
        """XOR var v's value into every output accumulator that uses it
        directly (copy on a row's first contribution)."""
        init = [r for r in rows_of.get(v, ()) if not touched[r]]
        accum = [r for r in rows_of.get(v, ()) if touched[r]]
        if init:
            code.extend((4, slot[v], len(init), *init))
            for r in init:
                touched[r] = True
        if accum:
            code.extend((3, slot[v], len(accum), *accum))

    def release(v: int) -> None:
        if op_uses[v] == 0 and slot[v] >= 0:
            free.append(slot[v])
            slot[v] = -1

    # inputs: load each used plane once, scatter its direct out
    # contributions immediately; it stays live only while later ops
    # still consume it
    for v in range(c):
        if op_uses[v] == 0 and v not in rows_of:
            continue
        slot[v] = alloc()
        f, p = divmod(v, GF_BITS)
        code.extend((2, slot[v], f, p))
        scatter(v)
        release(v)
    for dst, a, b in prog.ops:
        # dst gets its slot BEFORE the operands are released: the C
        # walker's xor2_w promises (__restrict) dst aliases neither
        d = alloc()
        code.extend((0, d, slot[a], slot[b]))
        slot[dst] = d
        op_uses[a] -= 1
        op_uses[b] -= 1
        release(a)
        release(b)
        scatter(dst)
        release(dst)
    for r in range(n_rows):
        code.extend((1, r, 1, r) if touched[r] else (1, r, 0))
    return np.asarray(code, dtype=np.int32), n_slots


def build_xor_program(abits: np.ndarray) -> XorProgram:
    """Greedy common-subexpression elimination over a GF(2) bit-matrix
    (Paar's algorithm): returns a straight-line XOR program computing
    ``y = abits @ x mod 2`` with shared intermediates.

    Reed-Solomon bit-matrices are dense (tens of terms per output
    plane) but massively share pair subexpressions; the raw per-row
    XOR chains the reference JITs (ec-code-avx.c unrolled chains) redo
    each shared pair per row.  The returned program cuts total XOR
    count ~2-3x, which is the whole game for the VPU-bound wide-k
    kernels.  Uncached — callers go through :func:`encode_program` /
    :func:`decode_program` etc., which hold the compiled artifacts in
    per-mask LRUs.
    """
    a = np.ascontiguousarray(abits, dtype=np.uint8)
    r, c = a.shape
    # incidence (rows, vars), preallocated for intermediates; the pair
    # co-occurrence matrix M is maintained INCREMENTALLY — extracting
    # pair (i, j) only changes M's rows/columns i, j and the new var's
    # (other pairs' co-occurrence is untouched), so each iteration
    # recomputes 3 mat-vecs instead of the full C^2 matmul (which made
    # the 16+4 build take minutes)
    cap = c + int(a.sum())
    cols = np.zeros((r, cap), dtype=bool)
    cols[:, :c] = a.astype(bool)
    m = np.zeros((cap, cap), dtype=np.int32)
    live = c
    m[:c, :c] = cols[:, :c].T.astype(np.int32) @ \
        cols[:, :c].astype(np.int32)
    np.fill_diagonal(m, 0)
    ops: list[tuple[int, int, int]] = []
    while True:
        sub = m[:live, :live]
        best = int(sub.argmax())
        i, j = divmod(best, live)
        if sub[i, j] < 2:
            break  # no pair shared by 2+ rows: chains are optimal now
        new = live
        both = cols[:, i] & cols[:, j]
        cols[both, i] = False
        cols[both, j] = False
        cols[:, new] = both
        live += 1
        ci = cols[:, :live].astype(np.int32)
        for v in (i, j, new):
            mv = ci.T @ ci[:, v]
            mv[v] = 0
            m[v, :live] = mv
            m[:live, v] = mv
        ops.append((new, int(i), int(j)))
    outs = tuple(tuple(int(v) for v in np.nonzero(row[:live])[0])
                 for row in cols)
    return XorProgram(tuple(ops), outs, c)


class ProgramLRU:
    """Per-key LRU of compiled :class:`XorProgram` artifacts.

    The reference keeps an LRU of inverted matrices keyed by the
    surviving-fragment bitmask (ec-method.c:200-245); caching only the
    bit-matrix leaves every backend to redo CSE (seconds at k=16) and
    recompile per request.  This cache holds the COMPILED program per
    mask instead — the mask key is ``(k, rows, ...)``, exactly the
    reference's keying with the geometry made explicit.

    Thread-safe (decode flushes run in batch.py's worker pool).  A miss
    builds outside the lock, so concurrent first requests for distinct
    masks don't serialize behind one k=16 CSE pass; duplicate concurrent
    builds of the same mask are wasted work, never wrong.  ``maxsize``
    is a plain attribute so tests can shrink it to force eviction.
    """

    def __init__(self, builder, maxsize: int = 128):
        self._builder = builder
        self.maxsize = maxsize
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, XorProgram] = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __call__(self, *key) -> XorProgram:
        with self._lock:
            prog = self._entries.get(key)
            if prog is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return prog
            self.misses += 1
        prog = self._builder(*key)
        with self._lock:
            self._entries[key] = prog
            self._entries.move_to_end(key)
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
                self.evictions += 1
        return prog

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: tuple) -> bool:
        with self._lock:
            return key in self._entries

    def cache_clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def cache_info(self) -> dict:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "size": len(self._entries), "maxsize": self.maxsize}


@functools.lru_cache(maxsize=64)
def encode_program(k: int, n: int, systematic: bool = False) -> XorProgram:
    """CSE'd XOR program of the full (n, k) generator — one per geometry
    (plain lru_cache: the key space is tiny, unlike decode masks)."""
    return build_xor_program(
        expand_bitmatrix(generator_matrix(k, n, systematic)))


@functools.lru_cache(maxsize=64)
def parity_program(k: int, n: int) -> XorProgram:
    """Program of the systematic generator's parity rows only."""
    return build_xor_program(parity_bits_cached(k, n))


def _build_decode_program(k: int, rows: tuple[int, ...],
                          systematic: bool = False) -> XorProgram:
    return build_xor_program(decode_bits_cached(k, rows, systematic))


def _build_reconstruct_program(k: int, rows: tuple[int, ...],
                               wanted: tuple[int, ...]) -> XorProgram:
    return build_xor_program(reconstruct_bits_cached(k, rows, wanted))


#: Per-surviving-mask LRU of compiled decode programs — THE decode-side
#: analog of the reference's inverted-matrix LRU, shared by every
#: backend.  Key: ``(k, rows_tuple, systematic)``.
DECODE_PROGRAMS = ProgramLRU(_build_decode_program, maxsize=128)

#: Per-(mask, wanted) LRU of systematic partial-decode programs — a
#: degraded systematic read compiles (and caches) ONLY the missing data
#: rows' program.  Key: ``(k, rows_tuple, wanted_tuple)``.
RECONSTRUCT_PROGRAMS = ProgramLRU(_build_reconstruct_program, maxsize=128)


def _program_cache_samples():
    """Unified-registry collector over both program LRUs (their
    cache_info counters stay where the decode hot path wants them)."""
    out = []
    for cache, lru in (("decode", DECODE_PROGRAMS),
                       ("reconstruct", RECONSTRUCT_PROGRAMS)):
        info = lru.cache_info()
        for event in ("hits", "misses", "evictions"):
            out.append(({"cache": cache, "event": event}, info[event]))
    return out


def _program_cache_sizes():
    return [({"cache": cache}, lru.cache_info()["size"])
            for cache, lru in (("decode", DECODE_PROGRAMS),
                               ("reconstruct", RECONSTRUCT_PROGRAMS))]


from ..core import metrics as _metrics  # noqa: E402

_metrics.REGISTRY.register(
    "gftpu_decode_program_cache_events_total", "counter",
    "compiled XOR-program LRU hits/misses/evictions per cache",
    _program_cache_samples)
_metrics.REGISTRY.register(
    "gftpu_decode_program_cache_size", "gauge",
    "compiled XOR-programs resident per LRU",
    _program_cache_sizes)


def decode_program(k: int, rows, systematic: bool = False) -> XorProgram:
    """Compiled decode program for the surviving-fragment mask ``rows``."""
    return DECODE_PROGRAMS(k, tuple(int(x) for x in rows), systematic)


def reconstruct_program(k: int, rows, wanted) -> XorProgram:
    """Compiled systematic partial-decode program: k survivors ``rows``
    -> only the ``wanted`` missing data rows."""
    return RECONSTRUCT_PROGRAMS(k, tuple(int(x) for x in rows),
                                tuple(int(x) for x in wanted))


def run_xor_program(prog: XorProgram, x: np.ndarray) -> np.ndarray:
    """Execute a program on stripe-major plane words (S, C, 64) ->
    (S, R, 64): the NumPy oracle for program-consuming backends (tests
    cross-check every backend's program execution against plain
    ``_xor_matmul_planes`` on the same matrix)."""
    s = x.shape[0]
    if x.shape[1] != prog.n_inputs:
        raise ValueError(f"plane rows {x.shape[1]} != program inputs "
                         f"{prog.n_inputs}")
    t = list(np.swapaxes(x, 0, 1))  # C views of (S, 64)
    for _dst, a, b in prog.ops:
        t.append(t[a] ^ t[b])
    out = np.zeros((s, len(prog.outs), WORD_SIZE), dtype=np.uint8)
    for r, o in enumerate(prog.outs):
        if not o:
            continue
        acc = t[o[0]]
        for v in o[1:]:
            acc = acc ^ t[v]
        out[:, r, :] = acc
    return out
