"""Unified erasure codec with runtime backend dispatch.

The analog of the reference's ``disperse.cpu-extensions`` option and
``ec_code_detect()`` runtime backend selection (reference
xlators/cluster/ec/src/ec-code.c:59-69, 977-1059): the option values
``{none, auto, x64, sse, avx}`` become

=============  =================================================
backend        implementation
=============  =================================================
``ref``        pure-NumPy bit-sliced oracle (ops/gf256.py)
``native``     C++ AVX2 XOR kernels via ctypes (native/)
``xla``        MXU binary matmul via jitted XLA (ops/gf256_xla.py)
``xla-xor``    VPU XOR chains via jitted XLA
``pallas-xor`` Pallas TPU kernel, static XOR chains in VMEM
``pallas-mxu`` Pallas TPU kernel, in-VMEM unpack + MXU matmul
``mesh``       multi-chip: stripes sharded over the device mesh's
               ``dp`` axis, fragments over ``frag`` (parallel/
               mesh_codec shard_map plane); decodes past a memory
               threshold ride the ring-pipelined ppermute reduce
``auto``       mesh on a multi-chip TPU host; pallas-xor on one
               chip (wide-k encode auto-routes to the MXU form);
               else native, else xla
=============  =================================================

Orthogonally to the backend, the ``cluster.mesh-codec`` volume key
(op-version 10) arms a mesh TIER in ops/batch.BatchingCodec: coalesced
stripe-cache flushes at/above ``stripe-cache-min-batch`` take the
(dp, frag) sharded launch regardless of which ladder backend serves
the small/fallback path — see docs/mesh_codec.md.

All backends are byte-exact against ``ref`` (the ``ec-cpu-extensions.t``
oracle, reproduced by tests/test_codec.py).  Decode work is cached per
surviving-fragment mask exactly like the reference's LRU of inverted
matrices (ec-method.c:200-245) — but one level further compiled: the
shared LRU (gf256.DECODE_PROGRAMS) holds the CSE'd straight-line XOR
*program* per mask, which the pallas/xla kernels unroll into their
traces and the native ladder executes directly (gf_decode_prog).
"""

from __future__ import annotations

import functools

import numpy as np

from . import gf256

BACKENDS = ("ref", "native", "xla", "xla-xor", "pallas-xor", "pallas-mxu",
            "mesh")

# mesh decodes larger than this ride the ring-pipelined ppermute path
# (streaming reduce over the frag axis instead of one all-gather whose
# gathered operand must fit each device)
MESH_RING_DECODE_BYTES = 64 << 20


# probe cache: (expires_monotonic|None, present, wedged).  A CLEAN
# answer caches forever; a TIMEOUT caches for _PROBE_RETRY_S so a
# transient slow init (staggered multi-host pod join) can recover
# instead of demoting the whole process lifetime to the CPU ladder.
_probe_state: list = []
_PROBE_RETRY_S = 300.0

# -- unified-registry scrape (core/metrics.py): which backends the
# live codecs resolved to, and what the device probe last said --------
import weakref as _weakref  # noqa: E402

from ..core import metrics as _metrics  # noqa: E402

_LIVE_CODECS: "_weakref.WeakSet" = _weakref.WeakSet()


def _codec_backend_samples():
    from collections import Counter as _Counter

    counts = _Counter(c.backend for c in list(_LIVE_CODECS))
    return [({"backend": b}, n) for b, n in counts.items()]


def _probe_samples():
    if not _probe_state:
        state = "unprobed"
    elif _probe_state[0][2]:
        state = "wedged"
    else:
        state = "present" if _probe_state[0][1] else "absent"
    return [({"state": s}, 1 if s == state else 0)
            for s in ("unprobed", "present", "absent", "wedged")]


_metrics.REGISTRY.register(
    "gftpu_codec_instances", "gauge",
    "live Codec objects by resolved backend", _codec_backend_samples)
_metrics.REGISTRY.register(
    "gftpu_codec_device_probe", "gauge",
    "device-probe cache state (1 on the active row)", _probe_samples)


def probe_wedged() -> bool:
    """True while the LAST device probe timed out (transport wedged):
    jax-touching backends must not be entered — backend init holds a
    global lock the abandoned probe thread may be stuck under."""
    return bool(_probe_state) and _probe_state[0][2]


def probe_with_deadline(fn, default, default_timeout_s: float = 45.0):
    """Run ``fn()`` on an abandonable DAEMON thread with a deadline
    (``GFTPU_TPU_PROBE_TIMEOUT`` overrides): returns ``(value,
    timed_out)`` — ``(default, True)`` if fn never answers.

    The wedge-safe probe primitive shared by every driver entry point
    that must ask jax about devices: a wedged accelerator transport
    hangs ``jax.devices()`` forever inside backend init, and an
    unguarded in-process call there eats the caller's whole timeout.  A
    plain daemon thread on purpose — executor pools are non-daemonic
    and the interpreter joins them at exit, so an abandoned wedged
    probe would turn every process exit into a hang."""
    import os
    import threading

    box: list = []

    def probe() -> None:
        try:
            box.append(fn())
        except Exception:
            box.append(default)

    t = threading.Thread(target=probe, daemon=True,
                         name="gftpu-deadline-probe")
    t.start()
    try:
        timeout = float(os.environ.get("GFTPU_TPU_PROBE_TIMEOUT",
                                       default_timeout_s))
    except ValueError:
        timeout = default_timeout_s
    t.join(max(1.0, timeout))
    if t.is_alive():
        return default, True
    return (box[0] if box else default), False


def virtual_mesh_env(n_devices: int | None = None,
                     env: dict | None = None) -> dict:
    """A child-process environment pinned to the VIRTUAL CPU mesh:
    CPU platform only, no pool address to dial (a wedged accelerator
    transport must be unreachable from the child), and — when
    ``n_devices`` is given — exactly that many forced host devices.
    The one copy of the scrub rules every subprocess spawner shares
    (bench, ``dryrun_multichip``): a rule added here (the
    PALLAS_AXON_POOL_IPS lesson) reaches them all."""
    import os

    out = dict(os.environ if env is None else env)
    out.pop("PALLAS_AXON_POOL_IPS", None)
    out["JAX_PLATFORMS"] = "cpu"
    flags = " ".join(
        f for f in out.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    if n_devices is not None:
        flags = (f"{flags} "
                 f"--xla_force_host_platform_device_count={n_devices}")
    out["XLA_FLAGS"] = flags.strip()
    return out


def _tpu_present() -> bool:
    """Device probe with a DEADLINE: a wedged accelerator transport
    (the pool tunnel hanging inside backend init) must degrade the
    codec to the CPU ladder, not wedge every volume mount that builds
    a codec."""
    import time as _time

    if _probe_state:
        expires, present, _w = _probe_state[0]
        if expires is None or _time.monotonic() < expires:
            return present

    def probe() -> bool:
        # a configured-but-unsettled jax.distributed join (parallel/
        # meshd) must run before the first backend init; this probe
        # thread is abandonable, so the bounded wait is safe
        from glusterfs_tpu.parallel import meshd

        meshd.settle_before_backend_init()
        import jax

        return any(d.platform in ("tpu", "axon") for d in jax.devices())

    present, timed_out = probe_with_deadline(probe, False)
    if timed_out:
        import warnings

        warnings.warn("TPU probe timed out (wedged device transport?); "
                      "using the CPU codec ladder")
        _probe_state[:] = [(_time.monotonic() + _PROBE_RETRY_S, False,
                            True)]
        return False
    _probe_state[:] = [(None, bool(present), False)]
    return _probe_state[0][1]


def detect(requested: str = "auto") -> str:
    """Resolve a requested backend name to an available one.

    Mirrors ec_code_detect's fall-forward: an unavailable explicit request
    raises (the reference logs + falls back; we prefer loud), ``auto``
    walks the ladder mesh (multi-chip) -> pallas-xor (one chip) ->
    native -> xla.  Uncached on purpose: the probe result can change
    (a transient timeout re-probes after _PROBE_RETRY_S) and the probe
    itself memoizes the expensive part.
    """
    if requested != "auto":
        if requested not in BACKENDS:
            raise ValueError(f"unknown backend {requested!r}; one of {BACKENDS}")
        if requested == "native":
            from glusterfs_tpu import native

            if not native.available():
                raise RuntimeError("native backend unavailable (no toolchain?)")
        return requested
    if _tpu_present():
        import jax

        accels = [d for d in jax.devices()
                  if d.platform in ("tpu", "axon")]
        # multi-chip host: the mesh data plane (stripes over dp,
        # fragments over frag) IS the scale-out path; one chip keeps
        # the single-device pallas kernels
        return "mesh" if len(accels) > 1 else "pallas-xor"
    from glusterfs_tpu import native

    if native.available():
        return "native"
    if probe_wedged():
        # the xla path would import jax and block on the SAME wedged
        # backend-init lock the abandoned probe thread sits under —
        # the bit-sliced numpy oracle is slow but cannot hang
        return "ref"
    return "xla"


@functools.cache
def _encode_bits_sys(k: int, n: int) -> np.ndarray:
    return gf256.expand_bitmatrix(gf256.systematic_matrix(k, n))


@functools.cache
def _encode_bits(k: int, n: int) -> np.ndarray:
    return gf256.expand_bitmatrix(gf256.encode_matrix(k, n))


class Codec:
    """Erasure codec for a (k data + r redundancy) dispersal.

    ``encode`` takes stripe-aligned bytes (length a multiple of
    ``stripe_size = k*512``) and returns ``(n, len/k)`` fragments;
    ``decode`` takes any k fragments + their indices and returns the bytes.
    Padding/RMW of unaligned user I/O belongs to the EC layer above
    (cluster/ec), not the codec — same split as ec-method.c vs
    ec-inode-write.c in the reference.
    """

    def __init__(self, k: int, r: int, backend: str = "auto",
                 systematic: bool = False):
        if k < 1 or r < 0 or k > gf256.MAX_FRAGMENTS:
            raise ValueError(f"bad k={k}, r={r} (k <= {gf256.MAX_FRAGMENTS})")
        self.k = k
        self.r = r
        self.n = k + r
        if self.n > 255:
            raise ValueError("k + r must be <= 255")
        self.fragment_chunk = gf256.CHUNK_SIZE
        self.stripe_size = k * gf256.CHUNK_SIZE
        # auto-resolved backends may re-route per geometry (wide-k
        # encode rides the MXU); an EXPLICIT backend is honored as-is
        self._auto = backend == "auto"
        self.backend = detect(backend)
        # systematic generator (gf256.systematic_matrix): data rows are
        # raw stripe chunks — healthy reads need no math, encode ships
        # only parity off-device, degraded reads reconstruct only the
        # missing rows.  Incompatible fragment format with the default
        # (reference-parity) code: fixed per volume at create.
        # systematic + mesh composes since ISSUE 12: encodes ride the
        # parity-rows-only sharded launch (mesh_codec._parity_fn);
        # degraded decodes take the ref systematic path (healthy reads
        # are host assembly and never decode at all)
        self.systematic = systematic
        _LIVE_CODECS.add(self)  # unified-registry scrape target

    # -- encode ------------------------------------------------------------

    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
        if data.size % self.stripe_size:
            raise ValueError(
                f"data length {data.size} not a multiple of stripe "
                f"{self.stripe_size}")
        if self.systematic:
            return self._encode_systematic(data)
        b = self.backend
        if b == "ref":
            return gf256.ref_encode(data, self.k, self.n)
        if b == "mesh":
            from glusterfs_tpu.parallel import mesh_codec

            return mesh_codec.sharded_encode(self.k, self.r, data)
        if b == "native":
            from glusterfs_tpu import native

            return native.encode(data, self.k, self.n,
                                 _encode_bits(self.k, self.n))
        if b == "xla":
            from . import gf256_xla

            return gf256_xla.encode(data, self.k, self.n, "matmul")
        if b == "xla-xor":
            from . import gf256_xla

            return gf256_xla.encode(data, self.k, self.n, "xor")
        from . import gf256_pallas

        # the CSE'd transposed XOR program beats the MXU sandwich at
        # every geometry now (16+4: 79 vs 40 GiB/s), so auto no longer
        # re-routes wide-k encodes; mxu stays an explicit backend
        form = "fused" if b == "pallas-xor" else "mxu"
        return gf256_pallas.encode(data, self.k, self.n, form)

    # -- decode ------------------------------------------------------------

    def decode(self, frags: np.ndarray, rows) -> np.ndarray:
        """Reconstruct from the k fragments ``frags`` with indices ``rows``."""
        rows = [int(x) for x in rows]
        if len(rows) != self.k or len(set(rows)) != self.k:
            raise ValueError(f"need {self.k} distinct fragment indices")
        if any(x < 0 or x >= self.n for x in rows):
            raise ValueError("fragment index out of range")
        frags = np.ascontiguousarray(frags, dtype=np.uint8)
        if self.systematic:
            return self._decode_systematic(frags, rows)
        b = self.backend
        if b == "ref":
            return gf256.ref_decode(frags, rows, self.k)
        if b == "mesh":
            from glusterfs_tpu.parallel import mesh_codec, ring_codec

            if frags.size > MESH_RING_DECODE_BYTES:
                return ring_codec.ring_decode(self.k, tuple(rows), frags)
            return mesh_codec.sharded_decode(self.k, tuple(rows), frags)
        if b == "native":
            from glusterfs_tpu import native

            return native.decode_program(
                frags, self.k, gf256.decode_program(self.k, tuple(rows)))
        if b in ("xla", "xla-xor"):
            from . import gf256_xla

            form = "xor" if b == "xla-xor" else "matmul"
            return gf256_xla.decode(frags, rows, self.k, form)
        from . import gf256_pallas

        form = "fused" if b == "pallas-xor" else "mxu"
        return gf256_pallas.decode(frags, rows, self.k, form)

    # -- systematic paths (disperse.systematic) ----------------------------

    def _data_rows(self, data: np.ndarray) -> np.ndarray:
        """Data fragments of the systematic code: a pure host reshape of
        the stripe-major bytes (fragment j = chunk j of every stripe)."""
        s = data.size // self.stripe_size
        c = self.fragment_chunk
        return np.ascontiguousarray(
            data.reshape(s, self.k, c).transpose(1, 0, 2)).reshape(
                self.k, s * c)

    def _encode_systematic(self, data: np.ndarray) -> np.ndarray:
        b = self.backend
        if b == "mesh":
            from glusterfs_tpu.parallel import mesh_codec

            # parity-rows-only sharded encode: the mesh computes just
            # the r parity fragments, data rows are host reshapes
            return mesh_codec.sharded_encode(self.k, self.r, data,
                                             systematic=True)
        if b in ("pallas-xor", "pallas-mxu"):
            # the device computes (and the link carries) ONLY parity
            from . import gf256_pallas

            s = data.size // self.stripe_size
            out = np.empty((self.n, s * self.fragment_chunk),
                           dtype=np.uint8)
            out[: self.k] = self._data_rows(data)
            out[self.k:] = gf256_pallas.parity(data, self.k, self.n)
            return out
        if b == "native":
            from glusterfs_tpu import native

            return native.encode(data, self.k, self.n,
                                 _encode_bits_sys(self.k, self.n))
        if b in ("xla", "xla-xor"):
            from . import gf256_xla

            form = "xor" if b == "xla-xor" else "matmul"
            return gf256_xla.encode(data, self.k, self.n, form,
                                    systematic=True)
        return gf256.ref_encode(data, self.k, self.n, systematic=True)

    def encode_delta(self, delta: np.ndarray) -> np.ndarray:
        """Parity-fragment deltas ((n-k), len/k) of a stripe-aligned
        XOR delta — the sub-stripe write primitive (parity-delta /
        parity-logging): linearity gives ``frag_i(old ⊕ Δ) =
        frag_i(old) ⊕ frag_i(Δ)``, and on a systematic volume the data
        rows of Δ are the overwritten bytes themselves, so a small
        write ships only the touched data slices plus these parity
        deltas (brick-side ``xorv`` applies them in place).  Only the
        parity submatrix of the generator is applied — no backend
        touches the k identity rows."""
        if not self.systematic:
            raise ValueError("delta encode needs the systematic layout "
                             "(non-systematic fragments are all "
                             "codewords; there is no verbatim data row "
                             "to delta against)")
        delta = np.ascontiguousarray(delta, dtype=np.uint8).ravel()
        if delta.size % self.stripe_size:
            raise ValueError(
                f"delta length {delta.size} not a multiple of stripe "
                f"{self.stripe_size}")
        b = self.backend
        if b == "mesh":
            from glusterfs_tpu.parallel import mesh_codec

            return mesh_codec.sharded_parity(self.k, self.r, delta)
        if b in ("pallas-xor", "pallas-mxu"):
            from . import gf256_pallas

            return gf256_pallas.parity(delta, self.k, self.n)
        if b == "native":
            from glusterfs_tpu import native

            # gf_encode walks whatever (rows, k*8) bit-matrix it is
            # handed: the parity submatrix with n-k output fragments
            return native.encode(delta, self.k, self.n - self.k,
                                 gf256.parity_bits_cached(self.k, self.n))
        if b in ("xla", "xla-xor"):
            from . import gf256_xla

            form = "xor" if b == "xla-xor" else "matmul"
            return gf256_xla.parity(delta, self.k, self.n, form)
        return gf256.ref_parity(delta, self.k, self.n)

    def reassemble(self, bufs, rows, frag_len: int) -> np.ndarray | None:
        """Healthy systematic fast path straight from fragment BUFFERS
        (the zero-staging lane of the read fan-out, ISSUE 3): when every
        data row survived, the answer is a pure interleave — each
        received buffer is written once, directly into its chunk
        positions of the output, with no intermediate ``frags`` staging
        array.  Buffers shorter than ``frag_len`` zero-fill (sparse
        tails, mirroring the EC layer's staging semantics).

        Returns the assembled stripe-major bytes, or None when this
        codec/row-set doesn't qualify (non-systematic, or a data row is
        missing) — the caller then stages and decodes."""
        if not self.systematic or sorted(int(r) for r in rows) != \
                list(range(self.k)):
            return None
        k, c = self.k, self.fragment_chunk
        if frag_len % c:
            raise ValueError(f"frag_len {frag_len} not a multiple of {c}")
        s = frag_len // c
        out = np.empty((s, k, c), dtype=np.uint8)
        for row, buf in zip(rows, bufs):
            a = np.frombuffer(buf, dtype=np.uint8)
            dst = out[:, int(row), :]
            whole = a.size // c
            rem = a.size % c
            if whole:
                dst[:whole] = a[: whole * c].reshape(whole, c)
            if rem:
                dst[whole, :rem] = a[whole * c:]
                dst[whole, rem:] = 0
            dst[whole + (1 if rem else 0):] = 0
        return out.reshape(-1)

    def _decode_systematic(self, frags: np.ndarray, rows) -> np.ndarray:
        k, c = self.k, self.fragment_chunk
        s = frags.shape[1] // c
        missing = [j for j in range(k) if j not in rows]
        if not missing:
            # healthy read: every data row survived — pure host assembly
            out = np.empty((s, k, c), dtype=np.uint8)
            for idx, row in enumerate(rows):
                out[:, row, :] = frags[idx].reshape(s, c)
            return out.reshape(-1)
        b = self.backend
        if b in ("pallas-xor", "pallas-mxu"):
            # degraded: reconstruct ONLY the missing data rows on device
            from . import gf256_pallas

            rec = gf256_pallas.reconstruct(frags, tuple(rows),
                                           tuple(missing), k)
            out = np.empty((s, k, c), dtype=np.uint8)
            for idx, row in enumerate(rows):
                if row < k:
                    out[:, row, :] = frags[idx].reshape(s, c)
            for i, j in enumerate(missing):
                out[:, j, :] = rec[i].reshape(s, c)
            return out.reshape(-1)
        if b == "native":
            from glusterfs_tpu import native

            return native.decode_program(
                frags, k, gf256.decode_program(k, tuple(rows), True))
        if b in ("xla", "xla-xor", "mesh"):
            # mesh systematic is encode-only (parity-rows sharded):
            # degraded reconstruction rides the single-device xla
            # kernels — available on every host the mesh resolves on,
            # and orders of magnitude over the bit-sliced ref oracle
            from . import gf256_xla

            form = "xor" if b == "xla-xor" else "matmul"
            return gf256_xla.decode(frags, rows, k, form, systematic=True)
        return gf256.ref_decode(frags, rows, k, systematic=True)

    # -- convenience -------------------------------------------------------

    def pad_length(self, nbytes: int) -> int:
        """Bytes after zero-padding up to a whole stripe (reference pads
        the tail stripe with zeros, ec-inode-write.c)."""
        s = self.stripe_size
        return (nbytes + s - 1) // s * s

    def encode_padded(self, data: np.ndarray) -> tuple[np.ndarray, int]:
        """Zero-pad to a stripe multiple and encode; returns (frags, nbytes)."""
        data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
        orig = data.size
        padded = self.pad_length(orig)
        if padded != orig:
            data = np.concatenate(
                [data, np.zeros(padded - orig, dtype=np.uint8)])
        return self.encode(data), orig

    def decode_padded(self, frags: np.ndarray, rows, nbytes: int) -> np.ndarray:
        """Decode and trim zero-padding back to ``nbytes``."""
        return self.decode(frags, rows)[:nbytes]
