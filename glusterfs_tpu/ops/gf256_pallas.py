"""Pallas TPU kernels for the GF(256) erasure codec.

The contraction ``y[r, :] = XOR_j { x[j, :] : abits[r, j] == 1 }`` over
plane-major byte data (see ops/gf256.py) is computed entirely in VMEM so the
8x bit-expanded intermediates of the XLA path never touch HBM.  Two kernel
bodies (reference analog: the JIT'd XOR-chain kernels of
xlators/cluster/ec/src/ec-code.c, selected by disperse.cpu-extensions):

* ``xor``: statically unrolled per-row XOR chains on the VPU — the direct
  TPU analog of the reference's AVX chains.  Coefficients are baked into the
  trace (per-matrix specialization, like the reference's per-matrix JIT with
  its LRU cache, ec-method.c:200-245).
* ``mxu``: in-kernel unpack -> int8 binary matmul on the MXU (mod 2) ->
  repack.  Coefficient bit-matrix arrives as a kernel operand, so decode
  does not recompile per surviving-fragment mask.

Data layout in/out of the kernels is plane-major ``(planes, W)``: plane row
``j`` of the input holds byte ``w`` of plane ``j & 7`` of chunk-column
``j >> 3``, across all stripes.  ``ops/codec.py`` wraps the stripe-major <->
plane-major transposes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import gf256

# Lane tile for uint8 is (32, 128); keep W tiles big to amortize grid overhead.
_TILE_W = 8192

# xor3 kernel geometry: plane rows viewed as (M, 128) so every row slice is
# a full (BM, 128) vreg tile (8 sublanes x 128 lanes fully used; the 2-D
# kernel's (1, W) slices waste 7/8 of each vreg).
_TILE3_M = 256  # measured best on v5e (probe: 44 GiB/s e2e encode 4+2)
_TILE3_W = _TILE3_M * 128  # bytes per plane row per grid step (32 KiB)

# VMEM working-set budget for the mxu kernel (the int32 matmul output
# dominates at R rows x 8*tile int32); stay well under the ~16 MiB more
# conservative TPU VMEM sizes.
_MXU_VMEM_BUDGET = 8 << 20


def _mxu_tile_w(r: int, c: int) -> int:
    """Largest power-of-two tile (dividing _TILE_W) whose mxu working set
    fits the VMEM budget: y (r, 8t) i32 + bits (c, 8t) i8 + x (c, t) i32."""
    t = _TILE_W
    while t > 512:
        working = r * 8 * t * 4 + c * 8 * t + c * t * 4 + (r + c) * t
        if working <= _MXU_VMEM_BUDGET:
            break
        t //= 2
    return t


def _xor_kernel_body(sels: tuple[tuple[int, ...], ...]):
    """Build a kernel computing out[r] = XOR of x[j] for j in sels[r]."""

    def kernel(x_ref, o_ref):
        x = x_ref[:]
        for r, sel in enumerate(sels):
            if not sel:
                o_ref[r : r + 1, :] = jnp.zeros_like(o_ref[r : r + 1, :])
                continue
            acc = x[sel[0] : sel[0] + 1, :]
            for j in sel[1:]:
                acc = acc ^ x[j : j + 1, :]
            o_ref[r : r + 1, :] = acc

    return kernel


def _mxu_kernel(a_ref, x_ref, o_ref):
    """Unpack -> binary matmul (mod 2) -> pack, all in VMEM.

    Bit positions use grouped order (all bit-0 columns, then all bit-1
    columns, ...) so everything stays rank-2: Mosaic can't insert minor dims
    on int8.  The bit dim is a free dim of the matmul, so any consistent
    order is valid as long as pack mirrors unpack.
    """
    x = x_ref[:].astype(jnp.int32)  # (C, TW); int8 shifts don't legalize
    tw = x.shape[1]
    bits = jnp.concatenate(
        [((x >> b) & 1).astype(jnp.int8) for b in range(8)], axis=1
    )  # (C, 8*TW)
    y = jax.lax.dot_general(
        a_ref[:],
        bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # (R, 8*TW)
    acc = y[:, 0:tw] & 1
    for b in range(1, 8):
        acc = acc | ((y[:, b * tw : (b + 1) * tw] & 1) << b)
    o_ref[:] = acc.astype(jnp.uint8)


def _xor3_kernel_body(sels: tuple[tuple[int, ...], ...]):
    """out[r] = XOR of x[j] for j in sels[r], on (BM, 128) row tiles."""

    def kernel(x_ref, o_ref):
        x = x_ref[:]
        for r, sel in enumerate(sels):
            if not sel:
                o_ref[r] = jnp.zeros_like(o_ref[r])
                continue
            acc = x[sel[0]]
            for j in sel[1:]:
                acc = acc ^ x[j]
            o_ref[r] = acc

    return kernel


@functools.lru_cache(maxsize=256)
def _xor3_apply_fn(sels: tuple[tuple[int, ...], ...], c: int,
                   interpret: bool):
    """(C, W) uint8 -> (R, W) uint8; W % _TILE3_W == 0; 3-D tiled."""
    r = len(sels)
    kernel = _xor3_kernel_body(sels)

    @jax.jit
    def run(x):
        w = x.shape[1]
        m = w // 128
        x3 = x.reshape(c, m, 128)
        grid = (m // _TILE3_M,)
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((r, m, 128), jnp.uint8),
            grid=grid,
            in_specs=[
                pl.BlockSpec((c, _TILE3_M, 128), lambda i: (0, i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((r, _TILE3_M, 128), lambda i: (0, i, 0),
                                   memory_space=pltpu.VMEM),
            interpret=interpret,
        )(x3)
        return out.reshape(r, w)

    return run


@functools.lru_cache(maxsize=256)
def _xor_apply_fn(sels: tuple[tuple[int, ...], ...], c: int, interpret: bool):
    """(C, W) uint8 -> (R, W) uint8 via static XOR chains; W % _TILE_W == 0."""
    r = len(sels)
    kernel = _xor_kernel_body(sels)

    @jax.jit
    def run(x):
        w = x.shape[1]
        grid = (w // _TILE_W,)
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((r, w), jnp.uint8),
            grid=grid,
            in_specs=[
                pl.BlockSpec((c, _TILE_W), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((r, _TILE_W), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            interpret=interpret,
        )(x)

    return run


@functools.lru_cache(maxsize=16)
def _mxu_apply_fn(r: int, c: int, interpret: bool):
    """(R*8, C*8) bitmatrix (int8), (C*8, W) bytes -> (R*8, W) bytes."""

    tile_w = _mxu_tile_w(r, c)

    @jax.jit
    def run(abits, x):
        w = x.shape[1]
        grid = (w // tile_w,)
        return pl.pallas_call(
            _mxu_kernel,
            out_shape=jax.ShapeDtypeStruct((r, w), jnp.uint8),
            grid=grid,
            in_specs=[
                pl.BlockSpec((r, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((c, tile_w), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((r, tile_w), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            interpret=interpret,
        )(abits, x)

    return run


def _sels_from_bits(abits: np.ndarray) -> tuple[tuple[int, ...], ...]:
    return tuple(tuple(int(j) for j in np.nonzero(row)[0]) for row in abits)


def apply_bitmatrix(
    abits: np.ndarray,
    x: jnp.ndarray,
    formulation: str = "xor",
    interpret: bool = False,
) -> jnp.ndarray:
    """Apply an (R, C) GF(2) bit-matrix to plane-major bytes (C, W) -> (R, W).

    W must be a multiple of _TILE_W (callers pad stripes accordingly).
    """
    if formulation not in ("xor", "xor3", "mxu"):
        raise ValueError(
            f"formulation must be 'xor', 'xor3' or 'mxu', got {formulation!r}")
    r, c = abits.shape
    if x.shape[0] != c:
        raise ValueError(f"plane rows {x.shape[0]} != bitmatrix columns {c}")
    if x.shape[1] % _TILE_W:
        raise ValueError(f"W must be a multiple of {_TILE_W}")
    if formulation == "xor3":
        if x.shape[1] % _TILE3_W:
            raise ValueError(f"W must be a multiple of {_TILE3_W} for xor3")
        return _xor3_apply_fn(_sels_from_bits(abits), c, interpret)(x)
    if formulation == "xor":
        return _xor_apply_fn(_sels_from_bits(abits), c, interpret)(x)
    return _mxu_apply_fn(r, c, interpret)(jnp.asarray(abits, jnp.int8), x)


# ---------------------------------------------------------------------------
# Fused wire-layout kernels (the production path).
#
# The transpose-sandwich wrappers below pay 3-4 extra HBM passes (XLA
# materializes the u8 fragment-major <-> plane-major transposes at ~1/6 of
# copy speed).  The fused kernels read and write the wire layouts directly
# and do the plane relayout in VMEM via 64-byte lane slices:
#
# * encode: stripe-major (S, k*512) blocks in, per-fragment (n, TS, 512)
#   blocks out — measured 98 GiB/s e2e on v5e (4+2, 64 MiB).
# * decode: per-fragment (k, TS, 512) blocks in, concatenated to one wide
#   (TS, k*512) VMEM value FIRST (slicing planes from k separate block
#   values is 25% slower), stripe-major out — measured 92 GiB/s e2e.
#
# Both keep fragments byte-exact with the reference layout
# (ec-method.c:393-433): fragment f = its 512-byte chunk from every stripe.
# ---------------------------------------------------------------------------

_FUSED_TS = 256  # stripes per grid step (measured best on v5e)

# Per-config tiles from an on-chip sweep of the TRANSPOSED program
# kernels (v5e, best of ts in {64,128,256,512}): encode/decode 4+2
# 109-118 GiB/s @256-512, 8+4 111/123 @256; k=16's larger per-step
# working set needs ts=128 (256 exceeds scoped VMEM).


def _enc_ts(k: int) -> int:
    return 128 if k >= 16 else _FUSED_TS


_dec_ts = _enc_ts


def _program_encode_kernel(ops: tuple, outs: tuple, k: int, n: int):
    """Straight-line XOR program body (gf256.xor_program): shared
    subexpressions are computed ONCE per grid step instead of once per
    output plane — these kernels are VPU-throughput-bound, so the
    ~2.7x XOR-count cut is ~the speedup.

    Transposed geometry: the wire layout's 64-byte bit-plane words
    sliced stripe-major are (ts, 64) values — HALF of every 128-lane
    vreg idle.  One in-VMEM transpose per block turns every program
    variable into a (64, ts) full-lane tile, doubling VPU utilization
    (measured: 16+4 encode 38 -> 79 GiB/s)."""

    def kernel(x_ref, o_ref):
        xt = x_ref[:].T  # (k*512, ts): planes are (64, ts) full tiles
        t = [xt[j * 64:(j + 1) * 64, :] for j in range(k * 8)]
        for dst, a, b in ops:
            t.append(t[a] ^ t[b])  # dst ids are dense: dst == len(t)
        for f in range(n):
            accs = []
            for b in range(8):
                o = outs[f * 8 + b]
                acc = t[o[0]]
                for v in o[1:]:
                    acc = acc ^ t[v]
                accs.append(acc)
            o_ref[f] = jnp.concatenate(accs, axis=0).T  # (ts, 512)

    return kernel


def _program_decode_kernel(ops: tuple, outs: tuple, k: int):
    """Decode body, same transposed program geometry as encode."""

    def kernel(x_ref, o_ref):
        # one wide value first: lane-slicing from k separate (ts, 512)
        # block values generates markedly slower code
        xt = jnp.concatenate([x_ref[f] for f in range(k)], axis=1).T
        t = [xt[j * 64:(j + 1) * 64, :] for j in range(k * 8)]
        for dst, a, b in ops:
            t.append(t[a] ^ t[b])
        cols = []
        for c in range(k):
            for b in range(8):
                o = outs[c * 8 + b]
                acc = t[o[0]]
                for v in o[1:]:
                    acc = acc ^ t[v]
                cols.append(acc)
        o_ref[:] = jnp.concatenate(cols, axis=0).T  # (ts, k*512)

    return kernel


@functools.lru_cache(maxsize=64)
def _fused_encode_fn(k: int, n: int, interpret: bool):
    """jitted: flat stripe-major bytes (S*k*512,) -> fragments (n, S*512).

    The kernel body executes the CSE'd straight-line XOR program
    (gf256.xor_program, ~0.4x the naive chain count) in ONE pallas
    call: shared intermediates span every output fragment, so the old
    wide-k group split (one call per fragment group, each re-reading
    the input because the naive unroll blew the compiler's appetite)
    would forfeit most of the sharing."""
    prog = gf256.encode_program(k, n)
    ts = _enc_ts(k)
    kernel = _program_encode_kernel(prog.ops, prog.outs, k, n)

    @jax.jit
    def run(flat):
        s = flat.shape[0] // (k * gf256.CHUNK_SIZE)
        sp = (s + ts - 1) // ts * ts
        x = flat.reshape(s, k * gf256.CHUNK_SIZE)
        if sp != s:
            x = jnp.pad(x, ((0, sp - s), (0, 0)))
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n, sp, 512), jnp.uint8),
            grid=(sp // ts,),
            in_specs=[pl.BlockSpec((ts, k * 512), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((n, ts, 512),
                                   lambda i: (0, i, 0),
                                   memory_space=pltpu.VMEM),
            interpret=interpret,
        )(x)
        return out[:, :s, :].reshape(n, s * gf256.CHUNK_SIZE)

    return run


@functools.lru_cache(maxsize=256)
def _fused_decode_fn(k: int, rows: tuple[int, ...], interpret: bool):
    """jitted: survivors (k, S*512) fragment-major -> flat bytes (S*k*512,).

    One jitted decoder per surviving mask (this LRU of compiled kernels
    sits on top of gf256.DECODE_PROGRAMS, the shared per-mask LRU of
    compiled XOR programs — together the compiled-program analog of the
    reference's inverted-matrix LRU, ec-method.c:200-245); the body runs
    the CSE'd XOR program in one pallas call (see _fused_encode_fn)."""
    prog = gf256.decode_program(k, rows)
    ts = _dec_ts(k)
    kernel = _program_decode_kernel(prog.ops, prog.outs, k)

    @jax.jit
    def run(frags):
        s = frags.shape[1] // gf256.CHUNK_SIZE
        sp = (s + ts - 1) // ts * ts
        x = frags.reshape(k, s, 512)
        if sp != s:
            x = jnp.pad(x, ((0, 0), (0, sp - s), (0, 0)))
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((sp, k * 512), jnp.uint8),
            grid=(sp // ts,),
            in_specs=[pl.BlockSpec((k, ts, 512),
                                   lambda i: (0, i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((ts, k * 512), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            interpret=interpret,
        )(x)
        return out[:s].reshape(s * k * gf256.CHUNK_SIZE)

    return run


# ---------------------------------------------------------------------------
# Systematic serving kernels (disperse.systematic): over a bandwidth-
# bound host<->device link (the dev tunnel moves ~10 MiB/s/direction)
# the transfer, not the XOR math, is the cost — so the device computes
# and ships ONLY what the host cannot reshape for itself: parity rows on
# encode, missing data rows on degraded decode.  gf256.systematic_matrix
# documents the design choice vs the reference's non-systematic code.
# ---------------------------------------------------------------------------


def _program_reconstruct_kernel(ops: tuple, outs: tuple, k: int, m: int):
    """Fragment-major survivors in -> fragment-major wanted rows out
    (decode-style input, encode-style output; same transposed CSE'd
    program geometry as _program_encode_kernel)."""

    def kernel(x_ref, o_ref):
        xt = jnp.concatenate([x_ref[f] for f in range(k)], axis=1).T
        t = [xt[j * 64:(j + 1) * 64, :] for j in range(k * 8)]
        for dst, a, b in ops:
            t.append(t[a] ^ t[b])
        for f in range(m):
            accs = []
            for b in range(8):
                o = outs[f * 8 + b]
                acc = t[o[0]]
                for v in o[1:]:
                    acc = acc ^ t[v]
                accs.append(acc)
            o_ref[f] = jnp.concatenate(accs, axis=0).T  # (ts, 512)

    return kernel


@functools.lru_cache(maxsize=64)
def _fused_parity_fn(k: int, n: int, interpret: bool):
    """jitted: flat stripe-major bytes (S*k*512,) -> parity fragments
    ONLY ((n-k), S*512) of the systematic code — D2H is r/k of the data
    instead of n/k."""
    prog = gf256.parity_program(k, n)
    ts = _enc_ts(k)
    r = n - k
    kernel = _program_encode_kernel(prog.ops, prog.outs, k, r)

    @jax.jit
    def run(flat):
        s = flat.shape[0] // (k * gf256.CHUNK_SIZE)
        sp = (s + ts - 1) // ts * ts
        x = flat.reshape(s, k * gf256.CHUNK_SIZE)
        if sp != s:
            x = jnp.pad(x, ((0, sp - s), (0, 0)))
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((r, sp, 512), jnp.uint8),
            grid=(sp // ts,),
            in_specs=[pl.BlockSpec((ts, k * 512), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((r, ts, 512), lambda i: (0, i, 0),
                                   memory_space=pltpu.VMEM),
            interpret=interpret,
        )(x)
        return out[:, :s, :].reshape(r, s * gf256.CHUNK_SIZE)

    return run


@functools.lru_cache(maxsize=256)
def _fused_reconstruct_fn(k: int, rows: tuple[int, ...],
                          wanted: tuple[int, ...], interpret: bool):
    """jitted: systematic survivors (k, S*512) fragment-major ->
    ONLY the ``wanted`` missing data rows (len(wanted), S*512) — D2H is
    missing/k of the data instead of all of it."""
    prog = gf256.reconstruct_program(k, rows, wanted)
    ts = _dec_ts(k)
    m = len(wanted)
    kernel = _program_reconstruct_kernel(prog.ops, prog.outs, k, m)

    @jax.jit
    def run(frags):
        s = frags.shape[1] // gf256.CHUNK_SIZE
        sp = (s + ts - 1) // ts * ts
        x = frags.reshape(k, s, 512)
        if sp != s:
            x = jnp.pad(x, ((0, 0), (0, sp - s), (0, 0)))
        out = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((m, sp, 512), jnp.uint8),
            grid=(sp // ts,),
            in_specs=[pl.BlockSpec((k, ts, 512), lambda i: (0, i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((m, ts, 512), lambda i: (0, i, 0),
                                   memory_space=pltpu.VMEM),
            interpret=interpret,
        )(x)
        return out[:, :s, :].reshape(m, s * gf256.CHUNK_SIZE)

    return run


# Pipelined-launch threshold.  Measured on the dev tunnel (16 MiB of
# data, 4+2): one whole launch 24 MiB/s vs 4 MiB chunks 16.7 — the
# per-call floor costs more than launch-ahead overlap buys at serving
# sizes, so only genuinely huge batches split (bounds device memory for
# them too).  The probe that motivated chunking measured a different
# link window; the tunnel swings 3x (docs/perf_variance.md).
_PARITY_CHUNK_BYTES = 64 << 20


def parity(data: np.ndarray, k: int, n: int,
           interpret: bool = False) -> np.ndarray:
    """Systematic parity rows ((n-k), S*512) for stripe-major bytes.

    Large inputs are split into fixed-shape chunks that are ALL
    launched before any result is fetched — the link, not the kernel,
    is the cost, and this pipelines its two directions."""
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    stripe = k * gf256.CHUNK_SIZE
    s = data.size // stripe
    cs = max(1, _PARITY_CHUNK_BYTES // stripe)
    fn = _fused_parity_fn(k, n, interpret)
    if s <= cs:
        return np.asarray(fn(jnp.asarray(data)))
    launches = []
    for off in range(0, s, cs):
        w = min(cs, s - off)
        chunk = data[off * stripe:(off + w) * stripe]
        if w < cs:  # pad the tail so every launch shares one jit shape
            chunk = np.concatenate(
                [chunk, np.zeros((cs - w) * stripe, dtype=np.uint8)])
        launches.append((fn(jnp.asarray(chunk)), w))
    return np.concatenate(
        [np.asarray(d)[:, : w * gf256.CHUNK_SIZE] for d, w in launches],
        axis=1)


def reconstruct(frags: np.ndarray, rows, wanted, k: int,
                interpret: bool = False) -> np.ndarray:
    """Missing systematic data rows from k survivors (fragment-major)."""
    fn = _fused_reconstruct_fn(k, tuple(int(x) for x in rows),
                               tuple(int(x) for x in wanted), interpret)
    return np.asarray(fn(jnp.asarray(frags)))


# ---------------------------------------------------------------------------
# Stripe-major wrappers (same API as gf256_xla): transpose sandwich.
# ---------------------------------------------------------------------------


def _pad_w(s: int) -> int:
    """Stripes padded so plane width S*64 is a multiple of every kernel's
    tile (_TILE3_W = 32 KiB covers _TILE_W = 8 KiB too)."""
    per = _TILE3_W // gf256.WORD_SIZE  # stripes per tile
    return (s + per - 1) // per * per


@functools.lru_cache(maxsize=64)
def _encode_fn(k: int, n: int, formulation: str, interpret: bool):
    abits_np = gf256.expand_bitmatrix(gf256.encode_matrix(k, n))

    @jax.jit
    def run(data):
        s = data.shape[0] // (k * gf256.CHUNK_SIZE)
        sp = _pad_w(s)
        x = data.reshape(s, k * 8, gf256.WORD_SIZE)
        x = jnp.pad(x, ((0, sp - s), (0, 0), (0, 0)))
        xt = x.transpose(1, 0, 2).reshape(k * 8, sp * gf256.WORD_SIZE)
        yt = apply_bitmatrix(abits_np, xt, formulation, interpret)
        y = yt.reshape(n * 8, sp, gf256.WORD_SIZE)[:, :s, :]
        # (n*8, S, 64) -> fragment-major (n, S*512)
        return (
            y.reshape(n, 8, s, gf256.WORD_SIZE)
            .transpose(0, 2, 1, 3)
            .reshape(n, s * gf256.CHUNK_SIZE)
        )

    return run


@functools.lru_cache(maxsize=256)
def _decode_fn(k: int, formulation: str, interpret: bool,
               rows: tuple[int, ...] | None):
    """Transpose-sandwich decode; static (xor/xor3) forms are cached per
    surviving mask ``rows`` — matching the per-mask program LRU keying —
    instead of per bit-matrix tuple (mxu passes rows=None: its bbits is
    a traced operand, one compile serves every mask)."""
    def run(frags, bbits_np):
        s = frags.shape[1] // gf256.CHUNK_SIZE
        sp = _pad_w(s)
        x = jnp.pad(
            frags.reshape(k, s, 8, gf256.WORD_SIZE).transpose(0, 2, 1, 3),
            ((0, 0), (0, 0), (0, sp - s), (0, 0)),
        ).reshape(k * 8, sp * gf256.WORD_SIZE)
        yt = apply_bitmatrix(bbits_np, x, formulation, interpret)
        y = yt.reshape(k * 8, sp, gf256.WORD_SIZE)[:, :s, :]
        # plane rows (k*8) are chunk-major within the stripe: chunk j of the
        # stripe is rows 8j..8j+7 -> output stripe-major bytes
        return (
            y.reshape(k, 8, s, gf256.WORD_SIZE)
            .transpose(2, 0, 1, 3)
            .reshape(s * k * gf256.CHUNK_SIZE)
        )

    if formulation in ("xor", "xor3"):
        bb = gf256.decode_bits_cached(k, rows)
        return jax.jit(lambda frags: run(frags, bb))
    return jax.jit(run)


def encode(data, k: int, n: int, formulation: str = "fused",
           interpret: bool = False) -> np.ndarray:
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    if data.size % (k * gf256.CHUNK_SIZE):
        raise ValueError("data length must be a multiple of k*512")
    if formulation == "fused":
        return np.asarray(_fused_encode_fn(k, n, interpret)(jnp.asarray(data)))
    return np.asarray(_encode_fn(k, n, formulation, interpret)(jnp.asarray(data)))


def decode(frags, rows, k: int, formulation: str = "fused",
           interpret: bool = False) -> np.ndarray:
    frags = np.ascontiguousarray(frags, dtype=np.uint8)
    rows = tuple(int(x) for x in rows)
    if formulation == "fused":
        fn = _fused_decode_fn(k, rows, interpret)
        return np.asarray(fn(jnp.asarray(frags)))
    if formulation in ("xor", "xor3"):
        fn = _decode_fn(k, formulation, interpret, rows)
        return np.asarray(fn(jnp.asarray(frags)))
    bbits_np = gf256.decode_bits_cached(k, rows)
    fn = _decode_fn(k, "mxu", interpret, None)
    return np.asarray(fn(jnp.asarray(frags), jnp.asarray(bbits_np, jnp.int8)))
