"""Block checksums — the libglusterfs checksum.c (gf_rchecksum)
analog, TPU-batchable.

The reference computes a weak rolling checksum + a strong digest per
block so AFR data heal can skip byte-identical regions
(afr-self-heal-data.c).  The weak sum here is Adler-32 (zlib.adler32
byte-compatible) — sequential by definition, but algebraically just
two weighted sums:

    A = 1 + sum(d_i)                 (mod 65521)
    B = n + sum((n - i) * d_i)       (mod 65521)

which makes a [batch, block] uint8 array one reduction pair on the
MXU-adjacent vector units — thousands of blocks checksummed per
launch, the coalesced-batch regime everything else in ops/ uses.
Strong digests stay sha256 on the host (cryptographic, not worth
emulating on-device).
"""

from __future__ import annotations

import zlib

import numpy as np

_MOD = 65521


def adler32_ref(block: bytes) -> int:
    """zlib oracle."""
    return zlib.adler32(block) & 0xFFFFFFFF


def adler32_batch_np(blocks: np.ndarray) -> np.ndarray:
    """NumPy fallback: [n, b] uint8 -> [n] uint32 adler32."""
    blocks = np.ascontiguousarray(blocks, dtype=np.uint8)
    n, b = blocks.shape
    d = blocks.astype(np.uint64)
    a = (1 + d.sum(axis=1)) % _MOD
    w = np.arange(b, 0, -1, dtype=np.uint64)
    bsum = (b + (d * w).sum(axis=1)) % _MOD
    return (bsum.astype(np.uint32) << 16) | a.astype(np.uint32)


_JIT_CACHE: dict = {}


def adler32_batch_jax(blocks):
    """jit-compiled batched adler32: [n, b] uint8 on device -> [n]
    uint32.  Weighted sums are taken in int32 segments small enough
    not to overflow, then folded mod 65521."""
    import jax
    import jax.numpy as jnp

    def fn(x):
        n, b = x.shape
        d = x.astype(jnp.uint32)
        # segment the weighted sum so partials stay under 2^31:
        # max term = 255 * seg_len * seg_count-scaled weights; use
        # float-free exact arithmetic by reducing in uint32 with
        # interleaved mods every segment
        seg = 4096
        pad = (-b) % seg
        dp = jnp.pad(d, ((0, 0), (0, pad)))
        w = jnp.pad(jnp.arange(b, 0, -1, dtype=jnp.uint32),
                    (0, pad))
        ds = dp.reshape(n, -1, seg)
        ws = w.reshape(-1, seg)
        a = (1 + jnp.sum(ds, axis=(1, 2))) % _MOD
        partial = jnp.sum(ds * ws[None, :, :] % _MOD,
                          axis=2) % _MOD  # [n, segs]
        bsum = (b + jnp.sum(partial, axis=1)) % _MOD
        return (bsum << 16) | a

    jitted = _JIT_CACHE.get("fn")
    if jitted is None:
        jitted = _JIT_CACHE["fn"] = jax.jit(fn)
    return jitted(blocks)


def adler32_batch(blocks: np.ndarray, backend: str = "auto"):
    """Backend ladder for the batched weak checksum — the
    disperse.cpu-extensions dispatch pattern applied to the rchecksum
    workload: TPU (jax) when a device is live, native C++ (AVX2
    auto-vectorized) when the toolchain built, NumPy always.
    Returns [n] uint32."""
    if backend in ("auto", "jax", "tpu"):
        try:
            import jax

            if backend != "auto" or any(
                    d.platform in ("tpu", "axon")
                    for d in jax.devices()):
                import jax.numpy as jnp

                return np.asarray(adler32_batch_jax(jnp.asarray(blocks)))
        except Exception:
            if backend != "auto":
                raise
    if backend in ("auto", "native"):
        from .. import native

        if native.available():
            return native.adler32_batch(blocks)
        if backend == "native":
            raise RuntimeError("native checksum backend unavailable")
    return adler32_batch_np(blocks)


def rchecksum(data: bytes, fips: bool = True) -> dict:
    """One block's weak+strong checksum (the posix rchecksum fop
    payload).  fips (storage.fips-mode-rchecksum): sha256; off = the
    reference's legacy md5 strong sum."""
    import hashlib

    strong = hashlib.sha256(data) if fips else hashlib.md5(data)
    return {"weak": adler32_ref(data), "strong": strong.hexdigest()}
