"""Process entry points — the glusterfsd analog.

Reference: glusterfsd/src/glusterfsd.c:2650 — one binary runs every
data-plane role, selected by the volfile it loads.  Same here: this
module turns a volfile into a served graph (brick server) or a mounted
client, from the command line or programmatically.

Usage:
    python -m glusterfs_tpu.daemon --volfile brick.vol --listen 24010
    python -m glusterfs_tpu.daemon --volfile brick.vol --listen 0 \
        --portfile /tmp/port   # writes the chosen port (tests use this)
"""

from __future__ import annotations

import argparse
import asyncio
import os
import signal
import sys

from .core.graph import Graph
from .protocol.server import BrickServer
from .core import gflog

log = gflog.get_logger("core.daemon")


async def serve_brick(volfile_text: str, host: str = "127.0.0.1",
                      port: int = 0, top_name: str | None = None,
                      portfile: str | None = None) -> BrickServer:
    """Activate a brick graph and serve it (returns the running server)."""
    graph = Graph.construct(volfile_text, top_name=top_name)
    await graph.activate()
    server = BrickServer(graph.top, host, port, graph=graph)
    await server.start()
    if portfile:
        tmp = portfile + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(server.port))
        os.replace(tmp, portfile)
    return server


def http_route_handler(routes):
    """A one-shot HTTP/1.0 responder over ``routes``: path ->
    ``async () -> (body_bytes, content_type_bytes)``.  ONE copy of the
    head parse / 404 / Content-Length plumbing, shared by the daemon
    metrics endpoint and the gateway worker-pool supervisor's
    aggregated endpoint — an endpoint or header fix lands everywhere."""
    async def handle(reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        try:
            try:
                head = await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"), 5)
            except (asyncio.IncompleteReadError, asyncio.LimitOverrunError,
                    asyncio.TimeoutError, ConnectionError):
                return
            line = head.split(b"\r\n", 1)[0].split()
            path = line[1].decode("latin-1") if len(line) > 1 else "/"
            path = path.split("?", 1)[0]
            route = routes.get(path)
            if route is None:
                writer.write(b"HTTP/1.0 404 Not Found\r\n"
                             b"Content-Length: 0\r\n\r\n")
                return
            body, ctype = await route()
            writer.write(b"HTTP/1.0 200 OK\r\n"
                         b"Content-Type: " + ctype + b"\r\n"
                         + f"Content-Length: {len(body)}\r\n\r\n".encode()
                         + body)
            await writer.drain()
        except ConnectionError:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    return handle


async def serve_metrics(host: str = "127.0.0.1",
                        port: int = 0) -> asyncio.AbstractServer:
    """Prometheus-style scrape endpoint (OFF by default — armed by
    ``--metrics-port``): a minimal HTTP/1.0 responder serving the
    unified registry's text dump at ``/metrics`` and the structured
    snapshot at ``/metrics.json`` (what ``gftpu volume metrics`` and
    the worker-pool supervisor ingest).  Read-only and
    allocation-light; scraping is a cold path by design."""
    import json

    from .core import flight, history, slo
    from .core.metrics import REGISTRY

    async def text():
        return REGISTRY.render().encode(), b"text/plain; version=0.0.4"

    async def structured():
        return (json.dumps(REGISTRY.snapshot()).encode(),
                b"application/json")

    async def incident_json():
        # the per-process incident door (single-process gateway / any
        # daemon with a metrics port): glusterd's incident fan-out
        # GETs this when no worker-pool supervisor is in front
        return (json.dumps(flight.snapshot(), default=repr).encode(),
                b"application/json")

    async def history_json():
        # the time dimension (ISSUE 20): windowed series reconstructed
        # from the delta-compressed sampler ring, with derived
        # per-counter rates (core/history.py)
        return (json.dumps(history.HISTORY.dump(), default=repr).encode(),
                b"application/json")

    async def alerts_json():
        return (json.dumps(slo.ENGINE.status(), default=repr).encode(),
                b"application/json")

    srv = await asyncio.start_server(
        http_route_handler({"/metrics": text, "/": text,
                            "/metrics.json": structured,
                            "/incident.json": incident_json,
                            "/metrics/history.json": history_json,
                            "/alerts.json": alerts_json}),
        host, port)
    log.info(6, "metrics endpoint on %s:%d", host,
             srv.sockets[0].getsockname()[1])
    return srv


def _dump_state(server: BrickServer, volfile: str) -> None:
    """SIGUSR1 statedump (reference glusterfsd.c:2230 wiring +
    statedump.c:831): full graph dump to a timestamped file next to
    the volfile — the de-facto live-debugging interface."""
    import json
    import time

    src = server.graph if server.graph is not None else server.top
    path = (os.path.splitext(volfile)[0]
            + f".dump.{int(time.time())}.{os.getpid()}")
    try:
        with open(path + ".tmp", "w") as f:
            json.dump(src.statedump(), f, indent=1, default=repr)
        os.replace(path + ".tmp", path)
        log.info(2, "statedump written to %s", path)
    except Exception as e:
        log.error(3, "statedump failed: %r", e)


async def _amain(args) -> None:
    if getattr(args, "eventsd", ""):
        # arm gf_event emission for this process (CLIENT_CONNECT /
        # POSIX_HEALTH_CHECK_FAILED ...); same effect as GFTPU_EVENTSD
        # in the environment, but explicit per-daemon
        from .core import events

        events.configure(args.eventsd)
    # cluster.mesh-distributed (ISSUE 12): a brick spawned into a
    # jax.distributed job (glusterd exports GFTPU_MESH_*) joins the
    # coordinator in the BACKGROUND — glusterd spawns bricks one at a
    # time awaiting each port, so a rank that blocked startup waiting
    # for siblings would deadlock the volume start.  Failure degrades
    # to the single-runtime plane, never wedges serving.
    from .parallel import meshd

    meshd.maybe_initialize()
    from .core import flight, history
    from .core.metrics import register_build_info

    flight.set_role("brick")
    register_build_info("brick")
    history.arm()
    with open(args.volfile) as f:
        text = f.read()
    server = await serve_brick(text, args.host, args.listen,
                               args.top or None, args.portfile or None)
    metrics_srv = None
    if getattr(args, "metrics_port", 0):
        metrics_srv = await serve_metrics(args.host, args.metrics_port)
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    loop.add_signal_handler(signal.SIGUSR1, _dump_state, server,
                            args.volfile)
    await stop.wait()
    if metrics_srv is not None:
        metrics_srv.close()
    await server.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gftpu-daemon")
    p.add_argument("--volfile", required=True)
    p.add_argument("--top", default="",
                   help="top layer name (default: unreferenced layer)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--listen", type=int, default=0,
                   help="TCP port (0 = ephemeral)")
    p.add_argument("--portfile", default="",
                   help="write the bound port here (for ephemeral ports)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="serve the unified metrics registry as a "
                        "Prometheus text endpoint on this port "
                        "(0 = off, the default)")
    p.add_argument("--eventsd", default="",
                   help="host:port of the local gftpu-eventsd: arms "
                        "gf_event lifecycle emission in this process "
                        "(same as the GFTPU_EVENTSD env var)")
    args = p.parse_args(argv)
    asyncio.run(_amain(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
