"""storage/posix — the brick: maps fops to a local filesystem directory.

Reference: xlators/storage/posix (posix-inode-fd-ops.c:1999 posix_writev,
posix-helpers.c:1352 GFID handle store).  Same responsibilities here:

* every object gets a GFID at creation.  Identity store, mirroring the
  reference's ``.glusterfs/xx/yy/gfid`` hardlink farm (posix-handle.h):
  - ``.glusterfs_tpu/handle/<hex>`` — a HARDLINK to the inode for regular
    files and symlinks.  fd-based fops resolve through it, so they stay
    correct when the path changes under them (rename, one of several hard
    links removed) and the inode cannot be reused while its gfid lives.
  - ``.glusterfs_tpu/gfid/<hex>`` — a text record: line 1 the dev:ino
    sidecar key, rest the current path (a best-effort hint for files, the
    authoritative mapping for directories, which cannot be hardlinked).
  Renaming a directory updates its own record; records of objects deeper
  in the tree keep working for files (handles) but directory hints below
  a renamed directory go stale — the reference's ancestry symlinks solve
  this; path-based fops (the normal access) are unaffected.
* xattrs (the version/dirty/size accounting written by EC/AFR) live in a
  sidecar JSON per GFID under ``.glusterfs_tpu/xattr/`` — independent of
  host-FS xattr support, atomically replaced on update.
* ``xattrop`` implements the atomic read-modify-write arithmetic the
  cluster layers' transactions depend on (reference posix xattrop).

Fops run under the layer's asyncio context; filesystem calls are blocking
but local (the io-threads analog can wrap this layer with a thread pool).
"""

from __future__ import annotations

import asyncio
import errno
import json
import os
import struct
import time
import zlib

from ..core.fops import FopError
from ..core.iatt import IAType, Iatt, ROOT_GFID, gfid_new
from ..core.layer import FdObj, Layer, Loc, register
from ..core.options import Option
from ..core import gflog

log = gflog.get_logger("posix")

META_DIR = ".glusterfs_tpu"

# virtual xattr: resolve a gfid-loc to its recorded volume path
# (reference glusterfs.gfid2path, posix-inode-fd-ops.c); the shd's
# gfid -> healable-path step rides on it
XA_GFID2PATH = "glusterfs_tpu.gfid2path"
# virtual xattr prefix: list gfids carrying a given xattr key
XA_SCAN_PREFIX = "glusterfs_tpu.scan."


def _fop_errno(e: OSError) -> FopError:
    return FopError(e.errno or errno.EIO, str(e))


FALLOC_FL_KEEP_SIZE = 0x01
FALLOC_FL_PUNCH_HOLE = 0x02

try:
    import ctypes as _ctypes

    _libc = _ctypes.CDLL(None, use_errno=True)
    _libc_fallocate = _libc.fallocate
except (OSError, AttributeError):  # non-Linux: posix_fallocate fallback
    _libc_fallocate = None


def _hex_val(v) -> str:
    """Canonical sidecar encoding of one xattr value (bytes -> hex;
    anything else through its str form) — create's init-xattrs and
    setxattr must stay byte-identical."""
    return (v if isinstance(v, bytes) else str(v).encode()).hex()


def split_gfid_record(content: str) -> tuple[str, str]:
    """Parse a gfid record -> (inokey, relpath).  Modern records are
    'dev:ino\\nrelpath' with a possibly-EMPTY key line (root is recorded
    before its first bind); legacy single-line records are the path
    alone (paths may legally contain newlines, which is why the key
    comes first and is validated, not the path)."""
    inokey, sep, relpath = content.partition("\n")
    if not sep:
        return "", content  # legacy single-line path
    if inokey and (":" not in inokey
                   or not inokey.replace(":", "").isdigit()):
        return "", content  # legacy path that itself contains newlines
    return inokey, relpath


def fold_journal(root: str) -> None:
    """Materialize a (quiesced/copied) brick store's sidecar journal:
    xattr records into the per-gfid JSON files, binding records into
    gfid pointer files.  Only safe on a store no live brick process is
    appending to (a snapshot copy, a restore target)."""
    xattr_dir = os.path.join(root, META_DIR, "xattr")
    gfid_dir = os.path.join(root, META_DIR, "gfid")
    journal = os.path.join(xattr_dir, "journal.jsonl")
    if not os.path.exists(journal):
        return
    with open(journal) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if "b" in rec:
                ghex, key, rel = rec["b"]
                gp = os.path.join(gfid_dir, ghex)
                # surrogateescape like _gfid_set: non-UTF-8 filenames
                # round-trip the journal as surrogates and a strict
                # text write would crash the fold mid-journal
                fd = os.open(gp + ".tmp",
                             os.O_CREAT | os.O_WRONLY | os.O_TRUNC,
                             0o644)
                try:
                    os.write(fd, (key + "\n" + rel)
                             .encode("utf-8", "surrogateescape"))
                finally:
                    os.close(fd)
                os.replace(gp + ".tmp", gp)
                continue
            if "u" in rec:
                try:
                    os.unlink(os.path.join(gfid_dir, rec["u"]))
                except OSError:
                    pass
                continue
            p = os.path.join(xattr_dir, rec["g"] + ".json")
            if rec["x"] is None:
                try:
                    os.unlink(p)
                except OSError:
                    pass
            else:
                with open(p + ".tmp", "w") as g:
                    json.dump(rec["x"], g)
                os.replace(p + ".tmp", p)
    os.unlink(journal)


def _journal_ino_map(xattr_dir: str) -> dict[str, str]:
    """dev:ino -> gfid hex from a journal's binding records (read-only:
    for indexing a LIVE source store whose journal we must not fold)."""
    out: dict[str, str] = {}
    try:
        with open(os.path.join(xattr_dir, "journal.jsonl")) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if "b" in rec:
                    ghex, key, _rel = rec["b"]
                    out[key] = ghex
                elif "u" in rec:
                    dead = rec["u"]
                    for k in [k for k, v in out.items() if v == dead]:
                        del out[k]
    except FileNotFoundError:
        pass
    return out


def rebuild_identity(root: str) -> int:
    """Re-key a brick store's identity after a file-level copy (snapshot
    restore): the dev:ino sidecars and the handle hardlink farm both
    refer to the ORIGINAL inodes, so every gfid would resolve stale and
    lookups would mint fresh gfids over the copied xattrs.  Walk the
    gfid records, rebind each to the copied file, and rebuild the
    handles.  Returns the number of rebound objects.  (The reference
    avoids this by snapshotting at the block layer — LVM preserves
    inodes; a store-level copy cannot.)"""
    gfid_dir = os.path.join(root, META_DIR, "gfid")
    xattr_dir = os.path.join(root, META_DIR, "xattr")
    handle_dir = os.path.join(root, META_DIR, "handle")
    if not os.path.isdir(gfid_dir):
        return 0
    # fold any sidecar journal into the materialized files first, so
    # the rebinding walk below sees the real final state.  Binding
    # records ("b"/"u") materialize as gfid pointer files — the ino-
    # sidecars they'd also produce are about to be wiped and rebuilt
    # against the copied inodes anyway.
    fold_journal(root)
    for d, pred in ((xattr_dir, lambda n: n.startswith("ino-")),
                    (handle_dir, lambda n: True)):
        if os.path.isdir(d):
            for n in os.listdir(d):
                if pred(n):
                    try:
                        os.unlink(os.path.join(d, n))
                    except OSError:
                        pass
    os.makedirs(handle_dir, exist_ok=True)
    count = 0
    for hexg in os.listdir(gfid_dir):
        if hexg.endswith(".tmp"):
            continue
        rec = os.path.join(gfid_dir, hexg)
        try:
            with open(rec) as f:
                _, relpath = split_gfid_record(f.read())
        except OSError:
            continue
        ap = os.path.normpath(os.path.join(root, relpath.lstrip("/")))
        if not os.path.lexists(ap):
            # object not in this copy: drop the orphaned identity
            for p in (rec, os.path.join(xattr_dir, hexg + ".json")):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            continue
        st = os.lstat(ap)
        key = f"{st.st_dev}:{st.st_ino}"
        with open(os.path.join(xattr_dir, "ino-" + key), "wb") as f:
            f.write(bytes.fromhex(hexg))
        with open(rec + ".tmp", "w") as f:
            f.write(key + "\n" + relpath)
        os.replace(rec + ".tmp", rec)
        if not os.path.isdir(ap):
            try:
                os.link(ap, os.path.join(handle_dir, hexg),
                        follow_symlinks=False)
            except OSError:
                pass
        count += 1
    return count


def snapshot_copy(src_root: str, dst_root: str) -> None:
    """Copy a brick store for a snapshot (glusterd-snapshot.c analog at
    the store level).  The handle hardlink farm is skipped — in a
    file-level copy it would duplicate every file's bytes; it is
    rebuilt by :func:`rebuild_identity` at restore.  The copied gfid
    records' path hints are then refreshed from a live dev:ino walk of
    the source: hints go stale under directory renames (only the
    renamed object's own record is rewritten), and a stale hint at
    restore would silently drop that object's identity and versioning
    xattrs.  Run under an armed barrier so the tree is stable."""
    import shutil

    def _skip_handles(d, names):
        return names if os.path.normpath(d).endswith(
            os.path.join(META_DIR, "handle")) else []

    shutil.copytree(src_root, dst_root, ignore=_skip_handles,
                    symlinks=True)
    # the copy carries the source's journal: materialize it in the COPY
    # (ours to mutate) so the pointer records below exist even for
    # journal-only bindings; the live source's journal is only INDEXED
    # in memory for the ino walk
    fold_journal(dst_root)
    xattr_dir = os.path.join(src_root, META_DIR, "xattr")
    gfid_dir = os.path.join(dst_root, META_DIR, "gfid")
    if not os.path.isdir(xattr_dir) or not os.path.isdir(gfid_dir):
        return
    ino_map = _journal_ino_map(xattr_dir)
    for dirpath, dirnames, filenames in os.walk(src_root):
        if dirpath == src_root and META_DIR in dirnames:
            dirnames.remove(META_DIR)
        for nm in dirnames + filenames:
            ap = os.path.join(dirpath, nm)
            try:
                st = os.lstat(ap)
            except OSError:
                continue
            key = f"{st.st_dev}:{st.st_ino}"
            hexg = ino_map.get(key)
            if hexg is None:
                try:
                    with open(os.path.join(xattr_dir, "ino-" + key),
                              "rb") as f:
                        hexg = f.read(16).hex()
                except OSError:
                    continue
            rec = os.path.join(gfid_dir, hexg)
            rel = "/" + os.path.relpath(ap, src_root)
            try:
                with open(rec) as f:
                    inokey, relpath = split_gfid_record(f.read())
            except OSError:
                continue
            if relpath != rel:
                with open(rec + ".tmp", "w") as f:
                    f.write(inokey + "\n" + rel)
                os.replace(rec + ".tmp", rec)


def _sys_fallocate(fdno: int, mode: int, offset: int, length: int) -> None:
    """fallocate(2) honoring mode flags (KEEP_SIZE, PUNCH_HOLE)."""
    if _libc_fallocate is None:
        if mode:
            raise OSError(errno.EOPNOTSUPP, "fallocate flags unsupported")
        os.posix_fallocate(fdno, offset, length)
        return
    if _libc_fallocate(_ctypes.c_int(fdno), _ctypes.c_int(mode),
                       _ctypes.c_long(offset), _ctypes.c_long(length)) != 0:
        err = _ctypes.get_errno()
        raise OSError(err, os.strerror(err))


@register("storage/posix")
class PosixLayer(Layer):
    """Bottom-of-brick storage layer."""

    OPTIONS = (
        Option("directory", "path", description="brick root directory"),
        Option("o-direct", "bool", default="off"),
        Option("update-link-count-parent", "bool", default="off"),
        Option("health-check-interval", "time", default="30",
               description="seconds between backend probes (0 = off); "
               "a failing backend marks the brick down "
               "(posix_health_check_thread_proc)"),
        Option("health-check-timeout", "time", default="10",
               description="a single probe hanging past this (D-state "
                           "disk) counts as failure "
                           "(storage.health-check-timeout)"),
        Option("create-mask", "str", default="0777",
               description="octal AND-mask on file create modes "
                           "(storage.create-mask, posix-metadata)"),
        Option("create-directory-mask", "str", default="0777",
               description="octal AND-mask on mkdir modes "
                           "(storage.create-directory-mask)"),
        Option("force-create-mode", "str", default="0000",
               description="octal bits OR-ed onto every created file "
                           "(storage.force-create-mode)"),
        Option("force-directory-mode", "str", default="0000",
               description="octal bits OR-ed onto every mkdir "
                           "(storage.force-directory-mode)"),
        Option("max-hardlinks", "int", default=100, min=0,
               description="EMLINK past this many links to one inode "
                           "(storage.max-hardlinks; 0 = unlimited)"),
        Option("reserve", "percent", default="1",
               description="refuse writes/creates when free space falls "
                           "under this percent (storage.reserve; reads "
                           "and deletes still pass so the operator can "
                           "recover)"),
        Option("owner-uid", "int", default=-1, min=-1,
               description="chown the brick root at init "
                           "(storage.owner-uid; -1 = leave)"),
        Option("owner-gid", "int", default=-1, min=-1,
               description="storage.owner-gid; -1 = leave"),
        Option("fips-mode-rchecksum", "bool", default="on",
               description="sha256 strong checksums (FIPS-allowed); "
                           "off = legacy md5 "
                           "(storage.fips-mode-rchecksum)"),
    )

    # journal records between sidecar compactions (the xattr write-path
    # cost model: one O_APPEND write per update instead of the four
    # syscalls of open+write+close+replace; same durability — neither
    # path fsyncs, both live in the page cache until the OS flushes)
    XATTR_COMPACT_EVERY = 4096
    # cache bounds: clean entries evict once past these, so a brick
    # serving millions of files stays O(cap) resident, not O(files);
    # dirty xattr entries are pinned until compaction persists them
    XATTR_CACHE_MAX = 65536
    INO_CACHE_MAX = 262144

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        root = self.opts.get("directory")
        if not root:
            raise ValueError(f"{self.name}: option directory is required")
        self.root = os.path.abspath(root)
        self._gfid_dir = os.path.join(self.root, META_DIR, "gfid")
        self._xattr_dir = os.path.join(self.root, META_DIR, "xattr")
        self._handle_dir = os.path.join(self.root, META_DIR, "handle")
        self._executor = None  # worker pool injected by io-threads
        # xattr sidecar cache + append journal (posix-metadata.c keeps
        # metadata in ONE xattr blob; the analog here is one in-memory
        # dict per gfid, journaled on update, compacted to the per-gfid
        # JSON files every XATTR_COMPACT_EVERY records)
        self._xa_cache: dict[bytes, dict] = {}
        self._xa_dirty: set[bytes] = set()
        self._ino_cache: dict[str, bytes] = {}  # "dev:ino" -> gfid
        # gfid bindings ride the SAME journal (this host's open(2) is
        # sandbox-priced at ~175us, so the old two-files-per-create
        # binding dominated the smallfile budget): journal-only until
        # compaction materializes the ino-/pointer files.  _gfid_mem
        # holds uncompacted bindings (bounded by the compaction
        # interval); files stay authoritative for everything older.
        self._gfid_mem: dict[bytes, tuple[str, str]] = {}
        self._bind_dirty: set[bytes] = set()
        self._xa_journal_path = os.path.join(self._xattr_dir,
                                             "journal.jsonl")
        self._xa_journal_fd: int | None = None
        self._xa_records = 0
        # compound batching: while a chain executes, journal records
        # accumulate here and land in ONE appended write at chain end
        # (a create+writev+fsetattr chain is one handle-farm
        # transaction instead of per-fop journal syscalls)
        self._jrnl_batch: list[str] | None = None

    def set_io_executor(self, executor) -> None:
        """io-threads hands us its worker pool; data-plane syscalls run
        there so a slow disk op cannot stall the brick's event loop
        (io-threads.c:236 iot_worker intent).  Metadata/sidecar fops stay
        on the loop — their read-modify-write sections rely on its
        serialization."""
        self._executor = executor

    async def _io(self, fn, *args):
        if self._executor is None:
            return fn(*args)
        return await asyncio.get_running_loop().run_in_executor(
            self._executor, fn, *args)

    async def init(self):
        os.makedirs(self.root, exist_ok=True)
        os.makedirs(self._gfid_dir, exist_ok=True)
        os.makedirs(self._xattr_dir, exist_ok=True)
        os.makedirs(self._handle_dir, exist_ok=True)
        self._xa_replay_journal()
        # root of the brick always has the fixed ROOT_GFID
        if not os.path.exists(self._gfid_path(ROOT_GFID)):
            self._gfid_set(ROOT_GFID, "/")
        if self.opts["owner-uid"] >= 0 or self.opts["owner-gid"] >= 0:
            try:  # storage.owner-uid/-gid: brand the brick root
                os.chown(self.root, self.opts["owner-uid"],
                         self.opts["owner-gid"])
            except OSError as e:
                log.warning(9, "%s: owner-uid/gid chown failed: %s",
                            self.name, e)
        self._mode_opts()
        self._reserve_checked = 0.0
        self._reserve_full = False
        self._failed_health: str | None = None
        if float(self.opts["health-check-interval"]) > 0:
            self._health_task = asyncio.create_task(self._health_loop())
        await super().init()

    def _mode_opts(self) -> None:
        """Parse the octal mode-mask options once (hot create path)."""

        def octal(key: str, dflt: int) -> int:
            try:
                return int(str(self.opts[key]), 8) & 0o7777
            except ValueError:
                log.warning(9, "%s: %s=%r is not octal; using %o",
                            self.name, key, self.opts[key], dflt)
                return dflt

        self._fmask = octal("create-mask", 0o777)
        self._dmask = octal("create-directory-mask", 0o777)
        self._fforce = octal("force-create-mode", 0)
        self._dforce = octal("force-directory-mode", 0)

    def _file_mode(self, mode: int) -> int:
        return (mode & self._fmask) | self._fforce

    def _dir_mode(self, mode: int) -> int:
        return (mode & self._dmask) | self._dforce

    @property
    def _mode_policy_active(self) -> bool:
        # with masks/forced bits configured the EXACT mode must land —
        # chmod after create, because the process umask (which the
        # reference's brick daemon zeroes at startup) filters open(2)'s
        # mode argument
        return (self._fforce or self._dforce or self._fmask != 0o777
                or self._dmask != 0o777)

    def _check_reserve(self) -> None:
        """storage.reserve: writes/creates fail with ENOSPC below the
        floor; reads and deletes pass (the operator's way out).  The
        statvfs is cached ~2s — this sits on the data hot path."""
        pct = float(self.opts["reserve"])
        if pct <= 0:
            return
        now = time.monotonic()
        if now - self._reserve_checked > 2.0:
            self._reserve_checked = now
            try:
                st = os.statvfs(self.root)
                free = st.f_bavail / max(1, st.f_blocks) * 100.0
                self._reserve_full = free < pct
            except OSError:
                self._reserve_full = False
        if self._reserve_full:
            raise FopError(errno.ENOSPC,
                           f"brick under storage.reserve floor "
                           f"({self.opts['reserve']}%)")

    async def fini(self):
        t = getattr(self, "_health_task", None)
        if t is not None:
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
            self._health_task = None
        # clean shutdown folds the xattr journal into the JSON files
        # (a kill skips this; init replays the journal instead)
        try:
            self._xa_compact()
        except OSError:
            pass
        await super().fini()

    def reconfigure(self, options: dict) -> None:
        old = float(self.opts["health-check-interval"])
        super().reconfigure(options)
        self._mode_opts()
        self._reserve_checked = 0.0  # re-probe under the new floor
        new = float(self.opts["health-check-interval"])
        if new == old or getattr(self, "_failed_health", None):
            return  # a failed brick stays down until respawn
        t = getattr(self, "_health_task", None)
        if t is not None:
            t.cancel()
            self._health_task = None
        if new > 0:
            try:
                self._health_task = asyncio.create_task(
                    self._health_loop())
            except RuntimeError:
                pass  # no running loop (offline reconfigure)

    # -- health checker (posix_health_check_thread_proc analog) ------------
    # The reference stats + writes a probe under .glusterfs every
    # interval; a failing backend (dead disk, unmounted FS) kills the
    # brick so clients fail over instead of hanging on EIO storage.
    # Here the brick marks itself down: every fop raises ENOTCONN and
    # CHILD_DOWN propagates up to the serving graph.

    async def _health_loop(self) -> None:
        from ..core.layer import Event

        interval = float(self.opts["health-check-interval"])
        probe = os.path.join(self.root, META_DIR, "health_check")
        while True:
            await asyncio.sleep(interval)
            try:
                def check() -> None:
                    os.statvfs(self.root)
                    with open(probe, "w") as f:
                        f.write(str(time.time()))
                        f.flush()
                        os.fsync(f.fileno())

                to = float(self.opts["health-check-timeout"])
                await asyncio.wait_for(asyncio.to_thread(check),
                                       to if to > 0 else None)
            except asyncio.CancelledError:
                raise
            except (OSError, asyncio.TimeoutError) as e:
                self._failed_health = str(e)
                log.error(9, "%s: backend health check failed: %s — "
                          "marking brick down", self.name, e)
                # events.h EVENT_POSIX_HEALTH_CHECK_FAILED: the
                # operator's page for "this brick's disk is dying"
                from ..core.events import gf_event

                gf_event("POSIX_HEALTH_CHECK_FAILED", brick=self.name,
                         path=self.root, error=str(e))
                self.notify(Event.CHILD_DOWN, None, None)
                return

    # -- path / gfid helpers ----------------------------------------------

    def _health_gate(self) -> None:
        """Every resolution path funnels here once the checker marks
        the backend dead: a brick must fail loudly (ENOTCONN), never
        serve stale metadata or record bookkeeping on a dead disk."""
        if getattr(self, "_failed_health", None):
            raise FopError(errno.ENOTCONN,
                           f"brick backend failed health check: "
                           f"{self._failed_health}")

    def _abs(self, path: str) -> str:
        self._health_gate()
        rel = path.lstrip("/")
        if rel.split("/", 1)[0] == META_DIR:
            raise FopError(errno.EPERM, "reserved namespace")
        out = os.path.normpath(os.path.join(self.root, rel))
        if not (out == self.root or out.startswith(self.root + os.sep)):
            raise FopError(errno.EPERM, f"path escapes brick: {path}")
        return out

    def _gfid_path(self, gfid: bytes) -> str:
        return os.path.join(self._gfid_dir, gfid.hex())

    def _gfid_set(self, gfid: bytes, relpath: str,
                  inokey: str | None = None) -> None:
        """Write the gfid pointer file: line 1 = the dev:ino binding key
        (so _gfid_del can clean up the ino- sidecar and inode-number
        reuse can't resurrect a deleted gfid), rest = relpath verbatim
        (paths may legally contain newlines, so the path goes last).
        Raw os.open: this sits on the per-create hot path and a
        buffered file object costs ~3x the syscalls."""
        tmp = self._gfid_path(gfid) + ".tmp"
        fd = os.open(tmp, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
        try:
            os.write(fd, ((inokey or "") + "\n" + relpath)
                     .encode("utf-8", "surrogateescape"))
        finally:
            os.close(fd)
        os.replace(tmp, self._gfid_path(gfid))

    def _gfid_read(self, gfid: bytes) -> tuple[str, str]:
        """-> (inokey, relpath); raises ESTALE when the gfid is unknown."""
        ent = self._gfid_mem.get(gfid)
        if ent is not None:
            return ent  # journal-only binding (not yet compacted)
        try:
            with open(self._gfid_path(gfid)) as f:
                return split_gfid_record(f.read())
        except FileNotFoundError:
            raise FopError(errno.ESTALE, f"no such gfid {gfid.hex()}") from None

    def _gfid_resolve(self, gfid: bytes) -> str:
        """GFID -> volume-relative path ('/a/b'): the recorded hint.
        Authoritative for directories; for files prefer _gfid_access."""
        return self._gfid_read(gfid)[1]

    def _handle_path(self, gfid: bytes) -> str:
        return os.path.join(self._handle_dir, gfid.hex())

    def _gfid_access(self, gfid: bytes) -> str:
        """GFID -> ABSOLUTE path for I/O.  Regular files/symlinks go via
        the handle hardlink (immune to rename/unlink of any one name);
        directories via the recorded path."""
        self._health_gate()
        hp = self._handle_path(gfid)
        if os.path.lexists(hp):
            return hp
        return self._abs(self._gfid_resolve(gfid))

    def _iatt_gfid(self, gfid: bytes) -> Iatt:
        try:
            st = os.lstat(self._gfid_access(gfid))
        except OSError as e:
            raise _fop_errno(e)
        return Iatt.from_stat(st, gfid)

    def _gfid_del(self, gfid: bytes) -> None:
        try:
            inokey, _ = self._gfid_read(gfid)
            if inokey:
                self._ino_cache.pop(inokey, None)
                os.unlink(os.path.join(self._xattr_dir, "ino-" + inokey))
        except (FopError, FileNotFoundError):
            pass
        if self._gfid_mem.pop(gfid, None) is not None or \
                gfid in self._bind_dirty:
            self._bind_dirty.add(gfid)
            self._journal_rec({"u": gfid.hex()})
        for p in (self._handle_path(gfid), self._gfid_path(gfid)):
            try:
                os.unlink(p)
            except FileNotFoundError:
                pass
        self._xattr_del(gfid)

    def _gfid_of(self, path: str) -> bytes | None:
        """Read the per-object gfid marker (sidecar next to xattr store).
        dev:ino -> gfid is immutable for an inode's lifetime, so a hit
        in the in-memory map skips the sidecar read every stat pays."""
        try:
            st = os.lstat(self._abs(path))
        except OSError as e:
            raise _fop_errno(e)
        key = f"{st.st_dev}:{st.st_ino}"
        g = self._ino_cache.get(key)
        if g is not None:
            return g
        p = os.path.join(self._xattr_dir, "ino-" + key)
        try:
            fd = os.open(p, os.O_RDONLY)
        except FileNotFoundError:
            return None
        try:
            g = os.read(fd, 16)
        finally:
            os.close(fd)
        if len(g) != 16:  # torn record from a crash mid-write
            return None
        if len(self._ino_cache) >= self.INO_CACHE_MAX:
            # shed an arbitrary half — but never a journal-only binding
            # (its ino- file doesn't exist yet; dropping the cache entry
            # would read as 'unbound' until compaction)
            shed = 0
            for k in list(self._ino_cache):
                if self._ino_cache[k] in self._bind_dirty:
                    continue
                del self._ino_cache[k]
                shed += 1
                if shed >= self.INO_CACHE_MAX // 2:
                    break
        self._ino_cache[key] = g
        return g

    def _gfid_bind(self, path: str, gfid: bytes) -> None:
        ap = self._abs(path)
        try:
            st = os.lstat(ap)
        except OSError as e:
            raise _fop_errno(e)
        key = f"{st.st_dev}:{st.st_ino}"
        rel = path if path.startswith("/") else "/" + path
        # journal-only binding (ONE appended record on the already-open
        # journal fd): the ino- and pointer files materialize at
        # compaction — creating two files per bind priced every create
        # at 2x open(2) on this sandboxed host
        self._ino_cache[key] = gfid
        self._gfid_mem[gfid] = (key, rel)
        self._bind_dirty.add(gfid)
        self._journal_rec({"b": [gfid.hex(), key, rel]})
        # handle hardlink for anything hardlinkable (reference
        # posix_handle_hard); directories keep the text record only
        if not os.path.isdir(ap):
            hp = self._handle_path(gfid)
            try:
                os.link(ap, hp, follow_symlinks=False)
            except FileExistsError:
                if not os.path.samestat(st, os.lstat(hp)):
                    os.unlink(hp)  # stale handle from a recycled gfid
                    os.link(ap, hp, follow_symlinks=False)
            except OSError as e:
                log.warning(2, "handle link failed for %s: %s", path, e)

    def _require_gfid(self, path: str) -> bytes:
        g = self._gfid_of(path)
        if g is None:  # legacy object: heal a fresh gfid (posix_gfid_set)
            g = gfid_new() if path not in ("/", "") else ROOT_GFID
            self._gfid_bind(path, g)
        return g

    def _loc_path(self, loc: Loc) -> str:
        if loc.path:
            return loc.path
        if loc.gfid:
            return self._gfid_resolve(loc.gfid)
        raise FopError(errno.EINVAL, "loc has neither path nor gfid")

    def _iatt(self, path: str, *, follow: bool = False) -> Iatt:
        try:
            st = os.stat(self._abs(path)) if follow else os.lstat(self._abs(path))
        except OSError as e:
            raise _fop_errno(e)
        return Iatt.from_stat(st, self._require_gfid(path))

    # -- xattr sidecar (in-memory cache + append journal) ------------------
    # Updates append ONE record to a per-brick journal and mutate the
    # cache; the per-gfid JSON files are rewritten only at compaction.
    # A killed brick replays the journal over the JSON files at init —
    # byte-for-byte the state an uncached store would have had, because
    # neither path fsyncs (page-cache durability either way).  All xattr
    # mutation runs on the brick event loop (see set_io_executor), so
    # the cache needs no locking.

    def _xattr_path(self, gfid: bytes) -> str:
        return os.path.join(self._xattr_dir, gfid.hex() + ".json")

    def _xa_replay_journal(self) -> None:
        try:
            with open(self._xa_journal_path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail record from a kill
                    if "b" in rec:  # gfid binding
                        ghex, key, rel = rec["b"]
                        g = bytes.fromhex(ghex)
                        self._gfid_mem[g] = (key, rel)
                        self._ino_cache[key] = g
                        self._bind_dirty.add(g)
                        self._xa_records += 1
                        continue
                    if "u" in rec:  # unbind
                        g = bytes.fromhex(rec["u"])
                        ent = self._gfid_mem.pop(g, None)
                        if ent is not None:
                            self._ino_cache.pop(ent[0], None)
                        self._bind_dirty.add(g)
                        self._xa_records += 1
                        continue
                    g = bytes.fromhex(rec["g"])
                    if rec["x"] is None:
                        self._xa_cache.pop(g, None)
                        try:
                            os.unlink(self._xattr_path(g))
                        except OSError:
                            pass
                    else:
                        self._xa_cache[g] = rec["x"]
                    self._xa_dirty.add(g)
                    self._xa_records += 1
        except FileNotFoundError:
            return

    def _journal_rec(self, rec: dict) -> None:
        if self._jrnl_batch is not None:
            # inside a compound chain: defer to one write (and defer
            # compaction too — it folds from memory, which already
            # holds this record's effect)
            self._jrnl_batch.append(json.dumps(rec) + "\n")
            self._xa_records += 1
            return
        if self._xa_journal_fd is None:
            self._xa_journal_fd = os.open(
                self._xa_journal_path,
                os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
        os.write(self._xa_journal_fd, (json.dumps(rec) + "\n").encode())
        self._xa_records += 1
        if self._xa_records >= self.XATTR_COMPACT_EVERY:
            self._xa_compact()

    def journal_batch(self):
        """Context manager: while held, journal records accumulate and
        land in ONE appended write at exit (compaction deferred with
        them).  Same page-cache durability as the per-record appends —
        neither path fsyncs — and the flush runs even on failure,
        because the records mirror state the in-memory caches already
        hold.  Nesting is a no-op; the brick's fops all run on one
        event loop, so records from interleaved requests simply join
        the batch in order.  protocol/server wraps every compound
        dispatch in this, so the batching engages no matter where in
        the brick graph the chain decomposed."""
        import contextlib

        @contextlib.contextmanager
        def batch():
            if self._jrnl_batch is not None:  # nested: already batching
                yield
                return
            self._jrnl_batch = []
            try:
                yield
            finally:
                buf, self._jrnl_batch = self._jrnl_batch, None
                if buf:
                    if self._xa_journal_fd is None:
                        self._xa_journal_fd = os.open(
                            self._xa_journal_path,
                            os.O_CREAT | os.O_WRONLY | os.O_APPEND,
                            0o644)
                    os.write(self._xa_journal_fd, "".join(buf).encode())
                if self._xa_records >= self.XATTR_COMPACT_EVERY:
                    self._xa_compact()

        return batch()

    async def compound(self, links, xdata: dict | None = None) -> list:
        """Chains land as one handle-farm transaction: every link runs
        through this layer's ordinary fops under one journal batch."""
        from ..rpc import compound as cfop

        with self.journal_batch():
            return await cfop.decompose(self, links, xdata)

    def _xa_append(self, gfid: bytes, xattrs: dict | None) -> None:
        self._xa_dirty.add(gfid)
        self._journal_rec({"g": gfid.hex(), "x": xattrs})

    def _xa_compact(self) -> None:
        """Fold the journal into the per-gfid JSON files (xattrs) and
        the ino-/pointer files (bindings), then truncate."""
        for g in self._xa_dirty:
            p = self._xattr_path(g)
            cur = self._xa_cache.get(g)
            if cur is None:
                try:
                    os.unlink(p)
                except OSError:
                    pass
                continue
            with open(p + ".tmp", "w") as f:
                json.dump(cur, f)
            os.replace(p + ".tmp", p)
        self._xa_dirty.clear()
        for g in self._bind_dirty:
            ent = self._gfid_mem.pop(g, None)
            if ent is None:
                # unbound since: drop any materialized remnants
                for p in (self._handle_path(g), self._gfid_path(g)):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
                continue
            key, rel = ent
            fd = os.open(os.path.join(self._xattr_dir, "ino-" + key),
                         os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
            try:
                os.write(fd, g)
            finally:
                os.close(fd)
            self._gfid_set(g, rel, inokey=key)
        self._bind_dirty.clear()
        self._xa_records = 0
        if self._xa_journal_fd is not None:
            os.close(self._xa_journal_fd)
            self._xa_journal_fd = None
        try:
            os.truncate(self._xa_journal_path, 0)
        except OSError:
            pass

    def drop_caches(self) -> None:
        """Forget all in-memory sidecar state and re-read the store —
        exactly what a kill + respawn does.  For tooling/tests that
        mutate the brick backend out-of-band under a live layer (a real
        brick replacement respawns the process, making this implicit).
        Nothing is written: the store may have been wiped/replaced, and
        compacting stale memory into it would resurrect dead state."""
        self._xa_cache.clear()
        self._xa_dirty.clear()
        self._ino_cache.clear()
        self._gfid_mem.clear()
        self._bind_dirty.clear()
        if self._xa_journal_fd is not None:
            os.close(self._xa_journal_fd)
            self._xa_journal_fd = None
        self._xa_records = 0
        self._xa_replay_journal()  # whatever journal the store now has

    def _xa_evict(self) -> None:
        """Bound the cache: shed clean entries once past the cap (dirty
        ones carry journal-only state and stay pinned to compaction)."""
        if len(self._xa_cache) <= self.XATTR_CACHE_MAX:
            return
        for g in list(self._xa_cache):
            if g not in self._xa_dirty:
                del self._xa_cache[g]
                if len(self._xa_cache) <= self.XATTR_CACHE_MAX // 2:
                    break

    def _xattr_load(self, gfid: bytes) -> dict[str, str]:
        cur = self._xa_cache.get(gfid)
        if cur is None:
            try:
                with open(self._xattr_path(gfid)) as f:
                    cur = json.load(f)
            except FileNotFoundError:
                cur = {}
            self._xa_cache[gfid] = cur
            self._xa_evict()
        return dict(cur)  # callers mutate-then-store; never alias cache

    def _xattr_store(self, gfid: bytes, xattrs: dict[str, str]) -> None:
        self._xa_cache[gfid] = dict(xattrs)
        self._xa_append(gfid, xattrs)
        self._xa_evict()

    def _xattr_del(self, gfid: bytes) -> None:
        """Drop a gfid's xattrs entirely (unlink/nuke paths)."""
        self._xa_cache.pop(gfid, None)
        self._xa_append(gfid, None)
        try:
            os.unlink(self._xattr_path(gfid))
        except OSError:
            pass

    # -- namespace fops ----------------------------------------------------

    async def lookup(self, loc: Loc, xdata: dict | None = None):
        path = self._loc_path(loc)
        ia = self._iatt(path)
        if (xdata or {}).get("get-xattrs"):
            # xdata piggyback (the reference's dict_t request keys on
            # lookup): the reply carries the inode's xattrs so cluster
            # layers fold their metadata fan-out into the lookup wave
            try:
                return ia, dict(await self.getxattr(loc, None))
            except FopError:
                pass
        return ia, {}

    async def stat(self, loc: Loc, xdata: dict | None = None):
        return self._iatt(self._loc_path(loc))

    async def fstat(self, fd: FdObj, xdata: dict | None = None):
        return self._iatt_gfid(fd.gfid)

    async def mkdir(self, loc: Loc, mode: int = 0o755,
                    xdata: dict | None = None):
        self._check_reserve()
        path = self._loc_path(loc)
        try:
            os.mkdir(self._abs(path), self._dir_mode(mode))
            if self._mode_policy_active:
                os.chmod(self._abs(path), self._dir_mode(mode))
        except OSError as e:
            raise _fop_errno(e)
        gfid = (xdata or {}).get("gfid-req") or gfid_new()
        self._gfid_bind(path, gfid)
        return self._iatt(path)

    async def mknod(self, loc: Loc, mode: int = 0o644, rdev: int = 0,
                    xdata: dict | None = None):
        path = self._loc_path(loc)
        try:
            # regular files only (block/char nodes are out of scope)
            fdno = os.open(self._abs(path),
                           os.O_CREAT | os.O_EXCL | os.O_WRONLY,
                           self._file_mode(mode))
            if self._mode_policy_active:
                os.fchmod(fdno, self._file_mode(mode))
            os.close(fdno)
        except OSError as e:
            raise _fop_errno(e)
        gfid = (xdata or {}).get("gfid-req") or gfid_new()
        self._gfid_bind(path, gfid)
        return self._iatt(path)

    async def create(self, loc: Loc, flags: int = 0, mode: int = 0o644,
                     xdata: dict | None = None):
        self._check_reserve()
        path = self._loc_path(loc)
        mode = self._file_mode(mode)
        try:
            # brick fds are always RDWR regardless of the client's access
            # mode (blindly OR-ing O_RDWR onto O_WRONLY yields the
            # can-do-nothing accmode 3): EC/AFR RMW and heal need read
            # access on write-only client fds, like the reference's ec
            # open-flag rewrite.  O_APPEND is stripped too — Linux
            # pwrite(2) ignores the offset on O_APPEND fds, which would
            # send EC's positional fragment writes to EOF
            fdno = os.open(self._abs(path),
                           (flags & ~(os.O_ACCMODE | os.O_APPEND))
                           | os.O_CREAT | os.O_RDWR, mode)
            if self._mode_policy_active:
                os.fchmod(fdno, mode)
        except OSError as e:
            raise _fop_errno(e)
        gfid = (xdata or {}).get("gfid-req") or gfid_new()
        self._gfid_bind(path, gfid)
        init = (xdata or {}).get("init-xattrs")
        if init:
            # cluster layers seed their counter xattrs in the SAME fop
            # as the create — one wave instead of create + setxattr
            self._xattr_store(gfid,
                              {k: _hex_val(v) for k, v in init.items()})
        else:
            # a just-bound gfid has no sidecar JSON: seed the cache so
            # the first getxattr doesn't pay a guaranteed-miss open
            self._xa_cache.setdefault(gfid, {})
        fd = FdObj(gfid, flags, path=path)
        fd.ctx_set(self, fdno)
        return fd, self._iatt(path)

    async def symlink(self, target: str, loc: Loc, xdata: dict | None = None):
        path = self._loc_path(loc)
        try:
            os.symlink(target, self._abs(path))
        except OSError as e:
            raise _fop_errno(e)
        gfid = (xdata or {}).get("gfid-req") or gfid_new()
        self._gfid_bind(path, gfid)
        return self._iatt(path)

    async def readlink(self, loc: Loc, xdata: dict | None = None):
        try:
            return os.readlink(self._abs(self._loc_path(loc)))
        except OSError as e:
            raise _fop_errno(e)

    async def link(self, oldloc: Loc, newloc: Loc, xdata: dict | None = None):
        oldp, newp = self._loc_path(oldloc), self._loc_path(newloc)
        maxl = self.opts["max-hardlinks"]
        if maxl:
            try:
                if os.stat(self._abs(oldp)).st_nlink >= maxl:
                    raise FopError(errno.EMLINK,
                                   f"storage.max-hardlinks ({maxl})")
            except OSError as e:
                raise _fop_errno(e)
        try:
            os.link(self._abs(oldp), self._abs(newp))
        except OSError as e:
            raise _fop_errno(e)
        return self._iatt(newp)

    async def unlink(self, loc: Loc, xdata: dict | None = None):
        path = self._loc_path(loc)
        gfid = self._gfid_of(path)
        try:
            os.unlink(self._abs(path))
        except OSError as e:
            raise _fop_errno(e)
        if gfid is not None:
            self._maybe_reap(gfid)
        return {}

    def _maybe_reap(self, gfid: bytes) -> None:
        """Drop the identity when no user-visible name remains: the handle
        hardlink holding nlink==1 means only the handle is left (reference
        posix janitor semantics)."""
        hp = self._handle_path(gfid)
        try:
            if os.lstat(hp).st_nlink > 1:
                return  # another hard link still names this inode
        except FileNotFoundError:
            pass  # directory or legacy object: no handle
        self._gfid_del(gfid)

    async def rmdir(self, loc: Loc, flags: int = 0, xdata: dict | None = None):
        path = self._loc_path(loc)
        gfid = self._gfid_of(path)
        try:
            os.rmdir(self._abs(path))
        except OSError as e:
            raise _fop_errno(e)
        if gfid is not None:
            self._gfid_del(gfid)
        return {}

    async def rename(self, oldloc: Loc, newloc: Loc, xdata: dict | None = None):
        oldp, newp = self._loc_path(oldloc), self._loc_path(newloc)
        gfid = self._gfid_of(oldp)
        try:
            dst_gfid = self._gfid_of(newp)
        except FopError:
            dst_gfid = None
        try:
            os.replace(self._abs(oldp), self._abs(newp))
        except OSError as e:
            raise _fop_errno(e)
        if dst_gfid is not None and dst_gfid != gfid:
            # overwritten destination: identity dies unless another hard
            # link still names its inode
            self._maybe_reap(dst_gfid)
        if gfid is not None:
            self._gfid_bind(newp, gfid)  # refresh path hint + dev:ino key
        return self._iatt(newp)

    # -- fd fops -----------------------------------------------------------

    async def open(self, loc: Loc, flags: int = os.O_RDWR,
                   xdata: dict | None = None):
        path = self._loc_path(loc)
        base = flags & ~(os.O_CREAT | os.O_ACCMODE | os.O_APPEND)
        try:
            # same access-mode/O_APPEND normalization as create
            # (directories reject O_RDWR; they come through opendir)
            try:
                fdno = os.open(self._abs(path), base | os.O_RDWR)
            except PermissionError:
                if flags & os.O_ACCMODE != os.O_RDONLY:
                    raise
                # a file the brick cannot write (0444 etc.): serve the
                # client's read-only open rather than failing it
                fdno = os.open(self._abs(path), base | os.O_RDONLY)
        except OSError as e:
            raise _fop_errno(e)
        fd = FdObj(self._require_gfid(path), flags, path=path)
        fd.ctx_set(self, fdno)
        return fd

    async def opendir(self, loc: Loc, xdata: dict | None = None):
        path = self._loc_path(loc)
        if not os.path.isdir(self._abs(path)):
            raise FopError(errno.ENOTDIR, path)
        fd = FdObj(self._require_gfid(path), path=path)
        fd.ctx_set(self, None)  # directory fds need no OS handle
        return fd

    def _os_fd(self, fd: FdObj) -> int:
        # a cached os-level fd would happily keep writing into the
        # dead backend's orphaned inodes — fd fops must fail like the
        # path fops so the layers above record blame and fail over
        # (the reference gets this by killing the brick)
        self._health_gate()
        fdno = fd.ctx_get(self)
        if fdno is None:
            # anonymous fd: open on demand via the handle hardlink
            try:
                fdno = os.open(self._gfid_access(fd.gfid), os.O_RDWR)
            except OSError as e:
                raise _fop_errno(e)
            fd.ctx_set(self, fdno)
        return fdno

    async def readv(self, fd: FdObj, size: int, offset: int,
                    xdata: dict | None = None):
        fdno = self._os_fd(fd)  # resolve on the loop (may open-on-demand)
        try:
            out = await self._io(os.pread, fdno, size, offset)
            at = (xdata or {}).get("frame-time-atime")
            if at is not None:  # ctime.noatime off: stamp client atime
                st = await self._io(os.fstat, fdno)
                await self._io(os.utime, fdno, (at, st.st_mtime))
            return out
        except OSError as e:
            raise _fop_errno(e)

    async def writev(self, fd: FdObj, data: bytes, offset: int,
                     xdata: dict | None = None):
        self._check_reserve()
        pre = (xdata or {}).get("pre-xattrop")
        if pre:
            # fallback for graphs with no features/index above (which
            # normally consumes the key): marker before data, same op
            await self.fxattrop(fd, "add64", dict(pre), None)
        fdno = self._os_fd(fd)

        def work():
            view = memoryview(data)
            pos = offset
            while view:
                n = os.pwrite(fdno, view, pos)
                if n <= 0:  # a 0-byte pwrite would loop forever
                    raise FopError(errno.EIO, "short write")
                view = view[n:]
                pos += n

        try:
            await self._io(work)
            ft = (xdata or {}).get("frame-time")
            if ft is not None:
                # client-stamped mtime (features/utime): every brick
                # stores the same instant instead of its own clock's;
                # atime is preserved (POSIX: write leaves atime alone)
                st = await self._io(os.fstat, fdno)
                await self._io(os.utime, fdno, (st.st_atime, ft))
        except OSError as e:
            raise _fop_errno(e)
        return self._iatt_gfid(fd.gfid)

    async def xorv(self, fd: FdObj, data: bytes, offset: int,
                   xdata: dict | None = None):
        """Read-xor-write at a byte offset (the parity-delta write
        plane's brick half, ISSUE 10): the stored bytes become
        ``stored ⊕ data`` in one local pass, so a parity-fragment
        update costs the client ZERO read round trips.  Bytes past EOF
        read as zeros (``0 ⊕ d = d``), so a delta landing on a sparse
        or short region degenerates to a plain write.  The whole op
        runs under one journal batch (the pre-xattrop marker's sidecar
        append coalesces with it).  Write-class and NEVER blindly
        retried: XOR self-cancels on double-apply."""
        self._check_reserve()
        with self.journal_batch():
            pre = (xdata or {}).get("pre-xattrop")
            if pre:
                await self.fxattrop(fd, "add64", dict(pre), None)
            fdno = self._os_fd(fd)

            def work():
                old = b""
                pos = offset
                want = len(data)
                while len(old) < want:
                    chunk = os.pread(fdno, want - len(old),
                                     pos + len(old))
                    if not chunk:
                        break  # EOF: the rest XORs against zeros
                    old += chunk
                buf = bytearray(data)
                if old:
                    x = int.from_bytes(old, "little") ^ \
                        int.from_bytes(buf[: len(old)], "little")
                    buf[: len(old)] = x.to_bytes(len(old), "little")
                view = memoryview(buf)
                pos = offset
                while view:
                    n = os.pwrite(fdno, view, pos)
                    if n <= 0:
                        raise FopError(errno.EIO, "short write")
                    view = view[n:]
                    pos += n

            try:
                await self._io(work)
            except OSError as e:
                raise _fop_errno(e)
        return self._iatt_gfid(fd.gfid)

    async def truncate(self, loc: Loc, size: int, xdata: dict | None = None):
        path = self._loc_path(loc)
        try:
            await self._io(os.truncate, self._abs(path), size)
            ft = (xdata or {}).get("frame-time")
            if ft is not None:
                st = await self._io(os.stat, self._abs(path))
                await self._io(os.utime, self._abs(path),
                               (st.st_atime, ft))
        except OSError as e:
            raise _fop_errno(e)
        return self._iatt(path)

    async def ftruncate(self, fd: FdObj, size: int, xdata: dict | None = None):
        try:
            await self._io(os.ftruncate, self._os_fd(fd), size)
        except OSError as e:
            raise _fop_errno(e)
        return self._iatt_gfid(fd.gfid)

    async def flush(self, fd: FdObj, xdata: dict | None = None):
        return {}

    async def fsync(self, fd: FdObj, datasync: int = 0,
                    xdata: dict | None = None):
        try:
            fdno = fd.ctx_get(self)
            if fdno is not None:
                await self._io(os.fdatasync if datasync else os.fsync, fdno)
        except OSError as e:
            raise _fop_errno(e)
        return {}

    async def fsyncdir(self, fd: FdObj, datasync: int = 0,
                       xdata: dict | None = None):
        return {}

    async def release(self, fd: FdObj) -> None:
        """Close the OS handle (not a wire fop; called by fd tables)."""
        fdno = fd.ctx_del(self)
        if fdno is not None:
            try:
                os.close(fdno)
            except OSError:
                pass

    async def readdir(self, fd: FdObj, size: int = 0, offset: int = 0,
                      xdata: dict | None = None):
        path = self._gfid_resolve(fd.gfid)
        try:
            names = sorted(os.listdir(self._abs(path)))
        except OSError as e:
            raise _fop_errno(e)
        names = [n for n in names if n != META_DIR]
        return [(n, None) for n in names[offset:]]

    async def readdirp(self, fd: FdObj, size: int = 0, offset: int = 0,
                       xdata: dict | None = None):
        path = self._gfid_resolve(fd.gfid)
        entries = await self.readdir(fd, size, offset, xdata)
        out = []
        for name, _ in entries:
            child = path.rstrip("/") + "/" + name
            try:
                out.append((name, self._iatt(child)))
            except FopError:
                continue
        return out

    # -- attrs / xattrs ----------------------------------------------------

    @staticmethod
    def _apply_attrs(ap: str, attrs: dict) -> None:
        if "mode" in attrs:
            os.chmod(ap, attrs["mode"])
        if "uid" in attrs or "gid" in attrs:
            os.chown(ap, attrs.get("uid", -1), attrs.get("gid", -1))
        if "atime" in attrs or "mtime" in attrs:
            st = os.stat(ap)
            now = time.time()  # value None = UTIME_NOW
            a = attrs.get("atime", st.st_atime)
            m = attrs.get("mtime", st.st_mtime)
            os.utime(ap, (now if a is None else a,
                          now if m is None else m))

    async def setattr(self, loc: Loc, attrs: dict, valid: int = 0,
                      xdata: dict | None = None):
        path = self._loc_path(loc)
        try:
            self._apply_attrs(self._abs(path), attrs)
        except OSError as e:
            raise _fop_errno(e)
        return self._iatt(path)

    async def fsetattr(self, fd: FdObj, attrs: dict, valid: int = 0,
                       xdata: dict | None = None):
        try:
            self._apply_attrs(self._gfid_access(fd.gfid), attrs)
        except OSError as e:
            raise _fop_errno(e)
        return self._iatt_gfid(fd.gfid)

    async def setxattr(self, loc: Loc, xattrs: dict, flags: int = 0,
                       xdata: dict | None = None):
        """Values are bytes on the wire (str accepted, stored utf-8).
        flags carry setxattr(2) semantics: XATTR_CREATE fails EEXIST on
        a present key, XATTR_REPLACE fails ENODATA on a missing one
        (lock-like xattr protocols through the mount depend on them)."""
        gfid = self._require_gfid(self._loc_path(loc))
        cur = self._xattr_load(gfid)
        for k, v in xattrs.items():
            if flags & os.XATTR_CREATE and k in cur:
                raise FopError(errno.EEXIST, k)
            if flags & os.XATTR_REPLACE and k not in cur:
                raise FopError(errno.ENODATA, k)
            cur[k] = _hex_val(v)
        self._xattr_store(gfid, cur)
        return {}

    async def fsetxattr(self, fd: FdObj, xattrs: dict, flags: int = 0,
                        xdata: dict | None = None):
        return await self.setxattr(Loc("", gfid=fd.gfid), xattrs, flags, xdata)

    async def getxattr(self, loc: Loc, name: str | None = None,
                       xdata: dict | None = None):
        """Returns {name: bytes}.  The virtual name
        ``glusterfs_tpu.gfid2path`` resolves the loc's gfid to its
        recorded volume path (reference glusterfs.gfid2path virtual
        xattr, posix-inode-fd-ops.c posix_get_gfid2path) — the self-heal
        daemon turns indexed gfids into healable paths with it."""
        if name == XA_GFID2PATH:
            if not loc.gfid:
                raise FopError(errno.EINVAL, "gfid2path needs a gfid loc")
            return {name: self._gfid_resolve(loc.gfid).encode()}
        if name and name.startswith(XA_SCAN_PREFIX):
            # which gfids carry xattr <key>?  (newline-joined hexes) —
            # lets brick layers rebuild in-memory state after a restart
            # (bit-rot-stub's quarantine set)
            key = name[len(XA_SCAN_PREFIX):]
            hexes = []
            # union of compacted files and the live cache (journal-only
            # gfids have no JSON file yet); cache wins on overlap
            cached = {g.hex() for g in self._xa_cache}
            for n in os.listdir(self._xattr_dir):
                if not n.endswith(".json") or n[:-5] in cached:
                    continue
                try:
                    with open(os.path.join(self._xattr_dir, n)) as f:
                        if key in json.load(f):
                            hexes.append(n[:-5])
                except (OSError, ValueError):
                    continue
            for g, xs in self._xa_cache.items():
                if key in xs:
                    hexes.append(g.hex())
            return {name: "\n".join(hexes).encode()}
        gfid = self._require_gfid(self._loc_path(loc))
        cur = self._xattr_load(gfid)
        if name is None:
            return {k: bytes.fromhex(v) for k, v in cur.items()}
        if name not in cur:
            raise FopError(errno.ENODATA, name)
        return {name: bytes.fromhex(cur[name])}

    async def fgetxattr(self, fd: FdObj, name: str | None = None,
                        xdata: dict | None = None):
        return await self.getxattr(Loc("", gfid=fd.gfid), name, xdata)

    async def removexattr(self, loc: Loc, name: str,
                          xdata: dict | None = None):
        gfid = self._require_gfid(self._loc_path(loc))
        cur = self._xattr_load(gfid)
        if name not in cur:
            raise FopError(errno.ENODATA, name)
        del cur[name]
        self._xattr_store(gfid, cur)
        return {}

    async def fremovexattr(self, fd: FdObj, name: str,
                           xdata: dict | None = None):
        return await self.removexattr(Loc("", gfid=fd.gfid), name, xdata)

    async def xattrop(self, loc: Loc, op: str, xattrs: dict,
                      xdata: dict | None = None):
        """Atomic arithmetic on xattr values (reference posix xattrop):
        op 'add64' adds int64s element-wise; 'set' stores; 'mixed' takes
        per-key ``[op, value]`` pairs so independent counters and
        absolute values (EC's version + size) commit in ONE atomic store
        — the reference packs them into a single xattrop dict the same
        way (ec_update_info).  Returns the resulting values."""
        gfid = self._require_gfid(self._loc_path(loc))
        cur = self._xattr_load(gfid)
        out: dict[str, bytes] = {}
        for key, spec in xattrs.items():
            if op == "mixed":
                kop, val = spec[0], spec[1]
            else:
                kop, val = op, spec
            if kop == "add64":
                old = bytes.fromhex(cur.get(key, "")) if key in cur else b""
                n = max(len(old), len(val)) // 8
                olds = list(struct.unpack(f">{n}q", old.ljust(n * 8, b"\0")))
                adds = struct.unpack(f">{n}q", val.ljust(n * 8, b"\0"))
                news = [a + b for a, b in zip(olds, adds)]
                res = struct.pack(f">{n}q", *news)
            elif kop == "set":
                res = val
            else:
                raise FopError(errno.EINVAL, f"xattrop op {kop!r}")
            cur[key] = res.hex()
            out[key] = res
        self._xattr_store(gfid, cur)
        return out

    async def fxattrop(self, fd: FdObj, op: str, xattrs: dict,
                       xdata: dict | None = None):
        return await self.xattrop(Loc("", gfid=fd.gfid), op, xattrs, xdata)

    # -- misc --------------------------------------------------------------

    async def access(self, loc: Loc, mask: int = 0, xdata: dict | None = None):
        if not os.access(self._abs(self._loc_path(loc)), mask):
            raise FopError(errno.EACCES, self._loc_path(loc))
        return {}

    async def statfs(self, loc: Loc, xdata: dict | None = None):
        # a dead disk's cached statvfs would keep min-free-disk
        # placing data here
        self._health_gate()
        try:
            sv = os.statvfs(self.root)
        except OSError as e:
            raise _fop_errno(e)
        return {"bsize": sv.f_bsize, "blocks": sv.f_blocks,
                "bfree": sv.f_bfree, "bavail": sv.f_bavail,
                "files": sv.f_files, "ffree": sv.f_ffree}

    async def seek(self, fd: FdObj, offset: int, what: str = "data",
                   xdata: dict | None = None):
        whence = os.SEEK_DATA if what == "data" else os.SEEK_HOLE
        try:
            return os.lseek(self._os_fd(fd), offset, whence)
        except OSError as e:
            raise _fop_errno(e)

    async def fallocate(self, fd: FdObj, mode: int, offset: int, length: int,
                        xdata: dict | None = None):
        """fallocate(2) with real mode flags via libc (posix_fallocate
        ignores FALLOC_FL_KEEP_SIZE and would grow the file)."""
        try:
            await self._io(_sys_fallocate, self._os_fd(fd), mode, offset,
                           length)
        except OSError as e:
            raise _fop_errno(e)
        return self._iatt_gfid(fd.gfid)

    async def discard(self, fd: FdObj, offset: int, length: int,
                      xdata: dict | None = None):
        """Punch a hole: FALLOC_FL_PUNCH_HOLE|KEEP_SIZE frees the blocks
        (posix_discard); falls back to zero-writing where the filesystem
        cannot punch."""
        fdno = self._os_fd(fd)
        try:
            await self._io(_sys_fallocate, fdno,
                           FALLOC_FL_PUNCH_HOLE | FALLOC_FL_KEEP_SIZE,
                           offset, length)
            return self._iatt_gfid(fd.gfid)
        except OSError:
            return await self.zerofill(fd, offset, length, xdata)

    async def zerofill(self, fd: FdObj, offset: int, length: int,
                       xdata: dict | None = None):
        try:
            await self._io(os.pwrite, self._os_fd(fd), b"\0" * length,
                           offset)
        except OSError as e:
            raise _fop_errno(e)
        return self._iatt_gfid(fd.gfid)

    async def rchecksum(self, fd: FdObj, offset: int, length: int,
                        xdata: dict | None = None):
        """Weak (adler32) + strong (sha256) checksum of a byte range —
        the posix_rchecksum fop (libglusterfs checksum.c): heal
        compares block checksums across bricks instead of shipping
        the bytes."""
        from ..ops.checksum import rchecksum as _rck

        data = await self.readv(fd, length, offset)
        return {**_rck(data, fips=self.opts["fips-mode-rchecksum"]),
                "len": len(data)}

    async def ipc(self, op: int = 0, xdata: dict | None = None):
        return {}

    async def icreate(self, loc: Loc, mode: int = 0o644,
                      xdata: dict | None = None):
        return await self.mknod(loc, mode, 0, xdata)

    async def put(self, loc: Loc, data: bytes, flags: int = 0,
                  mode: int = 0o644, xattrs: dict | None = None,
                  xdata: dict | None = None):
        fd, ia = await self.create(loc, flags, mode, xdata)
        try:
            await self.writev(fd, data, 0)
            if xattrs:
                await self.setxattr(loc, xattrs)
            return self._iatt(self._loc_path(loc))
        finally:
            await self.release(fd)

    async def copy_file_range(self, fd_in: FdObj, off_in: int, fd_out: FdObj,
                              off_out: int, length: int,
                              xdata: dict | None = None):
        data = await self.readv(fd_in, length, off_in)
        await self.writev(fd_out, data, off_out)
        return len(data)

    def dump_private(self) -> dict:
        return {"root": self.root,
                "gfids": len(os.listdir(self._gfid_dir))
                if os.path.isdir(self._gfid_dir) else 0}
