"""gftpu-meshd: multi-process ``jax.distributed`` coordinator glue.

The PR-8 ``Mesh(dp, frag)`` codec plane ran ONE jax runtime over every
visible device — which on a multi-host (or multi-brick-process) layout
means one interpreter owns all of them.  ``cluster.mesh-distributed``
(op-version 14) flips that: each brick daemon is one **process** of a
``jax.distributed`` job, binding its own local device(s), with the
coordinator riding brick 0's node.  The mesh then spans interpreters —
``jax.devices()`` is the GLOBAL device list, collectives cross process
boundaries over the distributed runtime, and the same
``parallel/mesh_codec`` programs shard over all of it (SNIPPETS.md
[1]/[3]: partition-rule maps + SPMD partitioner wrappers are exactly
this shape).

Wiring (mgmt/glusterd.py ``_mesh_env``): the brick spawner exports

    GFTPU_MESH_COORDINATOR = host:port      (brick 0's node)
    GFTPU_MESH_PROCESSES   = <brick count>
    GFTPU_MESH_RANK        = <brick index>

and the brick daemon calls :func:`maybe_initialize` at startup.  The
init runs on a BACKGROUND daemon thread with a hard deadline — the
wedge-safety rule every jax touchpoint in this tree follows
(ops/codec.probe_with_deadline): glusterd spawns bricks one at a time
awaiting each port, so a rank that blocked startup waiting for its
siblings would deadlock the whole volume start.  A rank that cannot
join within the deadline logs, stays single-process, and serves —
degraded to the PR-8 one-runtime plane, never wedged.

On CPU hosts the distributed backend needs a collectives
implementation; :func:`initialize` arms gloo (the only one this jaxlib
ships for CPU) before backend init — without it a multi-process CPU
mesh fails at dispatch with "Multiprocess computations aren't
implemented on the CPU backend".
"""

from __future__ import annotations

import os
import threading
import time

from ..core import gflog
from ..core import metrics as _metrics

log = gflog.get_logger("meshd")

ENV_COORDINATOR = "GFTPU_MESH_COORDINATOR"
ENV_PROCESSES = "GFTPU_MESH_PROCESSES"
ENV_RANK = "GFTPU_MESH_RANK"

#: distributed-init lifecycle: off (no env / never asked) -> joining ->
#: ready / failed
_state = {"status": "off", "coordinator": "", "processes": 0,
          "rank": -1, "error": ""}
_lock = threading.Lock()

_STATUS_GAUGE = {"off": 0, "joining": 1, "ready": 2, "failed": 3}

_metrics.REGISTRY.register(
    "gftpu_mesh_distributed", "gauge",
    "jax.distributed join state of this process "
    "(0 off, 1 joining, 2 ready, 3 failed; labels carry the job "
    "shape)",
    lambda: [({"coordinator": _state["coordinator"],
               "rank": str(_state["rank"]),
               "processes": str(_state["processes"])},
              _STATUS_GAUGE.get(_state["status"], 0))])


def state() -> dict:
    """A copy of the join state (statedumps / tests)."""
    with _lock:
        return dict(_state)


def configured(env=None) -> dict | None:
    """The job shape from the environment, or None when the brick was
    not spawned into a distributed mesh."""
    env = os.environ if env is None else env
    coord = env.get(ENV_COORDINATOR, "")
    if not coord:
        return None
    try:
        return {"coordinator": coord,
                "processes": int(env.get(ENV_PROCESSES, "1")),
                "rank": int(env.get(ENV_RANK, "0"))}
    except ValueError:
        log.warning(2, "malformed mesh env (%s=%r %s=%r); ignoring",
                    ENV_PROCESSES, env.get(ENV_PROCESSES),
                    ENV_RANK, env.get(ENV_RANK))
        return None


def arm_cpu_collectives() -> None:
    """Select gloo CPU collectives BEFORE the backend initializes (a
    no-op when jax already picked a platform with its own collectives,
    or on jax builds without the flag)."""
    try:
        import jax

        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - older jax: flag absent
        pass


def initialize(coordinator: str, num_processes: int, rank: int,
               timeout_s: float = 60.0) -> bool:
    """Join the distributed job; True on success.  BLOCKS up to
    ``timeout_s`` (jax's own initialization_timeout) — daemons must
    call :func:`maybe_initialize` instead, which runs this on a
    background thread."""
    with _lock:
        _state.update({"status": "joining", "coordinator": coordinator,
                       "processes": int(num_processes),
                       "rank": int(rank), "error": ""})
    try:
        arm_cpu_collectives()
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(num_processes), process_id=int(rank),
            initialization_timeout=max(1, int(timeout_s)))
        with _lock:
            _state["status"] = "ready"
        log.info(2, "joined distributed mesh %s as rank %d/%d",
                 coordinator, rank, num_processes)
        return True
    except Exception as e:  # noqa: BLE001 - stay single-process
        with _lock:
            _state.update({"status": "failed",
                           "error": repr(e)[:300]})
        log.warning(1, "distributed mesh join failed (%s rank %d): "
                    "%r — serving single-process", coordinator, rank, e)
        return False


def maybe_initialize(coordinator: str = "", num_processes: int = 0,
                     rank: int = -1,
                     timeout_s: float = 60.0) -> bool:
    """Non-blocking join: explicit args, or the spawner's environment.
    Returns True when a background join was STARTED (not when it
    succeeded — poll :func:`state`/``await``-loop for that).  Idempotent:
    a second call while joining/ready is a no-op."""
    if not coordinator:
        cfg = configured()
        if cfg is None:
            return False
        coordinator = cfg["coordinator"]
        num_processes = cfg["processes"]
        rank = cfg["rank"]
    with _lock:
        if _state["status"] in ("joining", "ready"):
            return False
        # mark joining BEFORE the thread starts, under the lock: a
        # probe thread observing 'off' in the spawn window would treat
        # the join as absent (settle_before_backend_init returns, the
        # probe initializes a single-process backend, the join fails
        # forever) — and a concurrent second maybe_initialize would
        # start a duplicate join whose loser overwrites the winner
        _state.update({"status": "joining", "coordinator": coordinator,
                       "processes": int(num_processes),
                       "rank": int(rank), "error": ""})
    threading.Thread(
        target=initialize,
        args=(coordinator, num_processes, rank, timeout_s),
        daemon=True, name=f"gftpu-meshd-join-{rank}").start()
    return True


def settle_before_backend_init(max_wait_s: float = 75.0) -> None:
    """Block THIS thread until a configured background join reaches a
    terminal state.  ``jax.distributed.initialize`` must run before
    the process's FIRST jax backend init — but the wedge-safe device
    probes (mesh_codec.device_count, codec._tpu_present) run on their
    own abandonable threads and may win that race, initializing a
    single-process backend and making the join fail forever.  Every
    backend-touching probe calls this first: a no-op outside a
    distributed job (and after the join settles), a bounded wait on
    the probe's OWN thread otherwise — the probe's abandon deadline
    still caps the caller.  If the join was configured but not yet
    started (import-order corner), it is started here (idempotent)."""
    if configured() is None:
        return
    if state()["status"] == "off":
        maybe_initialize()
    deadline = time.monotonic() + max_wait_s
    while time.monotonic() < deadline:
        if state()["status"] in ("ready", "failed", "off"):
            return
        time.sleep(0.1)


def wait_ready(timeout_s: float = 60.0) -> bool:
    """Poll the background join to a terminal state (tests/dryrun)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        st = state()["status"]
        if st == "ready":
            return True
        if st in ("failed", "off"):
            return False
        time.sleep(0.05)
    return False


# ---------------------------------------------------------------------------
# one-rank proof step (the dryrun's 2-process virtual-mesh attempt and
# tests/test_process_plane.py's handshake unit both exec this)
# ---------------------------------------------------------------------------


def rank_step(coordinator: str, num_processes: int, rank: int,
              k: int = 4, r: int = 2, stripes: int = 8) -> None:
    """Join a (virtual, CPU) distributed job and push ONE sharded
    encode through the global mesh — the cross-interpreter analog of
    ``__graft_entry__._dryrun_inline``'s raw-array step.

    Every rank builds the same deterministic stripe batch, contributes
    its dp-slice as its local shard, jits the shared
    ``mesh_codec._encode_fn`` over the GLOBAL mesh (dp = process
    count), and verifies its addressable output shards byte-for-byte
    against the single-process reference encode — proving the
    coordinator handshake AND that one sharded encode landed across
    interpreters.  Raises on any mismatch; the caller owns deadlines
    (it runs in a kill-able subprocess)."""
    import numpy as np

    if not initialize(coordinator, num_processes, rank,
                      timeout_s=45.0):
        raise RuntimeError(f"rank {rank}: distributed init failed: "
                           f"{state()['error']}")
    import jax

    assert jax.process_count() == num_processes, (
        jax.process_count(), num_processes)
    devs = jax.devices()  # GLOBAL: one cpu device per process
    assert len(devs) >= num_processes, len(devs)

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..ops import gf256
    from . import mesh_codec

    n = k + r
    mesh = Mesh(np.asarray(devs[:num_processes]).reshape(
        num_processes, 1), ("dp", "frag"))
    rng = np.random.default_rng(7)  # same bytes on every rank
    data = rng.integers(0, 256,
                        stripes * k * gf256.CHUNK_SIZE, dtype=np.uint8)
    x = data.reshape(stripes, k * 8, gf256.WORD_SIZE)
    per = stripes // num_processes
    local = x[rank * per:(rank + 1) * per]
    sharding = NamedSharding(mesh, P("dp", None, None))
    arr = jax.make_array_from_single_device_arrays(
        x.shape, sharding,
        [jax.device_put(local, jax.local_devices()[0])])
    fn = mesh_codec._encode_fn(k, n, mesh)
    y = fn(arr)  # (n*8, stripes, 64) sharded P("frag", "dp", None)
    # reference: the single-process systematic-free encode, re-laid
    # out plane-major (the inverse of sharded_encode's wire transform)
    frags = gf256.ref_encode(data, k, n)
    expect = frags.reshape(n, stripes, 8, gf256.WORD_SIZE) \
        .transpose(0, 2, 1, 3).reshape(n * 8, stripes,
                                       gf256.WORD_SIZE)
    checked = 0
    for shard in y.addressable_shards:
        got = np.asarray(shard.data)
        if not np.array_equal(got, expect[shard.index]):
            raise AssertionError(
                f"rank {rank}: sharded encode mismatch at "
                f"{shard.index}")
        checked += 1
    if checked == 0:
        raise AssertionError(f"rank {rank}: no addressable shards")
    print(f"meshd rank {rank}/{num_processes}: ok "
          f"({checked} shards verified)", flush=True)
