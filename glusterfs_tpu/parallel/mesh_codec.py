"""Sharded erasure-codec data plane over a TPU device mesh.

The reference's scale-out data plane is socket fan-out: a write scatters N
encoded fragments to N bricks, a degraded read gathers any K and decodes
(reference xlators/cluster/ec/src/ec-common.c:816-900 dispatch_all /
dispatch_min).  On a TPU pod the same dataflow is mesh-sharded compute:

* mesh axis ``dp`` — stripe batches (many concurrent fops coalesced), the
  data-parallel axis;
* mesh axis ``frag`` — the fragment dimension: each device computes/holds
  the fragments bound for its bricks, so the encode *is* the scatter (the
  tensor-parallel analog; XLA inserts the collectives that replace the
  reference's per-brick socket writes).

Decode reads fragments sharded over ``frag`` and reduces across them —
an all-gather over ICI replacing ``ec_dispatch_min`` network reads.

Everything is jit + NamedSharding; no data-dependent control flow.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gf256


def make_mesh(devices=None) -> Mesh:
    """Factor the device list into a (dp, frag) mesh."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    frag = 2 if n % 2 == 0 and n > 1 else 1
    dp = n // frag
    return Mesh(np.asarray(devices).reshape(dp, frag), ("dp", "frag"))


def _unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(*x.shape[:-1], x.shape[-1] * 8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    w8 = bits.shape[-1]
    b = bits.reshape(*bits.shape[:-1], w8 // 8, 8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    return (b * weights).sum(axis=-1, dtype=jnp.uint8)


def _apply(abits: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """(R*8, C*8) bitmatrix applied to batched chunks (B, C*8, 64)
    -> (B, R*8, 64); int8 matmul mod 2 (MXU)."""
    bits = _unpack_bits(x).astype(jnp.int8)  # (B, C8, 512)
    y = jnp.einsum("rc,bcw->brw", abits.astype(jnp.int8), bits,
                   preferred_element_type=jnp.int32)
    return _pack_bits((y & 1).astype(jnp.uint8))


@functools.lru_cache(maxsize=32)
def sharded_step_fn(k: int, r: int, mesh: Mesh):
    """One full data-plane step, jitted over the mesh.

    step(batch) with batch (B, k*8, 64) uint8 (B stripes, sharded over dp):
      1. encode -> fragments (n*8, B, 64), sharded over (frag, dp) — the
         scatter-to-bricks layout;
      2. degraded decode: reconstruct from the LAST k fragments (i.e. the
         k data fragments 0..r-1 all lost — worst-case reconstruction);
      3. parity: count mismatched bytes vs the input (must be 0).

    Returns (fragments, mismatches).  The decode forces an all-gather of
    fragment shards across ``frag``; the mismatch reduce crosses ``dp`` —
    both ride ICI like the reference's fan-in rides sockets.
    """
    n = k + r
    abits = jnp.asarray(gf256.expand_bitmatrix(gf256.encode_matrix(k, n)))
    rows = tuple(range(r, r + k))
    bbits = jnp.asarray(gf256.decode_bits_cached(k, rows))

    def step(batch):
        frags = _apply(abits, batch)              # (B, n*8, 64)
        frags = jnp.transpose(frags, (1, 0, 2))   # (n*8, B, 64) frag-major
        surv = frags.reshape(n, 8, *frags.shape[1:])[np.asarray(rows)]
        surv = surv.reshape(k * 8, *frags.shape[1:])
        surv = jnp.transpose(surv, (1, 0, 2))     # (B, k*8, 64)
        out = _apply(bbits, surv)                 # (B, k*8, 64)
        mism = jnp.sum((out != batch).astype(jnp.int32))
        return frags, mism

    in_s = NamedSharding(mesh, P("dp", None, None))
    out_s = (NamedSharding(mesh, P("frag", "dp", None)),
             NamedSharding(mesh, P()))
    return jax.jit(step, in_shardings=in_s, out_shardings=out_s)


def run_step(k: int, r: int, batch: np.ndarray, mesh: Mesh | None = None):
    """Convenience wrapper: shard, run, return (frags, mismatches)."""
    if mesh is None:
        mesh = make_mesh()
    fn = sharded_step_fn(k, r, mesh)
    frags, mism = fn(jnp.asarray(batch))
    return frags, int(mism)


@functools.lru_cache(maxsize=32)
def _encode_fn(k: int, n: int, mesh: Mesh):
    """Jitted encode, stripes sharded over ``dp``, fragments laid out
    over ``frag`` — the encode IS the scatter-to-bricks step."""
    abits = jnp.asarray(gf256.expand_bitmatrix(gf256.encode_matrix(k, n)))
    in_s = NamedSharding(mesh, P("dp", None, None))
    out_s = NamedSharding(mesh, P("frag", "dp", None))
    return jax.jit(
        lambda x: jnp.transpose(_apply(abits, x), (1, 0, 2)),
        in_shardings=in_s, out_shardings=out_s)


def sharded_encode(k: int, r: int, data: np.ndarray,
                   mesh: Mesh | None = None) -> np.ndarray:
    """Encode stripe-aligned bytes into wire-layout fragments
    ``(n, S*512)`` with stripes sharded over the mesh's ``dp`` axis and
    the fragment dimension over ``frag`` (the served-volume entry point
    the BatchingCodec's ``mesh`` backend feeds)."""
    if mesh is None:
        mesh = make_mesh()
    n = k + r
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    s = data.size // (k * gf256.CHUNK_SIZE)
    x = data.reshape(s, k * 8, gf256.WORD_SIZE)
    dp = mesh.devices.shape[0]
    pad = (-s) % dp
    if pad:
        x = np.concatenate(
            [x, np.zeros((pad, *x.shape[1:]), dtype=np.uint8)], axis=0)
    y = np.asarray(_encode_fn(k, n, mesh)(jnp.asarray(x)))  # (n*8, S', 64)
    y = y[:, :s, :]
    # plane-major -> wire fragment-major (n, S*512): fragment f's chunk
    # for stripe s' interleaves its 8 planes (same transform as the
    # single-chip sandwich, gf256_pallas._encode_fn)
    return (y.reshape(n, 8, s, gf256.WORD_SIZE)
             .transpose(0, 2, 1, 3)
             .reshape(n, s * gf256.CHUNK_SIZE))


@functools.lru_cache(maxsize=256)
def _decode_fn(k: int, rows: tuple[int, ...], mesh: Mesh):
    """Jitted degraded decode for one surviving mask, stripes sharded
    over ``dp`` (the LRU of per-mask jitted decoders mirrors the
    reference's LRU of inverted matrices, ec-method.c:200-245)."""
    bbits = jnp.asarray(gf256.decode_bits_cached(k, rows))
    sharding = NamedSharding(mesh, P("dp", None, None))
    return jax.jit(
        lambda x: _apply(bbits, x),
        in_shardings=sharding, out_shardings=sharding)


def sharded_decode(
    k: int,
    rows,
    frags: np.ndarray,
    mesh: Mesh | None = None,
) -> np.ndarray:
    """Decode k surviving fragments (fragment-major, (k, S*512)) into the
    original (S*k*512,) bytes, sharded over the mesh's ``dp`` axis.

    ``rows`` are the surviving fragment indices (any order-preserving
    k-subset of 0..n-1) — the ``ec_dispatch_min`` answer set.
    """
    if mesh is None:
        mesh = make_mesh()
    rows = tuple(int(x) for x in rows)
    x = gf256.frags_to_planes(frags, k)  # (S, k*8, 64), validates shape
    s = x.shape[0]
    dp = mesh.devices.shape[0]
    pad = (-s) % dp  # dp-sharded input must divide evenly; pad + trim
    if pad:
        x = np.concatenate(
            [x, np.zeros((pad, *x.shape[1:]), dtype=np.uint8)], axis=0)
    y = _decode_fn(k, rows, mesh)(jnp.asarray(x))
    return np.asarray(y)[:s].reshape(s * k * gf256.CHUNK_SIZE)
