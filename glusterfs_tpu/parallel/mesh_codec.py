"""Sharded erasure-codec data plane over a TPU device mesh.

The reference's scale-out data plane is socket fan-out: a write scatters N
encoded fragments to N bricks, a degraded read gathers any K and decodes
(reference xlators/cluster/ec/src/ec-common.c:816-900 dispatch_all /
dispatch_min).  On a TPU pod the same dataflow is mesh-sharded compute:

* mesh axis ``dp`` — stripe batches (many concurrent fops coalesced), the
  data-parallel axis;
* mesh axis ``frag`` — the fragment dimension: each device computes/holds
  the fragments bound for its bricks, so the encode *is* the scatter (the
  tensor-parallel analog; XLA inserts the collectives that replace the
  reference's per-brick socket writes).

Decode reads fragments sharded over ``frag`` and reduces across them —
an all-gather over ICI replacing ``ec_dispatch_min`` network reads.

Everything is jit + NamedSharding; no data-dependent control flow.
"""

from __future__ import annotations

import functools
import threading as _threading
import time as _time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import metrics as _metrics
from ..ops import gf256


def make_mesh(devices=None) -> Mesh:
    """Factor the device list into a (dp, frag) mesh."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    frag = 2 if n % 2 == 0 and n > 1 else 1
    dp = n // frag
    return Mesh(np.asarray(devices).reshape(dp, frag), ("dp", "frag"))


# -- wedge-safe device discovery + the process mesh ---------------------
#
# The serving path (ops/batch.BatchingCodec's mesh backend) must decide
# per flush whether a multi-device mesh exists — but asking jax for
# devices can hang forever on a wedged accelerator transport (the
# pool-tunnel failure that cost MULTICHIP_r05 its record).  So device
# discovery here is the same deadline-probe shape as ops/codec:
#
# * ``device_count()`` probes ONCE on an abandonable daemon thread and
#   caches a clean answer for the process lifetime (a timeout caches a
#   wedged 0 for _COUNT_RETRY_S, like codec._tpu_present);
# * ``device_count_cached()`` never blocks: it reports the cached
#   answer or 0-unprobed — the event-loop-side routing check
#   (BatchingCodec._route) uses ONLY this, so an unprobed or wedged
#   transport routes flushes down the existing ladder instead of
#   stalling fops behind a 45 s join.

_count_state: list = []  # [(expires_monotonic|None, count)]
_local_count_state: list = []  # same shape, jax.local_devices()
_COUNT_RETRY_S = 300.0


def _probed_count(state: list, fn, default_timeout_s: float) -> int:
    """Shared deadline-probe + cache for a device-count callable."""
    if state:
        expires, n = state[0]
        if expires is None or _time.monotonic() < expires:
            return n
    from ..ops.codec import probe_with_deadline

    # default -1 separates "fn raised" from a real 0-device answer:
    # both a timeout AND a transient error (plugin registration race at
    # startup) cache 0 only for _COUNT_RETRY_S — a clean answer caches
    # for the process lifetime
    n, timed_out = probe_with_deadline(fn, -1, default_timeout_s)
    if timed_out or n < 0:
        state[:] = [(_time.monotonic() + _COUNT_RETRY_S, 0)]
        return 0
    state[:] = [(None, int(n))]
    return state[0][1]


def device_count(default_timeout_s: float = 45.0) -> int:
    """Count ALL jax devices behind a deadline probe; cached.

    The distributed path (``cluster.mesh-distributed`` /
    parallel/meshd.py): once this process joined a ``jax.distributed``
    job, ``jax.devices()`` is the GLOBAL device list across every
    member process — exactly what the mesh tier must size its (dp,
    frag) plane over, since the whole point is one mesh spanning
    interpreters.  :func:`local_device_count` answers the
    this-process-only question (what the pre-14 single-runtime plane
    effectively saw)."""
    def count() -> int:
        # a configured-but-unsettled jax.distributed join must run
        # BEFORE the first backend init — this probe thread is
        # abandonable, so waiting here is safe (meshd no-ops outside
        # a distributed job)
        from . import meshd

        meshd.settle_before_backend_init()
        return len(jax.devices())

    return _probed_count(_count_state, count, default_timeout_s)


def local_device_count(default_timeout_s: float = 45.0) -> int:
    """Devices bound to THIS process (``jax.local_devices()``) — under
    a distributed mesh, one brick's share of the global plane; equal to
    :func:`device_count` in a single-process runtime.  Same wedge-safe
    deadline probing and caching as the global count."""
    def count() -> int:
        from . import meshd

        meshd.settle_before_backend_init()
        return len(jax.local_devices())

    return _probed_count(_local_count_state, count, default_timeout_s)


def device_count_cached() -> int:
    """The cached device count, 0 if never (successfully) probed.
    Never touches jax — safe on the event loop."""
    if _count_state:
        expires, n = _count_state[0]
        if expires is None or _time.monotonic() < expires:
            return n
    return 0


def device_count_transient() -> bool:
    """True while the cached answer is a RETRYABLE 0 (timeout or
    transient error, expiring after _COUNT_RETRY_S) rather than a clean
    for-the-process-lifetime count — warm loops key their retry on
    this."""
    return bool(_count_state) and _count_state[0][0] is not None


_process_mesh: list = []  # [Mesh] once built


def default_mesh() -> Mesh:
    """The process-wide (dp, frag) mesh over every visible device.

    Only call after ``device_count()`` answered cleanly (jax is then
    already initialized, so ``jax.devices()`` cannot block on backend
    init) — the BatchingCodec orders its calls exactly that way."""
    if not _process_mesh:
        _process_mesh.append(make_mesh())
    return _process_mesh[0]


def _mesh_device_samples():
    """gftpu_mesh_devices scrape: cached state only — a registry scrape
    must never trigger a jax probe."""
    if _process_mesh:
        dp, frag = _process_mesh[0].devices.shape
        return [({"axis": "total"}, dp * frag), ({"axis": "dp"}, dp),
                ({"axis": "frag"}, frag)]
    return [({"axis": "total"}, device_count_cached())]


_metrics.REGISTRY.register(
    "gftpu_mesh_devices", "gauge",
    "devices in the (dp, frag) codec mesh (total/dp/frag; total only "
    "until the mesh is built)", _mesh_device_samples)

# Serializes the jitted mesh-program CALLS, not just their
# construction: jax.jit is LAZY — the real trace + compile happens at
# the first call (and again per new input shape), so a lock released
# before ``fn(...)`` would still let the BatchingCodec's two flush
# workers race an encode and a decode first-compile (observed once as
# a pybind11 instance-allocation failure under e2e load).  Holding the
# lock across the call costs little: the backend serializes on-device
# execution anyway, and shape bucketing (ops/batch) bounds how often a
# call is a compile at all.
# graft-race GL07 machine-checks this extent now: the jit factories
# are tables.KNOWN_LAZY rows and every lock-spans-the-call site below
# is a declared tables.LAZY_UNDER_LOCK_OK row — shrinking the lock
# back off the call fails lint instead of reintroducing the empty
# critical region.
_BUILD_LOCK = _threading.Lock()


def _unpack_bits(x: jnp.ndarray) -> jnp.ndarray:
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (x[..., None] >> shifts) & jnp.uint8(1)
    return bits.reshape(*x.shape[:-1], x.shape[-1] * 8)


def _pack_bits(bits: jnp.ndarray) -> jnp.ndarray:
    w8 = bits.shape[-1]
    b = bits.reshape(*bits.shape[:-1], w8 // 8, 8)
    weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
    return (b * weights).sum(axis=-1, dtype=jnp.uint8)


def _apply(abits: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """(R*8, C*8) bitmatrix applied to batched chunks (B, C*8, 64)
    -> (B, R*8, 64); int8 matmul mod 2 (MXU)."""
    bits = _unpack_bits(x).astype(jnp.int8)  # (B, C8, 512)
    y = jnp.einsum("rc,bcw->brw", abits.astype(jnp.int8), bits,
                   preferred_element_type=jnp.int32)
    return _pack_bits((y & 1).astype(jnp.uint8))


@functools.lru_cache(maxsize=32)
def sharded_step_fn(k: int, r: int, mesh: Mesh):
    """One full data-plane step, jitted over the mesh.

    step(batch) with batch (B, k*8, 64) uint8 (B stripes, sharded over dp):
      1. encode -> fragments (n*8, B, 64), sharded over (frag, dp) — the
         scatter-to-bricks layout;
      2. degraded decode: reconstruct from the LAST k fragments (i.e. the
         k data fragments 0..r-1 all lost — worst-case reconstruction);
      3. parity: count mismatched bytes vs the input (must be 0).

    Returns (fragments, mismatches).  The decode forces an all-gather of
    fragment shards across ``frag``; the mismatch reduce crosses ``dp`` —
    both ride ICI like the reference's fan-in rides sockets.
    """
    n = k + r
    abits = jnp.asarray(gf256.expand_bitmatrix(gf256.encode_matrix(k, n)))
    rows = tuple(range(r, r + k))
    bbits = jnp.asarray(gf256.decode_bits_cached(k, rows))

    def step(batch):
        frags = _apply(abits, batch)              # (B, n*8, 64)
        frags = jnp.transpose(frags, (1, 0, 2))   # (n*8, B, 64) frag-major
        surv = frags.reshape(n, 8, *frags.shape[1:])[np.asarray(rows)]
        surv = surv.reshape(k * 8, *frags.shape[1:])
        surv = jnp.transpose(surv, (1, 0, 2))     # (B, k*8, 64)
        out = _apply(bbits, surv)                 # (B, k*8, 64)
        mism = jnp.sum((out != batch).astype(jnp.int32))
        return frags, mism

    in_s = NamedSharding(mesh, P("dp", None, None))
    out_s = (NamedSharding(mesh, P("frag", "dp", None)),
             NamedSharding(mesh, P()))
    return jax.jit(step, in_shardings=in_s, out_shardings=out_s)


def run_step(k: int, r: int, batch: np.ndarray, mesh: Mesh | None = None):
    """Convenience wrapper: shard, run, return (frags, mismatches)."""
    if mesh is None:
        mesh = make_mesh()
    with _BUILD_LOCK:
        fn = sharded_step_fn(k, r, mesh)
        frags, mism = fn(jnp.asarray(batch))
    return frags, int(mism)


@functools.lru_cache(maxsize=32)
def _encode_fn(k: int, n: int, mesh: Mesh):
    """Jitted encode, stripes sharded over ``dp``, fragments laid out
    over ``frag`` — the encode IS the scatter-to-bricks step."""
    abits = jnp.asarray(gf256.expand_bitmatrix(gf256.encode_matrix(k, n)))
    in_s = NamedSharding(mesh, P("dp", None, None))
    out_s = NamedSharding(mesh, P("frag", "dp", None))
    return jax.jit(
        lambda x: jnp.transpose(_apply(abits, x), (1, 0, 2)),
        in_shardings=in_s, out_shardings=out_s)


@functools.lru_cache(maxsize=32)
def _parity_fn(k: int, n: int, mesh: Mesh):
    """Jitted PARITY-ROWS-ONLY encode for the systematic layout
    (ISSUE 12 / ROADMAP item 5): the k data rows of a systematic code
    are verbatim stripe chunks — a host reshape, no math — so the mesh
    computes (and the interconnect carries) only the r parity
    fragments, sharded exactly like the full encode: stripes over
    ``dp``, the (parity) fragment dimension over ``frag``."""
    pbits = jnp.asarray(gf256.parity_bits_cached(k, n))
    in_s = NamedSharding(mesh, P("dp", None, None))
    out_s = NamedSharding(mesh, P("frag", "dp", None))
    return jax.jit(
        lambda x: jnp.transpose(_apply(pbits, x), (1, 0, 2)),
        in_shardings=in_s, out_shardings=out_s)


def _planes_to_wire(y: np.ndarray, rows: int, s: int) -> np.ndarray:
    """Plane-major (rows*8, S, 64) -> wire fragment-major
    (rows, S*512): fragment f's chunk for stripe s' interleaves its 8
    planes (same transform as the single-chip sandwich,
    gf256_pallas._encode_fn)."""
    return (y.reshape(rows, 8, s, gf256.WORD_SIZE)
             .transpose(0, 2, 1, 3)
             .reshape(rows, s * gf256.CHUNK_SIZE))


def sharded_encode(k: int, r: int, data: np.ndarray,
                   mesh: Mesh | None = None,
                   systematic: bool = False) -> np.ndarray:
    """Encode stripe-aligned bytes into wire-layout fragments
    ``(n, S*512)`` with stripes sharded over the mesh's ``dp`` axis and
    the fragment dimension over ``frag`` (the served-volume entry point
    the BatchingCodec's ``mesh`` backend feeds).

    ``systematic=True`` is the parity-rows-only lane: the mesh launch
    computes just the r parity fragments and the k data fragments are
    assembled host-side as pure reshapes — fragment-identical to the
    single-device systematic encode (property-pinned in
    tests/test_process_plane.py)."""
    if mesh is None:
        mesh = make_mesh()
    n = k + r
    data = np.ascontiguousarray(data, dtype=np.uint8).ravel()
    s = data.size // (k * gf256.CHUNK_SIZE)
    x = data.reshape(s, k * 8, gf256.WORD_SIZE)
    dp = mesh.devices.shape[0]
    pad = (-s) % dp
    if pad:
        x = np.concatenate(
            [x, np.zeros((pad, *x.shape[1:]), dtype=np.uint8)], axis=0)
    if systematic:
        with _BUILD_LOCK:
            y = np.asarray(_parity_fn(k, n, mesh)(jnp.asarray(x)))
        y = y[:, :s, :]  # (r*8, S, 64) parity planes
        out = np.empty((n, s * gf256.CHUNK_SIZE), dtype=np.uint8)
        # data rows: verbatim stripe chunks (ops/codec._data_rows)
        out[:k] = np.ascontiguousarray(
            data.reshape(s, k, gf256.CHUNK_SIZE)
                .transpose(1, 0, 2)).reshape(k, s * gf256.CHUNK_SIZE)
        out[k:] = _planes_to_wire(y, r, s)
        return out
    with _BUILD_LOCK:
        y = np.asarray(_encode_fn(k, n, mesh)(jnp.asarray(x)))
    # y: (n*8, S', 64)
    y = y[:, :s, :]
    return _planes_to_wire(y, n, s)


def sharded_parity(k: int, r: int, delta: np.ndarray,
                   mesh: Mesh | None = None) -> np.ndarray:
    """Parity-fragment deltas ``(r, S*512)`` of a stripe-aligned XOR
    delta over the mesh — the sub-stripe-write primitive
    (ops/codec.Codec.encode_delta) on the (dp, frag) plane.  Same
    parity-rows-only program as the systematic encode: linearity makes
    the parity of Δ exactly the parity delta."""
    if mesh is None:
        mesh = make_mesh()
    n = k + r
    delta = np.ascontiguousarray(delta, dtype=np.uint8).ravel()
    s = delta.size // (k * gf256.CHUNK_SIZE)
    x = delta.reshape(s, k * 8, gf256.WORD_SIZE)
    dp = mesh.devices.shape[0]
    pad = (-s) % dp
    if pad:
        x = np.concatenate(
            [x, np.zeros((pad, *x.shape[1:]), dtype=np.uint8)], axis=0)
    with _BUILD_LOCK:
        y = np.asarray(_parity_fn(k, n, mesh)(jnp.asarray(x)))
    return _planes_to_wire(y[:, :s, :], r, s)


@functools.lru_cache(maxsize=256)
def _decode_fn(k: int, rows: tuple[int, ...], mesh: Mesh):
    """Jitted degraded decode for one surviving mask, stripes sharded
    over ``dp`` (the LRU of per-mask jitted decoders mirrors the
    reference's LRU of inverted matrices, ec-method.c:200-245)."""
    bbits = jnp.asarray(gf256.decode_bits_cached(k, rows))
    sharding = NamedSharding(mesh, P("dp", None, None))
    return jax.jit(
        lambda x: _apply(bbits, x),
        in_shardings=sharding, out_shardings=sharding)


def sharded_decode(
    k: int,
    rows,
    frags: np.ndarray,
    mesh: Mesh | None = None,
) -> np.ndarray:
    """Decode k surviving fragments (fragment-major, (k, S*512)) into the
    original (S*k*512,) bytes, sharded over the mesh's ``dp`` axis.

    ``rows`` are the surviving fragment indices (any order-preserving
    k-subset of 0..n-1) — the ``ec_dispatch_min`` answer set.
    """
    if mesh is None:
        mesh = make_mesh()
    rows = tuple(int(x) for x in rows)
    x = gf256.frags_to_planes(frags, k)  # (S, k*8, 64), validates shape
    s = x.shape[0]
    dp = mesh.devices.shape[0]
    pad = (-s) % dp  # dp-sharded input must divide evenly; pad + trim
    if pad:
        x = np.concatenate(
            [x, np.zeros((pad, *x.shape[1:]), dtype=np.uint8)], axis=0)
    with _BUILD_LOCK:
        y = _decode_fn(k, rows, mesh)(jnp.asarray(x))
    return np.asarray(y)[:s].reshape(s * k * gf256.CHUNK_SIZE)
