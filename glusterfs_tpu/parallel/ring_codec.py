"""Ring-pipelined decode over the ``frag`` mesh axis.

The plain sharded decode (parallel/mesh_codec.py) lets XLA insert an
all-gather of fragment shards before reconstructing — simple, but every
device materializes ALL surviving fragments, so device memory bounds
the batch.  This module is the ring formulation — the same
communication pattern ring attention uses for long sequences, applied
to reconstruction:

* fragments stay sharded over the ring axis (each device holds its
  fragment group's bit-planes for the whole batch);
* the OUTPUT is stripe-sharded: device j owns stripe block j;
* an accumulator per stripe block travels the ring via ``ppermute``:
  at every step each device XORs in its fragments' contribution to the
  block currently visiting it, then forwards the block.  After p steps
  block j has collected every fragment group's contribution and sits
  on device j — a ring reduce-scatter with XOR as the reduction.

Per-step working set is one stripe BLOCK (1/p of the batch), so the
batch can exceed any single device's memory by the ring length — the
long-sequence scaling story.  Comm volume is (p-1)/p of the output,
pipelined with compute over ICI (reference analog: the fan-in of
``ec_dispatch_min`` network reads, ec-common.c:816-900, but streamed).

Role in the data plane: this is the memory-bounded ALTERNATIVE to
``mesh_codec.sharded_decode`` — ``ops/codec`` and the BatchingCodec's
mesh tier route decodes past ``MESH_RING_DECODE_BYTES`` through
:func:`ring_decode`; below the threshold the plain all-gather plane
wins (one collective, no p-step pipeline).  Exported via
``glusterfs_tpu.parallel``; the routing is pinned by
tests/test_mesh_plane.py::test_ring_codec_is_the_large_decode_alternative.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gf256


@functools.lru_cache(maxsize=64)
def _ring_decode_fn(k: int, rows: tuple[int, ...], mesh: Mesh):
    """Build the jitted ring decode for one surviving mask.

    Input: fragment bit-planes (k*8, S, 64) sharded over ``frag`` on
    the plane axis (each ring member holds k*8/p planes).
    Output: reconstructed planes (S, k*8, 64) sharded over ``frag`` on
    the STRIPE axis (stripe block j on device j).
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    p = mesh.devices.shape[mesh.axis_names.index("frag")]
    if (k * 8) % p:
        raise ValueError(f"k*8={k * 8} planes must divide over {p} "
                         "ring members")
    bbits = gf256.decode_bits_cached(k, rows)  # (k*8, k*8)

    def shard_body(planes, bb):
        # planes: (k*8/p, S, 64) — THIS member's fragment planes
        # bb:     (k*8, k*8/p)  — decode columns for these planes
        idx = jax.lax.axis_index("frag")
        s = planes.shape[1]
        blk = s // p

        def get_block(j):
            return jax.lax.dynamic_slice_in_dim(planes, j * blk, blk, 1)

        def contrib(j):
            """This member's XOR contribution to stripe block j:
            (blk, k*8, 64) = bb (k8, local) applied to local planes."""
            x = get_block(j)  # (local, blk, 64)
            # bitwise XOR-accumulate: out[r] = XOR over local planes c
            # with bb[r, c] == 1.  uint8 XOR has no matmul form; use
            # masked XOR-reduce over the (small) local plane dim.
            mask = bb.astype(jnp.uint8)  # (k8, local)
            # (k8, local, 1, 1) * (local, blk, 64) -> reduce local
            terms = mask[:, :, None, None] * x[None, :, :, :]
            out = terms[:, 0]
            for c in range(1, x.shape[0]):
                out = out ^ terms[:, c]
            return jnp.transpose(out, (1, 0, 2))  # (blk, k8, 64)

        # the accumulator starts as my contribution to the block that
        # will, after p-1 forwards, land on its owner
        acc = contrib((idx + (p - 1)) % p)

        def step(t, acc):
            # forward to the next ring member, then add my contribution
            # to the block that just arrived
            acc = jax.lax.ppermute(
                acc, "frag", [(d, (d + 1) % p) for d in range(p)])
            j = (idx + (p - 1) - (t + 1)) % p
            return acc ^ contrib(j)

        acc = jax.lax.fori_loop(0, p - 1, step, acc)
        return acc  # (blk, k8, 64): stripe block `idx`, fully reduced

    # split decode columns per member along the input-plane dim
    bb_full = jnp.asarray(bbits)

    # stripes shard over dp as well: each dp row runs its own
    # independent ring over its stripe slice (specs naming only frag
    # would replicate the whole problem dp times)
    kwargs = dict(mesh=mesh,
                  in_specs=(P("frag", "dp", None), P(None, "frag")),
                  out_specs=P(("dp", "frag"), None, None))
    try:  # jax>=0.8 renamed the replication-check knob
        fn = shard_map(shard_body, check_vma=False, **kwargs)
    except TypeError:
        fn = shard_map(shard_body, check_rep=False, **kwargs)

    @jax.jit
    def run(planes):
        return fn(planes, bb_full)

    return run


def ring_decode(k: int, rows, frags: np.ndarray,
                mesh: Mesh | None = None) -> np.ndarray:
    """Decode k surviving fragments (fragment-major (k, S*512)) into
    the original bytes via the ring pipeline.  Stripe counts that do
    not divide the ring length are zero-padded internally and trimmed
    from the result — callers need not align anything."""
    from . import mesh_codec

    if mesh is None:
        mesh = mesh_codec.make_mesh()
    rows = tuple(int(x) for x in rows)
    x = gf256.frags_to_planes(frags, k)    # (S, k*8, 64)

    s = x.shape[0]
    p = mesh.devices.shape[mesh.axis_names.index("frag")]
    dp = mesh.devices.shape[mesh.axis_names.index("dp")]
    pad = (-s) % (p * dp)  # dp slices, each ring-split into p blocks
    if pad:
        x = np.concatenate(
            [x, np.zeros((pad, *x.shape[1:]), dtype=np.uint8)], axis=0)
    planes = np.ascontiguousarray(np.transpose(x, (1, 0, 2)))
    # jit is lazy: the lock SPANS the call (a declared graft-race
    # tables.LAZY_UNDER_LOCK_OK site — GL07 verifies the extent)
    with mesh_codec._BUILD_LOCK:
        out = _ring_decode_fn(k, rows, mesh)(jnp.asarray(planes))
    out = np.asarray(out)[:s]              # (S, k*8, 64)
    return out.reshape(s * k * gf256.CHUNK_SIZE)
