"""Multi-device (mesh-sharded) codec data plane.

Public surface of the ICI scale-out story (ROADMAP item 2 — the mesh
analog of the reference's socket fan-out, ec-common.c:816-900):

* :func:`make_mesh` / :func:`default_mesh` — factor the visible devices
  into the ``(dp, frag)`` mesh (stripe batches shard over ``dp``, the
  fragment dimension over ``frag``; the encode IS the scatter-to-bricks
  step).
* :func:`device_count` / :func:`device_count_cached` /
  :func:`local_device_count` — wedge-safe device discovery (deadline
  probe; the cached form never blocks and is what serving-path routing
  reads).  Under a ``cluster.mesh-distributed`` job (``meshd``) the
  global count spans every member process; ``local_device_count`` is
  this process's share.
* :mod:`glusterfs_tpu.parallel.meshd` — the multi-process
  ``jax.distributed`` coordinator glue (ISSUE 12): brick daemons join
  a per-volume distributed job in the background, so the mesh plane
  binds one PROCESS per device instead of one runtime over all of
  them.
* :func:`sharded_encode` / :func:`sharded_decode` — the pjit'd
  NamedSharding entry points the BatchingCodec's mesh backend and the
  ``cpu-extensions=mesh`` Codec backend launch.
* :func:`ring_decode` — the all-to-all ALTERNATIVE to
  ``sharded_decode``: same answer, but fragments stay sharded over the
  ring (``frag``) axis and an XOR accumulator travels it via
  ``ppermute``, so per-device memory holds one stripe block instead of
  the whole gathered operand.  ``ops/codec.Codec`` routes mesh decodes
  past ``MESH_RING_DECODE_BYTES`` through it; below the threshold the
  plain all-gather plane wins (one collective, no p-step pipeline).
  tests/test_mesh_plane.py::test_ring_codec_is_the_large_decode_alternative
  pins the routing.
"""

from .mesh_codec import (  # noqa: F401
    default_mesh,
    device_count,
    device_count_cached,
    local_device_count,
    make_mesh,
    sharded_decode,
    sharded_encode,
)
from .ring_codec import ring_decode  # noqa: F401

__all__ = [
    "make_mesh", "default_mesh", "device_count", "device_count_cached",
    "local_device_count", "sharded_encode", "sharded_decode",
    "ring_decode",
]
