"""Test configuration: force an 8-device virtual CPU mesh.

Sharding/collective tests run on a virtual CPU mesh (no multi-chip TPU
hardware in CI); the driver separately dry-runs the multi-chip path.
Must be set before jax is imported anywhere.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
