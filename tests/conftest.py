"""Test configuration: force an 8-device virtual CPU mesh.

Sharding/collective tests run on a virtual CPU mesh (no multi-chip TPU
hardware in CI); the driver separately dry-runs the multi-chip path.

The XLA_FLAGS env var must be set before jax is imported anywhere; the
platform choice additionally needs ``jax.config.update`` because the
tunneled TPU plugin in this image registers itself regardless of the
``JAX_PLATFORMS`` env var.
"""
import os

# GFTPU_TEST_TPU=1 keeps the real device visible so the
# skip-if-no-tpu markers (real-lowering golden-vector parity in
# test_gf256_pallas.py) actually run:
#   GFTPU_TEST_TPU=1 pytest tests/test_gf256_pallas.py -k silicon
_USE_TPU = os.environ.get("GFTPU_TEST_TPU") == "1"

if not _USE_TPU:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

if not _USE_TPU:
    jax.config.update("jax_platforms", "cpu")
