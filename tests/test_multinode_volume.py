"""A volume whose bricks span TWO glusterd nodes: create/start spawn
on the right node, portmap syncs cluster-wide, clients mount through
either node, and node-local ops (top, status) aggregate across nodes —
the tests/cluster.rc multi-node volume scenario."""

import asyncio

import pytest

from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume


@pytest.mark.slow
def test_volume_spanning_two_nodes(tmp_path):
    async def run():
        d1 = Glusterd(str(tmp_path / "gd1"))
        await d1.start()
        d2 = Glusterd(str(tmp_path / "gd2"))
        await d2.start()
        try:
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("peer-probe", host=d2.host, port=d2.port)
                await c.call("volume-create", name="mn",
                             vtype="replicate",
                             bricks=[{"node": d1.uuid,
                                      "path": str(tmp_path / "n1b")},
                                     {"node": d2.uuid,
                                      "path": str(tmp_path / "n2b")}])
                await c.call("volume-start", name="mn")
                # each node spawned ITS brick
                assert "mn-brick-0" in d1.bricks
                assert "mn-brick-1" in d2.bricks
                assert "mn-brick-0" not in d2.bricks
                # portmap synced: both nodes know both ports
                for d in (d1, d2):
                    st = d.op_volume_status("mn")  # local view
                    ports = {b["name"]: b["port"] for b in st["bricks"]}
                    assert ports["mn-brick-0"] == d1.ports["mn-brick-0"]
                    assert ports["mn-brick-1"] == d2.ports["mn-brick-1"]
                    assert 0 not in ports.values()

            # mount through NODE 2 (volfile served with both ports)
            m = await mount_volume(d2.host, d2.port, "mn")
            try:
                await m.write_file("/cross", b"spans nodes" * 50)
                assert await m.read_file("/cross") == b"spans nodes" * 50
                # both replicas materialized, one per node
                assert (tmp_path / "n1b" / "cross").exists()
                assert (tmp_path / "n2b" / "cross").exists()
            finally:
                await m.unmount()

            # volume top aggregates BOTH nodes' bricks
            async with MgmtClient(d1.host, d1.port) as c:
                top = await c.call("volume-top", name="mn",
                                   metric="write")
                assert set(top["bricks"]) == {"mn-brick-0",
                                              "mn-brick-1"}, top
                for rows in top["bricks"].values():
                    assert any(r["path"] == "/cross" for r in rows)
                await c.call("volume-stop", name="mn")
            # stop reached both nodes
            assert "mn-brick-0" not in d1.bricks
            assert "mn-brick-1" not in d2.bricks
        finally:
            await d2.stop()
            await d1.stop()

    asyncio.run(run())
