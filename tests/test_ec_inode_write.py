"""EC allocation-class fops: fallocate / discard / zerofill / seek —
the tests/basic/ec/ec-fallocate.t + seek coverage analog.  Reference:
ec-inode-write.c (ec_fallocate/ec_discard/ec_zerofill), ec-inode-read.c
(ec_seek).  Zero stripes encode to zero fragments (linear code), so
holes line up across user space and fragments."""

import os

import numpy as np
import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc
from glusterfs_tpu.utils.volspec import ec_volfile

K, R = 4, 2
N = K + R
STRIPE = K * 512


def _rand(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


@pytest.fixture
def vol(tmp_path):
    g = Graph.construct(ec_volfile(tmp_path, N, R))
    c = SyncClient(g)
    c.mount()
    yield c, g.top, tmp_path
    c.close()


def test_zerofill_interior(vol):
    c, ec, _ = vol
    data = _rand(4 * STRIPE, seed=1).tobytes()
    c.write_file("/z", data)
    f = c.open("/z")
    off, ln = STRIPE // 2, 2 * STRIPE  # partial head + tail stripes
    c._run(ec.zerofill(f.fd, off, ln))
    f.close()
    got = c.read_file("/z")
    assert got[:off] == data[:off]
    assert got[off:off + ln] == b"\0" * ln
    assert got[off + ln:] == data[off + ln:]
    assert c.stat("/z").size == 4 * STRIPE


def test_zerofill_extends(vol):
    c, ec, _ = vol
    c.write_file("/ze", b"abc")
    f = c.open("/ze")
    c._run(ec.zerofill(f.fd, 3, 2 * STRIPE))
    f.close()
    assert c.stat("/ze").size == 3 + 2 * STRIPE
    assert c.read_file("/ze") == b"abc" + b"\0" * (2 * STRIPE)


def test_discard_keeps_size(vol):
    c, ec, _ = vol
    data = _rand(2 * STRIPE, seed=2).tobytes()
    c.write_file("/d", data)
    f = c.open("/d")
    # range crosses EOF: zeroing is clamped, size must not grow
    c._run(ec.discard(f.fd, STRIPE, 5 * STRIPE))
    f.close()
    assert c.stat("/d").size == 2 * STRIPE
    got = c.read_file("/d")
    assert got[:STRIPE] == data[:STRIPE]
    assert got[STRIPE:] == b"\0" * STRIPE


def test_fallocate_extends_and_keep_size(vol):
    c, ec, _ = vol
    data = _rand(STRIPE, seed=3).tobytes()
    c.write_file("/fa", data)
    f = c.open("/fa")
    ia = c._run(ec.fallocate(f.fd, 0, 0, 3 * STRIPE))
    assert ia.size == 3 * STRIPE
    # KEEP_SIZE: allocation only, size unchanged
    ia = c._run(ec.fallocate(f.fd, 1, 0, 10 * STRIPE))
    assert ia.size == 3 * STRIPE
    f.close()
    got = c.read_file("/fa")
    assert got[:STRIPE] == data
    assert got[STRIPE:] == b"\0" * (2 * STRIPE)
    info = c._run(ec.heal_info(Loc("/fa")))
    assert info["bad"] == [] and not info["dirty"]


def test_seek_data_and_hole(vol):
    """Sparse layout engineered to the FS hole granularity (4096B per
    fragment = 8 stripes of user data): data [0..8s), hole [8s..64s),
    data [64s..72s)."""
    c, ec, _ = vol
    s = STRIPE
    head = _rand(8 * s, seed=4).tobytes()
    tail = _rand(8 * s, seed=5).tobytes()
    f = c.create("/sp")
    f.write(head, 0)
    f.write(tail, 64 * s)
    f.close()
    f = c.open("/sp")
    fd = f.fd
    assert c._run(ec.seek(fd, 0, "data")) == 0
    hole = c._run(ec.seek(fd, 0, "hole"))
    assert 8 * s <= hole <= 64 * s  # first hole (granularity-dependent)
    if hole < 64 * s:
        assert c._run(ec.seek(fd, hole, "data")) == 64 * s
    assert c._run(ec.seek(fd, 64 * s, "hole")) == 72 * s  # EOF hole
    with pytest.raises(FopError):
        c._run(ec.seek(fd, 72 * s, "data"))  # ENXIO past EOF
    f.close()


def test_discard_interior_frees_blocks(vol):
    """The stripe-aligned interior punches real fragment holes
    (FALLOC_FL_PUNCH_HOLE) instead of writing zeros: allocated blocks
    DROP."""
    c, ec, base = vol
    s = STRIPE
    data = _rand(32 * s, seed=6).tobytes()
    c.write_file("/ph", data)
    frag = base / "brick0" / "ph"
    blocks_before = frag.stat().st_blocks
    f = c.open("/ph")
    c._run(ec.discard(f.fd, 8 * s, 16 * s))  # aligned interior
    f.close()
    assert frag.stat().st_blocks < blocks_before, "no blocks freed"
    got = c.read_file("/ph")
    assert got[: 8 * s] == data[: 8 * s]
    assert got[8 * s: 24 * s] == b"\0" * (16 * s)
    assert got[24 * s:] == data[24 * s:]


def test_afr_fallocate_keep_size(tmp_path):
    """FALLOC_FL_KEEP_SIZE must not grow the replicas (libc fallocate
    honors the flag; posix_fallocate would not)."""
    from glusterfs_tpu.utils.volspec import brick_volumes

    chunks, tops = brick_volumes(tmp_path, 3)
    chunks.append("volume afr\n    type cluster/replicate\n"
                  f"    subvolumes {' '.join(tops)}\nend-volume\n")
    g = Graph.construct("\n".join(chunks))
    c = SyncClient(g)
    c.mount()
    try:
        afr = g.top
        c.write_file("/ks", b"B" * 4096)
        f = c.open("/ks")
        c._run(afr.fallocate(f.fd, 1, 0, 65536))
        f.close()
        assert c.stat("/ks").size == 4096
        for i in range(3):
            assert (tmp_path / f"brick{i}" / "ks").stat().st_size == 4096, i
    finally:
        c.close()


def test_afr_allocation_fops_replicate(tmp_path):
    """fallocate/discard/zerofill must hit EVERY replica with counters —
    the default first-child passthrough would silently diverge them."""
    from glusterfs_tpu.utils.volspec import brick_volumes

    chunks, tops = brick_volumes(tmp_path, 3)
    chunks.append("volume afr\n    type cluster/replicate\n"
                  f"    subvolumes {' '.join(tops)}\nend-volume\n")
    g = Graph.construct("\n".join(chunks))
    c = SyncClient(g)
    c.mount()
    try:
        afr = g.top
        c.write_file("/r", b"A" * 4096)
        f = c.open("/r")
        c._run(afr.zerofill(f.fd, 1024, 2048))
        f.close()
        want = b"A" * 1024 + b"\0" * 2048 + b"A" * 1024
        for i in range(3):
            assert (tmp_path / f"brick{i}" / "r").read_bytes() == want, i
        info = c._run(afr.heal_info(Loc("/r")))
        assert info["bad"] == [] and not info["dirty"]
    finally:
        c.close()
