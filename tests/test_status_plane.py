"""Cluster health & client-accounting plane (ISSUE 5): per-client wire
accounting pinned against known transfer sizes, deep `volume status`
fan-out (clients/fds/inodes/callpool/mem/detail) with partial-coverage
reporting on downed nodes, heal-count from brick index counters, and
lifecycle event coverage (CLIENT_CONNECT/DISCONNECT, POSIX health
check, afr/ec quorum edges) landing in eventsd history."""

import asyncio
import os
import shutil

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core import events as events_mod
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.mgmt.eventsd import EventsDaemon
from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume

BRICK_VOLFILE = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume locks
    type features/locks
    subvolumes posix
end-volume
volume stats
    type debug/io-stats
    subvolumes locks
end-volume
"""

CLIENT_VOLFILE = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume stats
    option shm-transport off
end-volume
"""
# shm-transport off: this plane pins SOCKET bytes against known
# transfer sizes, and the same-host shm lane (default on, op-ver 17)
# deliberately moves payloads off the socket — per-connection
# bytes_rx/tx stay transport-level.  The armed lane's own accounting
# (header-only socket deltas, arena byte counters) is pinned in
# tests/test_shm_transport.py.


async def _connect(port):
    g = Graph.construct(CLIENT_VOLFILE.format(port=port))
    c = Client(g)
    await c.mount()
    for _ in range(200):
        if g.top.connected:
            break
        await asyncio.sleep(0.05)
    assert g.top.connected
    return c, g


@pytest.fixture
def eventsd_env():
    """In-process eventsd wired as this process's gf_event sink; the
    daemon handle is yielded for history assertions."""
    holder = {}

    async def start():
        d = EventsDaemon()
        udp, _ctl = await d.start()
        events_mod.configure(f"127.0.0.1:{udp}")
        holder["d"] = d
        return d

    holder["start"] = start
    yield holder
    events_mod.configure(None)
    os.environ.pop("GFTPU_EVENTSD", None)


# -- per-client wire accounting --------------------------------------------

def test_client_accounting_pinned_bytes(tmp_path):
    """The brick's per-client rx/tx counters match a known transfer
    size within protocol overhead, fop counts accumulate, and the
    client-side counters (the other end of the same socket) agree."""
    PAYLOAD = 65536

    async def run():
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        c, g = await _connect(server.port)
        await c.write_file("/acct", b"x" * PAYLOAD)
        st = await g.top._call("__status__", ("clients",), {})
        rows = [r for r in st["clients"] if not r["mgmt"]]
        assert len(rows) == 1
        row = rows[0]
        assert row["client"] == g.top.identity.hex()
        # pinned: the payload rode up exactly once (+ framing/handshake
        # overhead, well under a page)
        assert PAYLOAD <= row["bytes_rx"] <= PAYLOAD + 4096, row
        assert row["bytes_tx"] < 4096  # no reads yet
        assert row["fops"] >= 2 and row["fop_counts"].get("writev", 0) >= 1
        assert row["op_version"] >= 7  # advertised at SETVOLUME
        assert await c.read_file("/acct") == b"x" * PAYLOAD
        st = await g.top._call("__status__", ("clients",), {})
        row = [r for r in st["clients"] if not r["mgmt"]][0]
        assert PAYLOAD <= row["bytes_tx"] <= PAYLOAD + 4096, row
        # the client half agrees with the brick half (same socket)
        assert abs(g.top.bytes_tx - row["bytes_rx"]) < 512
        assert abs(g.top.bytes_rx - row["bytes_tx"]) < 512
        # per-client registry families scrape from the live server
        snap = REGISTRY.snapshot()
        assert any(s[0].get("client") == row["client"][:8]
                   for s in snap["gftpu_server_client_bytes_total"]
                   ["samples"])
        assert "gftpu_client_wire_bytes_total" in snap
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_status_kinds_answer_and_fds_tracked(tmp_path):
    """Every deep-status kind answers on a live brick; an open fd shows
    in the fd table and callpool/inodes/detail/mem carry live state."""

    async def run():
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        c, g = await _connect(server.port)
        f = await c.create("/held")
        await f.write(b"held open", 0)
        fds = await g.top._call("__status__", ("fds",), {})
        mine = [t for t in fds["fd_tables"]
                if t["client"] == g.top.identity.hex()]
        assert mine and mine[0]["count"] >= 1
        assert any(fd["path"] == "/held" for fd in mine[0]["fds"])
        ino = await g.top._call("__status__", ("inodes",), {})
        assert ino["identity"]["posix"]["ino_cache"] >= 1
        cp = await g.top._call("__status__", ("callpool",), {})
        assert any(o["client"] == g.top.identity.hex()
                   for o in cp["outstanding"])
        mem = await g.top._call("__status__", ("mem",), {})
        assert mem["max_rss_kb"] > 0
        assert "gftpu_wire_blob_stats" in mem["registry"]
        det = await g.top._call("__status__", ("detail",), {})
        be = det["backends"][0]
        assert be["health"] == "ok" and be["blocks_total"] > 0
        assert be["inodes_total"] > 0
        await f.close()
        await c.unmount()
        await server.stop()

    asyncio.run(run())


# -- lifecycle events ------------------------------------------------------

def test_connect_disconnect_events_and_row_drop(tmp_path, eventsd_env):
    """CLIENT_CONNECT lands in eventsd history at SETVOLUME, the
    client's row vanishes from `status clients` on disconnect, and
    CLIENT_DISCONNECT carries the final byte account."""

    async def run():
        ed = await eventsd_env["start"]()
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        c, g = await _connect(server.port)
        uid = g.top.identity.hex()
        await c.write_file("/f", b"y" * 8192)
        for _ in range(40):  # UDP datagram -> same-loop eventsd
            if any(e["event"] == "CLIENT_CONNECT" and e["client"] == uid
                   for e in ed.recent):
                break
            await asyncio.sleep(0.05)
        connect = [e for e in ed.recent
                   if e["event"] == "CLIENT_CONNECT"
                   and e["client"] == uid]
        assert connect and connect[0]["brick"] == "stats"
        await c.unmount()
        # the server notices EOF and reaps the client_t
        c2, g2 = await _connect(server.port)
        for _ in range(40):
            st = await g2.top._call("__status__", ("clients",), {})
            if all(r["client"] != uid for r in st["clients"]):
                break
            await asyncio.sleep(0.05)
        assert all(r["client"] != uid for r in st["clients"])
        for _ in range(40):
            if any(e["event"] == "CLIENT_DISCONNECT"
                   and e["client"] == uid for e in ed.recent):
                break
            await asyncio.sleep(0.05)
        disc = [e for e in ed.recent if e["event"] == "CLIENT_DISCONNECT"
                and e["client"] == uid]
        assert disc and disc[0]["bytes_rx"] >= 8192
        # BRICK_CONNECTED fired from the client side too
        assert any(e["event"] == "BRICK_CONNECTED" for e in ed.recent)
        await c2.unmount()
        await server.stop()

    asyncio.run(run())


def test_health_check_failure_event(tmp_path, eventsd_env):
    """A dying backend fires POSIX_HEALTH_CHECK_FAILED into eventsd
    (and the brick marks itself down, as before)."""
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/hb
    option health-check-interval 0.05
end-volume
"""

    async def run():
        ed = await eventsd_env["start"]()
        g = Graph.construct(vf)
        await g.activate()
        try:
            shutil.rmtree(tmp_path / "hb")  # the disk "dies"
            for _ in range(60):
                if any(e["event"] == "POSIX_HEALTH_CHECK_FAILED"
                       for e in ed.recent):
                    break
                await asyncio.sleep(0.05)
            evs = [e for e in ed.recent
                   if e["event"] == "POSIX_HEALTH_CHECK_FAILED"]
            assert evs and evs[0]["brick"] == "posix"
        finally:
            await g.fini()

    asyncio.run(run())


def test_afr_ec_quorum_transition_events(tmp_path, eventsd_env):
    """afr and ec emit quorum events exactly on the transition edge
    (not once per child flap)."""
    afr_vf = f"""
volume p0
    type storage/posix
    option directory {tmp_path}/a0
end-volume
volume p1
    type storage/posix
    option directory {tmp_path}/a1
end-volume
volume afr
    type cluster/replicate
    subvolumes p0 p1
end-volume
"""

    async def run():
        from glusterfs_tpu.core.layer import Event

        ed = await eventsd_env["start"]()
        g = Graph.construct(afr_vf)
        await g.activate()
        try:
            afr = g.top
            # quorum-type auto on replica 2: losing brick 0 loses the
            # first-brick tiebreak immediately
            afr.notify(Event.CHILD_DOWN, source=afr.children[0])
            afr.notify(Event.CHILD_DOWN, source=afr.children[1])  # no edge
            afr.notify(Event.CHILD_UP, source=afr.children[0])
            await asyncio.sleep(0.2)
            fails = [e for e in ed.recent
                     if e["event"] == "AFR_QUORUM_FAIL"]
            mets = [e for e in ed.recent
                    if e["event"] == "AFR_QUORUM_MET"]
            assert len(fails) == 1 and fails[0]["up"] == 1
            assert len(mets) == 1 and mets[0]["up"] == 1
        finally:
            await g.fini()

    asyncio.run(run())


def test_ec_min_bricks_events(tmp_path, eventsd_env):
    ec_vf = f"""
volume e0
    type storage/posix
    option directory {tmp_path}/e0
end-volume
volume e1
    type storage/posix
    option directory {tmp_path}/e1
end-volume
volume e2
    type storage/posix
    option directory {tmp_path}/e2
end-volume
volume ec
    type cluster/disperse
    option redundancy 1
    subvolumes e0 e1 e2
end-volume
"""

    async def run():
        from glusterfs_tpu.core.layer import Event

        ed = await eventsd_env["start"]()
        g = Graph.construct(ec_vf)
        await g.activate()
        try:
            ec = g.top  # k = 2 of 3
            ec.notify(Event.CHILD_DOWN, source=ec.children[0])
            ec.notify(Event.CHILD_DOWN, source=ec.children[1])  # < K
            ec.notify(Event.CHILD_UP, source=ec.children[1])    # >= K
            await asyncio.sleep(0.2)
            down = [e for e in ed.recent
                    if e["event"] == "EC_MIN_BRICKS_NOT_UP"]
            up = [e for e in ed.recent
                  if e["event"] == "EC_MIN_BRICKS_UP"]
            assert len(down) == 1 and down[0]["up"] == 1
            assert len(up) == 1 and up[0]["k"] == 2
        finally:
            await g.fini()

    asyncio.run(run())


def test_eventsd_registry_families():
    """eventsd's received/webhook counters are registry families, so
    the event plane itself is scrapeable."""

    async def run():
        d = EventsDaemon()
        await d.start()
        try:
            d.webhooks["http://127.0.0.1:1/x"] = {"delivered": 3,
                                                  "failed": 1}
            d._ingest({"event": "T"})
            snap = REGISTRY.snapshot()
            rec = [v for l, v in
                   snap["gftpu_events_received_total"]["samples"]]
            assert sum(rec) >= 1
            wh = {(l["url"], l["result"]): v for l, v in
                  snap["gftpu_events_webhook_total"]["samples"]}
            assert wh[("http://127.0.0.1:1/x", "delivered")] == 3
            assert wh[("http://127.0.0.1:1/x", "failed")] == 1
            # the emitting side counts too
            assert "gftpu_events_emitted_total" in snap
        finally:
            await d.stop()

    asyncio.run(run())


# -- glusterd plane --------------------------------------------------------

def test_tasks_section_in_plain_status(tmp_path):
    """An active remove-brick shows in plain `volume status` as a task
    row (the reference's status tasks section)."""

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        # no network needed: single-node txn runs in-process
        await d.op_volume_create(
            "tv", "distribute",
            [{"path": str(tmp_path / f"b{i}")} for i in range(2)])
        st = d.op_volume_status("tv")
        assert "tasks" not in st
        d.state["volumes"]["tv"]["remove-brick"] = {
            "status": "started", "bricks": ["tv-brick-1"],
            "progress": {"moved": 1}}
        st = d.op_volume_status("tv")
        assert st["tasks"] == [{"type": "remove-brick",
                                "status": "started",
                                "bricks": ["tv-brick-1"],
                                "progress": {"moved": 1}}]

    asyncio.run(run())


@pytest.mark.slow
def test_deep_status_fanout_merge_and_heal_count(tmp_path, eventsd_env):
    """Multi-brick fan-out: every deep-status kind merges both bricks'
    answers keyed by brick name, the mounted client appears with
    nonzero bytes, heal-count answers without mounting a client graph,
    and CLIENT_CONNECT reached eventsd from the brick subprocesses."""

    async def run():
        ed = await eventsd_env["start"]()
        # brick SUBPROCESSES inherit the sink through the environment
        os.environ["GFTPU_EVENTSD"] = f"127.0.0.1:{ed.udp_port}"
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="dv",
                             vtype="replicate",
                             bricks=[{"path": str(tmp_path / "b0")},
                                     {"path": str(tmp_path / "b1")}])
                await c.call("volume-start", name="dv")
            m = await mount_volume(d.host, d.port, "dv")
            try:
                await m.write_file("/one", b"a" * 32768)
                await m.write_file("/two", b"b" * 32768)
                bricks = {"dv-brick-0", "dv-brick-1"}
                for what in ("clients", "fds", "inodes", "callpool",
                             "detail", "mem"):
                    st = await d.op_volume_status_deep("dv", what)
                    assert set(st["bricks"]) == bricks, (what, st)
                    assert "partial" not in st
                st = await d.op_volume_status_deep("dv", "clients")
                for bname in bricks:
                    rows = [r for r in st["bricks"][bname]["clients"]
                            if not r["mgmt"]]
                    assert rows, st
                    assert any(r["bytes_rx"] >= 32768 for r in rows)
                hc = await d.op_volume_heal_count("dv")
                assert set(hc["bricks"]) == bricks
                assert hc["total"] == 0  # nothing pending
                for _ in range(60):
                    if any(e["event"] == "CLIENT_CONNECT"
                           for e in ed.recent):
                        break
                    await asyncio.sleep(0.1)
                assert any(e["event"] == "CLIENT_CONNECT"
                           for e in ed.recent)
            finally:
                await m.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_partial_fanout_on_downed_node(tmp_path):
    """A dead peer degrades every fan-out answer to a NAMED partial —
    not a hang, not a fake-complete merge."""

    async def run():
        d1 = Glusterd(str(tmp_path / "gd1"))
        await d1.start()
        d2 = Glusterd(str(tmp_path / "gd2"))
        await d2.start()
        try:
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("peer-probe", host=d2.host, port=d2.port)
                await c.call("volume-create", name="pv",
                             vtype="replicate",
                             bricks=[{"node": d1.uuid,
                                      "path": str(tmp_path / "n1b")},
                                     {"node": d2.uuid,
                                      "path": str(tmp_path / "n2b")}])
                await c.call("volume-start", name="pv")
            st = await d1.op_volume_status_deep("pv", "clients")
            assert set(st["bricks"]) == {"pv-brick-0", "pv-brick-1"}
            assert "partial" not in st
            await d2.stop()  # node down: bricks AND glusterd gone
            st = await d1.op_volume_status_deep("pv", "clients")
            assert "pv-brick-0" in st["bricks"]
            assert "pv-brick-1" not in st["bricks"]
            assert st["partial"] and \
                st["partial"][0].startswith(d2.uuid[:8])
            prof = await d1.op_volume_profile("pv")
            assert prof["partial"]
            top = await d1.op_volume_top("pv", metric="write")
            assert top["partial"]
        finally:
            await d2.stop()
            await d1.stop()

    asyncio.run(run())


# -- CLI rendering ---------------------------------------------------------

def test_cli_status_tables_and_partial_warning(capsys):
    from glusterfs_tpu.mgmt.cli import _status_human

    out = {"volume": "v", "what": "clients",
           "partial": ["deadbeef@127.0.0.1:1"],
           "bricks": {"v-brick-0": {"clients": [
               {"client": "ab" * 16, "addr": "127.0.0.1",
                "uptime": 12.3, "bytes_rx": 70000, "bytes_tx": 512,
                "fops": 9, "opened_fds": 1, "mgmt": False,
                "op_version": 8}]},
               "v-brick-1": {"offline": True}}}
    text = _status_human("clients", out)
    assert "WARNING: partial answer" in text and "deadbeef" in text
    assert "BRICK" in text and "68.4KiB" in text and "OFFLINE" in text
    fd_out = {"bricks": {"b0": {"fd_tables": [
        {"client": "cd" * 16, "count": 1,
         "fds": [{"fd": 3, "path": "/x", "gfid": "00" * 16,
                  "flags": 2}]}]}}}
    text = _status_human("fds", fd_out)
    assert "/x" in text and "CLIENT" in text
