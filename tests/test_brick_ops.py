"""Elasticity brick ops: add-brick growth, remove-brick drain + commit
(decommission rebalance), replace-brick rebuild
(glusterd-brick-ops.c / glusterd-replace-brick.c analogs)."""

import asyncio
import os

import pytest

from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                         mount_volume)


from tests.harness import wait_async as _wait


@pytest.mark.slow
def test_add_and_remove_brick_distribute(tmp_path):
    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="ev",
                             vtype="distribute",
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(2)])
                await c.call("volume-start", name="ev")
                m = await mount_volume(d.host, d.port, "ev")
                try:
                    names = [f"f{i:02d}" for i in range(16)]
                    for n in names:
                        await m.write_file(f"/{n}", n.encode())

                    # grow: third brick joins the layout after the
                    # pushed graph swap
                    out = await c.call("volume-add-brick", name="ev",
                                       bricks=[{"path":
                                                str(tmp_path / "b2")}])
                    assert out["added"] == ["ev-brick-2"]

                    async def swapped():
                        return any(
                            l.type_name == "protocol/client" and
                            "ev-client-2" == l.name
                            for l in m.graph.by_name.values())

                    assert await _wait(swapped), "client graph not swapped"
                    # everything still readable (lookup-everywhere)
                    for n in names:
                        assert await m.read_file(f"/{n}") == n.encode()
                    # rebalance settles files onto the 3-way layout
                    from glusterfs_tpu.cluster.dht import DistributeLayer

                    dht = next(l for l in m.graph.by_name.values()
                               if isinstance(l, DistributeLayer))
                    await dht.rebalance("/")
                    assert any((tmp_path / "b2" / n).exists()
                               for n in names), "no data moved to b2"

                    # shrink: drain b2 again
                    await c.call("volume-remove-brick", name="ev",
                                 bricks=["ev-brick-2"], action="start")

                    async def drained():
                        st = await c.call("volume-remove-brick",
                                          name="ev", bricks=[],
                                          action="status")
                        return st.get("status") == "completed"

                    assert await _wait(drained), "drain did not finish"
                    # all data back off the leaving brick
                    left = [n for n in names
                            if (tmp_path / "b2" / n).exists()
                            and (tmp_path / "b2" / n).stat().st_size]
                    assert not left, left
                    await c.call("volume-remove-brick", name="ev",
                                 bricks=[], action="commit")
                    info = await c.call("volume-info", name="ev")
                    assert len(info["ev"]["bricks"]) == 2

                    # commit pushes a 2-brick volfile; like the
                    # add-brick half above, wait for the swapped
                    # graph's clients to CONNECT before reading (the
                    # swap window is sub-second but real)
                    async def settled():
                        cls = [l for l in m.graph.by_name.values()
                               if l.type_name == "protocol/client"]
                        return len(cls) == 2 and \
                            all(l.connected for l in cls)

                    assert await _wait(settled), "post-commit swap"
                    for n in names:
                        assert await m.read_file(f"/{n}") == n.encode()
                finally:
                    await m.unmount()
                await c.call("volume-stop", name="ev")
        finally:
            await d.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_replace_brick_heals_replica(tmp_path):
    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="rv",
                             vtype="replicate",
                             bricks=[{"path": str(tmp_path / f"r{i}")}
                                     for i in range(2)])
                await c.call("volume-start", name="rv")
                m = await mount_volume(d.host, d.port, "rv")
                try:
                    await m.write_file("/keep", b"precious" * 64)
                finally:
                    await m.unmount()
                # swap replica 1 for an empty directory
                await c.call("volume-replace-brick", name="rv",
                             brick="rv-brick-1",
                             new_path=str(tmp_path / "r1new"))
                info = await c.call("volume-info", name="rv")
                assert info["rv"]["bricks"][1]["path"] == \
                    str(tmp_path / "r1new")

                async def healed():
                    p = tmp_path / "r1new" / "keep"
                    return p.exists() and \
                        p.read_bytes() == b"precious" * 64

                assert await _wait(lambda: healed()), \
                    "replaced brick not rebuilt"
                # distribute volumes must refuse (data loss)
                await c.call("volume-create", name="dv",
                             vtype="distribute",
                             bricks=[{"path": str(tmp_path / "dx")}])
                with pytest.raises(FopError):
                    await c.call("volume-replace-brick", name="dv",
                                 brick="dv-brick-0",
                                 new_path=str(tmp_path / "dy"))
                await c.call("volume-stop", name="rv")
        finally:
            await d.stop()

    asyncio.run(run())
