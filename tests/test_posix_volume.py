"""End-to-end single-brick volume: client API over storage/posix — the
minimum vertical slice (SURVEY.md §7 phase 0.4).  Mirrors the style of the
reference's tests/basic/ `.t` flow: create volume, mount, fop matrix,
introspect (reference tests/basic/ec/ec.t:27-60 fop matrix idea)."""

import asyncio
import os

import pytest

from glusterfs_tpu.api.glfs import Client, SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph

VOLFILE = """
volume brick0
    type storage/posix
    option directory {d}
end-volume
"""


@pytest.fixture
def client(tmp_path):
    c = SyncClient(Graph.construct(VOLFILE.format(d=tmp_path / "brick0")))
    c.mount()
    yield c
    c.close()


def test_file_roundtrip(client):
    f = client.create("/hello.txt")
    assert f.write(b"hello tpu world", 0) == 15
    f.close()
    assert client.read_file("/hello.txt") == b"hello tpu world"
    ia = client.stat("/hello.txt")
    assert ia.size == 15
    assert not ia.is_dir()


def test_fop_matrix(client):
    # mkdir / nested create / listdir / rename / link / symlink / unlink
    client.mkdir("/d1")
    client.mkdir("/d1/d2")
    client.write_file("/d1/d2/f", b"x" * 1000)
    assert client.listdir("/d1") == ["d2"]
    assert client.listdir("/d1/d2") == ["f"]
    client.rename("/d1/d2/f", "/d1/f2")
    assert client.read_file("/d1/f2") == b"x" * 1000
    client.link("/d1/f2", "/d1/hard")
    assert client.stat("/d1/hard").size == 1000
    client.symlink("f2", "/d1/sym")
    assert client.readlink("/d1/sym") == "f2"
    client.truncate("/d1/f2", 10)
    assert client.stat("/d1/f2").size == 10
    client.unlink("/d1/hard")
    client.unlink("/d1/sym")
    client.unlink("/d1/f2")
    client.rmdir("/d1/d2")
    client.rmdir("/d1")
    assert client.listdir("/") == []


def test_xattr_and_xattrop(client):
    client.write_file("/f", b"data")
    client.setxattr("/f", {"user.color": "blue"})
    assert client.getxattr("/f", "user.color") == {"user.color": b"blue"}
    with pytest.raises(FopError):
        client.getxattr("/f", "user.nope")


def test_overwrite_and_partial_io(client):
    client.write_file("/f", b"A" * 100)
    f = client.open("/f")
    f.write(b"BB", 50)
    assert f.read(4, 49) == b"ABBA"
    f.close()
    ia = client.stat("/f")
    assert ia.size == 100


def test_errors(client):
    with pytest.raises(FopError):
        client.stat("/nope")
    with pytest.raises(FopError):
        client.open("/nope")
    client.mkdir("/d")
    with pytest.raises(FopError):
        client.mkdir("/d")  # EEXIST


def test_statedump_introspection(client):
    client.write_file("/f", b"hi")
    d = client.statedump()
    assert d["layers"]["brick0"]["type"] == "storage/posix"
    assert d["layers"]["brick0"]["stats"]["writev"]["count"] >= 1
    assert d["itable"]["inodes"] >= 1


def test_statvfs(client):
    sv = client.statvfs("/")
    assert sv["bsize"] > 0 and sv["blocks"] > 0


def test_async_client(tmp_path):
    async def run():
        g = Graph.construct(VOLFILE.format(d=tmp_path / "b"))
        c = Client(g)
        await c.mount()
        f = await c.create("/a")
        await f.write(b"abc", 0)
        await f.close()
        out = await c.read_file("/a")
        await c.unmount()
        return out

    assert asyncio.run(run()) == b"abc"


def test_unlink_cleans_ino_binding(client, tmp_path):
    """Deleting a file must drop its dev:ino -> gfid sidecar, or inode
    reuse resolves a fresh file to the dead gfid (advisor r1 finding)."""
    client.write_file("/doomed", b"bytes")
    xattr_dir = tmp_path / "brick0" / ".glusterfs_tpu" / "xattr"
    # bindings are journal-only until compaction: materialize them so
    # the on-disk invariant is observable
    client.graph.by_name["brick0"]._xa_compact()
    before = {p.name for p in xattr_dir.iterdir() if p.name.startswith("ino-")}
    assert before, "expected an ino- binding after create"
    client.unlink("/doomed")
    client.graph.by_name["brick0"]._xa_compact()
    after = {p.name for p in xattr_dir.iterdir() if p.name.startswith("ino-")}
    assert after == set() or after < before
    # a new file must get a FRESH gfid even if the OS reuses the inode
    client.write_file("/reborn", b"other")
    assert client.stat("/reborn").size == 5
    assert client.read_file("/reborn") == b"other"


def test_rename_keeps_ino_binding_consistent(client, tmp_path):
    client.write_file("/a", b"payload")
    g_before = client.stat("/a").gfid
    client.rename("/a", "/b")
    assert client.stat("/b").gfid == g_before  # gfid survives rename
    client.unlink("/b")
    xattr_dir = tmp_path / "brick0" / ".glusterfs_tpu" / "xattr"
    client.graph.by_name["brick0"]._xa_compact()
    stale = [p.name for p in xattr_dir.iterdir() if p.name.startswith("ino-")]
    assert stale == []


def test_hardlink_unlink_keeps_gfid(client, tmp_path):
    """Unlinking one of two hard links must not destroy the surviving
    link's gfid binding (gfid stability across links)."""
    client.write_file("/a", b"shared")
    g = client.stat("/a").gfid
    client.link("/a", "/b")
    client.unlink("/a")
    assert client.stat("/b").gfid == g
    assert client.read_file("/b") == b"shared"


def test_rename_over_existing_cleans_dst_identity(client, tmp_path):
    """rename onto an existing file destroys the dst's gfid + sidecars;
    only the surviving file's bindings remain."""
    client.write_file("/src", b"winner")
    client.write_file("/dst", b"loser")
    g_src = client.stat("/src").gfid
    client.rename("/src", "/dst")
    assert client.stat("/dst").gfid == g_src
    meta = tmp_path / "brick0" / ".glusterfs_tpu"
    client.graph.by_name["brick0"]._xa_compact()
    gfids = [p.name for p in (meta / "gfid").iterdir()
             if p.name != "0" * 31 + "1"]  # exclude ROOT_GFID
    inos = [p.name for p in (meta / "xattr").iterdir()
            if p.name.startswith("ino-")]
    assert len(gfids) == 1 and len(inos) == 1


def test_filename_with_newline(client):
    """Paths may contain newlines; gfid pointer format must survive."""
    client.write_file("/a\nb", b"tricky")
    assert client.read_file("/a\nb") == b"tricky"
    st = client.stat("/a\nb")
    f = client.open("/a\nb")
    assert f.read(6, 0) == b"tricky"  # fd path resolves via gfid pointer
    f.close()
    client.unlink("/a\nb")


def test_fd_ops_on_surviving_hardlink(client):
    """fd-based fops must keep working when the path the fd was opened
    under disappears (handle hardlink farm, reference posix-handle.h)."""
    client.write_file("/a", b"0123456789")
    client.link("/a", "/b")
    f = client.open("/b")
    client.unlink("/a")
    assert f.read(10, 0) == b"0123456789"
    f.write(b"XX", 0)
    assert client.stat("/b").size == 10
    f.close()
    assert client.read_file("/b") == b"XX23456789"


def test_fd_identity_after_rename_over(client):
    """An fd open on a file that gets renamed over must keep addressing
    ITS inode (not the replacing file's)."""
    client.write_file("/src", b"sevenby")
    client.write_file("/dst", b"ninebytess")
    client.link("/dst", "/dst2")   # keeps dst's inode alive post-rename
    f = client.open("/dst")
    client.rename("/src", "/dst")
    st = f.fstat()
    assert st.size == 10           # still the old dst inode
    f.write(b"ZZ", 0)
    f.close()
    assert client.read_file("/dst2") == b"ZZnebytess"  # wrote to old inode
    assert client.read_file("/dst") == b"sevenby"      # src content intact


def test_rename_updates_fd_of_source(client):
    """An fd open on the rename SOURCE keeps working after the rename."""
    client.write_file("/x", b"hello")
    f = client.open("/x")
    client.rename("/x", "/y")
    f.write(b"HELLO", 0)
    f.close()
    assert client.read_file("/y") == b"HELLO"


def test_health_checker_marks_brick_down(tmp_path):
    """posix health checker (posix_health_check_thread_proc analog): a
    backend that stops accepting writes marks the brick down — fops
    raise ENOTCONN and CHILD_DOWN propagates, instead of every fop
    hitting raw EIO storage."""
    import errno
    import shutil

    spec = (f"volume brick0\n    type storage/posix\n"
            f"    option directory {tmp_path}/hb\n"
            f"    option health-check-interval 0.2\nend-volume\n")

    async def run():
        c = Client(Graph.construct(spec))
        await c.mount()
        posix = c.graph.by_name["brick0"]
        events = []
        # a fake parent records notifications (ec/afr would mark the
        # child down the same way)
        class Sink:
            def notify(self, ev, src, data):
                events.append(ev)
        posix.parents.append(Sink())
        await c.write_file("/ok", b"fine")
        f = await c.open("/ok", os.O_RDWR)  # pre-failure fd
        # kill the backend under the brick (unmounted/dead disk analog)
        shutil.rmtree(tmp_path / "hb")
        deadline = asyncio.get_event_loop().time() + 10
        while not events:
            assert asyncio.get_event_loop().time() < deadline, \
                "health check never fired"
            await asyncio.sleep(0.1)
        with pytest.raises(FopError) as ei:
            await c.write_file("/nope", b"x")
        assert ei.value.err == errno.ENOTCONN
        # fd fops on cached os-level fds must fail too — a silent
        # write into the dead backend's orphaned inode records no
        # blame and vanishes
        with pytest.raises(FopError) as ei:
            await f.write(b"gone", 0)
        assert ei.value.err == errno.ENOTCONN
        await c.unmount()

    asyncio.run(run())
