"""The multi-process data plane (ISSUE 12): shared-nothing gateway
worker pool + multi-process ``jax.distributed`` mesh.

Worker pool: byte-identical 64-client interleave through a workers=2
supervisor, the per-worker admission split, worker-crash respawn
serving the next request, the SCM_RIGHTS fd-passing fallback lane,
aggregated per-worker metrics families, and the op-version-14 managed
volume-set pin.  Mesh: the 2-process ``jax.distributed`` coordinator
handshake + cross-interpreter sharded encode, and the systematic mesh
tier's parity-rows-only encode property-pinned against the
single-device path.  Shared helpers: the rebalance throttle wave and
the rate-limited mgmt reconnect link also live in this PR.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from glusterfs_tpu.gateway.minihttp import fetch as http
from glusterfs_tpu.gateway.minihttp import request

BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume locks
    type features/locks
    subvolumes posix
end-volume
"""

CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume locks
end-volume
"""


class _Pool:
    """One supervisor subprocess over an in-process brick: the managed
    spawn shape (glusterd runs the same argv) without a glusterd."""

    def __init__(self, tmp_path, workers=2, fd_pass=False,
                 max_clients=64, metrics=True):
        self.tmp = str(tmp_path)
        self.workers = workers
        self.fd_pass = fd_pass
        self.max_clients = max_clients
        self.metrics = metrics
        self.port = 0
        self.metrics_port = 0
        self.proc = None
        self.server = None
        self.statusfile = os.path.join(self.tmp, "gw.status")

    async def __aenter__(self):
        from glusterfs_tpu.daemon import serve_brick

        os.makedirs(os.path.join(self.tmp, "b"), exist_ok=True)
        self.server = await serve_brick(
            BRICK.format(dir=os.path.join(self.tmp, "b")))
        volfile = os.path.join(self.tmp, "client.vol")
        with open(volfile, "w") as f:
            f.write(CLIENT.format(port=self.server.port))
        portfile = os.path.join(self.tmp, "gw.port")
        if self.metrics:
            import socket as _s

            probe = _s.socket()
            probe.bind(("127.0.0.1", 0))
            self.metrics_port = probe.getsockname()[1]
            probe.close()
        argv = [sys.executable, "-m", "glusterfs_tpu.gateway",
                "--volfile", volfile, "--workers", str(self.workers),
                "--pool", "1", "--portfile", portfile,
                "--statusfile", self.statusfile,
                "--max-clients", str(self.max_clients),
                "--metrics-port", str(self.metrics_port)]
        if self.fd_pass:
            argv.append("--fd-pass")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(argv, env=env,
                                     stdout=subprocess.DEVNULL,
                                     stderr=subprocess.PIPE)
        for _ in range(600):
            if os.path.exists(portfile):
                break
            assert self.proc.poll() is None, \
                self.proc.stderr.read().decode(errors="replace")[-2000:]
            await asyncio.sleep(0.1)
        assert os.path.exists(portfile), "supervisor never wrote port"
        with open(portfile) as f:
            self.port = int(f.read())
        return self

    async def __aexit__(self, *exc):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        if self.server is not None:
            await self.server.stop()
        return False

    def status(self) -> dict:
        with open(self.statusfile) as f:
            return json.load(f)

    async def metrics_json(self) -> dict:
        _s, _h, body = await http("127.0.0.1", self.metrics_port,
                                  "GET", "/metrics.json")
        return json.loads(body)

    async def workers_json(self) -> dict:
        _s, _h, body = await http("127.0.0.1", self.metrics_port,
                                  "GET", "/workers.json")
        return json.loads(body)


async def _interleave(pool: _Pool, n_clients: int, body: bytes) -> None:
    """n keep-alive connections PUT distinct objects then GET them
    back byte-identical — across worker processes, one namespace."""
    s, _, _ = await http("127.0.0.1", pool.port, "PUT", "/b")
    assert s == 200, s
    conns = []
    try:
        for _ in range(n_clients):
            conns.append(await asyncio.open_connection(
                "127.0.0.1", pool.port))

        async def one(i):
            r, w = conns[i]
            st, _, _ = await request(r, w, "PUT", f"/b/o{i}",
                                     body=body + str(i).encode())
            assert st == 200, (i, st)
            st, _, data = await request(r, w, "GET", f"/b/o{i}")
            assert st == 200, (i, st)
            assert data == body + str(i).encode(), \
                f"client {i}: bytes diverged across workers"

        await asyncio.gather(*(one(i) for i in range(n_clients)))
    finally:
        for _, w in conns:
            try:
                w.close()
            except Exception:  # noqa: BLE001
                pass


# -- worker pool: reuseport lane ---------------------------------------


def test_worker_pool_64_client_interleave_and_metrics(tmp_path):
    """The acceptance interleave: 64 concurrent HTTP clients against a
    workers=2 pool, byte-identical; the supervisor's aggregated
    families show BOTH workers' shards merged (requests sum across
    shards, gftpu_gateway_workers alive=2), and the admission split
    divided the connection budget per worker at spawn."""
    async def run():
        # budget 256 -> 128 per worker: the kernel's reuseport hash is
        # not exactly even, so 64 clients need headroom per shard (an
        # exact 32/32 split would 503 the skewed side — that's the
        # per-worker admission WORKING, but not what this test pins)
        async with _Pool(tmp_path, workers=2,
                         max_clients=256) as pool:
            st = pool.status()
            assert len(st["workers"]) == 2
            await _interleave(pool, 64, b"w" * 2048)
            fams = await pool.metrics_json()
            assert "gftpu_gateway_requests_total" in fams
            total = sum(v for _l, v in
                        fams["gftpu_gateway_requests_total"]["samples"])
            assert total >= 129  # bucket PUT + 64 PUTs + 64 GETs
            workers_fam = {tuple(sorted(lbl.items())): v for lbl, v in
                           fams["gftpu_gateway_workers"]["samples"]}
            alive = [v for k, v in workers_fam.items()
                     if ("state", "alive") in k]
            assert alive == [2]
            assert "gftpu_gateway_worker_respawns_total" in fams
            # admission split: each worker enforces its share
            wj = await pool.workers_json()
            per = [w["max_clients"] for w in wj["workers"]]
            assert per == [128, 128], per
            # under reuseport both workers should have turned frames;
            # under the fallback the distribution is parent-round-robin
            served = [sum(w["requests"].values())
                      for w in wj["workers"]]
            assert all(s > 0 for s in served), \
                f"a worker served nothing: {served}"

    asyncio.run(run())


def test_worker_crash_respawn_serves_next_request(tmp_path):
    """SIGKILL one worker: the supervisor respawns it (respawns
    counter + fresh pid in the statusfile) and requests keep being
    served throughout."""
    async def run():
        async with _Pool(tmp_path, workers=2, max_clients=32) as pool:
            await _interleave(pool, 4, b"x" * 512)
            victim = pool.status()["workers"][0]["pid"]
            os.kill(victim, signal.SIGKILL)
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                st = pool.status()
                if st["respawns"] >= 1 and \
                        all(w["alive"] for w in st["workers"]):
                    break
                await asyncio.sleep(0.2)
            st = pool.status()
            assert st["respawns"] >= 1, st
            assert victim not in [w["pid"] for w in st["workers"]]
            # the pool serves across and after the respawn window
            ok = 0
            for i in range(8):
                try:
                    s, _, data = await http("127.0.0.1", pool.port,
                                            "GET", "/b/o0")
                    if s == 200:
                        ok += 1
                except (ConnectionError, OSError):
                    pass  # a connection routed into the dying worker
                await asyncio.sleep(0.1)
            assert ok >= 6, f"pool dropped after worker kill ({ok}/8)"

    asyncio.run(run())


# -- worker pool: SCM_RIGHTS fd-passing fallback -----------------------


def test_fd_pass_fallback_lane(tmp_path):
    """--fd-pass forces the parent-accepts + SCM_RIGHTS lane (the
    no-reuseport-kernel fallback): mode recorded, 16-client interleave
    byte-identical, both workers fed by the round-robin."""
    async def run():
        async with _Pool(tmp_path, workers=2, fd_pass=True,
                         max_clients=32) as pool:
            assert pool.status()["mode"] == "fd-pass"
            await _interleave(pool, 16, b"f" * 1024)
            wj = await pool.workers_json()
            served = [sum(w["requests"].values())
                      for w in wj["workers"]]
            assert all(s > 0 for s in served), \
                f"round-robin starved a worker: {served}"

    asyncio.run(run())


# -- managed volume-set pin --------------------------------------------


def test_process_plane_keys_pinned_at_opversion_14(tmp_path):
    """gateway.workers / cluster.mesh-distributed store at cluster
    op-version 14 and refuse on a pre-14 cluster (the mixed-version
    skew guard every _V14 key rides)."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="pv",
                             vtype="distribute",
                             bricks=[{"path": str(tmp_path / "b0")}])
                for key in ("gateway.workers",
                            "cluster.mesh-distributed"):
                    res = await c.call("volume-set", name="pv",
                                       key=key, value="2"
                                       if key == "gateway.workers"
                                       else "on")
                    assert res["ok"], (key, res)
            d.op_version = 13
            async with MgmtClient(d.host, d.port) as c:
                for key in ("gateway.workers",
                            "cluster.mesh-distributed"):
                    with pytest.raises(OSError,
                                       match="op-version 14"):
                        await c.call("volume-set", name="pv",
                                     key=key, value="1")
        finally:
            await d.stop()

    asyncio.run(run())


def test_spawn_gateway_threads_workers_flag(tmp_path):
    """glusterd's gateway spawner passes --workers/--statusfile iff
    the key is set (argv inspected, no daemon actually spawned)."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd

    d = Glusterd(str(tmp_path / "gd"))
    captured = {}

    class _FakeProc:
        pid = 1

        def poll(self):
            return None

    import subprocess as _sp

    orig = _sp.Popen
    try:
        def fake_popen(argv, **kw):
            captured["argv"] = argv
            return _FakeProc()

        _sp.Popen = fake_popen
        vol = {"name": "wv", "type": "distribute", "status": "started",
               "bricks": [], "options": {"gateway.workers": "3"},
               "auth": {}}
        d._spawn_gateway(vol)
        assert "--workers" in captured["argv"]
        assert captured["argv"][
            captured["argv"].index("--workers") + 1] == "3"
        assert "--statusfile" in captured["argv"]
        d.gateway.clear()
        vol["options"] = {}
        d._spawn_gateway(vol)
        assert "--workers" not in captured["argv"]
    finally:
        _sp.Popen = orig


def test_spawn_gateway_divides_qos_rates_across_workers(tmp_path):
    """The PR-17 ceiling fix: N shared-nothing workers each get 1/N of
    the spawn-time --qos-* budget, so the AGGREGATE shed rate a client
    IP sees equals the workers=1 deployment (N workers must enforce
    the operator's ONE budget, not N of them)."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd

    d = Glusterd(str(tmp_path / "gd"))
    captured = {}

    class _FakeProc:
        pid = 1

        def poll(self):
            return None

    import subprocess as _sp

    orig = _sp.Popen
    try:
        def fake_popen(argv, **kw):
            captured["argv"] = argv
            return _FakeProc()

        _sp.Popen = fake_popen

        def spawn(workers):
            d.gateway.clear()
            opts = {"server.qos": "on",
                    "server.qos-fops-per-sec": "100",
                    "server.qos-bytes-per-sec": "1MB",
                    "server.qos-burst": "4"}
            if workers:
                opts["gateway.workers"] = str(workers)
            d._spawn_gateway({"name": "qv", "type": "distribute",
                              "status": "started", "bricks": [],
                              "options": opts, "auth": {}})
            argv = captured["argv"]

            def arg(flag):
                return float(argv[argv.index(flag) + 1])

            return (arg("--qos-fops"), arg("--qos-bytes"),
                    arg("--qos-burst"))

        one = spawn(0)          # no pool: full budget in one process
        two = spawn(2)          # pool of 2: half each
        assert one == (100.0, 1024.0 * 1024, 4.0), one
        assert two[0] * 2 == one[0], (one, two)
        assert two[1] * 2 == one[1], (one, two)
        assert two[2] * 2 == one[2], (one, two)
        # 0 = unlimited survives any pool width (never divided to
        # "almost off")
        d.gateway.clear()
        d._spawn_gateway({"name": "qv", "type": "distribute",
                          "status": "started", "bricks": [],
                          "options": {"server.qos": "on",
                                      "gateway.workers": "4"},
                          "auth": {}})
        argv = captured["argv"]
        assert float(argv[argv.index("--qos-fops") + 1]) == 0
    finally:
        _sp.Popen = orig


def test_mesh_env_threaded_through_brick_spawn(tmp_path):
    """cluster.mesh-distributed: _mesh_env hands every brick its rank,
    the brick count, and ONE stable coordinator endpoint (persisted in
    the volinfo so respawns redial the same port)."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd

    d = Glusterd(str(tmp_path / "gd"))
    bricks = [{"name": f"mv-brick-{i}", "node": d.uuid,
               "path": str(tmp_path / f"b{i}")} for i in range(3)]
    vol = {"name": "mv", "type": "distribute", "status": "started",
           "bricks": bricks,
           "options": {"cluster.mesh-distributed": "on"}}
    d.state["volumes"]["mv"] = vol
    envs = [d._mesh_env(vol, b) for b in bricks]
    assert all(e is not None for e in envs)
    coords = {e["GFTPU_MESH_COORDINATOR"] for e in envs}
    assert len(coords) == 1, "ranks must dial one coordinator"
    assert [e["GFTPU_MESH_RANK"] for e in envs] == ["0", "1", "2"]
    assert {e["GFTPU_MESH_PROCESSES"] for e in envs} == {"3"}
    assert vol.get("mesh-coordinator-port"), "port not persisted"
    # off volumes get no mesh env
    vol["options"] = {}
    assert d._mesh_env(vol, bricks[0]) is None


# -- multi-process jax.distributed mesh --------------------------------


@pytest.mark.slow
def test_distributed_mesh_2proc_handshake_and_sharded_encode():
    """The dryrun's 2-process virtual-mesh attempt as a unit: two rank
    subprocesses join a fresh coordinator (gloo CPU collectives) and
    push ONE sharded encode through the GLOBAL 2-device mesh, each
    verifying its addressable shards against the single-process
    reference — the coordinator handshake + a cross-interpreter
    sharded launch, deadline-pinned in kill-able subprocesses."""
    import __graft_entry__ as graft

    rec = graft._dryrun_distributed(150.0)
    assert rec["ok"], rec
    assert rec["mode"] == "distributed-2proc-virtual-mesh"
    assert rec["n_processes"] == 2


def test_meshd_env_glue_and_state():
    """meshd.configured parses the spawner's env; malformed env is
    ignored (a typo'd option must not crash a brick daemon)."""
    from glusterfs_tpu.parallel import meshd

    env = {meshd.ENV_COORDINATOR: "127.0.0.1:9999",
           meshd.ENV_PROCESSES: "4", meshd.ENV_RANK: "2"}
    assert meshd.configured(env) == {"coordinator": "127.0.0.1:9999",
                                     "processes": 4, "rank": 2}
    assert meshd.configured({}) is None
    bad = dict(env)
    bad[meshd.ENV_RANK] = "two"
    assert meshd.configured(bad) is None
    assert meshd.state()["status"] in ("off", "joining", "ready",
                                       "failed")


def test_local_vs_global_device_count():
    """The distributed path of device discovery: in this (single-
    process) runtime the global and local counts agree; both ride the
    same wedge-safe cache."""
    from glusterfs_tpu.parallel import mesh_codec

    assert mesh_codec.device_count() == 8
    assert mesh_codec.local_device_count() == 8
    assert mesh_codec.device_count_cached() == 8


# -- systematic mesh tier: parity property vs the single-device path ---


@pytest.mark.parametrize("k,r", [(4, 2), (8, 3)])
def test_mesh_systematic_encode_parity_property(k, r):
    """Property pin (ROADMAP item 5 code half): for random stripe
    batches, the parity-rows-only sharded encode and the sharded
    parity-delta are FRAGMENT-identical to the single-device
    systematic path."""
    from glusterfs_tpu.ops import gf256
    from glusterfs_tpu.ops.codec import Codec
    from glusterfs_tpu.parallel import mesh_codec

    ref = Codec(k, r, "ref", systematic=True)
    rng = np.random.default_rng(k * 100 + r)
    for _ in range(4):
        stripes = int(rng.integers(1, 40))
        data = rng.integers(0, 256, stripes * k * gf256.CHUNK_SIZE,
                            dtype=np.uint8)
        np.testing.assert_array_equal(
            mesh_codec.sharded_encode(k, r, data, systematic=True),
            ref.encode(data))
        np.testing.assert_array_equal(
            mesh_codec.sharded_parity(k, r, data),
            ref.encode_delta(data))


def test_mesh_systematic_delta_flush_rides_parity_lane():
    """BatchingCodec.encode_delta_async on a mesh-armed systematic
    codec lands on the mesh parity program (a 'delta' launch on the
    mesh counters), byte-identical to the single-device delta."""
    from glusterfs_tpu.ops import gf256
    from glusterfs_tpu.ops.batch import BatchingCodec
    from glusterfs_tpu.ops.codec import Codec

    codec = BatchingCodec(4, 2, "ref", mesh=True, min_batch=0,
                          systematic=True)
    ref = Codec(4, 2, "ref", systematic=True)
    d = np.random.default_rng(5).integers(
        0, 256, 16 * 4 * gf256.CHUNK_SIZE, dtype=np.uint8)

    async def run():
        assert await codec.ensure_mesh()
        pds = await codec.encode_delta_async(d)
        np.testing.assert_array_equal(pds, ref.encode_delta(d))
        assert codec.mesh_launches.get(("delta", "serve")) == 1

    asyncio.run(run())
    codec.close()


# -- shared helpers landed with this PR --------------------------------


def test_throttle_wave_width_and_peak():
    """svcutil.ThrottleWave: never more than `width` in flight, peak
    tracked, drain joins everything (the one loop both rebalance walks
    now share)."""
    from glusterfs_tpu.mgmt.svcutil import ThrottleWave

    inflight = {"now": 0, "peak": 0}

    async def job():
        inflight["now"] += 1
        inflight["peak"] = max(inflight["peak"], inflight["now"])
        await asyncio.sleep(0.01)
        inflight["now"] -= 1

    async def run():
        wave = ThrottleWave()
        for _ in range(12):
            await wave.admit(job(), width=3)
        await wave.drain()
        assert inflight["now"] == 0
        assert 1 <= inflight["peak"] <= 3
        assert wave.max_inflight <= 3

    asyncio.run(run())


def test_mgmt_link_reconnect_rate_limited_and_replays(tmp_path):
    """MgmtLink: survives a glusterd restart by reconnect + one replay
    of the failed push; while the endpoint stays down, reconnect
    attempts are rate-limited to one per interval."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd
    from glusterfs_tpu.mgmt.rebalanced import MgmtLink

    async def run():
        d = Glusterd(str(tmp_path / "gd1"))
        await d.start()
        port = d.port
        link = MgmtLink(d.host, port, min_reconnect_s=5.0)
        ps = await link.call("peer-status")
        assert "peers" in ps or ps is not None
        # restart glusterd on the SAME port under the held connection
        await d.stop()
        d2 = Glusterd(str(tmp_path / "gd2"), port=port)
        await d2.start()
        try:
            # the held connection is dead: transport error -> one
            # reconnect -> replay lands on the restarted glusterd
            ps2 = await link.call("peer-status")
            assert ps2 is not None
        finally:
            await link.close()
            await d2.stop()
        # dead endpoint: first dial fails honestly, the immediate
        # second attempt is rate-limited (no second dial burned)
        link2 = MgmtLink("127.0.0.1", port, min_reconnect_s=30.0)
        with pytest.raises(OSError):
            await link2.call("peer-status")
        t0 = time.monotonic()
        with pytest.raises(ConnectionError, match="rate-limited"):
            await link2.call("peer-status")
        assert time.monotonic() - t0 < 1.0, "rate limit should be fast"
        await link2.close()

    asyncio.run(run())
