"""Same-host shared-memory bulk lane (rpc/shm, op-version 17).

The pinned no-copy proof (a readv reply through the armed lane reaches
the client as memoryviews INTO the shared mapping while the socket
moves header-only bytes) plus the full fallback matrix the issue
demands: non-advertising peer, live downgrade mid-connection
(EOPNOTSUPP remembered like compound/xorv), arena exhaustion under a
concurrent burst (inline fallback, byte-identical), peer SIGKILL with
descriptors in flight (no leaked mappings), and cross-host simulation
(boot-id mismatch: the lane never arms).
"""

import asyncio
import gc
import os

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.rpc import shm, wire

from .harness import BRICK_VOLFILE, BrickProc

pytestmark = pytest.mark.skipif(
    not shm.supported(), reason="no memfd/SCM_RIGHTS on this platform")

BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume

volume locks
    type features/locks
    subvolumes posix
end-volume

volume srv
    type protocol/server
{opts}    subvolumes locks
end-volume
"""

CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume srv
end-volume
"""


async def _up(tmp_path, srv_opts="", timeout=200):
    server = await serve_brick(
        BRICK.format(dir=tmp_path / "b", opts=srv_opts))
    g = Graph.construct(CLIENT.format(port=server.port))
    c = Client(g)
    await c.mount()
    for _ in range(timeout):
        if g.top.connected:
            break
        await asyncio.sleep(0.05)
    assert g.top.connected
    return server, c, g.top


async def _settle(check, rounds=40):
    """GC-driven release: poll with collect until ``check`` holds."""
    for _ in range(rounds):
        gc.collect()
        if check():
            return True
        await asyncio.sleep(0.05)
    return check()


# -- the lane itself: arming + the pinned no-copy proof ---------------------

def test_lane_arms_and_readv_is_zero_copy(tmp_path):
    async def run():
        server, c, top = await _up(tmp_path)
        try:
            assert top._peer_shm and top._shm_rx is not None
            conn = next(iter(server.connections))
            assert conn.info()["shm"] == "armed"

            body = bytes(os.urandom(100_000))
            await c.write_file("/f", body)
            btx0, brx0 = top.bytes_tx, top.bytes_rx
            rx0 = shm.shm_stats["rx_bytes"]
            f = await c.open("/f", os.O_RDONLY)
            data = await top.readv(f.fd, len(body), 0)
            # the reply blob is a VIEW, not bytes — and it resolves
            # through the arena counters
            assert isinstance(data, memoryview), type(data)
            assert bytes(data) == body
            assert shm.shm_stats["rx_bytes"] - rx0 >= len(body)
            # header-only socket traffic: the 100 KB payload moved
            # through the mapping, the socket carried the frame header
            # + a 20-byte descriptor, both directions
            assert top.bytes_tx - btx0 < 600, top.bytes_tx - btx0
            assert top.bytes_rx - brx0 < 600, top.bytes_rx - brx0
            # shared-mapping proof: flip a byte through the SERVER's
            # mapping and watch it change under the client's view
            idx = bytes(conn.shm_tx.mm).find(body[:64])
            assert idx >= shm.HDR_SIZE
            conn.shm_tx.mm[idx] = data[0] ^ 0xFF
            assert data[0] == body[0] ^ 0xFF
            # release rides GC: dropping the view frees the descriptor
            # and the ack watermark hands the slot back to the producer
            del data
            assert await _settle(lambda: top._shm_rx.used() == 0)
            await f.close()
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


# -- fallback matrix --------------------------------------------------------

def test_non_advertising_peer_stays_inline(tmp_path):
    """network.shm-transport off on the brick: no advert, lane never
    arms, traffic is byte-identical inline — and nothing is counted as
    a fallback (declining is not failing)."""
    async def run():
        before = dict(shm.fallback_stats)
        server, c, top = await _up(
            tmp_path, srv_opts="    option shm-transport off\n")
        try:
            assert not top._peer_shm and top._shm_tx is None
            conn = next(iter(server.connections))
            assert conn.info()["shm"] == "off"
            body = b"inline only" * 999
            await c.write_file("/f", body)
            assert bytes(await c.read_file("/f")) == body
            assert shm.fallback_stats == before
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_cross_host_boot_id_mismatch_never_arms(tmp_path):
    """The cheap cross-host screen: a foreign boot-id means the
    side-channel cannot exist here — the client never dials and the
    lane never arms (fallback reason recorded)."""
    async def run():
        server = await serve_brick(
            BRICK.format(dir=tmp_path / "b", opts=""))
        g = Graph.construct(CLIENT.format(port=server.port))
        top = g.top
        orig = top._shm_arm

        async def foreign(ad):
            await orig({**ad, "boot-id": "another-machine-entirely"})

        top._shm_arm = foreign
        c = Client(g)
        miss0 = shm.fallback_stats.get("cross-host", 0)
        await c.mount()
        try:
            for _ in range(200):
                if top.connected:
                    break
                await asyncio.sleep(0.05)
            assert top.connected
            assert not top._peer_shm and top._shm_tx is None
            assert shm.fallback_stats.get("cross-host", 0) == miss0 + 1
            body = b"x" * 30_000
            await c.write_file("/f", body)
            assert bytes(await c.read_file("/f")) == body
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_live_downgrade_is_remembered_and_call_retried(tmp_path):
    """Mid-connection downgrade: the brick loses its rx arena, answers
    the next FL_SHM frame EOPNOTSUPP + shm-unsupported, and the client
    disarms, REMEMBERS the refusal (like compound/xorv) and resends
    that call inline — the caller never sees it."""
    async def run():
        server, c, top = await _up(tmp_path)
        try:
            assert top._peer_shm
            conn = next(iter(server.connections))
            conn.shm_rx.close()  # the brick's c2s mapping dies
            down0 = shm.fallback_stats.get("downgrade", 0)
            body = bytes(os.urandom(8192))
            await c.write_file("/f", body)  # blob -> FL_SHM -> refused
            assert bytes(await c.read_file("/f")) == body
            assert top._shm_refused and not top._peer_shm
            assert top._shm_tx is None and top._shm_rx is None
            assert shm.fallback_stats.get("downgrade", 0) == down0 + 1
            # the brick disarmed its half too: no FL_SHM reply can
            # chase the torn-down client mapping
            assert not conn.shm_tx_armed
            # ...and the refusal sticks across a reconnect
            await top._drop_connection()
            for _ in range(200):
                if top.connected:
                    break
                await asyncio.sleep(0.05)
            assert top.connected and not top._peer_shm
            assert bytes(await c.read_file("/f")) == body
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_arena_exhaustion_burst_falls_back_per_frame(tmp_path):
    """64 concurrent writers against a minimum-size (64 KiB) arena:
    frames that fit ride the lane, frames that don't ship inline
    (reason arena-full), and every byte lands intact — the per-frame
    fallback contract."""
    async def run():
        server, c, top = await _up(
            tmp_path, srv_opts="    option shm-arena-size 64KB\n")
        try:
            assert top._peer_shm
            assert top._shm_tx.cap == 64 * 1024 - shm.HDR_SIZE
            full0 = shm.fallback_stats.get("arena-full", 0)
            bodies = {i: bytes([i]) * (48 * 1024) for i in range(64)}

            async def one(i):
                await c.write_file(f"/f{i}", bodies[i])

            await asyncio.gather(*(one(i) for i in range(64)))
            # two 48 KiB frames can never share the ring: the burst
            # must have forced inline fallbacks
            assert shm.fallback_stats.get("arena-full", 0) > full0
            for i in range(64):
                assert bytes(await c.read_file(f"/f{i}")) == bodies[i], i
            # the lane survived the burst armed
            assert top._peer_shm and not top._shm_tx.dead
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_live_volume_set_off_is_per_frame(tmp_path):
    """Flipping shm-transport off while the lane is armed downgrades
    per frame, both directions, no reconnect — and flipping it back
    resumes the lane on the same connection."""
    async def run():
        server, c, top = await _up(tmp_path)
        try:
            assert top._peer_shm
            body = bytes(os.urandom(20_000))
            await c.write_file("/f", body)
            f = await c.open("/f", os.O_RDONLY)

            async def read_once():
                data = await top.readv(f.fd, len(body), 0)
                out = bytes(data)
                del data
                return out

            server.top.opts["shm-transport"] = False
            top.opts["shm-transport"] = False
            tx0 = shm.shm_stats["tx_frames"]
            assert await read_once() == body  # reply shipped inline
            await c.write_file("/g", body)    # request shipped inline
            assert shm.shm_stats["tx_frames"] == tx0
            server.top.opts["shm-transport"] = True
            top.opts["shm-transport"] = True
            assert await read_once() == body
            assert shm.shm_stats["tx_frames"] > tx0  # lane resumed
            await f.close()
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_peer_sigkill_reclaims_all_mappings(tmp_path):
    """SIGKILL the brick subprocess with a descriptor still held by a
    consumer view: the client's teardown defers the rx close under the
    live view (still readable — the memfd outlives its creator), and
    GC of the view drives live mappings back to baseline.  The leak
    audit."""
    brick = BrickProc(str(tmp_path), "b0")

    async def run():
        brick.start()
        g = Graph.construct(
            CLIENT.replace("option remote-subvolume srv",
                           "option remote-subvolume locks")
            .format(port=brick.port))
        top = g.top
        base = shm.live_mappings()
        c = Client(g)
        await c.mount()
        try:
            for _ in range(200):
                if top.connected:
                    break
                await asyncio.sleep(0.05)
            assert top.connected and top._peer_shm
            assert shm.live_mappings() == base + 2  # our tx + rx
            body = bytes(os.urandom(64 * 1024))
            await c.write_file("/f", body)
            f = await c.open("/f", os.O_RDONLY)
            data = await top.readv(f.fd, len(body), 0)
            assert isinstance(data, memoryview)

            brick.kill()  # descriptors in flight
            for _ in range(200):
                if not top.connected:
                    break
                await asyncio.sleep(0.05)
            assert not top.connected
            # fd-close semantics: the mapping (and our view) survive
            # the producer's death until WE let go
            assert bytes(data) == body
            del data
            assert await _settle(lambda: shm.live_mappings() == base), \
                shm.live_mappings()
        finally:
            await c.unmount()
            brick.kill()

    asyncio.run(run())


# -- codec-level sanity (no transport) --------------------------------------

def test_fl_shm_pack_unpack_roundtrip_and_watermark():
    """One frame through a tx/rx arena pair over the same buffer:
    descriptors resolve to views with the payload bytes, GC of the
    views advances the shared watermark, and the producer reclaims."""
    tx, fd = shm.ShmTx.create(256 * 1024)
    rx = shm.ShmRx.attach(fd)
    os.close(fd)
    try:
        payload = {"blob": wire.Blob(b"B" * 5000), "n": 7}
        frames = wire.pack_frames(3, wire.MT_REPLY, payload, tx)
        assert len(frames) == 1
        rec = bytes(frames[0])[4:]
        assert rec[5] == wire.FL_SHM
        assert tx.used() == 5000
        xid, mtype, out = wire.unpack(rec, rx)
        assert (xid, mtype) == (3, wire.MT_REPLY)
        assert bytes(out["blob"]) == b"B" * 5000 and out["n"] == 7
        del out
        gc.collect()
        assert rx.used() == 0
        # the reclaim is lazy: the next allocation reads the watermark
        assert tx.put_blobs([memoryview(b"z")]) is not None
        assert tx.used() == 1
        # an unarmed receiver must refuse the record, not misread it
        with pytest.raises(wire.ShmDecodeError):
            wire.unpack(rec, None)
    finally:
        tx.close()
        rx.close()
