"""Disperse (EC) volume end-to-end: write/read parity, unaligned RMW,
degraded reads, quorum, heal — the tests/basic/ec/ec.t + ec-read-policy.t
+ ec-data-heal.t analog running on a 4+2 volume of local bricks."""

import os

import numpy as np
import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc

K, R = 4, 2
N = K + R
STRIPE = K * 512


def volfile(base) -> str:
    from glusterfs_tpu.utils.volspec import ec_volfile

    return ec_volfile(base, N, R, options={"cpu-extensions": "auto"})


@pytest.fixture
def vol(tmp_path):
    g = Graph.construct(volfile(tmp_path))
    c = SyncClient(g)
    c.mount()
    yield c, g.top
    c.close()


def _rand(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_roundtrip_sizes(vol):
    c, ec = vol
    for i, size in enumerate([1, 511, 512, STRIPE - 1, STRIPE,
                              STRIPE + 1, 3 * STRIPE + 100, 1 << 20]):
        data = _rand(size, seed=i).tobytes()
        c.write_file(f"/f{i}", data)
        assert c.read_file(f"/f{i}") == data, f"size {size}"
        assert c.stat(f"/f{i}").size == size


def test_fragments_on_bricks(vol, tmp_path):
    c, ec = vol
    data = _rand(2 * STRIPE, seed=9).tobytes()
    c.write_file("/frag", data)
    # each brick holds exactly 2 chunks (1024 B) of fragment data
    for i in range(N):
        p = tmp_path / f"brick{i}" / "frag"
        assert p.stat().st_size == 2 * 512
    # fragments are the non-systematic codewords: no brick holds plaintext
    head = data[:512]
    for i in range(N):
        assert (tmp_path / f"brick{i}" / "frag").read_bytes()[:512] != head


def test_unaligned_overwrite_rmw(vol):
    c, ec = vol
    base = bytearray(_rand(3 * STRIPE, seed=3).tobytes())
    c.write_file("/rmw", bytes(base))
    f = c.open("/rmw")
    # overwrite a range crossing stripe boundaries at odd offsets
    patch = _rand(700, seed=4).tobytes()
    f.write(patch, 1800)
    base[1800:2500] = patch
    # append past EOF with a gap (zero fill)
    f.write(b"tail", len(base) + 100)
    f.close()
    expect = bytes(base) + b"\0" * 100 + b"tail"
    assert c.read_file("/rmw") == expect


def test_degraded_read(vol):
    c, ec = vol
    data = _rand(5 * STRIPE + 123, seed=5).tobytes()
    c.write_file("/deg", data)
    ec.set_child_up(0, False)
    ec.set_child_up(3, False)
    assert c.read_file("/deg") == data  # decode from any K survivors
    ec.set_child_up(0, True)
    ec.set_child_up(3, True)


def test_quorum_loss(vol):
    c, ec = vol
    c.write_file("/q", b"x" * STRIPE)
    for i in range(R + 1):  # drop to K-1 up
        ec.set_child_up(i, False)
    with pytest.raises(FopError):
        c.read_file("/q")
    with pytest.raises(FopError):
        c.write_file("/q2", b"y")
    for i in range(R + 1):
        ec.set_child_up(i, True)


def test_write_with_brick_down_then_heal(vol):
    c, ec = vol
    data = _rand(4 * STRIPE, seed=7).tobytes()
    c.write_file("/heal", data)
    # brick 1 dies; writes continue (degraded)
    ec.set_child_up(1, False)
    patch = _rand(STRIPE, seed=8).tobytes()
    f = c.open("/heal")
    f.write(patch, STRIPE)
    f.close()
    expect = data[:STRIPE] + patch + data[2 * STRIPE:]
    ec.set_child_up(1, True)  # brick returns with stale fragment
    # heal detects divergence
    info = c._run(ec.heal_info(Loc("/heal")))
    assert 1 in info["bad"]
    healed = c._run(ec.heal_file("/heal"))
    assert healed["healed"] == [1]
    info2 = c._run(ec.heal_info(Loc("/heal")))
    assert info2["bad"] == []
    # force reads to use the healed brick: drop two others
    ec.set_child_up(4, False)
    ec.set_child_up(5, False)
    assert c.read_file("/heal") == expect
    ec.set_child_up(4, True)
    ec.set_child_up(5, True)


def test_stale_brick_excluded_from_reads(vol):
    c, ec = vol
    data = _rand(2 * STRIPE, seed=11).tobytes()
    c.write_file("/stale", data)
    ec.set_child_up(2, False)
    newdata = _rand(2 * STRIPE, seed=12).tobytes()
    c.write_file("/stale", newdata)
    ec.set_child_up(2, True)  # stale brick is back and claims to be up
    # reads must never mix the stale fragment in (version filtering)
    for _ in range(2 * N):  # cycle round-robin through all combos
        assert c.read_file("/stale") == newdata


def test_truncate(vol):
    c, ec = vol
    data = _rand(3 * STRIPE, seed=13).tobytes()
    c.write_file("/t", data)
    c.truncate("/t", 1000)  # mid-stripe shrink
    assert c.read_file("/t") == data[:1000]
    c.truncate("/t", 5000)  # grow: zero-extend
    assert c.read_file("/t") == data[:1000] + b"\0" * 4000
    assert c.stat("/t").size == 5000


def test_namespace_ops(vol):
    c, ec = vol
    c.mkdir("/d")
    c.write_file("/d/x", b"1")
    assert c.listdir("/d") == ["x"]
    c.rename("/d/x", "/d/y")
    assert c.read_file("/d/y") == b"1"
    c.unlink("/d/y")
    c.rmdir("/d")
    assert c.listdir("/") == []


def test_ec_xattr_namespace_protected(vol):
    c, ec = vol
    c.write_file("/p", b"z")
    with pytest.raises(FopError):
        c.setxattr("/p", {"trusted.ec.version": b"hack"})
    c.setxattr("/p", {"user.ok": b"fine"})
    # internal xattrs are hidden from listing
    assert "trusted.ec.version" not in c.getxattr("/p")


def test_statedump(vol):
    c, ec = vol
    d = c.statedump()
    priv = d["layers"]["disp"]["private"]
    assert priv["fragments"] == K and priv["redundancy"] == R
    assert priv["up_count"] == N


def test_read_during_write_sees_whole_version(tmp_path):
    """A read racing a write on the same gfid must decode a consistent
    version — never a mix of old and new fragments.  Per-transaction
    lk-owners make the read's brick inodelk conflict with this client's
    own in-flight write (advisor r1 finding; reference frame lk_owner)."""
    import asyncio

    from glusterfs_tpu.api.glfs import Client

    # per-brick DISTINCT delay durations: hand-built spec (the shared
    # builder applies identical layers to every brick)
    out = []
    for i in range(N):
        # stagger each brick's writev completion so a racing read lands
        # while some bricks hold new fragments and others still old ones
        out.append(f"volume p{i}\n    type storage/posix\n"
                   f"    option directory {tmp_path}/brick{i}\nend-volume\n")
        out.append(f"volume d{i}\n    type debug/delay-gen\n"
                   f"    option enable writev\n"
                   f"    option delay-percentage 100\n"
                   f"    option delay-duration {50000 + i * 100000}\n"
                   f"    subvolumes p{i}\nend-volume\n")
        out.append(f"volume b{i}\n    type features/locks\n"
                   f"    subvolumes d{i}\nend-volume\n")
    subs = " ".join(f"b{i}" for i in range(N))
    out.append(f"volume disp\n    type cluster/disperse\n"
               f"    option redundancy {R}\n"
               f"    subvolumes {subs}\nend-volume\n")
    volspec = "\n".join(out)

    vers = [bytes(_rand(2 * STRIPE, seed=s)) for s in range(5)]

    async def run():
        c = Client(Graph.construct(volspec))
        await c.mount()
        await c.write_file("/f", vers[0])
        fd = await c.open("/f")
        await fd.read(2 * STRIPE, 0)  # warm the jit paths off the race
        mixed = 0
        for rnd in range(1, 5):
            wtask = asyncio.ensure_future(fd.write(vers[rnd], 0))
            await asyncio.sleep(0.3)  # inside the 0.05..0.55s brick window
            got = await fd.read(2 * STRIPE, 0)
            await wtask
            if got not in (vers[rnd - 1], vers[rnd]):
                mixed += 1
        await fd.close()
        await c.unmount()
        return mixed

    # without per-txn lk-owners this measures 3-4 mixed reads out of 4
    assert asyncio.run(run()) == 0, "read decoded a mix of write versions"


@pytest.mark.parametrize("k,r", [(2, 1), (4, 1), (4, 3), (8, 3),
                                 (8, 4), (16, 4)])
def test_config_sweep_roundtrip_and_degraded(tmp_path, k, r):
    """Redundancy sweep at the VOLUME level (the reference's
    ec-{3-1,4-1,5-2,6-2,12-4}.t config matrix): write, read back,
    degraded read with r bricks down."""
    from glusterfs_tpu.utils.volspec import ec_volfile

    g = Graph.construct(ec_volfile(tmp_path, k + r, r,
                                   options={"cpu-extensions": "auto"}))
    c = SyncClient(g)
    c.mount()
    try:
        data = _rand(k * 512 * 3 + 137, seed=k * 31 + r)  # unaligned
        c.write_file("/s", bytes(data))
        assert c.read_file("/s") == bytes(data)
        # degradation: wipe r whole brick stores, reads reconstruct
        import shutil

        for i in range(r):
            shutil.rmtree(tmp_path / f"brick{i}")
        assert c.read_file("/s") == bytes(data)
    finally:
        c.close()
