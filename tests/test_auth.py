"""Transport auth + TLS: addr allow/reject, login credentials, the
handshake gate, and TLS bricks (reference xlators/protocol/auth,
server_setvolume gf_authenticate, rpc-transport/socket SSL)."""

import asyncio
import subprocess

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.rpc import wire

from .harness import BRICK_VOLFILE

def _auth_brick(**opts) -> str:
    lines = "".join(f"    option {k} {v}\n"
                    for k, v in opts.items() if k != "dir" and v)
    return BRICK_VOLFILE + (
        "\nvolume srv\n    type protocol/server\n"
        f"{lines}    subvolumes locks\nend-volume\n")



CLIENT_VOLFILE = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume srv
    option reconnect-interval 0.1
{extra}end-volume
"""


async def _wait(pred, timeout=5.0):
    for _ in range(int(timeout / 0.05)):
        if pred():
            return True
        await asyncio.sleep(0.05)
    return pred()


def _mk_client(port: int, **opts) -> Graph:
    extra = "".join(f"    option {k} {v}\n" for k, v in opts.items())
    return Graph.construct(CLIENT_VOLFILE.format(port=port, extra=extra))


def test_auth_addr_reject(tmp_path):
    """auth.reject patterns drop the transport before any RPC."""
    async def run():
        server = await serve_brick(_auth_brick(**{
            "auth-allow": "*", "auth-reject": "127.*"}).format(
                dir=tmp_path / "b"))
        g = _mk_client(server.port)
        c = Client(g)
        await c.mount()
        assert not await _wait(lambda: g.top.connected, timeout=1.5)
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_auth_login(tmp_path):
    """Brick credentials: wrong/missing pair refused, right pair works."""
    async def run():
        server = await serve_brick(_auth_brick(**{
            "auth-user": "u1", "auth-password": "s3cret"}).format(
                dir=tmp_path / "b"))
        # no credentials -> handshake refused, never connects
        g0 = _mk_client(server.port)
        c0 = Client(g0)
        await c0.mount()
        assert not await _wait(lambda: g0.top.connected, timeout=1.5)
        await c0.unmount()
        # wrong password -> refused
        g1 = _mk_client(server.port, username="u1", password="wrong")
        c1 = Client(g1)
        await c1.mount()
        assert not await _wait(lambda: g1.top.connected, timeout=1.5)
        await c1.unmount()
        # right pair -> full fop access
        g2 = _mk_client(server.port, username="u1", password="s3cret")
        c2 = Client(g2)
        await c2.mount()
        assert await _wait(lambda: g2.top.connected)
        await c2.write_file("/x", b"authed")
        assert await c2.read_file("/x") == b"authed"
        await c2.unmount()
        await server.stop()

    asyncio.run(run())


def test_fop_before_handshake_refused(tmp_path):
    """The SETVOLUME gate: raw fops without a handshake get EACCES."""
    async def run():
        server = await serve_brick(_auth_brick().format(dir=tmp_path / "b"))
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       server.port)
        writer.write(wire.pack(1, wire.MT_CALL,
                               ["mkdir", [], {"loc": None}]))
        await writer.drain()
        rec = await asyncio.wait_for(wire.read_frame(reader), 5)
        _, mtype, payload = wire.unpack(rec)
        assert mtype == wire.MT_ERROR
        assert isinstance(payload, FopError) and payload.err == 13
        writer.close()
        await server.stop()

    asyncio.run(run())


@pytest.fixture(scope="module")
def tls_cert(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    cert, key = str(d / "brick.pem"), str(d / "brick.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "2", "-subj",
         "/CN=gftpu-test"], check=True, capture_output=True)
    return cert, key


def test_tls_brick(tmp_path, tls_cert):
    """TLS end-to-end: verified client works, plaintext client cannot."""
    cert, key = tls_cert

    async def run():
        server = await serve_brick(_auth_brick(**{
            "ssl": "on", "ssl-cert": cert, "ssl-key": key}).format(
                dir=tmp_path / "b"))
        # plaintext client never completes a handshake
        g0 = _mk_client(server.port)
        c0 = Client(g0)
        await c0.mount()
        assert not await _wait(lambda: g0.top.connected, timeout=1.5)
        await c0.unmount()
        # TLS client verifying the brick cert: full access
        g1 = _mk_client(server.port, ssl="on", **{"ssl-ca": cert})
        c1 = Client(g1)
        await c1.mount()
        assert await _wait(lambda: g1.top.connected)
        await c1.write_file("/t", b"over tls")
        assert await c1.read_file("/t") == b"over tls"
        await c1.unmount()
        await server.stop()

    asyncio.run(run())


@pytest.fixture(scope="module")
def tls_pki(tmp_path_factory):
    """A CA, a CA-signed brick cert, and two CA-signed client certs
    with different CNs — the auth.ssl-allow test matrix."""
    d = tmp_path_factory.mktemp("pki")
    ca_key, ca_cert = str(d / "ca.key"), str(d / "ca.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", ca_key, "-out", ca_cert, "-days", "2", "-subj",
         "/CN=gftpu-ca"], check=True, capture_output=True)

    def signed(cn: str) -> tuple[str, str]:
        key, csr = str(d / f"{cn}.key"), str(d / f"{cn}.csr")
        crt = str(d / f"{cn}.pem")
        subprocess.run(
            ["openssl", "req", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", csr, "-subj", f"/CN={cn}"],
            check=True, capture_output=True)
        subprocess.run(
            ["openssl", "x509", "-req", "-in", csr, "-CA", ca_cert,
             "-CAkey", ca_key, "-CAcreateserial", "-out", crt,
             "-days", "2"], check=True, capture_output=True)
        return crt, key

    return {"ca": ca_cert, "brick": signed("brick"),
            "good": signed("good-client"), "evil": signed("evil-client")}


def test_tls_cn_allow_list(tmp_path, tls_pki):
    """auth.ssl-allow (reference server.c:1857): a VALID CA-signed cert
    with the wrong CN is refused at SETVOLUME; the allowed CN gets full
    fop access over the same listener."""
    bcert, bkey = tls_pki["brick"]

    async def run():
        server = await serve_brick(_auth_brick(**{
            "ssl": "on", "ssl-cert": bcert, "ssl-key": bkey,
            "ssl-ca": tls_pki["ca"],
            "ssl-allow": "good-*"}).format(dir=tmp_path / "b"))

        def tls_client(cert, key):
            return _mk_client(server.port, ssl="on",
                              **{"ssl-ca": tls_pki["ca"],
                                 "ssl-cert": cert, "ssl-key": key})

        # valid certificate, wrong identity: transport refused
        g0 = tls_client(*tls_pki["evil"])
        c0 = Client(g0)
        await c0.mount()
        assert not await _wait(lambda: g0.top.connected, timeout=1.5)
        await c0.unmount()
        # allow-listed CN: full access
        g1 = tls_client(*tls_pki["good"])
        c1 = Client(g1)
        await c1.mount()
        assert await _wait(lambda: g1.top.connected)
        await c1.write_file("/cn", b"identified")
        assert await c1.read_file("/cn") == b"identified"
        await c1.unmount()
        await server.stop()

    asyncio.run(run())


def test_tls_cn_allow_list_requires_verified_cert(tmp_path, tls_pki):
    """ssl-allow with NO client-cert verification configured (no
    ssl-ca) fails closed: without a verified peer identity nothing
    matches the list."""
    bcert, bkey = tls_pki["brick"]

    async def run():
        server = await serve_brick(_auth_brick(**{
            "ssl": "on", "ssl-cert": bcert, "ssl-key": bkey,
            "ssl-allow": "good-*"}).format(dir=tmp_path / "b"))
        g = _mk_client(server.port, ssl="on",
                       **{"ssl-ca": tls_pki["ca"]})
        c = Client(g)
        await c.mount()
        assert not await _wait(lambda: g.top.connected, timeout=1.5)
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_unknown_remote_subvolume_explicit_error(tmp_path):
    """A handshake naming a subvolume that exists nowhere in the brick
    graph fails with an explicit unknown-remote-subvolume error
    (reference server_setvolume), not an opaque authentication failure
    against the wrong graph."""
    async def run():
        server = await serve_brick(
            _auth_brick().format(dir=tmp_path / "b"))
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", server.port)
        try:
            writer.write(wire.pack(1, wire.MT_CALL, [
                "__handshake__", [b"t", "no-such-subvol", {}], {}]))
            await writer.drain()
            _, mtype, payload = wire.unpack(await wire.read_frame(reader))
            assert mtype == wire.MT_REPLY
            assert payload["ok"] is False
            assert "unknown remote-subvolume" in payload["error"]
        finally:
            writer.close()
        await server.stop()

    asyncio.run(run())


def test_managed_volume_credentials(tmp_path):
    """glusterd generates per-volume credentials; the served client
    volfile carries them (trusted-volfile model) and a credential-less
    hand-built client is refused."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

    async def run():
        gd = Glusterd(str(tmp_path / "gd"))
        await gd.start()
        async with MgmtClient(gd.host, gd.port) as c:
            bricks = [{"path": str(tmp_path / f"b{i}")} for i in range(3)]
            await c.call("volume-create", name="av", vtype="disperse",
                         bricks=bricks, redundancy=1)
            await c.call("volume-start", name="av")
            spec = (await c.call("getspec", name="av"))["volfile"]
        vol = gd.state["volumes"]["av"]
        auth = vol["auth"]
        assert auth["username"] and auth["password"]
        assert auth["username"] in spec and auth["password"] in spec
        # the served volfile mounts and works
        g = Graph.construct(spec)
        cl = Client(g)
        await cl.mount()
        from glusterfs_tpu.core.layer import walk
        subs = [l for l in walk(g.top) if l.type_name == "protocol/client"]
        assert await _wait(lambda: all(l.connected for l in subs))
        await cl.write_file("/f", b"managed")
        assert await cl.read_file("/f") == b"managed"
        await cl.unmount()
        # a hand-built client with no credentials is refused
        port = gd.ports["av-brick-0"]
        g0 = Graph.construct(CLIENT_VOLFILE.format(port=port, extra="")
                             .replace("remote-subvolume srv",
                                      "remote-subvolume av-brick-0"))
        c0 = Client(g0)
        await c0.mount()
        assert not await _wait(lambda: g0.top.connected, timeout=1.5)
        await c0.unmount()
        await gd.stop()

    asyncio.run(run())


def test_auth_allow_excludes_clients_not_glusterd(tmp_path):
    """auth.allow that excludes this host locks clients out but
    glusterd's mgmt calls (volfile-only mgmt pair) still reconfigure
    bricks live."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

    async def run():
        gd = Glusterd(str(tmp_path / "gd"))
        await gd.start()
        async with MgmtClient(gd.host, gd.port) as c:
            bricks = [{"path": str(tmp_path / f"b{i}")} for i in range(3)]
            await c.call("volume-create", name="lv", vtype="disperse",
                         bricks=bricks, redundancy=1)
            await c.call("volume-start", name="lv")
            r = await c.call("volume-set", name="lv",
                             key="auth.allow", value="10.42.*")
            assert "reconfigured" in r["applied"]
            # mgmt path still live-reconfigures (no respawn needed)
            r = await c.call("volume-set", name="lv",
                             key="disperse.read-policy",
                             value="round-robin")
            assert "reconfigured" in r["applied"]
            spec = (await c.call("getspec", name="lv"))["volfile"]
        # mgmt pair never reaches client volfiles
        auth = gd.state["volumes"]["lv"]["auth"]
        assert auth["mgmt-password"] not in spec
        # a credentialed client from 127.0.0.1 is now refused by addr
        g = Graph.construct(spec)
        cl = Client(g)
        await cl.mount()
        from glusterfs_tpu.core.layer import walk
        subs = [l for l in walk(g.top)
                if l.type_name == "protocol/client"]
        assert not await _wait(lambda: any(l.connected for l in subs),
                               timeout=1.5)
        await cl.unmount()
        await gd.stop()

    asyncio.run(run())
