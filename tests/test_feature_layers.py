"""Feature layers: read-only, worm, trash, quota, shard
(reference tests/basic/{worm,quota,shard}* behaviors)."""

import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc


def _vol(tmp_path, *layers) -> str:
    out = [f"volume posix\n    type storage/posix\n"
           f"    option directory {tmp_path}/b\nend-volume\n"]
    prev = "posix"
    for i, (ltype, opts) in enumerate(layers):
        name = f"l{i}"
        body = "".join(f"    option {k} {v}\n" for k, v in opts.items())
        out.append(f"volume {name}\n    type {ltype}\n{body}"
                   f"    subvolumes {prev}\nend-volume\n")
        prev = name
    return "\n".join(out)


def _client(tmp_path, *layers) -> SyncClient:
    c = SyncClient(Graph.construct(_vol(tmp_path, *layers)))
    c.mount()
    return c


def test_read_only(tmp_path):
    c = _client(tmp_path, ("features/read-only", {}))
    with pytest.raises(FopError) as ei:
        c.write_file("/f", b"x")
    assert ei.value.err == 30  # EROFS
    assert c.listdir("/") == []
    c.graph.top.reconfigure({"read-only": "off"})
    c.write_file("/f", b"x")
    c.close()


def test_worm(tmp_path):
    c = _client(tmp_path, ("features/worm", {}))
    c.write_file("/f", b"forever")
    # appends allowed
    f = c.open("/f")
    f.write(b" and ever", 7)
    with pytest.raises(FopError):
        f.write(b"X", 0)  # overwrite denied
    f.close()
    with pytest.raises(FopError):
        c.unlink("/f")
    with pytest.raises(FopError):
        c.truncate("/f", 2)
    assert c.read_file("/f") == b"forever and ever"
    c.close()


def test_trash(tmp_path):
    c = _client(tmp_path, ("features/trash", {}))
    c.write_file("/doomed", b"save me")
    c.unlink("/doomed")
    assert not c.exists("/doomed")
    trash = c.listdir("/.trashcan")
    assert len(trash) == 1 and trash[0].startswith("doomed_")
    assert c.read_file(f"/.trashcan/{trash[0]}") == b"save me"
    c.close()


def test_quota(tmp_path):
    c = _client(tmp_path, ("features/quota", {}))
    q = c.graph.top
    c.mkdir("/limited")
    q.limit_set("/limited", 10000)
    c.write_file("/limited/ok", b"x" * 5000)
    with pytest.raises(FopError) as ei:
        c.write_file("/limited/toobig", b"y" * 8000)
    assert ei.value.err == 122  # EDQUOT
    # freeing space allows writes again
    c.unlink("/limited/ok")
    c.write_file("/limited/fits", b"z" * 8000)
    # outside the limited dir: unaffected
    c.write_file("/free", b"w" * 50000)
    c.close()


def test_quota_via_xattr(tmp_path):
    c = _client(tmp_path, ("features/quota", {}))
    c.mkdir("/d")
    c.setxattr("/d", {"trusted.glusterfs.quota.limit-set": b"1000"})
    with pytest.raises(FopError):
        c.write_file("/d/big", b"x" * 2000)
    c.close()


def test_shard(tmp_path):
    c = _client(tmp_path, ("features/shard", {"shard-block-size": "4KB"}))
    data = bytes(range(256)) * 64  # 16KB -> 4 shards
    c.write_file("/vm.img", data)
    assert c.stat("/vm.img").size == len(data)
    assert c.read_file("/vm.img") == data
    # shards exist on the store; listing hides /.shard
    assert c.listdir("/") == ["vm.img"]
    base = tmp_path / "b"
    shards = list((base / ".shard").iterdir())
    shard_files = [p for p in shards if p.name != ".glusterfs_tpu"]
    assert len(shard_files) == 3  # blocks 1..3 (block 0 at path)
    assert (base / "vm.img").stat().st_size == 4096
    # cross-shard overwrite
    f = c.open("/vm.img")
    f.write(b"@" * 5000, 3000)
    f.close()
    expect = bytearray(data)
    expect[3000:8000] = b"@" * 5000
    assert c.read_file("/vm.img") == bytes(expect)
    # truncate drops tail shards
    c.truncate("/vm.img", 5000)
    assert c.stat("/vm.img").size == 5000
    assert c.read_file("/vm.img") == bytes(expect)[:5000]
    # unlink cleans shards
    c.unlink("/vm.img")
    shard_files = [p for p in (base / ".shard").iterdir()
                   if p.name != ".glusterfs_tpu"]
    assert shard_files == []
    c.close()


def test_shard_sparse_and_append(tmp_path):
    c = _client(tmp_path, ("features/shard", {"shard-block-size": "4KB"}))
    f = c.create("/sparse")
    f.write(b"END", 10000)  # write far past EOF: holes as zero shards
    f.close()
    assert c.stat("/sparse").size == 10003
    out = c.read_file("/sparse")
    assert out[:10000] == b"\0" * 10000 and out[10000:] == b"END"
    c.close()


def test_worm_long_tail_fences(tmp_path):
    """graft-lint GL01 regression: the write vocabulary's long tail is
    fenced like its siblings (PR 10 had to fence xorv after the fact;
    link/discard/zerofill/fallocate/put had the same gap)."""
    c = _client(tmp_path, ("features/worm", {}))
    top = c.graph.top
    c.write_file("/f", b"committed")

    async def drive():
        f = await c._client.open("/f")
        with pytest.raises(FopError):  # new name for a wormed file
            await top.link(Loc("/f"), Loc("/f2"))
        with pytest.raises(FopError):  # hole punch mutates bytes
            await top.discard(f.fd, 0, 4)
        with pytest.raises(FopError):  # zeroing committed bytes
            await top.zerofill(f.fd, 0, 4)
        with pytest.raises(FopError):  # allocating over committed bytes
            await top.fallocate(f.fd, 0, 0, 4)
        # pure extension is the append analog: allowed
        await top.fallocate(f.fd, 0, 9, 16)
        with pytest.raises(FopError):  # whole-body replace of existing
            await top.put(Loc("/f"), b"overwrite")
        await top.put(Loc("/new"), b"fresh")  # create half allowed
        with pytest.raises(FopError):  # cfr destination is a write
            await top.copy_file_range(f.fd, 0, f.fd, 0, 4)
        await f.close()

    c._run(drive())
    assert c.read_file("/f")[:9] == b"committed"
    c.close()
