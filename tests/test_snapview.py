"""USS / snapview: browse activated snapshots under /.snaps (reference
features/snapview-client/server + snapshot activate)."""

import asyncio
import errno

import pytest

from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.layer import walk


@pytest.mark.slow
def test_uss_snaps_browse(tmp_path):
    """Write v1, snapshot + activate, overwrite with v2: the live file
    reads v2 while /.snaps/<snap>/ still serves v1, read-only."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    async def run():
        gd = Glusterd(str(tmp_path / "gd"))
        await gd.start()
        async with MgmtClient(gd.host, gd.port) as c:
            bricks = [{"path": str(tmp_path / f"b{i}")} for i in range(6)]
            await c.call("volume-create", name="sv", vtype="disperse",
                         bricks=bricks, redundancy=2)
            await c.call("volume-start", name="sv")
        cl = await mount_volume(gd.host, gd.port, "sv")
        try:
            subs = [l for l in walk(cl.graph.top)
                    if l.type_name == "protocol/client"]
            for _ in range(150):
                if all(l.connected for l in subs):
                    break
                await asyncio.sleep(0.1)
            await cl.write_file("/doc", b"version-one")
            await cl.mkdir("/sub")
            await cl.write_file("/sub/n", b"nested-v1")

            async with MgmtClient(gd.host, gd.port) as c:
                await c.call("snapshot-create", name="s1", volume="sv")
                # not activated yet: .snaps is empty
                assert await cl.listdir("/.snaps") == []
                await c.call("snapshot-activate", name="s1")

            await cl.write_file("/doc", b"version-TWO!")

            # live vs history
            assert await cl.read_file("/doc") == b"version-TWO!"
            assert await cl.listdir("/.snaps") == ["s1"]
            assert await cl.read_file("/.snaps/s1/doc") == b"version-one"
            assert await cl.read_file("/.snaps/s1/sub/n") == b"nested-v1"
            names = await cl.listdir("/.snaps/s1")
            assert sorted(names) == ["doc", "sub"]
            ia = await cl.stat("/.snaps/s1/doc")
            assert ia.size == len(b"version-one")
            # snapshots are immutable
            with pytest.raises(FopError) as ei:
                await cl.write_file("/.snaps/s1/doc", b"mutate")
            assert ei.value.err == errno.EROFS
            with pytest.raises(FopError):
                await cl.unlink("/.snaps/s1/doc")
            # unknown snapshot
            with pytest.raises(FopError) as ei:
                await cl.read_file("/.snaps/nope/doc")
            assert ei.value.err == errno.ENOENT

            # deactivate hides it again
            async with MgmtClient(gd.host, gd.port) as c:
                await c.call("snapshot-deactivate", name="s1")
            sv_layer = next(l for l in walk(cl.graph.top)
                            if l.type_name == "features/snapview")
            sv_layer._snaps_at = 0.0  # drop the list cache
            assert await cl.listdir("/.snaps") == []
        finally:
            await cl.unmount()
            await gd.stop()

    asyncio.run(run())
