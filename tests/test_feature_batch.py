"""Feature-xlator batch: leases, quiesce, gfid-access, posix-acl,
namespace, sdfs, utime, on-wire compression, selinux (SURVEY §2.7
rows)."""

import asyncio
import errno
import json
import os
import time

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc, walk
from glusterfs_tpu.rpc import wire


def _graph(tmp_path, *layers) -> Graph:
    out = [f"volume posix\n    type storage/posix\n"
           f"    option directory {tmp_path}/brick\nend-volume\n"]
    top = "posix"
    for i, (ltype, opts) in enumerate(layers):
        name = f"l{i}"
        body = "".join(f"    option {k} {v}\n" for k, v in opts.items())
        out.append(f"volume {name}\n    type {ltype}\n{body}"
                   f"    subvolumes {top}\nend-volume\n")
        top = name
    return Graph.construct("\n".join(out))


def test_quiesce_pause_and_replay(tmp_path):
    async def run():
        g = _graph(tmp_path, ("features/quiesce", {}))
        c = Client(g)
        await c.mount()
        await c.write_file("/a", b"before")
        q = g.top
        q.reconfigure({"quiesce": "on"})
        t = asyncio.create_task(c.write_file("/b", b"parked"))
        await asyncio.sleep(0.2)
        assert not t.done()  # held, not failed
        assert q.dump_private()["quiesced"]
        q.reconfigure({"quiesce": "off"})
        await asyncio.wait_for(t, 5)  # replayed
        assert await c.read_file("/b") == b"parked"
        await c.unmount()

    asyncio.run(run())


def test_gfid_access_virtual_path(tmp_path):
    async def run():
        g = _graph(tmp_path, ("features/gfid-access", {}))
        c = Client(g)
        await c.mount()
        await c.write_file("/real", b"by-gfid")
        ia = await c.stat("/real")
        hexg = ia.gfid.hex()
        data = await c.read_file(f"/.gfid/{hexg}")
        assert data == b"by-gfid"
        # dashed uuid form too
        import uuid
        dashed = str(uuid.UUID(bytes=ia.gfid))
        assert (await c.stat(f"/.gfid/{dashed}")).gfid == ia.gfid
        with pytest.raises(FopError) as ei:
            await c.stat("/.gfid/zz-not-a-uuid")
        assert ei.value.err == errno.EINVAL
        await c.unmount()

    asyncio.run(run())


def test_posix_acl_enforcement(tmp_path):
    async def run():
        g = _graph(tmp_path, ("system/posix-acl", {}))
        c = Client(g)
        await c.mount()
        await c.write_file("/guarded", b"secret")
        # file is 0o644 owned by our uid (root in CI): another uid has
        # r but not w
        top = g.top
        ia = await c.stat("/guarded")
        other = {"uid": ia.uid + 1000, "gid": ia.gid + 1000}
        await top.open(Loc("/guarded"), os.O_RDONLY, dict(other))
        with pytest.raises(FopError) as ei:
            await top.open(Loc("/guarded"), os.O_WRONLY, dict(other))
        assert ei.value.err == errno.EACCES
        # a named-user ACL entry grants rw to that uid only
        acl = [{"tag": "user", "qual": ia.uid + 1000, "perm": 6},
               {"tag": "mask", "qual": None, "perm": 6}]
        await top.setxattr(Loc("/guarded"),
                           {"system.posix_acl_access":
                            json.dumps(acl).encode()})
        await top.open(Loc("/guarded"), os.O_WRONLY, dict(other))
        third = {"uid": ia.uid + 2000, "gid": ia.gid + 2000}
        with pytest.raises(FopError):
            await top.open(Loc("/guarded"), os.O_WRONLY, dict(third))
        # identity-less (internal) callers bypass, like the reference
        await top.open(Loc("/guarded"), os.O_WRONLY)
        await c.unmount()

    asyncio.run(run())


def test_posix_acl_ownership_gates(tmp_path):
    """chmod/chown and ACL xattr changes need OWNERSHIP, not W: the
    owner of a 0444 file can chmod it, a group-writer cannot chown or
    replace the ACL; link needs W|X only on the NEW name's parent
    (reference posix-acl.c setattr/link gating)."""

    async def run():
        g = _graph(tmp_path, ("system/posix-acl", {}))
        c = Client(g)
        await c.mount()
        await c.write_file("/d", b"x")
        top = g.top
        ia = await c.stat("/d")
        owner = {"uid": ia.uid, "gid": ia.gid}
        stranger = {"uid": ia.uid + 1000, "gid": ia.gid + 1000}
        # owner may chmod their own read-only file
        await top.setattr(Loc("/d"), {"mode": 0o444}, xdata=dict(owner))
        await top.setattr(Loc("/d"), {"mode": 0o666}, xdata=dict(owner))
        # non-owner with W (0666 now) still may NOT chmod or set ACLs
        with pytest.raises(FopError) as ei:
            await top.setattr(Loc("/d"), {"mode": 0o600},
                              xdata=dict(stranger))
        assert ei.value.err == errno.EPERM
        with pytest.raises(FopError):
            await top.setxattr(
                Loc("/d"), {"system.posix_acl_access": b"[]"},
                xdata=dict(stranger))
        # ...but CAN set a plain user xattr (W-gated, mode is 0666)
        await top.setxattr(Loc("/d"), {"user.note": b"hi"},
                           xdata=dict(stranger))
        # link: source parent read-only is fine; only the destination
        # parent needs W|X
        await top.mkdir(Loc("/dst"), 0o777)
        await top.setattr(Loc("/dst"), {"mode": 0o777},
                          xdata=dict(owner))  # umask-proof
        await top.setattr(Loc("/"), {"mode": 0o555}, xdata=dict(owner))
        try:
            await top.link(Loc("/d"), Loc("/dst/hard"),
                           xdata=dict(stranger))
            assert (await c.read_file("/dst/hard")) == b"x"
        finally:
            await top.setattr(Loc("/"), {"mode": 0o755},
                              xdata=dict(owner))
        await c.unmount()

    asyncio.run(run())


def test_posix_acl_times_with_write_permission(tmp_path):
    """Touch-to-now (UTIME_NOW, value None) needs only W, not
    ownership — POSIX lets any writer touch timestamps to the current
    time; EXPLICIT timestamps and mixed payloads still demand
    ownership (utimensat(2); reference posix-acl setattr gating)."""

    async def run():
        g = _graph(tmp_path, ("system/posix-acl", {}))
        c = Client(g)
        await c.mount()
        await c.write_file("/t", b"x")
        top = g.top
        ia = await c.stat("/t")
        owner = {"uid": ia.uid, "gid": ia.gid}
        stranger = {"uid": ia.uid + 1000, "gid": ia.gid + 1000}
        await top.setattr(Loc("/t"), {"mode": 0o666}, xdata=dict(owner))
        # a W-holder may touch times to NOW
        before = (await c.stat("/t")).mtime
        await top.setattr(Loc("/t"), {"atime": None, "mtime": None},
                          xdata=dict(stranger))
        assert (await c.stat("/t")).mtime >= before
        # ...but may NOT set explicit times (mtime forgery)
        with pytest.raises(FopError) as ei:
            await top.setattr(Loc("/t"), {"mtime": 3.0},
                              xdata=dict(stranger))
        assert ei.value.err == errno.EPERM
        # the owner may set explicit times
        await top.setattr(Loc("/t"), {"mtime": 3.0}, xdata=dict(owner))
        assert int((await c.stat("/t")).mtime) == 3
        # without W (0644) even touch-to-now is refused
        await top.setattr(Loc("/t"), {"mode": 0o644}, xdata=dict(owner))
        with pytest.raises(FopError) as ei:
            await top.setattr(Loc("/t"), {"mtime": None},
                              xdata=dict(stranger))
        assert ei.value.err in (errno.EACCES, errno.EPERM)
        # mixed payload (times + mode) still needs ownership even with W
        await top.setattr(Loc("/t"), {"mode": 0o666}, xdata=dict(owner))
        with pytest.raises(FopError) as ei:
            await top.setattr(Loc("/t"), {"mtime": None, "mode": 0o600},
                              xdata=dict(stranger))
        assert ei.value.err == errno.EPERM
        await c.unmount()

    asyncio.run(run())


def test_posix_acl_gates_through_passthrough_layers(tmp_path):
    """Identity gates must hold when the layer below posix-acl defines
    fops as (*args, **kwargs) passthroughs (utime's stamped fops):
    extract_xdata falls back to the canonical posix signature."""

    async def run():
        g = _graph(tmp_path, ("features/utime", {}),
                   ("system/posix-acl", {}))
        c = Client(g)
        await c.mount()
        await c.write_file("/p", b"x")
        top = g.top
        ia = await c.stat("/p")
        stranger = {"uid": ia.uid + 1000, "gid": ia.gid + 1000}
        with pytest.raises(FopError) as ei:
            await top.setattr(Loc("/p"), {"mode": 0o777},
                              xdata=dict(stranger))
        assert ei.value.err == errno.EPERM
        # kwargs-passed ACL xattr hits the ownership gate too
        with pytest.raises(FopError):
            await top.setxattr(
                Loc("/p"), xattrs={"system.posix_acl_access": b"[]"},
                xdata=dict(stranger))
        await c.unmount()

    asyncio.run(run())


def test_namespace_tagging(tmp_path):
    async def run():
        g = _graph(tmp_path, ("features/namespace", {}))
        c = Client(g)
        await c.mount()
        await c.mkdir("/tenant-a")
        await c.write_file("/tenant-a/f", b"x")
        await c.write_file("/top", b"y")
        ns = g.top.dump_private()["namespaces"]
        assert ns.get("tenant-a", 0) > 0
        assert ns.get("top", 0) > 0
        await c.unmount()

    asyncio.run(run())


def test_sdfs_serializes_entry_fops(tmp_path):
    async def run():
        g = _graph(tmp_path, ("features/sdfs", {}))
        c = Client(g)
        await c.mount()
        # racing creates of the same name: exactly one wins, no torn
        # state (the serializer makes the loser see EEXIST, not a race)
        results = await asyncio.gather(
            *(g.top.create(Loc("/same"), os.O_CREAT | os.O_EXCL)
              for _ in range(8)), return_exceptions=True)
        ok = [r for r in results if not isinstance(r, BaseException)]
        errs = [r for r in results if isinstance(r, FopError)]
        assert len(ok) == 1 and len(errs) == 7
        assert all(e.err == errno.EEXIST for e in errs)
        assert g.top.dump_private()["serialized"] >= 8
        await c.unmount()

    asyncio.run(run())


def test_utime_client_stamp(tmp_path):
    async def run():
        g = _graph(tmp_path, ("features/utime", {}))
        c = Client(g)
        await c.mount()
        before = time.time()
        await c.write_file("/stamped", b"x")
        ia = await c.stat("/stamped")
        # mtime came from the client's clock at fop time
        assert before - 1 <= ia.mtime <= time.time() + 1
        await c.unmount()

    asyncio.run(run())


def test_selinux_xattr_translation(tmp_path):
    async def run():
        g = _graph(tmp_path, ("features/selinux", {}))
        c = Client(g)
        await c.mount()
        await c.write_file("/ctx", b"x")
        await g.top.setxattr(Loc("/ctx"), {
            "security.selinux": b"system_u:object_r:etc_t:s0"})
        # clients read it back under the security name
        xa = await g.top.getxattr(Loc("/ctx"), "security.selinux")
        assert xa["security.selinux"] == b"system_u:object_r:etc_t:s0"
        # at rest it lives in the trusted namespace
        raw = await g.top.children[0].getxattr(Loc("/ctx"), None)
        assert "trusted.glusterfs.selinux" in raw
        assert "security.selinux" not in raw
        await c.unmount()

    asyncio.run(run())


def test_wire_compression_roundtrip():
    big = {"blob": b"A" * 100000, "n": 42}
    frame = wire.pack_z(7, wire.MT_REPLY, big)
    assert len(frame) < 5000  # actually compressed
    xid, mtype, payload = wire.unpack(frame[4:])
    assert xid == 7 and mtype == wire.MT_REPLY
    assert payload["blob"] == b"A" * 100000 and payload["n"] == 42
    # small frames ship plain
    small = wire.pack_z(8, wire.MT_CALL, {"x": 1})
    assert small == wire.pack(8, wire.MT_CALL, {"x": 1})


def test_leases_grant_conflict_recall(tmp_path):
    async def run():
        g = _graph(tmp_path, ("features/locks", {}),
                   ("features/leases", {"recall-timeout": "0.3"}))
        c = Client(g)
        await c.mount()
        await c.write_file("/leased", b"v")
        top = g.top
        recalls = []
        top.set_upcall_sink(lambda targets, payload:
                            recalls.append((targets, payload)))
        # client A takes a RW lease
        tok_a = wire.CURRENT_CLIENT.set(b"client-A")
        await top.lease(Loc("/leased"), "grant", "rw", "lease-1")
        # A's own writes pass untouched
        await c.write_file("/leased", b"v2")
        wire.CURRENT_CLIENT.reset(tok_a)
        # client B writes: A is recalled; unreturned -> revoked after
        # the grace, then B proceeds
        tok_b = wire.CURRENT_CLIENT.set(b"client-B")
        t0 = time.monotonic()
        await c.write_file("/leased", b"from-B")
        took = time.monotonic() - t0
        wire.CURRENT_CLIENT.reset(tok_b)
        assert recalls and recalls[0][0] == [b"client-A"]
        assert recalls[0][1]["event"] == "lease-recall"
        assert took >= 0.25  # waited the recall grace
        assert await c.read_file("/leased") == b"from-B"
        # the revoked lease id cannot be re-granted
        tok_a = wire.CURRENT_CLIENT.set(b"client-A")
        with pytest.raises(FopError) as ei:
            await top.lease(Loc("/leased"), "grant", "rw", "lease-1")
        assert ei.value.err == errno.ESTALE
        # voluntary release path: grant + release, no recall needed
        await top.lease(Loc("/leased"), "grant", "rd", "lease-2")
        await top.lease(Loc("/leased"), "release", "rd", "lease-2")
        wire.CURRENT_CLIENT.reset(tok_a)
        assert top.dump_private()["leases"] == 0
        await c.unmount()

    asyncio.run(run())


def test_volgen_wires_batch_layers(tmp_path):
    from glusterfs_tpu.mgmt import volgen

    vi = {
        "name": "bv", "type": "disperse", "redundancy": 2,
        "bricks": [{"index": i, "host": "h", "port": 1,
                    "path": str(tmp_path / f"b{i}"),
                    "name": f"bv-brick-{i}", "node": "x"}
                   for i in range(6)],
        "options": {"features.leases": "on", "features.sdfs": "on",
                    "features.namespace": "on", "features.selinux": "on",
                    "features.gfid-access": "on", "features.utime": "on",
                    "features.acl": "on",
                    "network.compression": "on"},
    }
    btext = volgen.build_brick_volfile(vi, vi["bricks"][0])
    for t in ("features/leases", "features/sdfs", "features/namespace",
              "features/selinux"):
        assert f"type {t}" in btext, t
    ctext = volgen.build_client_volfile(vi)
    for t in ("features/gfid-access", "features/utime",
              "system/posix-acl", "features/quiesce"):
        assert f"type {t}" in ctext, t
    assert "option compression on" in ctext
    # both graphs construct
    Graph = __import__("glusterfs_tpu.core.graph",
                       fromlist=["Graph"]).Graph
    Graph.construct(btext)
    Graph.construct(ctext)


def test_wire_compression_e2e(tmp_path):
    """Compressed frames over a real brick connection: handshake
    negotiates, both directions survive, payloads stay byte-exact."""
    from glusterfs_tpu.daemon import serve_brick

    BRICK = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
"""
    CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume posix
    option compression on
end-volume
"""

    async def run():
        server = await serve_brick(BRICK)
        g = Graph.construct(CLIENT.format(port=server.port))
        c = Client(g)
        await c.mount()
        for _ in range(100):
            if g.top.connected:
                break
            await asyncio.sleep(0.05)
        blob = bytes(range(256)) * 4000  # 1MB compressible
        await c.write_file("/z", blob)
        assert await c.read_file("/z") == blob
        srv_conn = next(iter(server.connections))
        assert srv_conn.compress  # negotiated at handshake
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_utime_under_io_stats(tmp_path):
    """The realistic stacking (io-stats forwards xdata positionally):
    utime must bind into the child signature, not double-pass xdata."""
    async def run():
        g = _graph(tmp_path, ("features/utime", {}),
                   ("debug/io-stats", {}))
        c = Client(g)
        await c.mount()
        before = time.time()
        await c.write_file("/f", b"x" * 1000)
        await c.truncate("/f", 10)
        ia = await c.stat("/f")
        assert ia.size == 10
        assert before - 1 <= ia.mtime <= time.time() + 1
        await c.unmount()

    asyncio.run(run())
