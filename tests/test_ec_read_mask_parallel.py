"""disperse.ec-read-mask (ec.c:717-775 ec_assign_read_mask, applied
strictly at read dispatch like ec-inode-read.c:1375) and
disperse.parallel-writes (ec.c:284,868 + ec_is_range_conflict,
ec-common.c:185: non-conflicting writes dispatch concurrently inside
one eager window)."""

import asyncio

import numpy as np
import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.utils.volspec import ec_volfile

K, R = 4, 2
N = K + R
STRIPE = K * 512


def _rand(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def _settle(c, path):
    f = c.open(path)
    f.fsync()
    f.close()


def _mount(tmp_path, options=None):
    g = Graph.construct(ec_volfile(tmp_path, N, R, options=options or {}))
    c = SyncClient(g)
    c.mount()
    return c, g.top


def _readv_counts(ec):
    return [ec.children[i].stats["readv"].count
            if "readv" in ec.children[i].stats else 0 for i in range(N)]


# -- read-mask ---------------------------------------------------------


def test_read_mask_keeps_masked_bricks_out(tmp_path):
    c, ec = _mount(tmp_path, {"ec-read-mask": "0,1,2,3"})
    try:
        data = _rand(4 * STRIPE)
        c.write_file("/f", data)
        before = _readv_counts(ec)
        assert c.read_file("/f") == data
        after = _readv_counts(ec)
        assert after[4] == before[4] and after[5] == before[5], \
            "masked-out bricks served reads"
        assert sum(after) > sum(before)
    finally:
        c.close()


def test_read_mask_honored_in_degraded_read(tmp_path):
    """One masked-in brick down: reads come from the remaining masked
    ids, never from the masked-out brick even though it is up+clean."""
    c, ec = _mount(tmp_path, {"ec-read-mask": "0,1,2,3,4"})
    try:
        data = _rand(4 * STRIPE, seed=1)
        c.write_file("/g", data)
        _settle(c, "/g")  # close the write window (its cached
        # candidate set predates the degrade below)
        ec.up[1] = False  # degrade inside the mask
        before = _readv_counts(ec)
        assert c.read_file("/g") == data
        after = _readv_counts(ec)
        assert after[5] == before[5], "masked-out brick used for reads"
        assert after[1] == before[1]
    finally:
        c.close()


def test_read_mask_is_strict_like_reference(tmp_path):
    """fop->mask &= read_mask: if the masked set cannot supply K
    fragments the read fails rather than widening past the mask."""
    c, ec = _mount(tmp_path, {"ec-read-mask": "0,1,2,3"})
    try:
        data = _rand(2 * STRIPE, seed=2)
        c.write_file("/h", data)
        _settle(c, "/h")
        ec.up[3] = False  # only 3 masked candidates remain, K=4
        with pytest.raises(FopError):
            c.read_file("/h")
    finally:
        c.close()


def test_read_mask_never_fails_writes(tmp_path):
    """The mask is a read-tuning knob: a write's internal RMW reads
    ignore it (the reference applies it only at inode-read dispatch,
    ec-inode-read.c:1375) — a degraded masked set must not turn into
    write unavailability while >= K bricks are healthy."""
    c, ec = _mount(tmp_path, {"ec-read-mask": "0,1,2,3"})
    try:
        data = _rand(2 * STRIPE, seed=9)
        c.write_file("/w", data)
        ec.up[1] = False  # masked candidates drop below K
        f = c.open("/w")
        f.write(b"Z" * 100, 17)  # unaligned: needs an RMW read
        f.close()
    finally:
        c.close()
    exp = bytearray(data)
    exp[17:117] = b"Z" * 100
    c2, _ = _mount(tmp_path)  # unmasked view of the surviving bricks
    try:
        assert c2.read_file("/w") == bytes(exp)
    finally:
        c2.close()


def test_invalid_masks_log_and_clear(tmp_path):
    c, ec = _mount(tmp_path)
    try:
        for bad in ("0,1", "0,1,2,99", "0,1,x,3"):
            ec.reconfigure({"ec-read-mask": bad})
            assert ec._read_mask is None, bad
        ec.reconfigure({"ec-read-mask": "1,2,3,4"})
        assert ec._read_mask == frozenset({1, 2, 3, 4})
        ec.reconfigure({"ec-read-mask": ""})
        assert ec._read_mask is None
    finally:
        c.close()


# -- parallel-writes ---------------------------------------------------


def _spy_dispatch(ec, widen=0.05):
    """Count concurrently in-flight writev waves through _dispatch."""
    state = {"active": 0, "max": 0}
    orig = ec._dispatch

    async def spy(idxs, op, argfn):
        if op == "writev":
            state["active"] += 1
            state["max"] = max(state["max"], state["active"])
            await asyncio.sleep(widen)
        try:
            return await orig(idxs, op, argfn)
        finally:
            if op == "writev":
                state["active"] -= 1

    ec._dispatch = spy
    return state


def test_disjoint_writes_dispatch_concurrently(tmp_path):
    c, ec = _mount(tmp_path, {"eager-lock-timeout": 30})
    try:
        a = _rand(4 * STRIPE, seed=3)
        b = _rand(4 * STRIPE, seed=4)

        async def drive():
            f = await c._client.create("/p")
            await f.write(b"\0" * STRIPE, 0)  # window's solo first write
            state = _spy_dispatch(ec)
            await asyncio.gather(f.write(a, 0),
                                 f.write(b, 4 * STRIPE))
            await f.close()
            return state

        state = c._run(drive())
        assert state["max"] >= 2, "disjoint writes serialized"
        assert c.read_file("/p") == a + b
    finally:
        c.close()


def test_overlapping_writes_serialize(tmp_path):
    c, ec = _mount(tmp_path, {"eager-lock-timeout": 30})
    try:
        a = _rand(2 * STRIPE, seed=5)
        b = _rand(2 * STRIPE, seed=6)

        async def drive():
            f = await c._client.create("/q")
            await f.write(b"\0" * STRIPE, 0)
            state = _spy_dispatch(ec)
            # same aligned stripe range: must not interleave
            await asyncio.gather(f.write(a, 0), f.write(b, 0))
            await f.close()
            return state

        state = c._run(drive())
        assert state["max"] == 1, "overlapping writes ran concurrently"
        assert c.read_file("/q") in (a, b)
    finally:
        c.close()


def test_parallel_writes_off_serializes_everything(tmp_path):
    c, ec = _mount(tmp_path, {"parallel-writes": "off",
                              "eager-lock-timeout": 30})
    try:
        a = _rand(2 * STRIPE, seed=7)

        async def drive():
            f = await c._client.create("/r")
            await f.write(b"\0" * STRIPE, 0)
            state = _spy_dispatch(ec)
            await asyncio.gather(f.write(a, 0), f.write(a, 4 * STRIPE))
            await f.close()
            return state

        state = c._run(drive())
        assert state["max"] == 1
    finally:
        c.close()


def test_many_parallel_writers_integrity_and_size(tmp_path):
    """16 concurrent disjoint chunk writers through one fd: bytes land
    exactly, final size is the max end (the size-clobber case), and the
    settled file survives a fresh mount (post-op committed sanely)."""
    chunk = 2 * STRIPE
    parts = [_rand(chunk, seed=10 + i) for i in range(16)]
    c, ec = _mount(tmp_path, {"eager-lock-timeout": 0.05})
    try:
        async def drive():
            f = await c._client.create("/big")
            await f.write(parts[0], 0)  # solo first write lands pre-op
            await asyncio.gather(*(
                f.write(parts[i], i * chunk) for i in range(1, 16)))
            await f.close()

        c._run(drive())
        assert c.stat("/big").size == 16 * chunk
        assert c.read_file("/big") == b"".join(parts)
    finally:
        c.close()
    c2, _ = _mount(tmp_path)
    try:
        assert c2.read_file("/big") == b"".join(parts)
    finally:
        c2.close()
