"""Multi-process cluster-on-one-host harness — the tests/cluster.rc analog
(reference tests/cluster.rc:6-61 launch_cluster): brick daemons as real
subprocesses with ephemeral ports, clients connecting over TCP."""

from __future__ import annotations

import os
import subprocess
import sys
import time


BRICK_VOLFILE = """
volume posix
    type storage/posix
    option directory {dir}
end-volume

volume locks
    type features/locks
    subvolumes posix
end-volume
"""


class BrickProc:
    """One brick daemon subprocess."""

    def __init__(self, base: str, name: str,
                 volfile_tmpl: str | None = None):
        self.name = name
        self.dir = os.path.join(base, name)
        self.volfile = os.path.join(base, f"{name}.vol")
        self.portfile = os.path.join(base, f"{name}.port")
        with open(self.volfile, "w") as f:
            f.write((volfile_tmpl or BRICK_VOLFILE).format(dir=self.dir))
        self.proc: subprocess.Popen | None = None
        self.port: int | None = None

    def start(self, timeout: float = 15.0, port: int = 0) -> int:
        """port=0 picks an ephemeral port; a fixed port lets bounce
        tests restart the brick where clients expect it."""
        if os.path.exists(self.portfile):
            os.unlink(self.portfile)
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # bricks never need a TPU
        env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "glusterfs_tpu.daemon",
             "--volfile", self.volfile, "--listen", str(port),
             "--portfile", self.portfile],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        deadline = time.time() + timeout
        while time.time() < deadline:
            if os.path.exists(self.portfile):
                with open(self.portfile) as f:
                    self.port = int(f.read())
                return self.port
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"brick {self.name} died: "
                    f"{self.proc.stderr.read().decode()[-2000:]}")
            time.sleep(0.05)
        raise TimeoutError(f"brick {self.name} did not report a port")

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait()

    def terminate(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            self.proc.wait()


class Cluster:
    """N brick daemons (launch_cluster analog)."""

    def __init__(self, base: str, n: int):
        self.base = str(base)
        self.bricks = [BrickProc(self.base, f"brick{i}") for i in range(n)]

    def start(self) -> list[int]:
        return [b.start() for b in self.bricks]

    def stop(self) -> None:
        for b in self.bricks:
            b.terminate()

    def client_volfile(self, cluster_type: str | None = None,
                       options: dict | None = None) -> str:
        """Client graph: protocol/client per brick + optional cluster top."""
        out = []
        for i, b in enumerate(self.bricks):
            out.append(f"""
volume client{i}
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {b.port}
    option remote-subvolume locks
end-volume
""")
        if cluster_type:
            subs = " ".join(f"client{i}" for i in range(len(self.bricks)))
            opts = "".join(f"    option {k} {v}\n"
                           for k, v in (options or {}).items())
            out.append(f"volume top\n    type {cluster_type}\n{opts}"
                       f"    subvolumes {subs}\nend-volume\n")
        return "\n".join(out)


async def wait_async(pred, timeout: float = 60.0,
                     interval: float = 0.3) -> bool:
    """Poll an async predicate until true or timeout (EXPECT_WITHIN)."""
    import asyncio

    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        if await pred():
            return True
        if loop.time() > deadline:
            return False
        await asyncio.sleep(interval)


def spawn_fuse(server: str, volume: str, ready: str, mnt: str,
               timeout: float = 60.0):
    """Spawn the FUSE bridge for a managed volume and block until the
    mount is ready.  Returns the Popen; callers stop it with
    stop_fuse().  One home for the hardened recipe (module spawn, env
    scrub, readyfile poll with death detection)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "glusterfs_tpu.mount.fuse_bridge",
         "--server", server, "--volume", volume,
         "--readyfile", ready, str(mnt)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    deadline = time.time() + timeout
    while not os.path.exists(ready):
        if proc.poll() is not None:
            raise RuntimeError("fuse daemon died: "
                               + proc.stderr.read().decode()[-2000:])
        if time.time() > deadline:
            proc.terminate()
            raise TimeoutError("mount never became ready")
        time.sleep(0.1)
    return proc


def stop_fuse(proc, mnt: str) -> None:
    """Terminate the bridge, wait it out, and lazily unmount."""
    proc.terminate()
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()
    subprocess.run(["umount", "-l", str(mnt)],
                   stderr=subprocess.DEVNULL)
