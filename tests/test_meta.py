"""meta xlator: the /.meta introspection tree (reference xlators/meta;
tests/ec.rc reads .meta/graphs/active/<layer>/private as its oracle)."""

import asyncio
import errno
import json

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph

VOLFILE = """
volume posix
    type storage/posix
    option directory {base}/brick
end-volume

volume locks
    type features/locks
    subvolumes posix
end-volume

volume top
    type meta
    subvolumes locks
end-volume
"""


def test_meta_tree(tmp_path):
    async def run():
        g = Graph.construct(VOLFILE.format(base=tmp_path))
        c = Client(g)
        await c.mount()
        # normal I/O is untouched
        await c.write_file("/real", b"data")
        assert await c.read_file("/real") == b"data"
        # the virtual tree
        assert sorted(await c.listdir("/.meta")) == \
            ["connections", "graphs", "logging", "metrics", "version"]
        # the unified-registry dump serves as a file
        metrics = await c.read_file("/.meta/metrics")
        assert b"gftpu_wire_blob_stats" in metrics
        # transport accounting file: this graph has no protocol/client,
        # so the list is present but empty
        assert json.loads(await c.read_file("/.meta/connections")) == []
        assert await c.listdir("/.meta/graphs") == ["active"]
        assert sorted(await c.listdir("/.meta/graphs/active")) == \
            ["locks", "posix"]
        priv = json.loads(await c.read_file(
            "/.meta/graphs/active/posix/private"))
        assert "directory" in priv or priv  # layer state, live
        t = await c.read_file("/.meta/graphs/active/locks/type")
        assert t.strip() == b"features/locks"
        opts = json.loads(await c.read_file(
            "/.meta/graphs/active/posix/options"))
        assert opts["directory"].endswith("brick")
        ver = json.loads(await c.read_file("/.meta/version"))
        assert ver["version"]
        # stats reflect live traffic
        stats = json.loads(await c.read_file(
            "/.meta/graphs/active/posix/stats"))
        assert stats  # per-fop counters exist
        # read-only: mutations refuse
        with pytest.raises(FopError) as ei:
            await c.write_file("/.meta/version", b"nope")
        assert ei.value.err in (errno.EROFS, errno.EISDIR, errno.EEXIST)
        with pytest.raises(FopError):
            await c.unlink("/.meta/version")
        with pytest.raises(FopError):
            await c.mkdir("/.meta/newdir")
        # missing virtual path
        with pytest.raises(FopError) as ei:
            await c.read_file("/.meta/graphs/active/nope/private")
        assert ei.value.err == errno.ENOENT
        await c.unmount()

    asyncio.run(run())


def test_meta_stat_shapes(tmp_path):
    async def run():
        g = Graph.construct(VOLFILE.format(base=tmp_path))
        c = Client(g)
        await c.mount()
        ia = await c.stat("/.meta")
        assert ia.is_dir()
        ia = await c.stat("/.meta/version")
        assert not ia.is_dir() and ia.size > 0
        # listdir with stats (readdirp) works on virtual dirs
        entries = dict(await c.listdir_with_stat("/.meta/graphs/active"))
        assert "posix" in entries and entries["posix"].is_dir()
        await c.unmount()

    asyncio.run(run())


@pytest.mark.slow
def test_meta_on_managed_volume(tmp_path):
    """volgen puts meta at the top of every client graph; the disperse
    layer's private dump is readable exactly like tests/ec.rc does."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)
    from glusterfs_tpu.core.layer import walk

    async def run():
        gd = Glusterd(str(tmp_path / "gd"))
        await gd.start()
        async with MgmtClient(gd.host, gd.port) as c:
            bricks = [{"path": str(tmp_path / f"b{i}")} for i in range(6)]
            await c.call("volume-create", name="mv", vtype="disperse",
                         bricks=bricks, redundancy=2)
            await c.call("volume-start", name="mv")
        cl = await mount_volume(gd.host, gd.port, "mv")
        try:
            subs = [l for l in walk(cl.graph.top)
                    if l.type_name == "protocol/client"]
            for _ in range(150):
                if all(l.connected for l in subs):
                    break
                await asyncio.sleep(0.1)
            await cl.write_file("/f", b"x" * 1024)
            priv = json.loads(await cl.read_file(
                "/.meta/graphs/active/mv-disperse-0/private"))
            # the ec.rc oracle: k/redundancy/up state visible
            assert priv, priv
            names = await cl.listdir("/.meta/graphs/active")
            assert "mv-disperse-0" in names
            assert any(n.startswith("mv-client-") for n in names)
        finally:
            await cl.unmount()
            await gd.stop()

    asyncio.run(run())
