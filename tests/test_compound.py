"""Compound-fop pipeline: fused chains on the wire, reply-vector
semantics, short-circuit fd hygiene, mixed-version fallback, and the
volume key (rpc/compound.py; ISSUE 2 tentpole).

The headline here is the wire-frame-counting proof: a small-file
create+write costs ~4 RPC round trips as singles (create, fstat,
writev, flush) and ONE as a chain with cluster.use-compound-fops on.
"""

import asyncio
import errno
import os

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc, walk
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.rpc import compound as cfop

from .harness import BRICK_VOLFILE

CLIENT_VOLFILE = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume locks
    option compound-fops {cf}
end-volume

volume wb
    type performance/write-behind
    option compound-fops {cf}
    subvolumes c0
end-volume
"""


async def _wait_connected(layer, timeout=10.0):
    for _ in range(int(timeout / 0.05)):
        if layer.connected:
            return True
        await asyncio.sleep(0.05)
    return layer.connected


async def _mounted(tmp_path, cf="on", brick_opts=""):
    brick = BRICK_VOLFILE.format(dir=tmp_path / "b")
    if brick_opts:
        brick += ("\nvolume srv\n    type protocol/server\n"
                  f"{brick_opts}    subvolumes locks\nend-volume\n")
    server = await serve_brick(brick)
    g = Graph.construct(CLIENT_VOLFILE.format(port=server.port, cf=cf)
                        .replace("remote-subvolume locks",
                                 "remote-subvolume srv")
                        if brick_opts else
                        CLIENT_VOLFILE.format(port=server.port, cf=cf))
    c = Client(g)
    await c.mount()
    cl = next(l for l in walk(g.top)
              if l.type_name == "protocol/client")
    assert await _wait_connected(cl)
    return server, c, cl


def test_create_write_roundtrips(tmp_path):
    """ISSUE 2 acceptance bar: small-file create+write drops from ~4
    RPC round trips to <=2 (measured: 1) with compound fops on."""
    async def run():
        server, c, cl = await _mounted(tmp_path, cf="on")
        base = cl.rpc_roundtrips
        await c.write_file("/one", b"z" * 4096)
        fused = cl.rpc_roundtrips - base
        assert await c.read_file("/one") == b"z" * 4096
        await c.unmount()
        await server.stop()

        server, c, cl = await _mounted(tmp_path / "off", cf="off")
        base = cl.rpc_roundtrips
        await c.write_file("/one", b"z" * 4096)
        singles = cl.rpc_roundtrips - base
        assert await c.read_file("/one") == b"z" * 4096
        await c.unmount()
        await server.stop()

        assert fused <= 2, f"compound path took {fused} round trips"
        assert singles >= 3, \
            f"singles baseline took only {singles} round trips"
        assert fused < singles

    asyncio.run(run())


def test_reply_vector_maps_links_one_to_one(tmp_path):
    """Every link gets exactly one vector entry, in order, with the
    chain-released fd stripped to None (it no longer exists)."""
    async def run():
        server, c, cl = await _mounted(tmp_path)
        replies = await c.graph.top.compound([
            ("create", (Loc("/v"), os.O_RDWR | os.O_EXCL, 0o644), {}),
            ("writev", (cfop.FdRef(0), b"vector" * 800, 0), {}),
            ("flush", (cfop.FdRef(0),), {}),
            ("release", (cfop.FdRef(0),), {}),
        ])
        assert len(replies) == 4
        assert [st for st, _ in replies] == ["ok"] * 4
        created = replies[0][1]
        assert created[0] is None  # released in-chain: never escapes
        assert created[1].size == 0 or hasattr(created[1], "gfid")
        postbuf = replies[1][1]
        assert postbuf.size == 4800  # writev postbuf reflects the write
        # no fd-table entry survived the chain on the brick (checked
        # BEFORE read_file, whose own release is fire-and-forget)
        assert all(not conn.fds for conn in server.connections)
        assert await c.read_file("/v") == b"vector" * 800
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_short_circuit_leaves_no_orphan_fd(tmp_path):
    """A mid-chain error skips the rest, reports per-link status, and
    releases every fd the chain created — brick fd tables stay empty
    and the client sees no half-open handle."""
    async def run():
        server, c, cl = await _mounted(tmp_path)
        replies = await c.graph.top.compound([
            ("create", (Loc("/sc"), os.O_RDWR | os.O_EXCL, 0o644), {}),
            ("open", (Loc("/definitely-missing"), os.O_RDONLY), {}),
            ("writev", (cfop.FdRef(0), b"never", 0), {}),
        ])
        assert [st for st, _ in replies] == ["ok", "err", "skip"]
        assert isinstance(replies[1][1], FopError)
        assert replies[1][1].err == errno.ENOENT
        # the created fd was cleaned up server-side: stripped from the
        # reply AND retired from the per-connection fd table
        assert cfop.fd_of(replies[0][1]) is None
        assert all(not conn.fds for conn in server.connections)
        # the create itself applied (POSIX partial application), but
        # the skipped writev did not
        f = await c.open("/sc", os.O_RDONLY)
        try:
            assert await f.read(64, 0) == b""
        finally:
            await f.close()
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_mixed_version_fallback_to_singles(tmp_path):
    """A brick that doesn't advertise compound (compound-fops off =
    the downgraded-peer stand-in) gets plain single fops from a
    compound-enabled client — same results, more round trips."""
    async def run():
        server, c, cl = await _mounted(
            tmp_path, cf="on",
            brick_opts="    option compound-fops off\n")
        assert not cl._peer_compound
        base = cl.rpc_roundtrips
        await c.write_file("/fb", b"fallback")
        assert cl.rpc_roundtrips - base >= 3  # decomposed into singles
        assert await c.read_file("/fb") == b"fallback"
        # direct chains decompose client-side too, same reply contract
        replies = await c.graph.top.compound([
            ("create", (Loc("/fb2"), os.O_RDWR | os.O_EXCL, 0o644), {}),
            ("writev", (cfop.FdRef(0), b"fb2", 0), {}),
            ("release", (cfop.FdRef(0),), {}),
        ])
        assert [st for st, _ in replies] == ["ok"] * 3
        assert await c.read_file("/fb2") == b"fb2"
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_peer_downgrade_mid_connection(tmp_path):
    """A brick reconfigured to refuse chains mid-connection answers
    EOPNOTSUPP once; the client remembers and decomposes from then on
    (graceful per-peer fallback, no error surfaces to the caller)."""
    async def run():
        server, c, cl = await _mounted(
            tmp_path, cf="on",
            brick_opts="    option compound-fops on\n")
        assert cl._peer_compound
        # flip the server off underneath the live connection (the
        # protocol/server top re-reads the option per request)
        server.top.opts["compound-fops"] = False
        await c.write_file("/after-downgrade", b"still works")
        assert await c.read_file("/after-downgrade") == b"still works"
        assert not cl._peer_compound  # remembered the refusal
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_chain_validation():
    """Malformed chains are refused up front with EINVAL."""
    with pytest.raises(FopError):
        cfop.validate([])
    with pytest.raises(FopError):
        cfop.validate([("writev", (cfop.FdRef(0), b"x", 0), {})])  # fwd ref
    with pytest.raises(FopError):
        cfop.validate([("not-a-fop", (), {})])
    with pytest.raises(FopError):
        cfop.validate([("compound", ([],), {})])  # no nesting
    with pytest.raises(FopError):
        # release may only target an in-chain fd
        cfop.validate([("release", ("something",), {})])
    with pytest.raises(FopError):
        cfop.validate([("stat", (Loc("/x"),), {})] * (cfop.MAX_LINKS + 1))


def test_lock_fops_never_fused(tmp_path):
    """Chains carrying lock fops decompose at the client so the
    reconnect lock-replay bookkeeping in fop_call stays authoritative."""
    async def run():
        server, c, cl = await _mounted(tmp_path)
        await c.write_file("/lk", b"data")
        base = cl.rpc_roundtrips
        replies = await c.graph.top.compound([
            ("inodelk", ("dom", Loc("/lk"), "lock"),
             {"xdata": {"lk-owner": b"o1"}}),
            ("inodelk", ("dom", Loc("/lk"), "unlock"),
             {"xdata": {"lk-owner": b"o1"}}),
        ])
        assert [st for st, _ in replies] == ["ok", "ok"]
        assert cl.rpc_roundtrips - base == 2  # one frame per lock fop
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_posix_journal_batching(tmp_path):
    """Brick-side: a chained create+writev+fsetattr lands as ONE
    journal append (one handle-farm transaction), and the journaled
    state survives a cold restart (drop_caches replay)."""
    from glusterfs_tpu.storage.posix import PosixLayer

    async def run():
        posix = PosixLayer("p", {"directory": str(tmp_path / "pb")})
        await posix.init()
        try:
            writes = []
            real_write = os.write

            def counting_write(fd, data):
                if fd == posix._xa_journal_fd:
                    writes.append(bytes(data))
                return real_write(fd, data)

            import glusterfs_tpu.storage.posix as posix_mod

            posix_mod.os.write = counting_write
            try:
                replies = await posix.compound([
                    ("create",
                     (Loc("/j"), os.O_RDWR | os.O_EXCL, 0o644),
                     {"xdata": {"init-xattrs": {"trusted.v": b"\x01"}}}),
                    ("writev", (cfop.FdRef(0), b"journal", 0), {}),
                    ("fsetattr", (cfop.FdRef(0), {"mode": 0o600}), {}),
                    ("release", (cfop.FdRef(0),), {}),
                ])
            finally:
                posix_mod.os.write = real_write
            assert [st for st, _ in replies] == ["ok"] * 4
            journal_appends = [w for w in writes if b'"' in w]
            assert len(journal_appends) == 1, \
                f"expected one batched append, saw {len(journal_appends)}"
            assert journal_appends[0].count(b"\n") >= 2  # bind + xattrs
            # the batched journal replays to the same state
            posix.drop_caches()
            ia = await posix.stat(Loc("/j"))
            assert ia.mode & 0o777 == 0o600
            xa = await posix.getxattr(Loc("/j"), "trusted.v")
            assert xa["trusted.v"] == b"\x01"
        finally:
            await posix.fini()

    asyncio.run(run())


def test_server_batches_journal_around_dispatch(tmp_path):
    """The brick wraps every compound dispatch in the posix journal
    batch, so the handle-farm coalescing holds even though the locks
    layer above posix decomposes the chain."""
    from glusterfs_tpu.storage.posix import PosixLayer

    async def run():
        server, c, cl = await _mounted(tmp_path)
        posix = next(l for l in walk(server.top)
                     if isinstance(l, PosixLayer))
        entered = []
        orig = PosixLayer.journal_batch

        def spying(self):
            entered.append(True)
            return orig(self)

        PosixLayer.journal_batch = spying
        try:
            await c.write_file("/jb", b"batched")
        finally:
            PosixLayer.journal_batch = orig
        assert entered, "server did not enter the posix journal batch"
        assert posix._jrnl_batch is None  # batch closed after dispatch
        assert await c.read_file("/jb") == b"batched"
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_compound_on_managed_graph_parity(tmp_path):
    """End-to-end through a full managed client stack (perf layers +
    cluster) on an in-process disperse volume: chains decompose where
    layers demand it and results stay byte-identical."""
    from glusterfs_tpu.utils.volspec import ec_volfile

    async def run():
        spec = ec_volfile(str(tmp_path), 6, 2)
        # arm compound at the graph edge the way volgen would
        spec = spec.replace("type cluster/disperse",
                            "type cluster/disperse\n"
                            "    option cpu-extensions native")
        g = Graph.construct(spec + """
volume wbtop
    type performance/write-behind
    option compound-fops on
    subvolumes disp
end-volume
""")
        c = Client(g)
        await c.mount()
        for i in range(4):
            await c.write_file(f"/m{i}", os.urandom(3000 + i))
        datas = [await c.read_file(f"/m{i}") for i in range(4)]
        assert [len(d) for d in datas] == [3000, 3001, 3002, 3003]
        st = await c.stat("/m3")
        assert st.size == 3003
        await c.unmount()

    asyncio.run(run())


def test_volgen_compound_key_reaches_all_ends():
    """cluster.use-compound-fops lands on protocol/client,
    performance/write-behind and protocol/server alike."""
    from glusterfs_tpu.mgmt import volgen

    volinfo = {
        "name": "cv", "type": "distribute",
        "bricks": [{"name": "cv-brick-0", "host": "127.0.0.1",
                    "path": "/tmp/cvb", "index": 0, "port": 0}],
        "options": {"cluster.use-compound-fops": "on"},
    }
    cvol = volgen.build_client_volfile(volinfo)
    bvol = volgen.build_brick_volfile(volinfo, volinfo["bricks"][0])
    client_stanza = cvol.split("volume cv-client-0")[1] \
                        .split("end-volume")[0]
    wb_stanza = cvol.split("volume cv-write-behind")[1] \
                    .split("end-volume")[0]
    srv_stanza = bvol.split("volume cv-brick-0-server")[1] \
                     .split("end-volume")[0]
    for stanza in (client_stanza, wb_stanza, srv_stanza):
        assert "compound-fops on" in stanza
    # and it is op-version gated like every cross-version key
    assert volgen.OPTION_MIN_OPVERSION["cluster.use-compound-fops"] == 5


def test_wb_fused_ftruncate_resets_logical_end(tmp_path):
    """A fused ftruncate through write-behind must reset the absorbed-
    bytes high-water mark — otherwise later write replies inflate a
    shrunk file's size and upper caches serve the stale length."""
    async def run():
        server, c, cl = await _mounted(tmp_path)
        f = await c.create("/le", os.O_RDWR)
        await f.write(b"x" * 100_000, 0)   # logical_end = 100000
        # the fuse SETATTR shape: ftruncate+setattr as one chain
        replies = await c.graph.top.compound([
            ("ftruncate", (f.fd, 10), {}),
            ("setattr", (Loc("/le"), {"mode": 0o600}), {})])
        assert [st for st, _ in replies] == ["ok", "ok"]
        ia = await c.graph.top.writev(f.fd, b"tiny", 0)
        assert ia.size == 10, ia.size  # not inflated back to 100000
        await f.close()
        st = await c.stat("/le")
        assert st.size == 10
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_read_file_chain_roundtrips(tmp_path):
    """ISSUE 3 read mirror of the create chain: a small-file read_file
    (lookup+open+readv+release) costs ONE round trip fused and >= 3 as
    singles."""
    async def run():
        server, c, cl = await _mounted(tmp_path, cf="on")
        await c.write_file("/rf", b"r" * 9000)
        base = cl.rpc_roundtrips
        assert await c.read_file("/rf") == b"r" * 9000
        fused = cl.rpc_roundtrips - base
        await c.unmount()
        await server.stop()

        server, c, cl = await _mounted(tmp_path / "off", cf="off")
        await c.write_file("/rf", b"r" * 9000)
        c.itable = type(c.itable)()  # cold dentry cache, like run 1
        base = cl.rpc_roundtrips
        assert await c.read_file("/rf") == b"r" * 9000
        singles = cl.rpc_roundtrips - base
        await c.unmount()
        await server.stop()

        assert fused == 1, f"read chain took {fused} round trips"
        assert singles >= 3, \
            f"singles baseline took only {singles} round trips"

    asyncio.run(run())


def test_read_chain_mixed_version_fallback(tmp_path):
    """A brick that doesn't advertise compound serves the read chain as
    decomposed singles — byte-identical result, more round trips."""
    async def run():
        server, c, cl = await _mounted(
            tmp_path, cf="on",
            brick_opts="    option compound-fops off\n")
        assert not cl._peer_compound
        payload = bytes(range(256)) * 64
        await c.write_file("/mv", payload)
        base = cl.rpc_roundtrips
        assert await c.read_file("/mv") == payload
        assert cl.rpc_roundtrips - base >= 3  # decomposed into singles
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_read_chain_decomposes_through_nontransparent_layer(tmp_path):
    """A layer with its own readv (and no compound forward override)
    forces decomposition — the chain's links run through that layer's
    fop methods and the result stays byte-identical."""
    from glusterfs_tpu.core.layer import Layer, register

    @register("test/readv-tap")
    class ReadvTap(Layer):
        taps = 0

        async def readv(self, fd, size, offset, xdata=None):
            type(self).taps += 1
            return await self.children[0].readv(fd, size, offset, xdata)

    async def run():
        g = Graph.construct(f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume

volume tap
    type test/readv-tap
    subvolumes posix
end-volume
""")
        c = Client(g)
        await c.mount()
        payload = b"tapped" * 2000
        await c.write_file("/t", payload)
        replies = await g.top.compound([
            ("lookup", (Loc("/t"),), {}),
            ("open", (Loc("/t"), os.O_RDONLY), {}),
            ("readv", (cfop.FdRef(1), 1 << 20, 0), {}),
            ("release", (cfop.FdRef(1),), {})])
        assert [st for st, _ in replies] == ["ok"] * 4
        assert bytes(replies[2][1]) == payload
        assert ReadvTap.taps >= 1  # the link went THROUGH the layer
        await c.unmount()

    asyncio.run(run())


def test_ec_read_chain_byte_identical(tmp_path):
    """Read chains through an EC 4+2 graph (where cluster/disperse
    decomposes them) return exactly what the unchained path returns —
    healthy AND degraded."""
    from glusterfs_tpu.cluster.ec import DisperseLayer
    from glusterfs_tpu.utils.volspec import ec_volfile

    async def run():
        spec = ec_volfile(str(tmp_path), 6, 2)
        g = Graph.construct(spec + """
volume wbtop
    type performance/write-behind
    option compound-fops on
    subvolumes disp
end-volume
""")
        c = Client(g)
        await c.mount()
        ec = next(l for l in walk(g.top)
                  if isinstance(l, DisperseLayer))
        payload = bytes(range(256)) * 300  # multi-stripe, odd tail
        await c.write_file("/ec", payload + b"tail")
        chained = await c.read_file("/ec")
        f = await c.open("/ec", os.O_RDONLY)
        unchained = await f.read(1 << 20, 0)
        await f.close()
        assert chained == unchained == payload + b"tail"
        # degraded: two children down -> read-mask/decode path
        ec.up[0] = ec.up[4] = False
        degraded = await c.read_file("/ec")
        assert degraded == payload + b"tail"
        ec.up[0] = ec.up[4] = True
        await c.unmount()

    asyncio.run(run())


def test_wb_window_flush_is_one_chain(tmp_path):
    """A multi-chunk write-behind window + the flush that drains it
    ride one compound frame (flushed windows as chains)."""
    async def run():
        server, c, cl = await _mounted(tmp_path)
        f = await c.create("/win", os.O_RDWR)
        # two DISJOINT chunks so the window holds two entries
        await f.write(b"a" * 100, 0)
        await f.write(b"b" * 100, 5000)
        base = cl.rpc_roundtrips
        await f.close()  # flush drains the window
        assert cl.rpc_roundtrips - base == 1
        got = await c.read_file("/win")
        assert got[:100] == b"a" * 100
        assert got[5000:5100] == b"b" * 100
        await c.unmount()
        await server.stop()

    asyncio.run(run())
