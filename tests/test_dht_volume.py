"""Distribute (DHT) volume e2e: hash placement, dirs-everywhere, merged
readdir, rename linkto, global lookup, rebalance
(tests/basic/distribute analog)."""

import numpy as np
import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc
from glusterfs_tpu.cluster.dht import dm_hash

N = 4


def volfile(base) -> str:
    out = []
    for i in range(N):
        out.append(f"volume b{i}\n    type storage/posix\n"
                   f"    option directory {base}/brick{i}\nend-volume\n")
    subs = " ".join(f"b{i}" for i in range(N))
    out.append(f"volume dist\n    type cluster/distribute\n"
               f"    subvolumes {subs}\nend-volume\n")
    return "\n".join(out)


@pytest.fixture
def vol(tmp_path):
    c = SyncClient(Graph.construct(volfile(tmp_path)))
    c.mount()
    yield c, c.graph.top, tmp_path
    c.close()


def test_hash_distribution(vol):
    c, dht, base = vol
    names = [f"file{i:03d}" for i in range(40)]
    for n in names:
        c.write_file(f"/{n}", n.encode())
    # every file is on exactly its hashed brick
    for n in names:
        hi = dht.hashed_idx(n)
        for i in range(N):
            exists = (base / f"brick{i}" / n).exists()
            assert exists == (i == hi), (n, i, hi)
    # distribution is reasonably even
    counts = [sum(1 for n in names if dht.hashed_idx(n) == i)
              for i in range(N)]
    assert all(cnt > 0 for cnt in counts)
    # reads work
    for n in names:
        assert c.read_file(f"/{n}") == n.encode()


def test_dirs_on_all_bricks(vol):
    c, dht, base = vol
    c.mkdir("/d1")
    for i in range(N):
        assert (base / f"brick{i}" / "d1").is_dir()
    c.write_file("/d1/f", b"x")
    assert c.listdir("/d1") == ["f"]
    c.unlink("/d1/f")
    c.rmdir("/d1")
    for i in range(N):
        assert not (base / f"brick{i}" / "d1").exists()


def test_merged_readdir(vol):
    c, dht, base = vol
    names = sorted(f"n{i}" for i in range(12))
    for n in names:
        c.write_file(f"/{n}", b".")
    assert c.listdir("/") == names


def test_rename_cross_subvol_linkto(vol):
    c, dht, base = vol
    src, dst = "alpha", "beta"
    # ensure they hash differently (pick dst accordingly)
    if dht.hashed_idx(src) == dht.hashed_idx(dst):
        dst = "gamma2"
        assert dht.hashed_idx(src) != dht.hashed_idx(dst)
    c.write_file(f"/{src}", b"content")
    c.rename(f"/{src}", f"/{dst}")
    assert c.read_file(f"/{dst}") == b"content"
    # data stayed on src's hashed brick; linkto exists on dst's
    si, di = dht.hashed_idx(src), dht.hashed_idx(dst)
    assert (base / f"brick{si}" / dst).read_bytes() == b"content"
    assert (base / f"brick{di}" / dst).exists()  # linkto pointer
    # linkto hidden from listings
    assert c.listdir("/").count(dst) == 1
    # stat follows the pointer
    assert c.stat(f"/{dst}").size == 7


def test_rebalance(vol):
    c, dht, base = vol
    src, dst = "alpha", "beta"
    if dht.hashed_idx(src) == dht.hashed_idx(dst):
        dst = "gamma2"
    c.write_file(f"/{src}", b"move me")
    c.rename(f"/{src}", f"/{dst}")
    res = c._run(dht.rebalance("/"))
    assert len(res["moved"]) == 1
    di = dht.hashed_idx(dst)
    assert (base / f"brick{di}" / dst).read_bytes() == b"move me"
    assert c.read_file(f"/{dst}") == b"move me"
    # no stray copies
    count = sum((base / f"brick{i}" / dst).exists() for i in range(N))
    assert count == 1


def test_statfs_aggregates(vol):
    c, dht, base = vol
    sv = c.statvfs("/")
    single = c._run(dht.children[0].statfs(Loc("/")))
    assert sv["blocks"] >= single["blocks"] * N


def test_unlink_and_errors(vol):
    c, dht, base = vol
    c.write_file("/gone", b"x")
    c.unlink("/gone")
    with pytest.raises(FopError):
        c.read_file("/gone")


def test_dm_hash_stability():
    # placement must be deterministic across runs/processes
    assert dm_hash("file001") == dm_hash("file001")
    vals = {dm_hash(f"f{i}") for i in range(100)}
    assert len(vals) == 100  # no trivial collisions in small sample


def test_rename_over_existing_destination(vol):
    """Rename onto an existing cross-subvol destination must unlink the
    old dst file, not convert it into a linkto over live data (advisor
    round-1 finding; reference dht_rename dst-cached unlink)."""
    c, dht, base = vol
    src, dst = "alpha", "beta"
    if dht.hashed_idx(src) == dht.hashed_idx(dst):
        dst = "gamma2"
        assert dht.hashed_idx(src) != dht.hashed_idx(dst)
    c.write_file(f"/{src}", b"new data")
    c.write_file(f"/{dst}", b"old destination payload")
    c.rename(f"/{src}", f"/{dst}")
    assert c.read_file(f"/{dst}") == b"new data"
    assert c.stat(f"/{dst}").size == len(b"new data")
    # exactly one real copy + at most one linkto pointer remain
    si = dht.hashed_idx(src)
    assert (base / f"brick{si}" / dst).read_bytes() == b"new data"
    assert c.listdir("/").count(dst) == 1
    assert src not in c.listdir("/")


def test_rebalance_throttle_and_status(vol):
    """cluster.rebal-throttle (dht-rebalance.c:3269 migrator scaling):
    lazy runs one migration at a time and yields the loop between
    files so client I/O interleaves; aggressive runs migrations wide.
    The live defrag status publishes progress + concurrency."""
    import asyncio

    c, dht, base = vol

    def misplace(n_files, tag):
        # write through dht, then force every file onto the WRONG brick
        # by renaming at brick level (classic post-add-brick shape)
        names = []
        for i in range(n_files):
            name = f"{tag}{i:02d}"
            c.write_file(f"/{name}", name.encode() * 64)
            hi = dht.hashed_idx(name)
            wrong = (hi + 1) % N
            (base / f"brick{hi}" / name).rename(
                base / f"brick{wrong}" / name)
            names.append(name)
        return names

    names = misplace(12, "lz")
    dht.reconfigure({"rebal-throttle": "lazy"})

    async def lazy_run():
        interleaved = 0
        task = asyncio.ensure_future(dht.rebalance("/"))
        # client I/O keeps getting served while the lazy crawl runs
        while not task.done():
            await c.graph.top.lookup(Loc(f"/{names[0]}"))
            interleaved += 1
            await asyncio.sleep(0)
        return task.result(), interleaved

    res, interleaved = c._run(lazy_run())
    st = res["status"]
    assert st["state"] == "completed"
    assert st["throttle"] == "lazy"
    assert st["max_inflight"] == 1  # one migrator: yields to clients
    assert st["moved"] >= 12 and st["bytes_moved"] > 0
    assert interleaved > 0  # client fops interleaved with the crawl
    for name in names:  # data settled on the hashed brick
        assert c.read_file(f"/{name}") == name.encode() * 64

    names = misplace(12, "ag")
    dht.reconfigure({"rebal-throttle": "aggressive"})
    res = c._run(dht.rebalance("/"))
    st = res["status"]
    assert st["throttle"] == "aggressive"
    assert st["max_inflight"] > 1  # migrations actually ran wide
    assert st["moved"] >= 12
    for name in names:
        assert c.read_file(f"/{name}") == name.encode() * 64
