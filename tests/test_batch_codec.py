"""Batching codec (stripe-cache analog): concurrent fop codec work must
coalesce into one device batch per tick, with a CPU-ladder cutoff for
small batches (reference ec.c:286 stripe-cache + north-star
"HBM-resident batches" requirement)."""

import asyncio

import numpy as np
import pytest

from glusterfs_tpu.ops import gf256
from glusterfs_tpu.ops.batch import BatchingCodec

K, R = 4, 2
STRIPE = K * 512


def _rand(n, seed):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_concurrent_encodes_one_launch():
    codec = BatchingCodec(K, R, "xla", window=0.005, min_batch=0)

    async def run():
        datas = [_rand(STRIPE * (i + 1), i) for i in range(8)]
        outs = await asyncio.gather(
            *(codec.encode_async(d) for d in datas))
        return datas, outs

    datas, outs = asyncio.run(run())
    assert codec.launches == 1, "8 concurrent encodes must share 1 launch"
    assert codec.max_batch == 8
    for d, o in zip(datas, outs):
        assert np.array_equal(o, gf256.ref_encode(d, K, K + R))


def test_concurrent_decodes_group_by_mask():
    codec = BatchingCodec(K, R, "xla", window=0.005, min_batch=0)
    rng_rows = [(0, 1, 2, 3), (1, 3, 4, 5), (0, 1, 2, 3)]
    datas = [_rand(STRIPE * 2, 10 + i) for i in range(3)]
    frag_sets = [gf256.ref_encode(d, K, K + R) for d in datas]

    async def run():
        return await asyncio.gather(*(
            codec.decode_async(fr[np.asarray(rows)], rows)
            for fr, rows in zip(frag_sets, rng_rows)))

    outs = asyncio.run(run())
    # two distinct masks -> exactly two launches
    assert codec.launches == 2
    for d, o in zip(datas, outs):
        assert np.array_equal(o, d)


def test_small_batch_falls_back_to_cpu_ladder():
    codec = BatchingCodec(K, R, "xla", window=0.002,
                          min_batch=1 << 20)  # everything is "small"

    async def run():
        d = _rand(STRIPE, 3)
        return d, await codec.encode_async(d)

    d, out = asyncio.run(run())
    assert codec.launches == 0, "small batch must not hit the device path"
    assert codec.cpu_launches == 1
    assert np.array_equal(out, gf256.ref_encode(d, K, K + R))


def test_sequential_calls_do_not_starve():
    codec = BatchingCodec(K, R, "xla", window=0.001, min_batch=0)

    async def run():
        outs = []
        for i in range(3):  # strictly sequential: each waits its window
            d = _rand(STRIPE, 20 + i)
            outs.append((d, await codec.encode_async(d)))
        return outs

    for d, o in asyncio.run(run()):
        assert np.array_equal(o, gf256.ref_encode(d, K, K + R))


def test_ec_volume_concurrent_writes_coalesce(tmp_path):
    """N concurrent client writes on an EC volume must be served by fewer
    codec launches than fops (the served-data-path coalescing the north
    star asks for), and every byte must round-trip."""
    from glusterfs_tpu.api.glfs import Client
    from glusterfs_tpu.core.graph import Graph
    from glusterfs_tpu.utils.volspec import ec_volfile

    volspec = ec_volfile(tmp_path, K + R, R, options={
        "cpu-extensions": "xla", "stripe-cache": "on",
        "stripe-cache-window": 2000, "stripe-cache-min-batch": 0})

    datas = {f"/f{i}": bytes(_rand(4 * STRIPE, 40 + i)) for i in range(12)}

    async def run():
        c = Client(Graph.construct(volspec))
        await c.mount()
        ec = c.graph.top
        await asyncio.gather(*(
            c.write_file(p, d) for p, d in datas.items()))
        writes_launches = ec.codec.launches
        reads = await asyncio.gather(*(
            c.read_file(p) for p in datas))
        await c.unmount()
        return writes_launches, ec.codec.launches, reads

    wl, total_l, reads = asyncio.run(run())
    assert wl < 12, f"12 concurrent writes took {wl} launches (no coalescing)"
    for (p, d), got in zip(datas.items(), reads):
        assert got == d, p
