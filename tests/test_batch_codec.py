"""Batching codec (stripe-cache analog): concurrent fop codec work must
coalesce into one device batch per tick, with a CPU-ladder cutoff for
small batches (reference ec.c:286 stripe-cache + north-star
"HBM-resident batches" requirement)."""

import asyncio

import numpy as np
import pytest

from glusterfs_tpu.ops import gf256
from glusterfs_tpu.ops.batch import BatchingCodec

K, R = 4, 2
STRIPE = K * 512


def _rand(n, seed):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_concurrent_encodes_one_launch():
    codec = BatchingCodec(K, R, "xla", window=0.005, min_batch=0)

    async def run():
        datas = [_rand(STRIPE * (i + 1), i) for i in range(8)]
        outs = await asyncio.gather(
            *(codec.encode_async(d) for d in datas))
        return datas, outs

    datas, outs = asyncio.run(run())
    assert codec.launches == 1, "8 concurrent encodes must share 1 launch"
    assert codec.max_batch == 8
    for d, o in zip(datas, outs):
        assert np.array_equal(o, gf256.ref_encode(d, K, K + R))


def test_concurrent_decodes_group_by_mask():
    codec = BatchingCodec(K, R, "xla", window=0.005, min_batch=0)
    rng_rows = [(0, 1, 2, 3), (1, 3, 4, 5), (0, 1, 2, 3)]
    datas = [_rand(STRIPE * 2, 10 + i) for i in range(3)]
    frag_sets = [gf256.ref_encode(d, K, K + R) for d in datas]

    async def run():
        return await asyncio.gather(*(
            codec.decode_async(fr[np.asarray(rows)], rows)
            for fr, rows in zip(frag_sets, rng_rows)))

    outs = asyncio.run(run())
    # two distinct masks -> exactly two launches
    assert codec.launches == 2
    for d, o in zip(datas, outs):
        assert np.array_equal(o, d)


def test_small_batch_falls_back_to_cpu_ladder():
    codec = BatchingCodec(K, R, "xla", window=0.002,
                          min_batch=1 << 20)  # everything is "small"

    async def run():
        d = _rand(STRIPE, 3)
        return d, await codec.encode_async(d)

    d, out = asyncio.run(run())
    assert codec.launches == 0, "small batch must not hit the device path"
    assert codec.cpu_launches == 1
    assert np.array_equal(out, gf256.ref_encode(d, K, K + R))


def test_sequential_calls_do_not_starve():
    codec = BatchingCodec(K, R, "xla", window=0.001, min_batch=0)

    async def run():
        outs = []
        for i in range(3):  # strictly sequential: each waits its window
            d = _rand(STRIPE, 20 + i)
            outs.append((d, await codec.encode_async(d)))
        return outs

    for d, o in asyncio.run(run()):
        assert np.array_equal(o, gf256.ref_encode(d, K, K + R))


class _SlowDeviceCodec(BatchingCodec):
    """Device launches take a fixed wall time (a slow-tunnel stand-in)."""

    DELAY = 0.25

    def encode(self, data):
        import time as _t

        _t.sleep(self.DELAY)
        return super().encode(data)


def test_flushes_pipeline_do_not_serialize():
    """Batch N+1 must fill and dispatch while batch N is on the device:
    two flushes with a 0.25 s device round trip must finish in well under
    the 0.5 s a serialized (on-loop, blocking) flush design would take,
    and the event loop must keep ticking during a flush (VERDICT r2
    weak #1: every flush was a blocking round trip on the loop)."""
    import time as _t

    codec = _SlowDeviceCodec(K, R, "xla", window=0.001, min_batch=0)
    ticks = 0

    async def ticker():
        nonlocal ticks
        while True:
            await asyncio.sleep(0.01)
            ticks += 1

    async def run():
        d = _rand(STRIPE * 4, 1)
        # warm the jit cache for the bucket shape OFF the clock (the
        # waves below pad to the same 16-stripe bucket)
        await codec.encode_async(d)
        tick_task = asyncio.ensure_future(ticker())
        t0 = _t.perf_counter()
        wave_a = [asyncio.ensure_future(codec.encode_async(d))
                  for _ in range(4)]
        await asyncio.sleep(0.005)  # window expires -> flush A in flight
        wave_b = [asyncio.ensure_future(codec.encode_async(d))
                  for _ in range(4)]
        outs = await asyncio.gather(*wave_a, *wave_b)
        dt = _t.perf_counter() - t0
        tick_task.cancel()
        return outs, dt

    outs, dt = asyncio.run(run())
    assert codec.launches == 3, "warmup + two timed flushes expected"
    assert dt < 2 * _SlowDeviceCodec.DELAY * 0.9, (
        f"flushes serialized: {dt:.3f}s for two overlappable "
        f"{_SlowDeviceCodec.DELAY}s launches")
    assert ticks >= 10, f"event loop starved during flushes ({ticks} ticks)"
    want = gf256.ref_encode(_rand(STRIPE * 4, 1), K, K + R)
    for o in outs:
        assert np.array_equal(o, want)


def test_measured_break_even_routing():
    """With calibrated models, each flush goes to the predicted-faster
    path: a high-overhead device model routes small flushes to the CPU
    ladder; a near-zero-overhead device model routes them to the device."""
    codec = BatchingCodec(K, R, "xla", window=0.001, min_batch=1)
    # hand-calibrate: device = 1 s overhead + fast rate; native = fast
    codec._dev.overhead, codec._dev.rate, codec._dev.samples = 1.0, 1e12, 2
    codec._nat.overhead, codec._nat.rate, codec._nat.samples = 0.0, 1e9, 2
    codec._cal_state = "done"

    async def one(d):
        return await codec.encode_async(d)

    d = _rand(STRIPE * 2, 7)
    out = asyncio.run(one(d))
    assert np.array_equal(out, gf256.ref_encode(d, K, K + R))
    assert codec.cpu_launches == 1 and codec.launches == 0, \
        "slow-device model must route to the CPU ladder"
    be = codec.break_even_bytes()
    assert be is not None and be > STRIPE * 2

    # flip: device is effectively free -> device path wins
    codec._dev.overhead, codec._dev.rate = 0.0, 1e12
    codec._nat.rate = 1e6
    out = asyncio.run(one(d))
    assert np.array_equal(out, gf256.ref_encode(d, K, K + R))
    assert codec.launches == 1, "fast-device model must route to the device"


def test_ensure_calibrated_measures_both_paths():
    codec = BatchingCodec(K, R, "xla", window=0.001)

    async def run():
        return await codec.ensure_calibrated()

    assert asyncio.run(run()) is True
    stats = codec.dump_stats()
    assert stats["calibration"] == "done"
    assert stats["device_model"] is not None
    assert stats["native_model"] is not None
    assert stats["device_model"]["rate_MiB_s"] > 0


def test_ec_volume_concurrent_writes_coalesce(tmp_path):
    """N concurrent client writes on an EC volume must be served by fewer
    codec launches than fops (the served-data-path coalescing the north
    star asks for), and every byte must round-trip."""
    from glusterfs_tpu.api.glfs import Client
    from glusterfs_tpu.core.graph import Graph
    from glusterfs_tpu.utils.volspec import ec_volfile

    volspec = ec_volfile(tmp_path, K + R, R, options={
        "cpu-extensions": "xla", "stripe-cache": "on",
        "stripe-cache-window": 2000, "stripe-cache-min-batch": 0})

    datas = {f"/f{i}": bytes(_rand(4 * STRIPE, 40 + i)) for i in range(12)}

    async def run():
        c = Client(Graph.construct(volspec))
        await c.mount()
        ec = c.graph.top
        await asyncio.gather(*(
            c.write_file(p, d) for p, d in datas.items()))
        writes_launches = ec.codec.launches
        reads = await asyncio.gather(*(
            c.read_file(p) for p in datas))
        await c.unmount()
        return writes_launches, ec.codec.launches, reads

    wl, total_l, reads = asyncio.run(run())
    assert wl < 12, f"12 concurrent writes took {wl} launches (no coalescing)"
    for (p, d), got in zip(datas.items(), reads):
        assert got == d, p


def test_small_codec_lazy_build_is_race_free():
    """graft-race GL09 regression (ISSUE 14): _small()'s lazy native
    codec used to be built with an UNLOCKED check-then-assign, and the
    routing path (event loop) races the calibration path (flush-pool
    thread) into it — two racers must converge on ONE codec instance,
    built under the codec lock."""
    import threading

    codec = BatchingCodec(K, R, "xla", min_batch=1 << 20)
    assert codec._cpu is None  # device backend: still lazy

    built = []
    start = threading.Barrier(8)

    def race():
        start.wait()
        built.append(codec._small())

    threads = [threading.Thread(target=race) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert len(built) == 8
    assert all(b is built[0] for b in built), \
        "racing _small() calls built more than one small codec"
    assert built[0] is not codec  # device backend got a CPU sibling
    # CPU-ladder backends alias self at construction (pre-publication):
    # no lazy cross-context write exists at all
    cpu = BatchingCodec(K, R, "native", min_batch=1 << 20)
    assert cpu._cpu is cpu


def test_calibration_schedule_check_is_locked():
    """graft-race GL09 regression (ISSUE 14): the debounce check read
    _cal_state WITHOUT the lock while _calibrate (pool thread) writes
    it under the lock; the locked read must still debounce — exactly
    one timer per idle gap, and a non-idle state schedules nothing."""
    codec = BatchingCodec(K, R, "xla", min_batch=1 << 20)

    async def run():
        codec._maybe_schedule_calibration()
        t1 = codec._cal_timer
        codec._maybe_schedule_calibration()  # debounced: same timer
        t2 = codec._cal_timer
        with codec._lock:
            codec._cal_state = "done"
        t1.cancel()
        codec._cal_timer = None
        codec._maybe_schedule_calibration()  # non-idle: no new timer
        t3 = codec._cal_timer
        return t1, t2, t3

    t1, t2, t3 = asyncio.run(run())
    assert t1 is t2 and t1 is not None
    assert t3 is None
