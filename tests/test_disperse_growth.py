"""Growing a single-group disperse volume into distributed-disperse
by add-brick (whole groups), then shrinking back by remove-brick of a
group — the glusterd-brick-ops.c disperse-geometry paths."""

import asyncio

import pytest

from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                         mount_volume)


from tests.harness import wait_async as _wait


@pytest.mark.slow
def test_disperse_volume_grows_to_distributed(tmp_path):
    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="gv", vtype="disperse",
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(3)],
                             redundancy=1)
                await c.call("volume-start", name="gv")
                m = await mount_volume(d.host, d.port, "gv")
                try:
                    names = [f"f{i:02d}" for i in range(12)]
                    for n in names:
                        await m.write_file(f"/{n}", n.encode() * 40)

                    # partial group must be refused (2+1 geometry)
                    with pytest.raises(FopError):
                        await c.call("volume-add-brick", name="gv",
                                     bricks=[{"path":
                                              str(tmp_path / "bx")}])

                    # whole group: 3 more bricks -> 2x(2+1)
                    out = await c.call(
                        "volume-add-brick", name="gv",
                        bricks=[{"path": str(tmp_path / f"b{i}")}
                                for i in range(3, 6)])
                    assert len(out["added"]) == 3
                    info = await c.call("volume-info", name="gv")
                    assert info["gv"]["group-size"] == 3

                    async def swapped():
                        types = [l.type_name
                                 for l in m.graph.by_name.values()]
                        return (types.count("cluster/disperse") == 2
                                and "cluster/distribute" in types)

                    assert await _wait(swapped), "graph not distributed"
                    # old data readable; new files spread to group 2
                    for n in names:
                        assert await m.read_file(f"/{n}") == \
                            n.encode() * 40
                    for i in range(12, 30):
                        await m.write_file(f"/g{i}", b"NEW")
                    import os as _os

                    g2 = [f for f in _os.listdir(tmp_path / "b3")
                          if f.startswith("g")]
                    assert g2, "no new data placed on the second group"

                    # drain + remove the SECOND group
                    await c.call(
                        "volume-remove-brick", name="gv",
                        bricks=[f"gv-brick-{i}" for i in range(3, 6)],
                        action="start")

                    async def drained():
                        st = await c.call("volume-remove-brick",
                                          name="gv", bricks=[],
                                          action="status")
                        return st.get("status") == "completed"

                    assert await _wait(drained), "drain never finished"
                    await c.call("volume-remove-brick", name="gv",
                                 bricks=[], action="commit")
                    info = await c.call("volume-info", name="gv")
                    assert len(info["gv"]["bricks"]) == 3
                    # everything still readable after the shrink
                    for n in names:
                        assert await m.read_file(f"/{n}") == \
                            n.encode() * 40
                    for i in range(12, 30):
                        assert await m.read_file(f"/g{i}") == b"NEW"
                finally:
                    await m.unmount()
                await c.call("volume-stop", name="gv")
        finally:
            await d.stop()

    asyncio.run(run())
