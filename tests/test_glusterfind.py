"""glusterfind: session-based incremental change lists from the brick
changelog journals (reference tools/glusterfind + changelog history
API)."""

import asyncio
import os

import pytest

from glusterfs_tpu.tools.glusterfind import coalesce


def _r(op, path, ts, path2=""):
    rec = {"ts": ts, "op": op, "path": path, "gfid": ""}
    if path2:
        rec["path2"] = path2
    return rec


def test_coalesce_rules():
    # NEW + writes stays NEW
    assert coalesce([_r("create", "/a", 1), _r("writev", "/a", 2)]) == \
        [("NEW", "/a")]
    # born and died inside the window: nothing
    assert coalesce([_r("create", "/b", 1), _r("unlink", "/b", 2)]) == []
    # pre-existing modified then deleted: DELETE
    assert coalesce([_r("writev", "/c", 1), _r("unlink", "/c", 2)]) == \
        [("DELETE", "/c")]
    # metadata-only change: MODIFY
    assert coalesce([_r("setattr", "/d", 1)]) == [("MODIFY", "/d")]
    # replica echoes dedupe
    assert coalesce([_r("create", "/e", 1), _r("create", "/e", 1.001),
                     _r("writev", "/e", 2), _r("writev", "/e", 2.001)]) \
        == [("NEW", "/e")]
    # rename of a pre-existing file
    assert coalesce([_r("rename", "/f", 1, "/g")]) == \
        [("RENAME", "/f", "/g")]
    # NEW then renamed: NEW at the final path
    assert coalesce([_r("create", "/h", 1),
                     _r("rename", "/h", 2, "/i")]) == [("NEW", "/i")]
    # rename chain keeps the original name
    assert coalesce([_r("rename", "/j", 1, "/k"),
                     _r("rename", "/k", 2, "/l")]) == \
        [("RENAME", "/j", "/l")]
    # delete after re-create is NEW again
    assert coalesce([_r("unlink", "/m", 1), _r("create", "/m", 2)]) == \
        [("NEW", "/m")]


@pytest.mark.slow
def test_glusterfind_session_lifecycle(tmp_path):
    """create -> changes -> pre (lists them) -> post -> pre (empty) ->
    more changes -> pre (only the new ones), via the real CLI entry."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)
    from glusterfs_tpu.tools import glusterfind as gf
    import argparse

    async def run():
        gd = Glusterd(str(tmp_path / "gd"))
        await gd.start()
        async with MgmtClient(gd.host, gd.port) as c:
            bricks = [{"path": str(tmp_path / f"b{i}")} for i in range(2)]
            await c.call("volume-create", name="fv", vtype="replicate",
                         bricks=bricks, group_size=2)
            await c.call("volume-start", name="fv")

        def ns(**kw):
            return argparse.Namespace(
                server=f"{gd.host}:{gd.port}",
                session_dir=str(tmp_path / "sessions"), **kw)

        await gf.cmd_create(ns(session="s1", volume="fv"))
        cl = await mount_volume(gd.host, gd.port, "fv")
        from glusterfs_tpu.core.layer import walk
        subs = [l for l in walk(cl.graph.top)
                if l.type_name == "protocol/client"]
        for _ in range(150):
            if all(l.connected for l in subs):
                break
            await asyncio.sleep(0.1)
        await cl.write_file("/one", b"1")
        await cl.mkdir("/dir")
        await cl.write_file("/dir/two", b"2")
        await asyncio.sleep(0.05)

        out1 = str(tmp_path / "pre1.txt")
        r = await gf.cmd_pre(ns(session="s1", volume="fv", outfile=out1))
        lines = set(open(out1).read().splitlines())
        assert "NEW /one" in lines and "NEW /dir" in lines \
            and "NEW /dir/two" in lines, lines
        assert r["changes"] == len(lines)
        await gf.cmd_post(ns(session="s1", volume="fv"))

        # nothing new: empty increment
        out2 = str(tmp_path / "pre2.txt")
        await gf.cmd_pre(ns(session="s1", volume="fv", outfile=out2))
        assert open(out2).read() == ""
        await gf.cmd_post(ns(session="s1", volume="fv"))

        # incremental: only the delta since post
        await cl.write_file("/one", b"updated")
        await cl.unlink("/dir/two")
        await asyncio.sleep(0.05)
        out3 = str(tmp_path / "pre3.txt")
        await gf.cmd_pre(ns(session="s1", volume="fv", outfile=out3))
        lines = set(open(out3).read().splitlines())
        assert "MODIFY /one" in lines and "DELETE /dir/two" in lines, lines
        assert not any(l.endswith(" /dir") for l in lines)

        listing = await gf.cmd_list(ns())
        assert "fv" in listing["s1"]
        await gf.cmd_delete(ns(session="s1", volume="fv"))
        assert (await gf.cmd_list(ns())) == {}

        await cl.unmount()
        await gd.stop()

    asyncio.run(run())


def test_coalesce_replica_echo_of_dropped_file():
    """Both replicas journal create AND unlink: the duplicate unlink
    must not resurrect a born-and-died file as DELETE (found by the
    e2e CLI drive on a 2-replica volume)."""
    recs = [_r("create", "/t", 1), _r("create", "/t", 1.01),
            _r("writev", "/t", 2), _r("writev", "/t", 2.01),
            _r("unlink", "/t", 3), _r("unlink", "/t", 3.01)]
    assert coalesce(recs) == []
    # but a genuine re-create after the drop is NEW again
    assert coalesce(recs + [_r("create", "/t", 4)]) == [("NEW", "/t")]


def test_coalesce_rename_replica_echo():
    """A replica's rename echo must not downgrade NEW to RENAME (the
    consumer would rename a path it never received)."""
    recs = [_r("create", "/a", 1), _r("create", "/a", 1.01),
            _r("rename", "/a", 2, "/b"), _r("rename", "/a", 2.01, "/b")]
    assert coalesce(recs) == [("NEW", "/b")]
    # echoed rename of a pre-existing file stays one RENAME
    recs = [_r("rename", "/x", 1, "/y"), _r("rename", "/x", 1.01, "/y")]
    assert coalesce(recs) == [("RENAME", "/x", "/y")]


@pytest.mark.slow
def test_glusterfind_history_over_rpc_only(tmp_path, monkeypatch):
    """Changelog history reaches glusterfind through the brick RPC (the
    gf-history-changelog.c + changelog-rpc.c contract): with local
    journal reading disabled entirely, pre still lists the changes."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)
    from glusterfs_tpu.tools import glusterfind as gf
    import argparse

    async def run():
        gd = Glusterd(str(tmp_path / "gd"))
        await gd.start()
        async with MgmtClient(gd.host, gd.port) as c:
            bricks = [{"path": str(tmp_path / f"b{i}")} for i in range(2)]
            await c.call("volume-create", name="rv", vtype="replicate",
                         bricks=bricks, group_size=2)
            await c.call("volume-start", name="rv")

        def ns(**kw):
            return argparse.Namespace(
                server=f"{gd.host}:{gd.port}",
                session_dir=str(tmp_path / "sessions"), **kw)

        await gf.cmd_create(ns(session="s", volume="rv"))
        cl = await mount_volume(gd.host, gd.port, "rv")
        from glusterfs_tpu.core.layer import walk
        subs = [l for l in walk(cl.graph.top)
                if l.type_name == "protocol/client"]
        for _ in range(150):
            if all(l.connected for l in subs):
                break
            await asyncio.sleep(0.1)
        await cl.write_file("/wire-only", b"x")
        await asyncio.sleep(0.05)

        # sever the local path: any attempt to read a journal from disk
        # blows up — the records can only have crossed the brick RPC
        def boom(*a, **k):
            raise AssertionError("local journal read attempted")
        monkeypatch.setattr(gf, "_scan", boom)

        out = str(tmp_path / "pre.txt")
        r = await gf.cmd_pre(ns(session="s", volume="rv", outfile=out))
        assert r["mode"] == "changelog"
        assert "NEW /wire-only" in open(out).read().splitlines()

        await cl.unmount()
        await gd.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_glusterfind_full_crawl_fallback(tmp_path):
    """A session created AFTER data already exists (changelog enabled
    late) cannot be served from the journals — pre falls back to the
    namespace crawl and lists everything as NEW (reference
    tools/glusterfind/src/brickfind.py)."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)
    from glusterfs_tpu.tools import glusterfind as gf
    import argparse

    async def run():
        gd = Glusterd(str(tmp_path / "gd"))
        await gd.start()
        async with MgmtClient(gd.host, gd.port) as c:
            bricks = [{"path": str(tmp_path / f"b{i}")} for i in range(2)]
            await c.call("volume-create", name="cv", vtype="replicate",
                         bricks=bricks, group_size=2)
            await c.call("volume-start", name="cv")

        # data lands BEFORE the session (and before changelog exists)
        cl = await mount_volume(gd.host, gd.port, "cv")
        from glusterfs_tpu.core.layer import walk
        subs = [l for l in walk(cl.graph.top)
                if l.type_name == "protocol/client"]
        for _ in range(150):
            if all(l.connected for l in subs):
                break
            await asyncio.sleep(0.1)
        await cl.write_file("/old-one", b"1")
        await cl.mkdir("/olddir")
        await cl.write_file("/olddir/old-two", b"2")
        await cl.unmount()

        def ns(**kw):
            return argparse.Namespace(
                server=f"{gd.host}:{gd.port}",
                session_dir=str(tmp_path / "sessions"), **kw)

        await gf.cmd_create(ns(session="late", volume="cv"))
        # the session's epoch is "now", but the journals started even
        # later (create enabled them): force the uncovered window by
        # rewinding the committed timestamp to before the volume's data
        sp = gf._session_path(str(tmp_path / "sessions"), "late", "cv")
        gf._write_ts(os.path.join(sp, "status"), 1.0)

        out = str(tmp_path / "pre.txt")
        r = await gf.cmd_pre(ns(session="late", volume="cv", outfile=out))
        assert r["mode"] == "full-crawl", r
        lines = set(open(out).read().splitlines())
        assert {"NEW /old-one", "NEW /olddir", "NEW /olddir/old-two"} \
            <= lines, lines
        await gd.stop()

    asyncio.run(run())
