"""Incident flight recorder + cluster-wide trace capture (core/flight,
the __incident__ RPC, glusterd's incident fan-out): the bounded record
ring and its registry families, snapshot section isolation, auto-
capture rate-limit/size-bound/pruning, failure-event triggers, the
satellite pin that the wire trace id survives the FL_SHM bulk lane and
the compound envelope (brick spans join the client trace on both
transports), gateway X-Gftpu-Trace + error-body trace + access-log
lines, and the managed cluster bundle merge with partial naming."""

import asyncio
import json
import os

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core import flight, gflog, tracing
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.daemon import serve_brick

from .harness import BRICK_VOLFILE

CLIENT_VOLFILE = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume locks
end-volume
"""


@pytest.fixture(autouse=True)
def _flight_reset():
    """Flight state is process-global (the point of the module); tests
    must not leak capture arming or ring contents into each other."""
    saved = (flight.INCIDENT_DIR, flight.INCIDENT_MAX_BYTES,
             flight.INCIDENT_MIN_INTERVAL, flight.ROLE,
             flight.ACCESS_LOG)
    flight.RING.clear()
    flight._last_capture = 0.0
    yield
    (flight.INCIDENT_DIR, flight.INCIDENT_MAX_BYTES,
     flight.INCIDENT_MIN_INTERVAL, flight.ROLE,
     flight.ACCESS_LOG) = saved
    flight.RING.clear()
    flight._last_capture = 0.0
    flight._sections.pop("t", None)
    flight._sections.pop("boom", None)


async def _connect(port, volfile=CLIENT_VOLFILE):
    g = Graph.construct(volfile.format(port=port))
    c = Client(g)
    await c.mount()
    for _ in range(200):
        if g.top.connected:
            break
        await asyncio.sleep(0.05)
    assert g.top.connected
    return c, g


def _bundles(d):
    return sorted(f for f in os.listdir(d)
                  if f.startswith("incident-") and f.endswith(".json"))


# -- the recorder ----------------------------------------------------------

def test_ring_bounded_and_counted():
    """record() is bounded by the ring size and counted per kind in
    gftpu_flight_records_total."""
    flight.set_ring_size(32)
    try:
        before = dict(flight._record_counts)
        for i in range(100):
            flight.record("t_kind", i=i)
        assert len(flight.RING) == 32
        assert flight.RING[-1]["i"] == 99  # newest kept
        snap = REGISTRY.snapshot()
        counts = {l["kind"]: v for l, v in
                  snap["gftpu_flight_records_total"]["samples"]}
        assert counts["t_kind"] - before.get("t_kind", 0) == 100
    finally:
        flight.set_ring_size(512)


def test_snapshot_sections_isolated():
    """A registered section lands in the bundle; a raising section
    degrades to an error stub without poisoning the snapshot."""
    flight.add_section("t", lambda: {"x": 1})
    flight.add_section("boom", lambda: 1 / 0)
    flight.record("marker", tag="here")
    snap = flight.snapshot(spans=10)
    assert snap["t"] == {"x": 1}
    assert "ZeroDivisionError" in snap["boom"]["error"]
    assert any(r["kind"] == "marker" for r in snap["records"])
    assert {"ts", "pid", "role", "spans", "metrics"} <= set(snap)
    # the whole bundle is JSON-able with the capture encoder
    json.loads(flight._jsonable_dumps(snap))


def test_capture_rate_limit_force_and_prune(tmp_path):
    """One bundle per min-interval (the breaker-flap guard), force
    skips the limit but never the size bound, and the pruner deletes
    oldest-first until the dir fits."""
    d = str(tmp_path / "inc")
    flight.configure_capture(incident_dir=d, max_bytes=1 << 30,
                             min_interval=3600.0)
    p1 = flight.maybe_capture("BRICK_DISCONNECTED")
    assert p1 and os.path.exists(p1)
    assert flight.maybe_capture("BRICK_DISCONNECTED") is None  # limited
    p2 = flight.maybe_capture("manual", force=True)
    assert p2 and p2 != p1
    body = json.load(open(p2))
    assert body["reason"] == "manual" and body["pid"] == os.getpid()
    snap = REGISTRY.snapshot()
    outcomes = {l["outcome"]: v for l, v in
                snap["gftpu_incident_captures_total"]["samples"]}
    assert outcomes["written"] >= 2 and outcomes["rate_limited"] >= 1
    # size bound: a tiny budget keeps only the newest bundle(s)
    sizes = {f: os.path.getsize(os.path.join(d, f))
             for f in _bundles(d)}
    flight.prune_dir(d, max(sizes.values()))
    left = _bundles(d)
    assert len(left) < len(sizes)
    assert os.path.basename(p2) in left  # newest survived
    flight.prune_dir(d, 0)
    assert _bundles(d) == []


def test_failure_event_auto_capture(tmp_path):
    """A failure-class gf_event auto-captures a local bundle; routine
    lifecycle events only land in the ring."""
    d = str(tmp_path / "inc")
    flight.configure_capture(incident_dir=d, max_bytes=1 << 30,
                             min_interval=0.0)
    from glusterfs_tpu.core.events import gf_event

    gf_event("VOLUME_START", volume="v0")  # routine: ring only
    assert _bundles(d) == [] if os.path.isdir(d) else True
    gf_event("BRICK_DISCONNECTED", brick="b0", volume="v0")
    names = _bundles(d)
    assert len(names) == 1 and "BRICK_DISCONNECTED" in names[0]
    bundle = json.load(open(os.path.join(d, names[0])))
    assert bundle["reason"] == "BRICK_DISCONNECTED"
    kinds = [r["kind"] for r in bundle["records"]]
    assert "event" in kinds
    evs = [r for r in bundle["records"] if r["kind"] == "event"]
    assert any(e["event"] == "VOLUME_START" for e in evs)


def test_error_fop_lands_span_tree_in_ring(tmp_path):
    """A failed root fop records an error_fop entry carrying its span
    tree — the flight ring keeps the evidence the log line drops."""
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
"""

    async def run():
        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        try:
            flight.RING.clear()
            with pytest.raises(Exception):
                await c.read_file("/definitely-not-there")
            errs = [r for r in flight.RING
                    if r["kind"] == "error_fop"]
            assert errs, list(flight.RING)
            assert errs[0]["trace"] and "posix" in errs[0]["tree"]
        finally:
            await c.unmount()

    asyncio.run(run())


def test_slow_fop_record_carries_tree(tmp_path):
    """Slow-fop span trees land in the flight ring (not just the log),
    with the {layer,op} identity the labeled counter uses."""
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume slow
    type debug/delay-gen
    option delay-duration 20000
    option delay-percentage 100
    option enable writev
    subvolumes posix
end-volume
volume stats
    type debug/io-stats
    option slow-fop-threshold 0.005
    subvolumes slow
end-volume
"""

    async def run():
        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        try:
            flight.RING.clear()
            await c.write_file("/f", b"x")
            slow = [r for r in flight.RING if r["kind"] == "slow_fop"]
            assert slow, list(flight.RING)
            rec = slow[0]
            assert rec["op"] == "writev" and rec["ms"] >= 5
            assert "writev" in rec["tree"] and rec["trace"]
        finally:
            tracing.SLOW_FOP_THRESHOLD = 0.0
            await c.unmount()

    asyncio.run(run())


# -- the brick's __incident__ RPC ------------------------------------------

def test_incident_rpc_returns_bundle(tmp_path):
    """__incident__ answers the process flight bundle over the
    authenticated wire, including the per-client accounting section."""

    async def run():
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        c, g = await _connect(server.port)
        try:
            await c.write_file("/x", b"data" * 512)
            bundle = await g.top._call("__incident__", (), {})
            assert bundle["pid"] == os.getpid()  # in-process brick
            assert any(s["op"] == "writev" for s in bundle["spans"])
            assert "metrics" in bundle
            rows = [r for r in bundle["clients"]["clients"]
                    if not r["mgmt"]]
            assert rows and rows[0]["bytes_rx"] >= 2048
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


# -- satellite pin: trace id survives FL_SHM and compound ------------------

def test_trace_survives_shm_bulk_lane(tmp_path):
    """The trailing wire trace element rides the control frame, so a
    payload moved through the PR-18 FL_SHM arena still joins the brick
    spans to the client's trace — pinned against the armed lane."""
    from glusterfs_tpu.rpc import shm

    if not shm.supported():
        pytest.skip("no memfd/SCM_RIGHTS on this platform")

    async def run():
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        c, g = await _connect(server.port)
        try:
            assert g.top._peer_shm  # the bulk lane IS armed
            tx0 = shm.shm_stats["tx_bytes"]
            tid = tracing.new_trace_id()
            tracing.arm(tid)
            tracing.SPANS.clear()
            await c.write_file("/big", b"z" * 100_000)
            # the payload rode the arena, not the socket
            assert shm.shm_stats["tx_bytes"] - tx0 >= 100_000
            spans = [s for s in tracing.SPANS if s[3] == "writev"]
            by_layer = {s[2]: s[0] for s in spans}
            # client graph AND brick graph spans carry the armed id:
            # the codec kept the trace element beside the blob lanes
            assert by_layer.get("c0") == tid, spans
            assert by_layer.get("posix") == tid, spans
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_trace_survives_compound_envelope(tmp_path):
    """A compound chain over the wire keeps ONE trace id: the envelope
    carries the trailing trace element and every brick-side link span
    joins the client's trace."""

    async def run():
        from glusterfs_tpu.core.layer import Loc
        from glusterfs_tpu.rpc import compound as cfop

        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        c, g = await _connect(server.port)
        try:
            tid = tracing.new_trace_id()
            tracing.arm(tid)
            tracing.SPANS.clear()
            replies = await g.top.compound([
                ("create", (Loc("/cf"), os.O_RDWR, 0o644), {}),
                ("writev", (cfop.FdRef(0), b"abc" * 200, 0), {}),
                ("flush", (cfop.FdRef(0),), {}),
                ("release", (cfop.FdRef(0),), {})])
            assert cfop.first_error(replies) is None
            spans = list(tracing.SPANS)
            assert spans and all(s[0] == tid for s in spans), spans
            # brick-side link spans (the posix layer lives across the
            # wire) joined the same trace
            posix_ops = {s[3] for s in spans if s[2] == "posix"}
            assert {"create", "writev"} <= posix_ops, spans
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


# -- gateway: trace header, error bodies, access log -----------------------

GW_BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume locks
    type features/locks
    subvolumes posix
end-volume
volume upcall
    type features/upcall
    subvolumes locks
end-volume
"""

GW_CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume upcall
end-volume
"""


async def _start_gateway(volfile_text, **kw):
    from glusterfs_tpu.gateway import ClientPool, ObjectGateway
    from glusterfs_tpu.api.glfs import wait_connected

    async def factory():
        g = Graph.construct(volfile_text)
        c = Client(g)
        await c.mount()
        await wait_connected(g)
        return c

    gw = ObjectGateway(ClientPool(factory, 2), volume="fltest", **kw)
    await gw.start()
    return gw


def test_gateway_trace_header_and_access_log(tmp_path):
    """Every gateway response names its request trace in X-Gftpu-Trace,
    and diagnostics.access-log emits one structured line per request
    (method, path, status, bytes, ms, trace)."""
    from glusterfs_tpu.gateway.minihttp import fetch as http

    async def run():
        server = await serve_brick(GW_BRICK.format(dir=tmp_path / "b"))
        gw = await _start_gateway(GW_CLIENT.format(port=server.port))
        flight.set_access_log(True)
        try:
            st, hd, _ = await http(gw.host, gw.port, "PUT", "/bkt")
            assert st == 200 and hd.get("x-gftpu-trace")
            st, hd, _ = await http(gw.host, gw.port, "GET", "/bkt/no")
            assert st == 404 and hd.get("x-gftpu-trace")
            lines = [m for m in gflog.recent_messages(80)
                     if '"method"' in m]
            assert len(lines) >= 2, gflog.recent_messages(20)
            row = json.loads(lines[-1][lines[-1].index("{"):])
            assert row["method"] == "GET" and row["status"] == 404
            assert row["path"] == "/bkt/no" and row["trace"]
            assert "ms" in row and "bytes" in row
            # the header and the log line name the SAME trace
            assert row["trace"] == hd["x-gftpu-trace"]
        finally:
            flight.set_access_log(False)
            await gw.stop()
            await server.stop()

    asyncio.run(run())


def test_gateway_shed_503_names_trace(tmp_path):
    """The admission-shed 503 carries the trace id in its JSON body
    (and the header), so a client-side report joins the flight ring."""
    from glusterfs_tpu.gateway.minihttp import fetch as http

    async def run():
        server = await serve_brick(GW_BRICK.format(dir=tmp_path / "b"))
        gw = await _start_gateway(GW_CLIENT.format(port=server.port),
                                  max_clients=0)
        try:
            st, hd, body = await http(gw.host, gw.port, "GET", "/")
            assert st == 503
            err = json.loads(body)
            assert err["error"] == "gateway saturated"
            assert err["trace"] and err["trace"] == \
                hd.get("x-gftpu-trace")
        finally:
            await gw.stop()
            await server.stop()

    asyncio.run(run())


# -- managed cluster: capture fan-out, list/show, partial ------------------

@pytest.mark.slow
def test_cluster_incident_capture_merge_and_show(tmp_path):
    """`volume incident capture` merges brick __incident__ answers,
    with at least one trace id whose spans come from TWO distinct
    brick processes (one replicated write = one client trace touching
    both bricks); list/show round-trip the bundle; a second capture
    after killing a brick names it offline."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="iv",
                             vtype="replicate",
                             bricks=[{"path": str(tmp_path / "b0")},
                                     {"path": str(tmp_path / "b1")}])
                await c.call("volume-start", name="iv")
            m = await mount_volume(d.host, d.port, "iv")
            try:
                await m.write_file("/traced", b"t" * 4096)
                assert await m.read_file("/traced") == b"t" * 4096
                out = await d.op_volume_incident_capture("iv")
                assert "partial" not in out
                assert {"iv-brick-0", "iv-brick-1"} <= \
                    set(out["processes"])
                bundle = json.load(open(out["bundle"]))
                procs = bundle["processes"]
                # pid-distinct processes (real brick subprocesses)
                pids = {procs[b]["pid"] for b in
                        ("iv-brick-0", "iv-brick-1")}
                assert len(pids) == 2
                # ≥1 trace id spanning BOTH brick processes: the
                # replicated write fanned one client trace out
                per_brick = [
                    {s["trace"] for s in procs[b]["spans"]}
                    for b in ("iv-brick-0", "iv-brick-1")]
                shared = per_brick[0] & per_brick[1]
                assert shared, per_brick
                # list/show round-trip
                rows = d.op_volume_incident_list("iv")["bundles"]
                assert [r["name"] for r in rows] == \
                    [os.path.basename(out["bundle"])]
                shown = d.op_volume_incident_show("iv")
                assert shown["volume"] == "iv"
                assert shown["processes"].keys() == procs.keys()
                shown2 = d.op_volume_incident_show(
                    "iv", bundle=rows[0]["name"])
                assert shown2 == shown
                # kill one brick: the next capture reports it offline
                # instead of silently shrinking the merge
                d.bricks["iv-brick-0"].kill()
                d.bricks["iv-brick-0"].wait(timeout=5)
                out2 = await d.op_volume_incident_capture("iv")
                b2 = json.load(open(out2["bundle"]))
                assert b2["processes"]["iv-brick-0"].get("offline"), b2
                assert "spans" in b2["processes"]["iv-brick-1"]
            finally:
                await m.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_incident_capture_partial_names_dead_peer(tmp_path):
    """A downed NODE degrades the capture to a NAMED partial — the
    volume-status contract, not a fake-complete cluster bundle."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

    async def run():
        d1 = Glusterd(str(tmp_path / "gd1"))
        await d1.start()
        d2 = Glusterd(str(tmp_path / "gd2"))
        await d2.start()
        try:
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("peer-probe", host=d2.host, port=d2.port)
                await c.call("volume-create", name="pv",
                             vtype="replicate",
                             bricks=[{"node": d1.uuid,
                                      "path": str(tmp_path / "n1b")},
                                     {"node": d2.uuid,
                                      "path": str(tmp_path / "n2b")}])
                await c.call("volume-start", name="pv")
            await d2.stop()
            out = await d1.op_volume_incident_capture("pv")
            assert out["partial"] and \
                out["partial"][0].startswith(d2.uuid[:8])
            bundle = json.load(open(out["bundle"]))
            assert bundle["partial"] == out["partial"]
            assert "pv-brick-0" in bundle["processes"]
            assert "pv-brick-1" not in bundle["processes"]
        finally:
            await d2.stop()
            await d1.stop()

    asyncio.run(run())
