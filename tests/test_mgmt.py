"""Management plane e2e: glusterd volume lifecycle (create/start/mount/
set/stop/delete), volgen output, CLI command surface, peers + txn —
the tests/basic/glusterd + volume.rc analog."""

import asyncio
import io
import sys

import pytest

from glusterfs_tpu.mgmt import volgen
from glusterfs_tpu.mgmt.cli import main as cli_main
from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient, MgmtError,
                                         mount_volume)


# -- volgen ----------------------------------------------------------------

def _volinfo(tmp_path, vtype="disperse", n=6, **kw):
    return {
        "name": "tv", "type": vtype, "redundancy": 2,
        "bricks": [{"index": i, "host": "127.0.0.1", "port": 4000 + i,
                    "path": str(tmp_path / f"b{i}"),
                    "name": f"tv-brick-{i}", "node": "x"}
                   for i in range(n)],
        "options": kw.get("options", {}),
        **{k: v for k, v in kw.items() if k != "options"},
    }


def test_volgen_brick_volfile(tmp_path):
    from glusterfs_tpu.core.graph import Graph

    vi = _volinfo(tmp_path)
    text = volgen.build_brick_volfile(vi, vi["bricks"][0])
    g = Graph.construct(text)
    assert g.top.type_name == "protocol/server"
    types = [l.type_name for l in g.by_name.values()]
    assert "storage/posix" in types and "features/locks" in types
    assert "debug/io-stats" in types


def test_volgen_client_volfile(tmp_path):
    from glusterfs_tpu.core.graph import Graph

    vi = _volinfo(tmp_path, options={"performance.io-cache": "on"})
    text = volgen.build_client_volfile(vi)
    g = Graph.construct(text)
    types = [l.type_name for l in g.by_name.values()]
    assert types.count("protocol/client") == 6
    assert "cluster/disperse" in types
    assert "performance/write-behind" in types  # default on
    assert "performance/io-cache" in types  # enabled by option
    assert "debug/io-stats" in types
    assert g.top.type_name == "meta"


def test_volgen_distributed_disperse(tmp_path):
    from glusterfs_tpu.core.graph import Graph

    vi = _volinfo(tmp_path, n=12)
    vi["group-size"] = 6
    text = volgen.build_client_volfile(vi)
    g = Graph.construct(text)
    types = [l.type_name for l in g.by_name.values()]
    assert types.count("cluster/disperse") == 2
    assert "cluster/distribute" in types


# -- glusterd lifecycle ----------------------------------------------------

@pytest.mark.slow
def test_glusterd_volume_lifecycle(tmp_path):
    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                bricks = [{"path": str(tmp_path / f"b{i}")}
                          for i in range(6)]
                await c.call("volume-create", name="vol1", vtype="disperse",
                             bricks=bricks, redundancy=2)
                info = await c.call("volume-info", name="vol1")
                assert info["vol1"]["status"] == "created"
                await c.call("volume-start", name="vol1")
                status = await c.call("volume-status", name="vol1")
                assert all(b["online"] for b in status["bricks"])
                # duplicate create fails
                with pytest.raises(Exception):
                    await c.call("volume-create", name="vol1",
                                 vtype="disperse", bricks=bricks,
                                 redundancy=2)
                # volume set flows into the client volfile
                await c.call("volume-set", name="vol1",
                             key="disperse.read-policy", value="first-k")
                spec = await c.call("getspec", name="vol1")
                assert "option read-policy first-k" in spec["volfile"]

            # mount and do I/O through the full managed stack
            client = await mount_volume(d.host, d.port, "vol1")
            ec = None
            for layer in client.graph.by_name.values():
                if layer.type_name == "cluster/disperse":
                    ec = layer
            for _ in range(150):
                if all(ch.connected for ch in ec.children):
                    break
                await asyncio.sleep(0.1)
            assert all(ch.connected for ch in ec.children)
            f = await client.create("/hello")
            await f.write(b"managed!", 0)
            await f.close()
            assert await client.read_file("/hello") == b"managed!"
            await client.unmount()

            # `volume top`: brick-side per-path counters over the RPC
            async with MgmtClient(d.host, d.port) as c:
                top = await c.call("volume-top", name="vol1",
                                   metric="write")
                rows = [r for rows_ in top["bricks"].values()
                        for r in rows_]
                assert any(r["path"] == "/hello" and r["writes"] >= 1
                           for r in rows), top
                # `volume profile`: BRICK-side cumulative fop stats
                prof = await c.call("volume-profile", name="vol1")
                assert len(prof["bricks"]) == 6
                assert all(p["fops"]["writev"]["count"] >= 1
                           for p in prof["bricks"].values()), prof

            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-stop", name="vol1")
                with pytest.raises(Exception):
                    await c.call("getspec", name="vol1")  # not started
                await c.call("volume-delete", name="vol1")
                info = await c.call("volume-info")
                assert info == {}
        finally:
            await d.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_glusterd_peers_and_txn(tmp_path):
    async def run():
        d1 = Glusterd(str(tmp_path / "n1"))
        d2 = Glusterd(str(tmp_path / "n2"))
        await d1.start()
        await d2.start()
        try:
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("peer-probe", host=d2.host, port=d2.port)
                st = await c.call("peer-status")
                assert len(st["peers"]) == 1
                # cluster txn replicates volinfo to the peer
                await c.call("volume-create", name="pv", vtype="replicate",
                             bricks=[{"path": str(tmp_path / "pb0")},
                                     {"path": str(tmp_path / "pb1")}],
                             redundancy=0)
            assert "pv" in d2.state["volumes"]
            # txn lock blocks concurrent ops
            d2._txn_holder = "someone-else"
            async with MgmtClient(d1.host, d1.port) as c:
                with pytest.raises(Exception):
                    await c.call("volume-create", name="pv2",
                                 vtype="replicate",
                                 bricks=[{"path": str(tmp_path / "x0")},
                                         {"path": str(tmp_path / "x1")}],
                                 redundancy=0)
            d2._txn_holder = None
        finally:
            await d1.stop()
            await d2.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_peer_volinfo_reconciliation(tmp_path):
    """A peer that was down during a config txn catches up on restart:
    peer-hello carries per-volume generation counters and the newer
    volinfo is imported (glusterd friend-sm volinfo import analog);
    a missed volume-delete travels as a tombstone instead of being
    resurrected by the returning peer."""
    async def run():
        d1 = Glusterd(str(tmp_path / "r1"))
        d2 = Glusterd(str(tmp_path / "r2"))
        await d1.start()
        await d2.start()
        try:
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("peer-probe", host=d2.host, port=d2.port)
                await c.call("volume-create", name="rv", vtype="replicate",
                             bricks=[{"path": str(tmp_path / "rb0")},
                                     {"path": str(tmp_path / "rb1")}],
                             redundancy=0)
            assert "rv" in d2.state["volumes"]
            gen0 = d1.state["volumes"]["rv"]["version"]
            # peer goes down; a volume-set commits without it
            await d2.stop()
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("volume-set", name="rv",
                             key="performance.io-cache", value="on")
            assert d1.state["volumes"]["rv"]["version"] > gen0
            assert d2.state["volumes"]["rv"].get("options", {}).get(
                "performance.io-cache") != "on"
            # peer restarts: the start-time re-handshake imports the
            # missed generation
            d2b = Glusterd(str(tmp_path / "r2"))
            await d2b.start()
            try:
                for _ in range(100):
                    if d2b.state["volumes"].get("rv", {}).get(
                            "options", {}).get(
                            "performance.io-cache") == "on":
                        break
                    await asyncio.sleep(0.05)
                vol = d2b.state["volumes"]["rv"]
                assert vol["options"]["performance.io-cache"] == "on"
                assert vol["version"] == \
                    d1.state["volumes"]["rv"]["version"]
            finally:
                await d2b.stop()
            # missed DELETE: tombstone wins over the stale volinfo
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("volume-delete", name="rv")
            d2c = Glusterd(str(tmp_path / "r2"))
            await d2c.start()
            try:
                for _ in range(100):
                    if "rv" not in d2c.state["volumes"]:
                        break
                    await asyncio.sleep(0.05)
                assert "rv" not in d2c.state["volumes"]
                assert "rv" in d2c.state.get("tombstones", {})
            finally:
                await d2c.stop()
        finally:
            await d1.stop()

    asyncio.run(run())


# -- CLI -------------------------------------------------------------------

@pytest.mark.slow
def test_cli_surface(tmp_path, capsys):
    async def start():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        return d

    loop = asyncio.new_event_loop()
    d = loop.run_until_complete(start())
    import threading

    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    try:
        server = f"--server=127.0.0.1:{d.port}"
        bricks = [f"localhost:{tmp_path}/cb{i}" for i in range(6)]
        assert cli_main([server, "volume", "create", "cvol",
                         "disperse", "2", *bricks]) == 0
        assert cli_main([server, "volume", "start", "cvol"]) == 0
        assert cli_main([server, "--json", "volume", "info", "cvol"]) == 0
        out = capsys.readouterr().out
        assert '"cvol"' in out and '"started"' in out
        assert cli_main([server, "volume", "set", "cvol",
                         "disperse.read-policy", "first-k"]) == 0
        assert cli_main([server, "volume", "status", "cvol"]) == 0
        out = capsys.readouterr().out
        assert "online" in out
        assert cli_main([server, "peer", "status"]) == 0
        assert cli_main([server, "volume", "stop", "cvol"]) == 0
        assert cli_main([server, "volume", "delete", "cvol"]) == 0
        # error path: unknown volume
        assert cli_main([server, "volume", "start", "nope"]) == 1
    finally:
        fut = asyncio.run_coroutine_threadsafe(d.stop(), loop)
        fut.result(timeout=10)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=5)

    asyncio_fix = None  # keep pytest happy
