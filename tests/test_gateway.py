"""S3-flavored HTTP object gateway (gateway/, ISSUE 6): dialect
round-trips, ≥64-client interleaved concurrency, ranged GET riding
SGBuf segments into the socket with no join, multipart PUT landing as
compound/write-behind chains (round-trip count pinned), admission
throttling with lifecycle events, fuse-stack↔gateway coherence, and
the registry families."""

import asyncio
import hashlib
import json
import os

import pytest

from glusterfs_tpu.api.glfs import Client, wait_connected
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import walk
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.gateway import ClientPool, ObjectGateway
# one request per connection (Connection: close); the SHARED client —
# bench's ladder and the ci.sh smoke drive the same code
from glusterfs_tpu.gateway.minihttp import fetch as http
from glusterfs_tpu.protocol.client import ClientLayer

BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume locks
    type features/locks
    subvolumes posix
end-volume
volume upcall
    type features/upcall
    subvolumes locks
end-volume
"""

CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume upcall
{copts}end-volume
{layers}"""


def client_volfile(port, copts="", layers=""):
    return CLIENT.format(port=port, copts=copts, layers=layers)


def pool_factory(volfile_text):
    async def factory():
        g = Graph.construct(volfile_text)
        c = Client(g)
        await c.mount()
        await wait_connected(g)
        return c
    return factory




async def start_gateway(volfile_text, pool=2, max_clients=512):
    gw = ObjectGateway(ClientPool(pool_factory(volfile_text), pool),
                       max_clients=max_clients, volume="gwtest")
    await gw.start()
    return gw


# -- dialect -----------------------------------------------------------


def test_object_dialect_roundtrip(tmp_path):
    """PUT/GET/HEAD/DELETE + bucket ops + ETag + conditional GET +
    ranges: the full surface against one brick."""
    async def run():
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        gw = await start_gateway(client_volfile(server.port))
        H, P = gw.host, gw.port
        payload = bytes(range(256)) * 64  # 16 KiB
        try:
            st, _, _ = await http(H, P, "PUT", "/bkt")
            assert st == 200
            st, _, _ = await http(H, P, "PUT", "/bkt")  # idempotent
            assert st == 200
            st, hd, _ = await http(H, P, "PUT", "/bkt/a/b/obj",
                                   body=payload)
            assert st == 200
            etag = hd["etag"]
            assert etag.strip('"') == hashlib.sha256(payload).hexdigest()
            # missing bucket refused, not implicitly created
            st, _, _ = await http(H, P, "PUT", "/nobkt/x", body=b"x")
            assert st == 404
            st, hd, data = await http(H, P, "GET", "/bkt/a/b/obj")
            assert st == 200 and data == payload and hd["etag"] == etag
            # conditional GET: matching ETag short-circuits the body
            st, _, data = await http(H, P, "GET", "/bkt/a/b/obj",
                                     headers={"if-none-match": etag})
            assert st == 304 and data == b""
            st, hd, data = await http(H, P, "HEAD", "/bkt/a/b/obj")
            assert st == 200 and data == b""
            assert int(hd["content-length"]) == len(payload)
            assert hd["etag"] == etag
            # ranged forms: mid-window, open end, suffix, past-EOF
            st, hd, data = await http(
                H, P, "GET", "/bkt/a/b/obj",
                headers={"range": "bytes=100-299"})
            assert st == 206 and data == payload[100:300]
            assert hd["content-range"] == \
                f"bytes 100-299/{len(payload)}"
            st, _, data = await http(H, P, "GET", "/bkt/a/b/obj",
                                     headers={"range": "bytes=16000-"})
            assert st == 206 and data == payload[16000:]
            st, _, data = await http(H, P, "GET", "/bkt/a/b/obj",
                                     headers={"range": "bytes=-100"})
            assert st == 206 and data == payload[-100:]
            st, hd, _ = await http(H, P, "GET", "/bkt/a/b/obj",
                                   headers={"range": "bytes=99999-"})
            assert st == 416
            assert hd["content-range"] == f"bytes */{len(payload)}"
            st, _, data = await http(H, P, "GET", "/")
            assert st == 200
            assert [b["name"] for b in json.loads(data)["buckets"]] \
                == ["bkt"]
            st, _, _ = await http(H, P, "DELETE", "/bkt")
            assert st == 409  # not empty
            st, _, _ = await http(H, P, "DELETE", "/bkt/a/b/obj")
            assert st == 204
            st, _, _ = await http(H, P, "GET", "/bkt/a/b/obj")
            assert st == 404
        finally:
            await gw.stop()
            await server.stop()

    asyncio.run(run())


def test_listing_delimiter_and_marker_paging(tmp_path):
    """GET /bucket?list: sorted keys, prefix filter, delimiter ->
    common_prefixes, marker paging walks the whole keyspace."""
    async def run():
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        gw = await start_gateway(client_volfile(server.port))
        H, P = gw.host, gw.port
        try:
            await http(H, P, "PUT", "/bkt")
            keys = ["zz", "a/1", "a/2", "a/sub/3", "b/4", "top"]
            for k in keys:
                st, _, _ = await http(H, P, "PUT", f"/bkt/{k}",
                                      body=k.encode())
                assert st == 200
            st, _, data = await http(H, P, "GET", "/bkt?list")
            out = json.loads(data)
            assert [k["key"] for k in out["keys"]] == sorted(keys)
            assert out["keys"][0]["size"] == len("a/1")
            # delimiter groups below the first separator
            st, _, data = await http(H, P, "GET",
                                     "/bkt?list&delimiter=/")
            out = json.loads(data)
            assert out["common_prefixes"] == ["a/", "b/"]
            assert [k["key"] for k in out["keys"]] == ["top", "zz"]
            # delimiter under a prefix directory
            st, _, data = await http(
                H, P, "GET", "/bkt?list&delimiter=/&prefix=a/")
            out = json.loads(data)
            assert out["common_prefixes"] == ["a/sub/"]
            assert [k["key"] for k in out["keys"]] == ["a/1", "a/2"]
            # marker paging, two per page
            got, marker = [], ""
            for _ in range(10):
                st, _, data = await http(
                    H, P, "GET",
                    f"/bkt?list&max-keys=2&marker={marker}")
                out = json.loads(data)
                got += [k["key"] for k in out["keys"]]
                if not out["truncated"]:
                    break
                marker = out["next_marker"]
            assert got == sorted(keys)
            # max-keys=0: empty NON-truncated page (a truncated answer
            # with no marker would loop paging clients forever)
            st, _, data = await http(H, P, "GET",
                                     "/bkt?list&max-keys=0")
            out = json.loads(data)
            assert out["keys"] == [] and not out["truncated"]
        finally:
            await gw.stop()
            await server.stop()

    asyncio.run(run())


# -- concurrency -------------------------------------------------------


def test_concurrent_64_clients_byte_identical(tmp_path):
    """≥64 interleaved PUT/GET HTTP clients multiplexed onto a small
    glfs pool: every round trip byte-identical, every request 200."""
    async def run():
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        gw = await start_gateway(
            client_volfile(server.port,
                           copts="    option compound-fops on\n"),
            pool=4)
        H, P = gw.host, gw.port
        try:
            st, _, _ = await http(H, P, "PUT", "/c")
            assert st == 200

            async def one(i: int):
                body = (bytes(range(256)) * 8)[i:] + bytes([i])
                st, hd, _ = await http(H, P, "PUT", f"/c/obj{i}",
                                       body=body)
                assert st == 200, (i, st)
                st, hd, data = await http(H, P, "GET", f"/c/obj{i}")
                assert st == 200, (i, st)
                assert data == body, f"client {i}: bytes differ"
                assert hd["etag"].strip('"') == \
                    hashlib.sha256(body).hexdigest()
                return len(data)

            sizes = await asyncio.gather(*(one(i) for i in range(64)))
            assert len(sizes) == 64
            assert gw.requests.get(("PUT", 200), 0) >= 65
            assert gw.requests.get(("GET", 200), 0) >= 64
        finally:
            await gw.stop()
            await server.stop()

    asyncio.run(run())


# -- zero-copy GET path ------------------------------------------------


def test_ranged_get_serves_sg_segments_without_join(tmp_path):
    """A ranged GET whose window spans io-cache pages is written to the
    socket as SGBuf segments via one writelines — the gateway never
    joins the payload (body_writes['sg'] counts the proof)."""
    async def run():
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        layers = """
volume ioc
    type performance/io-cache
    option page-size 4KB
    subvolumes c0
end-volume
"""
        gw = await start_gateway(client_volfile(server.port,
                                                layers=layers))
        H, P = gw.host, gw.port
        payload = bytes(range(256)) * 128  # 32 KiB = 8 pages
        try:
            await http(H, P, "PUT", "/z")
            st, _, _ = await http(H, P, "PUT", "/z/obj", body=payload)
            assert st == 200
            # warm the page cache on every pool member (round-robin)
            for _ in range(gw.pool.size):
                st, _, data = await http(H, P, "GET", "/z/obj")
                assert st == 200 and data == payload
            before = dict(gw.body_writes)
            segs_before = gw.sg_segments
            st, _, data = await http(
                H, P, "GET", "/z/obj",
                headers={"range": "bytes=1000-20999"})
            assert st == 206 and data == payload[1000:21000]
            assert gw.body_writes["sg"] == before["sg"] + 1, \
                "ranged GET did not ride the multi-segment lane"
            assert gw.sg_segments - segs_before >= 2
        finally:
            await gw.stop()
            await server.stop()

    asyncio.run(run())


# -- multipart PUT through write chains --------------------------------


def test_multipart_put_roundtrips_pinned(tmp_path):
    """A chunked streaming PUT lands through write-behind windows and
    compound chains: the wire cost is CONSTANT in the chunk count —
    create(temp) + fsetxattr + ONE window+flush chain + the atomic
    rename commit = 4 round trips for an 8-chunk body (release is
    local fd retirement, and the create iatt seeds the window so no
    per-write fstat fires), vs ≥12 unfused."""
    async def run():
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        chunks = [bytes([i]) * 8192 for i in range(8)]
        whole = b"".join(chunks)

        async def put_once(copts, layers, path):
            gw = await start_gateway(
                client_volfile(server.port, copts=copts,
                               layers=layers), pool=1)
            H, P = gw.host, gw.port
            try:
                await http(H, P, "PUT", "/m")
                cl = next(l for l in walk(
                    gw.pool.clients[0].graph.top)
                    if isinstance(l, ClientLayer))
                base = cl.rpc_roundtrips
                st, hd, _ = await http(H, P, "PUT", f"/m/{path}",
                                       chunks=chunks)
                assert st == 200
                rts = cl.rpc_roundtrips - base
                st, _, data = await http(H, P, "GET", f"/m/{path}")
                assert st == 200 and data == whole
                assert hd["etag"].strip('"') == \
                    hashlib.sha256(whole).hexdigest()
                return rts
            finally:
                await gw.stop()

        wb = """
volume wb
    type performance/write-behind
    option compound-fops on
    option window-size 1MB
    subvolumes c0
end-volume
"""
        try:
            fused = await put_once(
                "    option compound-fops on\n", wb, "obj")
            plain = await put_once("", "", "obj2")
            # create(1) + fsetxattr(1) + window-drain-with-flush
            # chain(1) + rename-commit(1); the 8 writevs never hit the
            # wire individually
            assert fused == 4, f"fused chunked PUT took {fused} RTs"
            # unfused: create + 8 writev + fsetxattr + flush + rename
            assert plain >= 12, f"unfused PUT took only {plain} RTs"
        finally:
            await server.stop()

    asyncio.run(run())


def test_encoded_slash_traversal_rejected(tmp_path):
    """%2F-encoded separators must not smuggle '..' segments past the
    component check — the cross-bucket escape is refused, not served."""
    async def run():
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        gw = await start_gateway(client_volfile(server.port))
        H, P = gw.host, gw.port
        try:
            await http(H, P, "PUT", "/tenantA")
            await http(H, P, "PUT", "/tenantB")
            st, _, _ = await http(H, P, "PUT", "/tenantB/secret",
                                  body=b"classified")
            assert st == 200
            evil = "/tenantA/x%2F..%2F..%2FtenantB%2Fsecret"
            for method in ("GET", "DELETE"):
                st, _, data = await http(H, P, method, evil)
                assert st == 400, f"{method} {evil} -> {st}"
            st, _, data = await http(H, P, "GET", "/tenantB/secret")
            assert st == 200 and data == b"classified"
            # plain '..' components stay rejected too
            st, _, _ = await http(H, P, "GET", "/tenantA/../tenantB/"
                                              "secret")
            assert st == 400
        finally:
            await gw.stop()
            await server.stop()

    asyncio.run(run())


def test_large_get_streams_windows(tmp_path):
    """A GET past the streaming threshold is served as bounded read
    windows (several socket writes), byte-identical — the whole object
    is never materialized as one frame."""
    async def run():
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        gw = await start_gateway(client_volfile(server.port))
        H, P = gw.host, gw.port
        import numpy as np
        payload = np.random.default_rng(3).integers(
            0, 256, 12 << 20, dtype=np.uint8).tobytes()  # 12 MiB
        try:
            await http(H, P, "PUT", "/big")
            st, _, _ = await http(H, P, "PUT", "/big/obj",
                                  body=payload)
            assert st == 200
            before = sum(gw.body_writes.values())
            st, hd, data = await http(H, P, "GET", "/big/obj")
            assert st == 200 and data == payload
            assert int(hd["content-length"]) == len(payload)
            # 12 MiB / 4 MiB window = 3 windowed writes
            assert sum(gw.body_writes.values()) - before >= 3
            # a large range streams too
            st, _, data = await http(
                H, P, "GET", "/big/obj",
                headers={"range": f"bytes=1000-{10 << 20}"})
            assert st == 206 and data == payload[1000:(10 << 20) + 1]
        finally:
            await gw.stop()
            await server.stop()

    asyncio.run(run())


def test_failed_small_compound_put_commits_nothing(tmp_path):
    """A mid-chain failure in the small-PUT compound (create ok,
    writev ENOSPC) must not leave a partial object at the key —
    chains skip, they don't roll back, so the gateway cleans up."""
    async def run():
        brick = BRICK.format(dir=tmp_path / "b") + """
volume egen
    type debug/error-gen
    option enable writev
    option failure 100
    option error-no ENOSPC
    subvolumes upcall
end-volume
"""
        server = await serve_brick(brick)
        gw = await start_gateway(client_volfile(
            server.port, copts="    option compound-fops on\n")
            .replace("remote-subvolume upcall", "remote-subvolume egen"),
            pool=1)
        H, P = gw.host, gw.port
        try:
            st, _, _ = await http(H, P, "PUT", "/e")
            assert st == 200
            st, _, _ = await http(H, P, "PUT", "/e/obj", body=b"data")
            assert st == 507, f"expected 507 ENOSPC, got {st}"
            st, _, _ = await http(H, P, "GET", "/e/obj")
            assert st == 404, \
                f"partial object committed by failed chain ({st})"
        finally:
            await gw.stop()
            await server.stop()

    asyncio.run(run())


def test_truncated_chunked_put_not_committed(tmp_path):
    """A chunked PUT whose client dies before the terminal 0-chunk
    must NOT be committed as a complete object with a valid ETag."""
    async def run():
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        gw = await start_gateway(client_volfile(server.port))
        H, P = gw.host, gw.port
        try:
            await http(H, P, "PUT", "/t")
            r, w = await asyncio.open_connection(H, P)
            chunk = b"x" * 8192
            w.write(b"PUT /t/torn HTTP/1.1\r\nhost: gw\r\n"
                    b"transfer-encoding: chunked\r\n\r\n"
                    + f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
            await w.drain()
            w.close()  # die before the 0-chunk
            await asyncio.sleep(0.2)
            st, _, _ = await http(H, P, "GET", "/t/torn")
            assert st == 404, \
                f"torn chunked upload was committed (GET -> {st})"
        finally:
            await gw.stop()
            await server.stop()

    asyncio.run(run())


# -- throttling + lifecycle events -------------------------------------


def test_admission_throttle_and_events(tmp_path):
    """Past max_clients live connections the gateway sheds load with
    503 + GATEWAY_CLIENT_THROTTLED; start/stop emit lifecycle events
    (datagrams observed on a stand-in eventsd socket)."""
    import socket

    from glusterfs_tpu.core import events as gf_events

    async def run():
        sink = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sink.bind(("127.0.0.1", 0))
        sink.setblocking(False)
        gf_events.configure(f"127.0.0.1:{sink.getsockname()[1]}")
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        gw = await start_gateway(client_volfile(server.port),
                                 pool=1, max_clients=2)
        H, P = gw.host, gw.port
        try:
            # park 2 connections mid-request (slow readers occupy the
            # admission slots), then the 3rd is refused
            holders = [await asyncio.open_connection(H, P)
                       for _ in range(2)]
            for _, w in holders:
                w.write(b"GET / HTTP/1.1\r\n")  # incomplete: stays open
                await w.drain()
            await asyncio.sleep(0.1)
            st, _, _ = await http(H, P, "GET", "/")
            assert st == 503
            assert gw.throttled == 1
            assert gw.events["GATEWAY_CLIENT_THROTTLED"] == 1
            for _, w in holders:
                w.close()
        finally:
            await gw.stop()
            await server.stop()
            gf_events.configure(None)
        assert gw.events["GATEWAY_START"] == 1
        assert gw.events["GATEWAY_STOP"] == 1
        seen = set()
        for _ in range(16):
            try:
                seen.add(json.loads(sink.recv(65536))["event"])
            except BlockingIOError:
                break
        sink.close()
        assert {"GATEWAY_START", "GATEWAY_CLIENT_THROTTLED",
                "GATEWAY_STOP"} <= seen, seen

    asyncio.run(run())


# -- registry families -------------------------------------------------


def test_gateway_metrics_families(tmp_path):
    """The request/latency/inflight/byte/throttle families are present
    on the unified registry and move with traffic."""
    async def run():
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        gw = await start_gateway(client_volfile(server.port))
        H, P = gw.host, gw.port
        try:
            await http(H, P, "PUT", "/f")
            await http(H, P, "PUT", "/f/k", body=b"x" * 4096)
            await http(H, P, "GET", "/f/k")
            snap = REGISTRY.snapshot()
            for fam in ("gftpu_gateway_requests_total",
                        "gftpu_gateway_inflight",
                        "gftpu_gateway_bytes_total",
                        "gftpu_gateway_request_seconds",
                        "gftpu_gateway_throttled_total",
                        "gftpu_gateway_body_writes_total",
                        "gftpu_gateway_events_total"):
                assert fam in snap, f"missing family {fam}"
            # sum across instances: earlier tests' gateways may not be
            # GC'd yet and the family scrapes every live one
            reqs: dict = {}
            for s in snap["gftpu_gateway_requests_total"]["samples"]:
                k = (s[0]["method"], s[0]["status"])
                reqs[k] = reqs.get(k, 0) + s[1]
            assert reqs[("PUT", "200")] >= 2
            assert reqs[("GET", "200")] >= 1
            assert any(s[0]["method"] == "GET" and
                       s[0]["quantile"] == "50" and s[1] > 0
                       for s in snap["gftpu_gateway_request_seconds"]
                       ["samples"])
            rx: dict = {}
            for s in snap["gftpu_gateway_bytes_total"]["samples"]:
                rx[s[0]["dir"]] = rx.get(s[0]["dir"], 0) + s[1]
            assert rx["rx"] >= 4096 and rx["tx"] >= 4096
        finally:
            await gw.stop()
            await server.stop()

    asyncio.run(run())


# -- managed lifecycle (glusterd spawner + volume gateway op) ----------


@pytest.mark.slow
def test_managed_gateway_lifecycle(tmp_path):
    """`volume gateway NAME start` spawns the daemon from the volume's
    gateway.* keys, status reports pid+port, HTTP works against the
    managed volume, stop retires it; `volume stop` also kills it."""
    async def run():
        from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="gv",
                             vtype="distribute",
                             bricks=[{"path": str(tmp_path / "b0")}])
                await c.call("volume-start", name="gv")
                await c.call("volume-set", name="gv",
                             key="gateway.pool-size", value="2")
                st = await c.call("volume-gateway", name="gv",
                                  action="start")
                assert st["ok"]
                port = 0
                for _ in range(600):  # daemon pays imports + mounts
                    st = await c.call("volume-gateway", name="gv",
                                      action="status")
                    if st["gateway"]["online"] and \
                            st["gateway"]["port"]:
                        port = st["gateway"]["port"]
                        break
                    await asyncio.sleep(0.1)
                assert port, f"gateway never came up: {st}"
                assert st["gateway"]["status"] == "started"
                assert st["gateway"]["pid"] > 0
                # real HTTP against the managed volume (retry while the
                # listener's pool finishes connecting)
                body = b"managed" * 512
                s = 0
                for _ in range(100):
                    try:
                        s, _, _ = await http("127.0.0.1", port, "PUT",
                                             "/bkt")
                        if s == 200:
                            break
                    except (ConnectionError, OSError):
                        pass
                    await asyncio.sleep(0.1)
                assert s == 200, "spawned gateway unreachable"
                s, hd, _ = await http("127.0.0.1", port, "PUT",
                                      "/bkt/k", body=body)
                assert s == 200
                s, _, data = await http("127.0.0.1", port, "GET",
                                        "/bkt/k")
                assert s == 200 and data == body
                st = await c.call("volume-gateway", name="gv",
                                  action="stop")
                for _ in range(100):
                    st = await c.call("volume-gateway", name="gv",
                                      action="status")
                    if not st["gateway"]["online"]:
                        break
                    await asyncio.sleep(0.1)
                assert not st["gateway"]["online"]
                assert st["gateway"]["status"] == "stopped"
        finally:
            await d.stop()

    asyncio.run(run())


# -- coherence against a fuse-stack client -----------------------------


def test_gateway_writes_invalidate_fuse_stack_client(tmp_path):
    """The two-front-door scenario: a fuse-side client stack (md-cache
    + io-cache over the wire) holds cached stat + pages; the gateway
    overwrites the object over HTTP; the brick's upcall push must
    revalidate BOTH caches — the next read sees the new bytes without
    any TTL expiring (timeouts here are an hour)."""
    async def run():
        server = await serve_brick(BRICK.format(dir=tmp_path / "b"))
        gw = await start_gateway(client_volfile(server.port), pool=1)
        H, P = gw.host, gw.port
        fuse_side = Graph.construct(client_volfile(server.port, layers="""
volume ioc
    type performance/io-cache
    option page-size 4KB
    option cache-timeout 3600
    subvolumes c0
end-volume
volume mdc
    type performance/md-cache
    option timeout 3600
    subvolumes ioc
end-volume
"""))
        fc = Client(fuse_side)
        await fc.mount()
        await wait_connected(fuse_side)
        v1 = b"a" * 8192
        v2 = b"b" * 16384
        try:
            await http(H, P, "PUT", "/coh")
            st, _, _ = await http(H, P, "PUT", "/coh/obj", body=v1)
            assert st == 200
            # fuse-side reads + stats: md-cache and io-cache now hold it
            assert await fc.read_file("/coh/obj") == v1
            assert (await fc.stat("/coh/obj")).size == len(v1)
            mdc = fuse_side.by_name["mdc"]
            inv0 = mdc.invalidations
            # gateway overwrites through its own graph
            st, _, _ = await http(H, P, "PUT", "/coh/obj", body=v2)
            assert st == 200
            for _ in range(100):  # the push, not a TTL
                if mdc.invalidations > inv0:
                    break
                await asyncio.sleep(0.05)
            assert mdc.invalidations > inv0, "no upcall reached md-cache"
            assert (await fc.stat("/coh/obj")).size == len(v2)
            assert await fc.read_file("/coh/obj") == v2, \
                "io-cache served stale pages after gateway overwrite"
            # and the reverse door: fuse-side write, gateway sees it
            await fc.write_file("/coh/obj2", b"from-fuse")
            st, _, data = await http(H, P, "GET", "/coh/obj2")
            assert st == 200 and data == b"from-fuse"
        finally:
            await fc.unmount()
            await gw.stop()
            await server.stop()

    asyncio.run(run())
