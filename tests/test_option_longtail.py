"""Behavior checks for the round-5 option long tail — a spot sample of
the new keys' actual consumption (the map integrity test already pins
every key to a declared option; these pin a few to real effects)."""

import asyncio
import errno
import os
import time

import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc


def _graph(tmp_path, layers: str) -> Graph:
    return Graph.construct(f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
{layers}
""")


def _client(tmp_path, layers: str) -> SyncClient:
    c = SyncClient(_graph(tmp_path, layers))
    c.mount()
    return c


# -- posix policy ------------------------------------------------------


def test_posix_create_masks_and_forced_mode(tmp_path):
    g = Graph.construct(f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
    option create-mask 0770
    option force-create-mode 0444
    option create-directory-mask 0750
end-volume
""")
    c = SyncClient(g)
    c.mount()
    try:
        f = c.create("/m", mode=0o777)
        f.close()
        mode = os.stat(tmp_path / "b" / "m").st_mode & 0o7777
        assert mode == (0o777 & 0o770) | 0o444
        c.mkdir("/d", 0o777)
        dmode = os.stat(tmp_path / "b" / "d").st_mode & 0o7777
        assert dmode == 0o750
    finally:
        c.close()


def test_posix_max_hardlinks(tmp_path):
    g = Graph.construct(f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
    option max-hardlinks 3
end-volume
""")
    c = SyncClient(g)
    c.mount()
    try:
        c.write_file("/h", b"x")
        c.link("/h", "/h1")
        # the gfid handle hardlink counts too: nlink is already 3
        with pytest.raises(FopError) as ei:
            c.link("/h", "/h2")
        assert ei.value.err == errno.EMLINK
    finally:
        c.close()


# -- locks: mandatory locking -----------------------------------------


def test_mandatory_locking_forced(tmp_path):
    c = _client(tmp_path, """
volume locks
    type features/locks
    option mandatory-locking forced
    subvolumes posix
end-volume
""")
    try:
        top = c.graph.top
        c.write_file("/f", b"0" * 1024)

        async def drive():
            f = await c._client.open("/f")
            await top.lk(f.fd, "setlkw",
                         {"type": "wr", "start": 0, "len": 512},
                         xdata={"lk-owner": b"ownerA"})
            # another owner's write inside the locked range: EAGAIN
            with pytest.raises(FopError) as ei:
                await top.writev(f.fd, b"x" * 10, 100,
                                 xdata={"lk-owner": b"ownerB"})
            assert ei.value.err == errno.EAGAIN
            # outside the range: allowed
            await top.writev(f.fd, b"y" * 10, 700,
                             xdata={"lk-owner": b"ownerB"})
            # the lock owner writes fine
            await top.writev(f.fd, b"z" * 10, 0,
                             xdata={"lk-owner": b"ownerA"})
            await top.lk(f.fd, "setlk",
                         {"type": "unlck", "start": 0, "len": 512},
                         xdata={"lk-owner": b"ownerA"})
            await f.close()

        c._run(drive())
    finally:
        c.close()


# -- worm retention ----------------------------------------------------


def test_worm_file_level_retention(tmp_path):
    c = _client(tmp_path, """
volume worm
    type features/worm
    option worm off
    option worm-file-level on
    option auto-commit-period 0.2
    option default-retention-period 0.3
    subvolumes posix
end-volume
""")
    try:
        c.write_file("/w", b"immutable")
        f = c.open("/w")
        f.write(b"still ok", 0)  # inside the commit window
        f.close()
        time.sleep(0.4)  # past auto-commit: file turns WORM
        f = c.open("/w")
        with pytest.raises(FopError) as ei:
            f.write(b"denied", 0)
        assert ei.value.err == errno.EROFS
        f.close()
        with pytest.raises(FopError):
            c.unlink("/w")  # retention still live
        time.sleep(0.5)  # retention expired: deletable (default on)
        c.unlink("/w")
    finally:
        c.close()


# -- trash -------------------------------------------------------------


def test_trash_dir_and_eliminate_path(tmp_path):
    c = _client(tmp_path, """
volume trash
    type features/trash
    option trash-dir .recycle
    option eliminate-path *.tmp
    subvolumes posix
end-volume
""")
    try:
        c.write_file("/keepme", b"data")
        c.unlink("/keepme")
        held = c.listdir("/.recycle")
        assert any(n.startswith("keepme_") for n in held)
        c.write_file("/scratch.tmp", b"data")
        c.unlink("/scratch.tmp")  # eliminated: really deleted
        held = c.listdir("/.recycle")
        assert not any("scratch" in n for n in held)
    finally:
        c.close()


# -- changelog ---------------------------------------------------------


def test_changelog_capture_del_path(tmp_path):
    for flag, expect_path in (("on", True), ("off", False)):
        base = tmp_path / flag
        c = _client(base, f"""
volume changelog
    type features/changelog
    option capture-del-path {flag}
    subvolumes posix
end-volume
""")
        try:
            c.write_file("/victim", b"x")
            c.unlink("/victim")
            import glob
            import json

            recs = []
            for seg in glob.glob(
                    str(base / "b" / ".glusterfs_tpu" / "changelog" /
                        "CHANGELOG.*")):
                with open(seg) as fh:
                    recs += [json.loads(l) for l in fh if l.strip()]
            dels = [r for r in recs if r["op"] == "unlink"]
            assert dels
            assert any(bool(r["path"]) == expect_path for r in dels)
        finally:
            c.close()


# -- volgen structural: pass-through + client-io-threads --------------


def test_passthrough_and_client_io_threads_volgen(tmp_path):
    from glusterfs_tpu.mgmt import volgen

    vi = {
        "name": "v", "type": "disperse", "redundancy": 2,
        "id": "x", "version": 1,
        "auth": {"username": "u", "password": "p",
                 "mgmt-username": "m", "mgmt-password": "mp"},
        "bricks": [{"name": f"v-brick-{i}", "path": str(tmp_path / str(i)),
                    "host": "127.0.0.1", "node": "n", "index": i}
                   for i in range(6)],
        "options": {"performance.io-cache-pass-through": "on",
                    "performance.client-io-threads": "on"},
    }
    text = volgen.build_client_volfile(vi)
    g = Graph.construct(text)
    types = [l.type_name for l in g.by_name.values()]
    assert "performance/io-cache" not in types  # passed through
    assert "performance/io-threads" in types   # client iot inserted
    assert "performance/write-behind" in types  # others untouched


# -- dht: rsync-hash munging ------------------------------------------


def test_dht_rsync_hash_regex_places_temp_with_final(tmp_path):
    from glusterfs_tpu.utils.volspec import brick_volumes

    chunks, tops = brick_volumes(tmp_path, 4)
    chunks.append("volume dht\n    type cluster/distribute\n"
                  "    subvolumes " + " ".join(tops) + "\nend-volume\n")
    g = Graph.construct("\n".join(chunks))
    c = SyncClient(g)
    c.mount()
    try:
        dht = g.top
        final = dht.hashed_idx("bigfile.bin")
        temp = dht.hashed_idx(".bigfile.bin.Xy12Zq")
        assert final == temp, "rsync temp name hashed elsewhere"
        dht.reconfigure({"rsync-hash-regex": "none"})
        # with munging off the names are just different strings (they
        # MAY collide; assert the munge path itself is off)
        assert dht._munge_name(".bigfile.bin.Xy12Zq") == \
            ".bigfile.bin.Xy12Zq"
    finally:
        c.close()


# -- afr: quorum-type none + read pin ---------------------------------


def test_afr_quorum_type_and_read_pin(tmp_path):
    from glusterfs_tpu.utils.volspec import brick_volumes

    chunks, tops = brick_volumes(tmp_path, 3)
    chunks.append("volume afr\n    type cluster/replicate\n"
                  "    option quorum-type none\n"
                  "    option choose-local off\n"
                  "    option read-subvolume-index 2\n"
                  "    subvolumes " + " ".join(tops) + "\nend-volume\n")
    g = Graph.construct("\n".join(chunks))
    c = SyncClient(g)
    c.mount()
    try:
        afr = g.top
        c.write_file("/q", b"data" * 256)
        before = afr.children[2].stats["readv"].count \
            if "readv" in afr.children[2].stats else 0
        assert c.read_file("/q") == b"data" * 256
        after = afr.children[2].stats["readv"].count
        assert after > before, "read-subvolume-index pin ignored"
        # quorum-type none: 1 of 3 children is enough to write
        afr.set_child_up(0, False)
        afr.set_child_up(1, False)
        c.write_file("/solo", b"one child")
    finally:
        c.close()


def test_mandatory_locking_fences_content_long_tail(tmp_path):
    """graft-lint GL01 regression: mandatory byte-range locks fence
    EVERY content mutator, not just readv/writev/xorv — truncate,
    discard, fallocate, zerofill and copy_file_range were slipping
    past another owner's lock."""
    c = _client(tmp_path, """
volume locks
    type features/locks
    option mandatory-locking forced
    subvolumes posix
end-volume
""")
    try:
        top = c.graph.top
        c.write_file("/f", b"0" * 1024)

        async def drive():
            f = await c._client.open("/f")
            await top.lk(f.fd, "setlkw",
                         {"type": "wr", "start": 0, "len": 512},
                         xdata={"lk-owner": b"ownerA"})
            b = {"lk-owner": b"ownerB"}
            for blocked in (
                    top.truncate(Loc("/f", gfid=f.fd.gfid), 100,
                                 xdata=b),
                    top.ftruncate(f.fd, 100, xdata=b),
                    top.discard(f.fd, 100, 10, xdata=b),
                    top.fallocate(f.fd, 0, 100, 10, xdata=b),
                    top.zerofill(f.fd, 100, 10, xdata=b),
                    top.copy_file_range(f.fd, 600, f.fd, 100, 10,
                                        xdata=b)):
                with pytest.raises(FopError) as ei:
                    await blocked
                assert ei.value.err == errno.EAGAIN
            # outside the locked range: allowed
            await top.discard(f.fd, 600, 10, xdata=b)
            # the holder itself passes
            await top.zerofill(f.fd, 0, 10,
                               xdata={"lk-owner": b"ownerA"})
            await top.lk(f.fd, "setlk",
                         {"type": "unlck", "start": 0, "len": 512},
                         xdata={"lk-owner": b"ownerA"})
            await f.close()

        c._run(drive())
    finally:
        c.close()


def test_worm_file_level_long_tail(tmp_path):
    """graft-lint GL01 regression: a RETAINED file's metadata and
    retention state are fenced — setattr is denied and
    trusted.worm.state cannot be stripped (de-WORMing by removexattr)."""
    c = _client(tmp_path, """
volume worm
    type features/worm
    option worm off
    option worm-file-level on
    option auto-commit-period 0.2
    option default-retention-period 30
    subvolumes posix
end-volume
""")
    try:
        c.write_file("/w", b"immutable")
        time.sleep(0.3)  # past auto-commit: file turns WORM
        top = c.graph.top

        async def drive():
            with pytest.raises(FopError) as ei:
                await top.setattr(Loc("/w"), {"mode": 0o777})
            assert ei.value.err == errno.EROFS
            with pytest.raises(FopError) as ei:
                await top.removexattr(Loc("/w"), "trusted.worm.state")
            assert ei.value.err == errno.EPERM

        c._run(drive())
    finally:
        c.close()
