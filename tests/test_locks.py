"""features/locks: inodelk domains, entrylk, POSIX lk, owner semantics,
blocking/non-blocking, disconnect cleanup (reference
xlators/features/locks tests + tests/basic/locks)."""

import asyncio

import pytest

from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import FdObj, Loc
from glusterfs_tpu.core.iatt import gfid_new

VOLFILE = """
volume posix
    type storage/posix
    option directory {d}
end-volume

volume locks
    type features/locks
    subvolumes posix
end-volume
"""


@pytest.fixture
def locks(tmp_path):
    g = Graph.construct(VOLFILE.format(d=tmp_path / "brick"))
    asyncio.run(g.activate())
    return g.by_name["locks"]


def test_inodelk_exclusion(locks):
    async def run():
        loc = Loc("/")
        a, b = {"lk-owner": b"A"}, {"lk-owner": b"B"}
        await locks.inodelk("d1", loc, "lock", "wr", 0, -1, a)
        # same owner re-locks fine (no self-conflict)
        await locks.inodelk("d1", loc, "lock", "wr", 0, -1, a)
        with pytest.raises(FopError):  # other owner, non-blocking
            await locks.inodelk("d1", loc, "lock-nb", "wr", 0, -1, b)
        # other domain is independent
        await locks.inodelk("d2", loc, "lock-nb", "wr", 0, -1, b)
        # blocking lock waits until unlock
        acquired = asyncio.Event()

        async def waiter():
            await locks.inodelk("d1", loc, "lock", "wr", 0, -1, b)
            acquired.set()

        t = asyncio.create_task(waiter())
        await asyncio.sleep(0.01)
        assert not acquired.is_set()
        await locks.inodelk("d1", loc, "unlock", "wr", 0, -1, a)
        await locks.inodelk("d1", loc, "unlock", "wr", 0, -1, a)
        await asyncio.wait_for(acquired.wait(), 2)
        await t

    asyncio.run(run())


def test_rd_locks_share(locks):
    async def run():
        loc = Loc("/")
        await locks.inodelk("d", loc, "lock-nb", "rd", 0, -1,
                            {"lk-owner": b"A"})
        await locks.inodelk("d", loc, "lock-nb", "rd", 0, -1,
                            {"lk-owner": b"B"})
        with pytest.raises(FopError):
            await locks.inodelk("d", loc, "lock-nb", "wr", 0, -1,
                                {"lk-owner": b"C"})

    asyncio.run(run())


def test_range_locks(locks):
    async def run():
        loc = Loc("/")
        await locks.inodelk("d", loc, "lock-nb", "wr", 0, 100,
                            {"lk-owner": b"A"})
        # non-overlapping range: fine
        await locks.inodelk("d", loc, "lock-nb", "wr", 100, 200,
                            {"lk-owner": b"B"})
        with pytest.raises(FopError):  # overlaps [0,100)
            await locks.inodelk("d", loc, "lock-nb", "wr", 50, 60,
                                {"lk-owner": b"C"})

    asyncio.run(run())


def test_entrylk(locks):
    async def run():
        loc = Loc("/")
        await locks.entrylk("d", loc, "file1", "lock-nb", "wr",
                            {"lk-owner": b"A"})
        with pytest.raises(FopError):
            await locks.entrylk("d", loc, "file1", "lock-nb", "wr",
                                {"lk-owner": b"B"})
        await locks.entrylk("d", loc, "file2", "lock-nb", "wr",
                            {"lk-owner": b"B"})

    asyncio.run(run())


def test_posix_lk(locks):
    async def run():
        fd = FdObj(gfid_new())
        a, b = {"lk-owner": b"A"}, {"lk-owner": b"B"}
        await locks.lk(fd, "setlk", {"type": "wr", "start": 0, "len": 10}, a)
        got = await locks.lk(fd, "getlk",
                             {"type": "wr", "start": 5, "len": 1}, b)
        assert got["type"] == "wr"  # conflicting lock reported
        with pytest.raises(FopError):
            await locks.lk(fd, "setlk",
                           {"type": "wr", "start": 0, "len": 10}, b)
        await locks.lk(fd, "setlk", {"type": "unlck"}, a)
        got = await locks.lk(fd, "getlk",
                             {"type": "wr", "start": 5, "len": 1}, b)
        assert got["type"] == "unlck"

    asyncio.run(run())


def test_release_client(locks):
    async def run():
        loc = Loc("/")
        await locks.inodelk("d", loc, "lock", "wr", 0, -1,
                            {"lk-owner": b"dead-client"})
        assert locks.release_client(b"dead-client") == 1
        await locks.inodelk("d", loc, "lock-nb", "wr", 0, -1,
                            {"lk-owner": b"B"})

    asyncio.run(run())


def test_getactivelk_and_dump(locks):
    async def run():
        loc = Loc("/")
        await locks.inodelk("d", loc, "lock", "wr", 0, -1,
                            {"lk-owner": b"A"})
        active = await locks.getactivelk(loc)
        assert len(active) == 1 and active[0]["domain"] == "d"
        assert locks.dump_private()["granted"] == 1

    asyncio.run(run())
