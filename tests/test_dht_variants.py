"""DHT variants: nufa (local-preferred create, nufa.c) and switch
(pattern-routed placement, switch.c), and their volgen wiring."""

import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.graph import Graph

N = 3


def volfile(base, dht_type: str, opts: dict) -> str:
    out = []
    for i in range(N):
        out.append(f"volume b{i}\n    type storage/posix\n"
                   f"    option directory {base}/brick{i}\nend-volume\n")
    subs = " ".join(f"b{i}" for i in range(N))
    body = "".join(f"    option {k} {v}\n" for k, v in opts.items())
    out.append(f"volume top\n    type {dht_type}\n{body}"
               f"    subvolumes {subs}\nend-volume\n")
    return "\n".join(out)


def _mounted(tmp_path, dht_type, opts):
    c = SyncClient(Graph.construct(volfile(tmp_path, dht_type, opts)))
    c.mount()
    return c


def test_nufa_creates_locally_with_linkto(tmp_path):
    c = _mounted(tmp_path, "cluster/nufa",
                 {"local-volume-name": "b1"})
    try:
        top = c.graph.top
        names = [f"f{i:02d}" for i in range(12)]
        for n in names:
            c.write_file(f"/{n}", n.encode())
        # data always lands on the local subvol
        for n in names:
            assert (tmp_path / "brick1" / n).read_bytes() == n.encode()
            hi = top.hashed_idx(n)
            if hi != 1:  # linkto pointer on the hashed brick
                assert (tmp_path / f"brick{hi}" / n).exists()
        # any client resolves the file through the pointer
        for n in names:
            assert c.read_file(f"/{n}") == n.encode()
        # unlink removes data AND pointer
        c.unlink(f"/{names[0]}")
        for i in range(N):
            assert not (tmp_path / f"brick{i}" / names[0]).exists()
    finally:
        c.close()


def test_nufa_unknown_local_volume_rejected(tmp_path):
    with pytest.raises(ValueError):
        _mounted(tmp_path, "cluster/nufa",
                 {"local-volume-name": "nope"})


def test_switch_pattern_routing(tmp_path):
    c = _mounted(tmp_path, "cluster/switch",
                 {"pattern-switch-case": "*.jpg:b0;*.log:b1|b2"})
    try:
        top = c.graph.top
        for n in ("a.jpg", "b.jpg", "zz.jpg"):
            c.write_file(f"/{n}", b"J")
            assert (tmp_path / "brick0" / n).exists()
        # multi-subvol rule spreads within the named set only
        logs = [f"w{i}.log" for i in range(8)]
        for n in logs:
            c.write_file(f"/{n}", b"L")
            on = [i for i in range(N)
                  if (tmp_path / f"brick{i}" / n).exists()
                  and (tmp_path / f"brick{i}" / n).stat().st_size]
            assert on and set(on) <= {1, 2}, (n, on)
        # unmatched names hash normally
        c.write_file("/plain", b"P")
        hi = top.hashed_idx("plain")
        assert (tmp_path / f"brick{hi}" / "plain").read_bytes() == b"P"
        # everything resolves through lookup
        for n in ("a.jpg", *logs, "plain"):
            assert c.read_file(f"/{n}")
    finally:
        c.close()


def test_switch_bad_rule_rejected(tmp_path):
    with pytest.raises(ValueError):
        _mounted(tmp_path, "cluster/switch",
                 {"pattern-switch-case": "*.jpg:zzz"})


def test_volgen_emits_variants(tmp_path):
    from glusterfs_tpu.mgmt import volgen

    vi = {
        "name": "nv", "type": "distribute", "redundancy": 0,
        "bricks": [{"index": i, "host": "h", "port": 1,
                    "path": str(tmp_path / f"b{i}"),
                    "name": f"nv-brick-{i}", "node": "x"}
                   for i in range(2)],
        "options": {"cluster.nufa": "on",
                    "cluster.nufa-local-volume-name": "nv-client-1"},
    }
    text = volgen.build_client_volfile(vi)
    assert "type cluster/nufa" in text
    assert "option local-volume-name nv-client-1" in text
    vi["options"] = {"cluster.switch-pattern": "*.jpg:nv-client-0"}
    text = volgen.build_client_volfile(vi)
    assert "type cluster/switch" in text
    assert "option pattern-switch-case *.jpg:nv-client-0" in text
    # variants apply to the distributed-X aggregate layer too
    vi2 = {
        "name": "dv", "type": "replicate", "redundancy": 0,
        "group-size": 2,
        "bricks": [{"index": i, "host": "h", "port": 1,
                    "path": str(tmp_path / f"db{i}"),
                    "name": f"dv-brick-{i}", "node": "x"}
                   for i in range(4)],
        "options": {"cluster.nufa": "on",
                    "cluster.nufa-local-volume-name":
                        "dv-replicate-0"},
    }
    text = volgen.build_client_volfile(vi2)
    assert "type cluster/nufa" in text
    assert "option local-volume-name dv-replicate-0" in text


def test_nufa_write_file_overwrite_does_not_fork(tmp_path):
    """write_file on an existing file through a DIFFERENT nufa-local
    client must overwrite, never fork: O_EXCL create resolves existence
    cluster-wide before targeting the scheduler's subvol (two data
    copies with an orphan — or a linkto stamped over real data — was
    the failure)."""
    import asyncio
    import os as _os

    from glusterfs_tpu.api.glfs import Client
    from glusterfs_tpu.core.graph import Graph

    def volfile(local):
        out = []
        for i in range(2):
            out.append(f"""
volume b{i}
    type storage/posix
    option directory {tmp_path}/nb{i}
end-volume
""")
        out.append(f"volume top\n    type cluster/nufa\n"
                   f"    option local-volume-name {local}\n"
                   f"    subvolumes b0 b1\nend-volume\n")
        return "\n".join(out)

    async def run():
        c1 = Client(Graph.construct(volfile("b1")))
        await c1.mount()
        await c1.write_file("/f00", b"old-contents")
        await c1.unmount()
        c0 = Client(Graph.construct(volfile("b0")))
        await c0.mount()
        await c0.write_file("/f00", b"new")
        assert await c0.read_file("/f00") == b"new"
        await c0.unmount()
        # exactly ONE data copy exists across the bricks (a linkto
        # pointer is fine; two data files is the fork)
        datas = []
        for i in range(2):
            p = tmp_path / f"nb{i}" / "f00"
            if p.exists() and p.stat().st_size > 0:
                datas.append((i, p.read_bytes()))
        assert datas == [(1, b"new")] or datas == [(0, b"new")], datas

    asyncio.run(run())
