"""GF(256) field + reference-codec tests.

Golden vectors in tests/golden/ec_golden.npz were produced by driving the
reference's portable C kernel (xlators/cluster/ec/src/ec-code-c.c via its
ec_code_c_prepare/linear/interleaved entry points, the exact call sequence of
ec-method.c:393-433) — byte equality here proves bit-exact parity with the
reference's on-wire fragment format.
"""

import pathlib

import numpy as np
import pytest

from glusterfs_tpu.ops import gf256

GOLDEN = np.load(pathlib.Path(__file__).parent / "golden" / "ec_golden.npz")
CONFIGS = [(2, 1), (4, 2), (4, 3), (8, 3), (8, 4), (16, 4)]


class TestField:
    def test_mul_identity_and_zero(self):
        a = np.arange(256)
        assert np.array_equal(gf256.gf_mul(a, 1), a)
        assert np.array_equal(gf256.gf_mul(a, 0), np.zeros(256))

    def test_mul_commutative_associative(self):
        rng = np.random.default_rng(0)
        a, b, c = rng.integers(0, 256, (3, 1000))
        assert np.array_equal(gf256.gf_mul(a, b), gf256.gf_mul(b, a))
        assert np.array_equal(
            gf256.gf_mul(gf256.gf_mul(a, b), c),
            gf256.gf_mul(a, gf256.gf_mul(b, c)),
        )

    def test_div_inverts_mul(self):
        rng = np.random.default_rng(1)
        a = rng.integers(0, 256, 1000)
        b = rng.integers(1, 256, 1000)
        assert np.array_equal(gf256.gf_div(gf256.gf_mul(a, b), b), a)

    def test_distributive_over_xor(self):
        rng = np.random.default_rng(2)
        a, b, c = rng.integers(0, 256, (3, 1000))
        lhs = gf256.gf_mul(a, b ^ c)
        rhs = gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)
        assert np.array_equal(lhs, rhs)

    def test_mul_2_matches_polynomial(self):
        # x*2 = x<<1 xor (0x11D if overflow)
        a = np.arange(256)
        expect = (a << 1) ^ np.where(a >= 128, 0x11D, 0)
        assert np.array_equal(gf256.gf_mul(a, 2), expect & 0xFF)

    def test_bitmatrix_is_mul(self):
        bm = gf256.bitmatrices()
        rng = np.random.default_rng(3)
        for c in [0, 1, 2, 3, 91, 128, 255]:
            x = rng.integers(0, 256, 64)
            xbits = ((x[:, None] >> np.arange(8)) & 1).astype(np.uint8)  # (64, q)
            ybits = (xbits @ bm[c].T) % 2  # (64, p)
            y = (ybits << np.arange(8)).sum(axis=1)
            assert np.array_equal(y, gf256.gf_mul(x, c)), f"c={c}"


class TestMatrices:
    @pytest.mark.parametrize("k,r", CONFIGS)
    def test_decode_inverts_encode_matrix(self, k, r):
        n = k + r
        a = gf256.encode_matrix(k, n)
        rows = list(range(r, r + k))  # an arbitrary surviving set
        b = gf256.decode_matrix(k, rows)
        prod = np.zeros((k, k), dtype=np.uint8)
        for i in range(k):
            for j in range(k):
                prod[i, j] = np.bitwise_xor.reduce(gf256.gf_mul(b[i], a[rows][:, j]))
        assert np.array_equal(prod, np.eye(k, dtype=np.uint8))

    def test_any_k_rows_invertible_4_2(self):
        import itertools

        k, n = 4, 6
        for rows in itertools.combinations(range(n), k):
            gf256.decode_matrix(k, list(rows))  # raises if singular


class TestGoldenParity:
    @pytest.mark.parametrize("k,r", CONFIGS)
    def test_encode_matches_reference_kernel(self, k, r):
        n = k + r
        data = GOLDEN[f"in_{k}_{r}"]
        frags = gf256.ref_encode(data, k, n)
        for i in range(n):
            expect = GOLDEN[f"frag_{k}_{r}_{i}"]
            assert np.array_equal(frags[i], expect), f"fragment {i} of {k}+{r}"

    @pytest.mark.parametrize("k,r", CONFIGS)
    @pytest.mark.parametrize("which", [0, 1])
    def test_decode_matches_reference_kernel(self, k, r, which):
        data = GOLDEN[f"in_{k}_{r}"]
        rows = GOLDEN[f"decmask_{k}_{r}_{which}"].astype(int)
        frags = np.stack([GOLDEN[f"frag_{k}_{r}_{i}"] for i in rows])
        out = gf256.ref_decode(frags, rows, k)
        assert np.array_equal(out, data)

    @pytest.mark.parametrize("k,r", [(4, 2), (8, 4)])
    def test_roundtrip_random_masks(self, k, r):
        import itertools

        n = k + r
        rng = np.random.default_rng(42)
        data = rng.integers(0, 256, k * gf256.CHUNK_SIZE * 2, dtype=np.uint8)
        frags = gf256.ref_encode(data, k, n)
        combos = list(itertools.combinations(range(n), k))
        for rows in combos[:: max(1, len(combos) // 8)]:
            out = gf256.ref_decode(frags[list(rows)], list(rows), k)
            assert np.array_equal(out, data), f"rows={rows}"
