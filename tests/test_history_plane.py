"""Time-series metrics history + SLO burn-rate alerting (ISSUE 20):
the delta-compressed ring and its carry-forward reconstruction,
counter-reset-aware increase()/rate(), percentile trajectories over
synthetic bucket rings (monotone counters, respawn resets, sampler
gaps), burn-rate fast/slow edge cases, rule-grammar validation, live
reconfigure of every v19 key, the brick daemon's /metrics/history.json
endpoint, and the managed end-to-end storm: error-gen trips an
error-ratio rule -> ALERT_RAISED over real UDP eventsd -> an
auto-captured incident bundle whose history section shows the ramp ->
CLEARED once the storm stops."""

import asyncio
import json
import os

import pytest

from glusterfs_tpu.core import history, slo
from glusterfs_tpu.core.history import (HistoryRing, increase,
                                        merge_series,
                                        percentile_trajectory, rate)
from glusterfs_tpu.core.metrics import LogHistogram
from glusterfs_tpu.core.slo import SloEngine, parse_rules


def snap(families: dict[str, tuple[str, list]]) -> dict:
    """Synthetic REGISTRY.snapshot() shape:
    ``{family: (type, [(labels, value), ...])}`` -> snapshot dict."""
    return {name: {"type": mtype, "help": "", "samples": samples}
            for name, (mtype, samples) in families.items()}


def counter_snap(errors: float, total: float) -> dict:
    return snap({
        "gftpu_fop_errors_total": ("counter", [({"op": "readv"}, errors)]),
        "gftpu_fops_total": ("counter", [({"op": "readv"}, total)]),
    })


# -- ring storage + reconstruction -----------------------------------------

def test_ring_delta_compression_and_carry_forward():
    """Only changed keys are stored per tick; series() rebuilds a
    DENSE series by carrying unchanged values forward."""
    r = HistoryRing(interval=1.0, retention=1000.0)
    r.sample(snap({"a_total": ("counter", [({}, 1)]),
                   "g": ("gauge", [({}, 5)])}), now=100.0)
    r.sample(snap({"a_total": ("counter", [({}, 2)]),
                   "g": ("gauge", [({}, 5)])}), now=101.0)  # g unchanged
    r.sample(snap({"a_total": ("counter", [({}, 2)]),
                   "g": ("gauge", [({}, 7)])}), now=102.0)
    # stored deltas: tick 2 carries only a_total, tick 3 only g
    stored = list(r._samples)
    assert set(stored[1][1]) == {"a_total"}
    assert set(stored[2][1]) == {"g"}
    s = r.series(now=102.0)
    assert s["g"] == [[100.0, 5], [101.0, 5], [102.0, 7]]
    assert s["a_total"] == [[100.0, 1], [101.0, 2], [102.0, 2]]
    d = r.dump()
    assert d["samples"] == 3
    assert (d["first_ts"], d["last_ts"]) == (100.0, 102.0)
    assert "a_total" in d["rates"]  # counters get derived rates
    assert "g" not in d["rates"]    # gauges don't


def test_ring_retention_and_windowed_series():
    r = HistoryRing(interval=1.0, retention=10.0)
    import time as _t
    now = _t.time()
    for i in range(30):
        r.sample(snap({"x": ("gauge", [({}, i)])}), now=now - 30 + i)
    assert len(r) <= 11  # retention trimmed the old ticks
    recent = r.series(window=5.0, now=now)
    assert all(ts >= now - 5.0 for ts, _ in recent["x"])
    # non-numeric samples never enter the ring
    r.sample(snap({"s": ("gauge", [({}, "stately")]),
                   "x": ("gauge", [({}, 99)])}), now=now)
    assert "s" not in r.series(now=now)


# -- counter math ----------------------------------------------------------

def test_increase_monotone_reset_and_window():
    mono = [[0.0, 10], [1.0, 15], [2.0, 25]]
    assert increase(mono) == 15
    # counter reset (daemon respawn): the drop contributes the
    # post-reset ABSOLUTE value, not a negative delta
    reset = [[0.0, 100], [1.0, 110], [2.0, 4], [3.0, 9]]
    assert increase(reset) == 10 + 4 + 5
    # window edges: the point before t0 is the carried baseline, so
    # the delta landing ON the window's first in-range point counts
    assert increase(mono, t0=1.0, t1=2.0) == 15
    assert increase(mono, t0=1.5) == 10
    assert increase(mono, t0=0.5) == 15


def test_rate_handles_gaps_and_sparse_windows():
    pts = [[0.0, 0], [10.0, 100]]
    assert rate(pts) == pytest.approx(10.0)
    # window shorter than the gap -> one point -> 0.0, never a div/0
    assert rate(pts, window=5.0) == 0.0
    assert rate([], window=5.0) == 0.0
    assert rate([[3.0, 7]]) == 0.0


def test_percentile_trajectory_monotone_reset_and_gap():
    """p99 per tick from windowed bucket-counter increments: monotone
    growth tracks the hot bucket, a counter reset (respawn) still
    yields sane values, and a tick with an empty window (sampler gap /
    no traffic) reports an explicit 0.0 point."""
    # buckets 4 (~16us) and 10 (~1ms): all early increments land in 4,
    # later ones in 10 -> the p99 trajectory climbs bucket bounds
    bs = {4: [[0.0, 0], [1.0, 100], [2.0, 100]],
          10: [[0.0, 0], [1.0, 1], [2.0, 200]]}
    traj = percentile_trajectory(bs, 99.0, window=1.5)
    by_ts = dict((ts, v) for ts, v in traj)
    assert by_ts[1.0] == pytest.approx(LogHistogram.bound(4))
    assert by_ts[2.0] == pytest.approx(LogHistogram.bound(10))
    # p50 at t=2: 100 in bucket 4 vs 199 in bucket 10 within window
    p50 = dict((ts, v) for ts, v in
               percentile_trajectory(bs, 50.0, window=1.5))
    assert p50[2.0] == pytest.approx(LogHistogram.bound(10))
    # counter reset mid-series: the post-reset absolute value counts
    bs_reset = {4: [[0.0, 50], [1.0, 60], [2.0, 3]]}
    t = dict((ts, v) for ts, v in
             percentile_trajectory(bs_reset, 99.0, window=1.5))
    assert t[2.0] == pytest.approx(LogHistogram.bound(4))
    # gap: no increments inside the window -> explicit 0.0, never
    # interpolated away
    bs_gap = {4: [[0.0, 0], [1.0, 10], [50.0, 10]]}
    t = dict((ts, v) for ts, v in
             percentile_trajectory(bs_gap, 99.0, window=2.0))
    assert t[50.0] == 0.0


def test_merge_series_sums_counters_maxes_quantiles():
    """The gateway supervisor's per-worker merge: union time grid,
    carry-forward per worker, counters/gauges SUM, quantile-labeled
    gauges take the MAX."""
    d1 = {"series": {"c_total": [[1.0, 10], [3.0, 20]],
                     'lat{quantile="p99"}': [[1.0, 0.5]]}}
    d2 = {"series": {"c_total": [[2.0, 100]],
                     'lat{quantile="p99"}': [[2.0, 0.2]]}}
    m = merge_series([d1, d2])
    assert m["workers"] == 2
    # t=1: only worker1 (10); t=2: 10 carried + 100; t=3: 20 + 100
    assert m["series"]["c_total"] == [[1.0, 10], [2.0, 110], [3.0, 120]]
    q = dict((ts, v) for ts, v in m["series"]['lat{quantile="p99"}'])
    assert q[2.0] == 0.5  # max, not 0.7 (summing a p99 is meaningless)


# -- SLO engine ------------------------------------------------------------

def _fed_engine(feeds: list[tuple[float, float, float]]) -> SloEngine:
    """Engine over a private ring fed (now, errors, total) ticks."""
    ring = HistoryRing(interval=1.0, retention=100000.0)
    for now, errs, total in feeds:
        ring.sample(counter_snap(errs, total), now=now)
    return SloEngine(ring=ring)


def test_error_ratio_rule_raises_and_clears_on_edges():
    eng = _fed_engine([(t, 0.0, 10.0 * t) for t in range(1, 11)])
    eng.set_rules([{"name": "errs", "kind": "error-ratio",
                    "errors": "gftpu_fop_errors_total",
                    "total": "gftpu_fops_total",
                    "target": 0.05, "window": 5}])
    assert eng.evaluate(now=10.0) == {}
    # the storm: errors ramp to 50% of traffic
    for t in range(11, 16):
        eng.ring.sample(counter_snap(5.0 * (t - 10), 10.0 * t), now=t)
    active = eng.evaluate(now=15.0)
    assert "errs" in active and active["errs"]["observed"] > 0.05
    # a second breaching evaluation is NOT a second transition
    eng.evaluate(now=15.5)
    assert [e["edge"] for e in eng.transitions] == ["RAISED"]
    # recovery: healthy traffic pushes the errors out of the window
    for t in range(16, 26):
        eng.ring.sample(counter_snap(25.0, 10.0 * t), now=t)
    assert eng.evaluate(now=25.0) == {}
    assert [e["edge"] for e in eng.transitions] == ["RAISED", "CLEARED"]
    assert eng.transitions[-1]["duration"] > 0


def test_error_ratio_zero_traffic_never_breaches():
    eng = _fed_engine([(1.0, 7.0, 100.0), (2.0, 7.0, 100.0),
                       (50.0, 7.0, 100.0)])
    eng.set_rules([{"name": "idle", "kind": "error-ratio",
                    "errors": "gftpu_fop_errors_total",
                    "total": "gftpu_fops_total",
                    "target": 0.01, "window": 10}])
    # no increase in total inside the window: no budget burned
    assert eng.evaluate(now=50.0) == {}


def test_burn_rate_slow_window_vetoes_a_blip():
    """A fast-window spike with a healthy slow window must NOT raise —
    the multiwindow contract — while sustained burn over BOTH raises,
    and recovery in the fast window alone clears."""
    rule = {"name": "burn", "kind": "burn-rate",
            "errors": "gftpu_fop_errors_total",
            "total": "gftpu_fops_total",
            "slo": 0.99, "fast": 10, "slow": 100, "factor": 5}
    # 95s of clean heavy traffic, then a 5s blip at 10% errors
    eng2 = _fed_engine([(float(t), 0.0, 100.0 * t)
                        for t in range(1, 96)]
                       + [(float(t), 10.0 * (t - 95), 100.0 * t)
                          for t in range(96, 101)])
    eng2.set_rules([rule])
    # fast: 50 errs / 500 total = 10% -> burn 10 >= 5;
    # slow: 50 / 10000 = 0.5% -> burn 0.5 < 5 -> VETO
    assert eng2.evaluate(now=100.0) == {}
    # sustained: the same ratio over the whole slow window raises
    eng3 = _fed_engine([(float(t), 2.0 * t, 10.0 * t)
                        for t in range(1, 101)])
    eng3.set_rules([rule])
    active = eng3.evaluate(now=100.0)
    assert "burn" in active  # both windows burn at 20/1 percent
    assert active["burn"]["observed"] >= 5  # fast-window burn rate
    # recovery: clean fast window clears even while slow still burns
    for t in range(101, 121):
        eng3.ring.sample(counter_snap(200.0, 10.0 * t), now=float(t))
    assert eng3.evaluate(now=120.0) == {}
    assert [e["edge"] for e in eng3.transitions] == ["RAISED", "CLEARED"]


def test_burn_rate_zero_traffic_windows_never_breach():
    eng = _fed_engine([(1.0, 0.0, 0.0), (2.0, 0.0, 0.0)])
    eng.set_rules([{"name": "b", "kind": "burn-rate",
                    "errors": "gftpu_fop_errors_total",
                    "total": "gftpu_fops_total", "slo": 0.999}])
    assert eng.evaluate(now=2.0) == {}


def test_latency_threshold_and_absence_rules():
    ring = HistoryRing(interval=1.0, retention=100000.0)
    ring.sample(snap({"gftpu_gateway_request_seconds":
                      ("gauge", [({"quantile": "p99"}, 0.01)])}),
                now=1.0)
    eng = SloEngine(ring=ring)
    eng.set_rules([
        {"name": "lat", "kind": "latency-threshold",
         "metric": "gftpu_gateway_request_seconds",
         "labels": {"quantile": "p99"}, "target": 0.5, "window": 30},
        {"name": "gone", "kind": "absence",
         "metric": "app_heartbeat", "window": 10},
    ])
    # absence: app_heartbeat never produced a point, so once the
    # window has elapsed the rule breaches (newest defaults to 0.0)
    active = eng.evaluate(now=15.0)
    assert "gone" in active and "lat" not in active
    # latency: a p99 spike over target raises; the fresh heartbeat
    # clears the absence alert on the same pass
    ring.sample(snap({"gftpu_gateway_request_seconds":
                      ("gauge", [({"quantile": "p99"}, 0.9)]),
                      "app_heartbeat": ("gauge", [({}, 1)])}),
                now=16.0)
    active = eng.evaluate(now=17.0)
    assert "lat" in active and "gone" not in active
    # far future: every point is stale -> latency goes silent (no
    # observation is not a breach) while absence flips back on
    assert eng.evaluate(now=500.0).keys() == {"gone"}


def test_rule_removal_clears_its_active_alert():
    eng = _fed_engine([(t, 5.0 * t, 10.0 * t) for t in range(1, 11)])
    rule = {"name": "r", "kind": "error-ratio",
            "errors": "gftpu_fop_errors_total",
            "total": "gftpu_fops_total", "target": 0.1, "window": 5}
    eng.set_rules([rule])
    assert "r" in eng.evaluate(now=10.0)
    eng.set_rules([])  # volume reset / rules removed
    assert eng.active == {}
    assert eng.transitions[-1]["reason"] == "rule-removed"


def test_parse_rules_grammar_and_validation():
    ok, errs = parse_rules("")
    assert (ok, errs) == ([], [])
    _, errs = parse_rules("{not json")
    assert errs and "JSON" in errs[0]
    _, errs = parse_rules('{"name": "x"}')
    assert errs == ["slo-rules must be a JSON array of rule objects"]
    rules, errs = parse_rules(json.dumps([
        {"name": "good", "kind": "absence", "metric": "m"},
        {"name": "good", "kind": "absence", "metric": "m"},  # dup
        {"name": "nokind", "kind": "windmill", "metric": "m"},
        {"name": "missing", "kind": "error-ratio"},
        {"name": "badslo", "kind": "burn-rate", "errors": "e",
         "total": "t", "slo": 2.0},
        {"name": "nan", "kind": "absence", "metric": "m",
         "window": "soon"},
    ]))
    assert [r["name"] for r in rules] == ["good"]
    assert len(errs) == 5


# -- live v19 reconfigure ---------------------------------------------------

def test_iostats_reconfigure_every_v19_key(tmp_path):
    """Every op-version-19 key applies LIVE through io-stats
    reconfigure: the ring retunes interval/retention in place (keeping
    its samples) and the SLO engine swaps rule sets."""
    from glusterfs_tpu.api.glfs import Client
    from glusterfs_tpu.core.graph import Graph

    saved = (history.HISTORY.interval, history.HISTORY.retention,
             slo.ENGINE.rules, slo.ENGINE.rule_errors)
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume stats
    type debug/io-stats
    option history-interval 2
    option history-retention 77
    subvolumes posix
end-volume
"""
    async def run():
        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        try:
            st = g.by_name["stats"]
            assert history.HISTORY.interval == 2.0
            assert history.HISTORY.retention == 77.0
            history.HISTORY.sample(counter_snap(0, 1))
            kept = len(history.HISTORY)
            rules = json.dumps([{"name": "live", "kind": "absence",
                                 "metric": "app_heartbeat_gone"}])
            st.reconfigure({"history-interval": "5",
                            "history-retention": "123",
                            "slo-rules": rules})
            assert history.HISTORY.interval == 5.0
            assert history.HISTORY.retention == 123.0
            assert len(history.HISTORY) >= kept  # retune kept samples
            assert [r["name"] for r in slo.ENGINE.rules] == ["live"]
            # a bad rule set loses itself, never the daemon
            st.reconfigure({"slo-rules": "{broken"})
            assert slo.ENGINE.rules == []
            assert slo.ENGINE.rule_errors
        finally:
            await c.unmount()

    try:
        asyncio.run(run())
    finally:
        history.HISTORY.configure(interval=saved[0], retention=saved[1])
        slo.ENGINE.set_rules(saved[2], saved[3])


# -- the daemon endpoint ----------------------------------------------------

@pytest.mark.slow
def test_brick_history_endpoint_serves_windows(tmp_path):
    """A SPAWNED brick daemon samples its own registry and serves
    /metrics/history.json with >=2 sampler windows of real series,
    derived counter rates, and the build-info identity row."""
    import subprocess
    import sys
    import time as _t

    vf = tmp_path / "b.vol"
    vf.write_text(f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume locks
    type features/locks
    subvolumes posix
end-volume
volume stats
    type debug/io-stats
    option history-interval 0.2
    subvolumes locks
end-volume
""")
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        mport = s.getsockname()[1]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    portfile = tmp_path / "b.port"
    proc = subprocess.Popen(
        [sys.executable, "-m", "glusterfs_tpu.daemon",
         "--volfile", str(vf), "--listen", "0",
         "--portfile", str(portfile), "--metrics-port", str(mport)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)

    async def get_json(path):
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       mport)
        writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
        await writer.drain()
        body = await reader.read()
        writer.close()
        assert b"200" in body.split(b"\r\n", 1)[0], body[:200]
        return json.loads(body.split(b"\r\n\r\n", 1)[1])

    async def run():
        deadline = _t.time() + 30
        while not portfile.exists():
            assert proc.poll() is None, proc.stderr.read().decode()[-2000:]
            assert _t.time() < deadline, "brick never reported a port"
            await asyncio.sleep(0.05)
        # the sampler is armed by the daemon: wait out >=2 windows
        doc = None
        deadline = _t.time() + 30
        while _t.time() < deadline:
            doc = await get_json("/metrics/history.json")
            if doc["samples"] >= 3 and \
                    doc["last_ts"] - doc["first_ts"] >= 2 * 0.2:
                break
            await asyncio.sleep(0.2)
        assert doc["interval"] == pytest.approx(0.2)
        assert doc["samples"] >= 3, doc["samples"]
        assert doc["last_ts"] - doc["first_ts"] >= 2 * 0.2
        # real sampled series from the live registry, with the ticker
        # counter ramping and a derived rate
        tick_keys = [k for k in doc["series"]
                     if k.startswith("gftpu_history_samples_total")
                     and 'outcome="sampled"' in k]
        assert tick_keys, sorted(doc["series"])[:10]
        pts = doc["series"][tick_keys[0]]
        assert len(pts) >= 2 and pts[-1][1] > pts[0][1]
        assert doc["rates"].get(tick_keys[0], 0) > 0
        # build-info identity rides the same registry (satellite 1)
        snap_doc = await get_json("/metrics.json")
        bi = snap_doc["gftpu_build_info"]["samples"]
        assert bi and bi[0][0]["role"] == "brick"
        assert bi[0][0]["op_version"] == "19"
        # the alerts surface answers (no rules -> empty shape)
        alerts = await get_json("/alerts.json")
        assert alerts["active"] == [] and alerts["rules"] == []

    try:
        asyncio.run(run())
    finally:
        proc.terminate()
        proc.wait(timeout=10)


# -- the managed end-to-end storm ------------------------------------------

@pytest.mark.slow
def test_alert_storm_end_to_end(tmp_path):
    """The acceptance chain: an injected error-gen storm on a managed
    volume trips an error-ratio rule inside a brick daemon ->
    ALERT_RAISED arrives over real UDP eventsd -> the brick
    auto-captures an incident bundle whose history section shows the
    error-rate ramp and whose alerts section names the rule -> `volume
    alerts` lists the RAISED alert cluster-wide (and `volume status`
    grows an alerts block) -> the alert CLEARS after the storm and the
    CLEARED edge lands in `volume alerts history`."""
    from glusterfs_tpu.core import events as gf_events
    from glusterfs_tpu.core.fops import FopError
    from glusterfs_tpu.mgmt.eventsd import EventsDaemon
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    # the op label scopes the ratio to readv: benign errno traffic on
    # other ops (ENODATA getxattrs ride every write) must not pollute
    # the signal, and a quiet readv plane (total increase 0) must read
    # as "no observation", not as breach or clear noise
    rules = json.dumps([{
        "name": "readv-errors", "kind": "error-ratio",
        "errors": "gftpu_fop_errors_total",
        "total": "gftpu_fops_total",
        "labels": {"op": "readv"},
        "target": 0.05, "window": 4,
    }], separators=(",", ":"))
    inc_dir = str(tmp_path / "incidents")

    async def run():
        ev = EventsDaemon()
        udp, _ctl = await ev.start()
        os.environ["GFTPU_EVENTSD"] = f"127.0.0.1:{udp}"
        gf_events.configure(f"127.0.0.1:{udp}")
        d = Glusterd(str(tmp_path / "gd"))
        try:
            await d.start()
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="av",
                             vtype="replicate",
                             bricks=[{"path": str(tmp_path / "b0")},
                                     {"path": str(tmp_path / "b1")}])
                await c.call("volume-start", name="av")
                for k, v in (("diagnostics.history-interval", "0.25"),
                             ("diagnostics.slo-rules", rules),
                             ("diagnostics.incident-dir", inc_dir),
                             ("diagnostics.incident-min-interval", "0")):
                    await c.call("volume-set", name="av", key=k, value=v)
            # `volume alerts NAME rules` answers from the option alone
            shown = await d.op_volume_alerts("av", "rules")
            assert [r["name"] for r in shown["rules"]] == \
                ["readv-errors"]
            m = await mount_volume(d.host, d.port, "av")
            try:
                await m.write_file("/f", b"x" * 8192)
                assert await m.read_file("/f") == b"x" * 8192
                # no storm, traffic flowing: no alert
                out = await d.op_volume_alerts("av")
                assert out["active"] == []
                # ARM THE STORM: every readv on every brick fails
                async with MgmtClient(d.host, d.port) as c:
                    await c.call("volume-set", name="av",
                                 key="debug.error-gen", value="on")
                    await c.call("volume-set", name="av",
                                 key="debug.error-fops", value="readv")
                    await c.call("volume-set", name="av",
                                 key="debug.error-failure", value="100")
                deadline = asyncio.get_event_loop().time() + 60
                active = []
                while asyncio.get_event_loop().time() < deadline:
                    try:
                        await m.read_file("/f")
                    except FopError:
                        pass
                    out = await d.op_volume_alerts("av")
                    active = [a for a in out["active"]
                              if a["rule"] == "readv-errors"]
                    if active:
                        break
                    await asyncio.sleep(0.3)
                assert active, "storm never raised the alert"
                assert active[0]["observed"] > 0.05
                assert active[0]["process"].startswith("av-brick-")
                # the RAISED edge arrived over REAL UDP
                raised = [e for e in ev.recent
                          if e.get("event") == "ALERT_RAISED"]
                assert raised and \
                    raised[0]["rule"] == "readv-errors"
                # ...and auto-captured an incident bundle whose
                # history section shows the error-rate ramp
                caps = []
                deadline = asyncio.get_event_loop().time() + 20
                while asyncio.get_event_loop().time() < deadline:
                    caps = [f for f in
                            (os.listdir(inc_dir)
                             if os.path.isdir(inc_dir) else [])
                            if "ALERT_RAISED" in f]
                    if caps:
                        break
                    await asyncio.sleep(0.3)
                assert caps, "ALERT_RAISED never auto-captured"
                bundle = json.load(
                    open(os.path.join(inc_dir, sorted(caps)[0])))
                hist = bundle["history"]
                err_series = [pts for k, pts in hist["series"].items()
                              if k.startswith("gftpu_fop_errors_total")]
                assert err_series, sorted(hist["series"])[:10]
                ramp = max(pts[-1][1] - pts[0][1]
                           for pts in err_series)
                assert ramp > 0, "history section shows no error ramp"
                assert bundle["alerts"]["active"][0]["rule"] == \
                    "readv-errors"
                # volume status grew an alerts block (fan-out cached)
                st = d.op_volume_status("av")
                assert st["alerts"]["rules"] == 1
                assert st["alerts"]["active"][0]["rule"] == \
                    "readv-errors"
                # STOP THE STORM by shifting traffic to writes (only
                # readv is error-gen'd) — NOT by volume-set, which
                # would restart the bricks and lose the raising
                # process's transition history.  Healthy writes push
                # the error ratio under target and the alert clears
                # in the same process that raised it.
                deadline = asyncio.get_event_loop().time() + 60
                while asyncio.get_event_loop().time() < deadline:
                    await m.write_file("/f", b"y" * 4096)
                    out = await d.op_volume_alerts("av")
                    if not out["active"]:
                        break
                    await asyncio.sleep(0.3)
                assert out["active"] == [], "alert never cleared"
                hist_out = await d.op_volume_alerts("av", "history")
                edges = [t["edge"] for t in hist_out["history"]
                         if t["rule"] == "readv-errors"]
                assert "RAISED" in edges and "CLEARED" in edges
                cleared = [e for e in ev.recent
                           if e.get("event") == "ALERT_CLEARED"]
                assert cleared, "CLEARED edge never reached eventsd"
            finally:
                await m.unmount()
        finally:
            await d.stop()
            os.environ.pop("GFTPU_EVENTSD", None)
            gf_events.configure(None)
            await ev.stop()

    asyncio.run(run())
