"""Eager-lock reuse + delayed combined post-op: consecutive writes on
one inode share a single inodelk + pre-op + post-op (ec-common.c:2176
ec_lock_reuse, :2377 delayed xattrop), the post-op commits version+size+
dirty in ONE atomic mixed xattrop, and a client crash between data write
and post-op heals correctly."""

import asyncio
import os

import numpy as np
import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc
from glusterfs_tpu.mgmt.shd import crawl_once
from glusterfs_tpu.utils.volspec import ec_volfile

K, R = 4, 2
N = K + R
STRIPE = K * 512

BRICK_LAYERS = [("features/locks", {}), ("features/index", {})]


def _rand(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def _index_entries(base, i):
    d = os.path.join(str(base), f"brick{i}", ".glusterfs_tpu", "indices",
                     "xattrop")
    return sorted(os.listdir(d)) if os.path.isdir(d) else []


@pytest.fixture
def vol(tmp_path):
    g = Graph.construct(ec_volfile(
        tmp_path, N, R, brick_layers=BRICK_LAYERS,
        # long timeout: windows close deterministically via fd close /
        # drain points, never via a racing timer
        options={"eager-lock-timeout": 30}))
    c = SyncClient(g)
    c.mount()
    yield c, g.top, tmp_path
    c.close()


def _ctrl_counts(brick_top):
    """Control-plane fop counts as seen by the brick (EC-issued waves)."""
    return {op: (brick_top.stats[op].count if op in brick_top.stats else 0)
            for op in ("inodelk", "getxattr", "xattrop", "setxattr",
                       "writev")}


def test_sequential_writes_amortize_to_one_wave(vol):
    """20 sequential stripe writes: 1 inodelk pair + 1 metadata fetch +
    1 pre-op + 1 combined post-op for the WHOLE window — ~1.25 waves per
    write, vs 6 with per-fop transactions (VERDICT weak #6)."""
    c, ec, base = vol
    f = c.create("/seq")
    brick0 = ec.children[0]
    before = _ctrl_counts(brick0)
    chunk = _rand(STRIPE, seed=1).tobytes()
    for i in range(20):
        f.write(chunk, i * STRIPE)
    f.close()
    after = _ctrl_counts(brick0)
    d = {op: after[op] - before[op] for op in after}
    assert d["writev"] == 20
    ctrl = d["inodelk"] + d["getxattr"] + d["xattrop"] + d["setxattr"]
    # lock+unlock (2 inodelk) + 1 getxattr + pre-op + combined post-op
    assert ctrl <= 8, f"control waves too high: {d}"
    # the data is committed and consistent
    assert c.read_file("/seq") == chunk * 20
    assert c.stat("/seq").size == 20 * STRIPE
    info = c._run(ec.heal_info(Loc("/seq")))
    assert info["bad"] == [] and not info["dirty"]
    for i in range(N):
        assert _index_entries(base, i) == []


def test_stat_and_read_during_open_window(vol):
    """Deferred size commit must not be observable: stat/read mid-window
    serve from the cached window metadata."""
    c, ec, base = vol
    f = c.create("/win")
    data = _rand(2 * STRIPE, seed=2).tobytes()
    f.write(data, 0)
    # window still open (no close): stat sees the new size, read sees
    # the new bytes
    assert c.stat("/win").size == 2 * STRIPE
    assert c.read_file("/win") == data
    f.write(data, 2 * STRIPE)
    assert c.stat("/win").size == 4 * STRIPE
    f.close()
    assert c.stat("/win").size == 4 * STRIPE


def test_crash_between_write_and_postop_heals(vol):
    """Client dies after fragment writes but before the delayed post-op:
    bricks hold new data + dirty marks + old counters.  The index feeds
    the shd, which reconverges the file (VERDICT next-round #5 done
    criterion)."""
    c, ec, base = vol
    data = _rand(2 * STRIPE, seed=3).tobytes()
    c.write_file("/cr", data)
    newstripe = _rand(STRIPE, seed=4).tobytes()
    f = c.open("/cr")
    f.fsync()  # durability point: commit the baseline post-op (close
    # alone defers it, reference post-op-delay semantics)
    f.write(newstripe, 0)

    async def crash():
        # simulate process death: the window state evaporates without a
        # post-op; the server releases a dead client's locks, which
        # _inodelk_unwind stands in for here
        gfid = (await ec.lookup(Loc("/cr")))[0].gfid
        st = ec._eager.pop(gfid)
        if st.timer is not None:
            st.timer.cancel()
        await ec._inodelk_unwind(Loc("/cr", gfid=gfid), st.locked, st.owner)
        return gfid

    gfid = c._run(crash())
    # dirty stuck on every brick -> pending index holds the gfid
    for i in range(N):
        assert _index_entries(base, i) == [gfid.hex()], f"brick {i}"
    report = c._run(crawl_once(c._client))
    assert [h["path"] for h in report["healed"]] == ["/cr"]
    for i in range(N):
        assert _index_entries(base, i) == []
    # all bricks agree afterwards: any K decode identically
    seen = set()
    for drop in ((4, 5), (0, 1)):
        for i in drop:
            ec.set_child_up(i, False)
        got = c.read_file("/cr")
        assert got[STRIPE:] == data[STRIPE:]
        seen.add(got[:STRIPE])
        for i in drop:
            ec.set_child_up(i, True)
    assert len(seen) == 1, "bricks diverge after crash heal"
    info = c._run(ec.heal_info(Loc("/cr")))
    assert info["bad"] == [] and not info["dirty"]


def test_window_survives_interleaved_read(vol):
    """A read between writes keeps the window open (lock reuse), stays
    correct, and adds no extra lock/pre-op waves."""
    c, ec, base = vol
    f = c.create("/rw")
    brick0 = ec.children[0]
    before = _ctrl_counts(brick0)
    a = _rand(STRIPE, seed=5).tobytes()
    b = _rand(STRIPE, seed=6).tobytes()
    f.write(a, 0)
    assert f.read(STRIPE, 0) == a
    f.write(b, STRIPE)
    assert f.read(2 * STRIPE, 0) == a + b
    f.close()
    after = _ctrl_counts(brick0)
    d = {op: after[op] - before[op] for op in after}
    ctrl = d["inodelk"] + d["getxattr"] + d["xattrop"] + d["setxattr"]
    assert ctrl <= 8, f"interleaved read broke the window: {d}"
    assert c.read_file("/rw") == a + b


def test_concurrent_write_and_truncate_no_inversion(vol):
    """ftruncate inside an open eager window must not deadlock: _Txn
    flushes the window under the local lock before winding its own
    inodelk (the drain needs the local lock the txn holds — waiting on
    the brick lock instead would stall until the lock timeout)."""
    c, ec, base = vol
    data = _rand(4 * STRIPE, seed=9).tobytes()

    async def drive():
        cl = c._client
        f = await cl.create("/ci")
        await f.write(data, 0)          # window open (timeout 30)

        async def trunc():
            await ec.truncate(Loc("/ci"), 2 * STRIPE)

        async def more_writes():
            for i in range(3):
                await ec.writev(f.fd, data[:STRIPE], i * STRIPE)

        await asyncio.wait_for(
            asyncio.gather(trunc(), more_writes()), timeout=10)
        await f.close()

    c._run(drive())
    assert c.stat("/ci").size in (2 * STRIPE, 3 * STRIPE)
    info = c._run(ec.heal_info(Loc("/ci")))
    assert info["bad"] == []


def test_max_hold_caps_continuous_writer(tmp_path):
    """A continuous writer must not hold the cluster lock forever: the
    window force-flushes at eager-lock-max-hold so FIFO brick locks let
    other clients in (contention-yield bound)."""
    g = Graph.construct(ec_volfile(
        tmp_path, N, R, brick_layers=BRICK_LAYERS,
        options={"eager-lock-timeout": 5, "eager-lock-max-hold": 0.2}))
    c = SyncClient(g)
    c.mount()
    try:
        ec = g.top
        chunk = _rand(STRIPE, seed=10).tobytes()

        async def stream():
            cl = c._client
            f = await cl.create("/hold")
            flushes = 0
            loop = asyncio.get_running_loop()
            t0 = loop.time()
            while loop.time() - t0 < 0.8:
                await f.write(chunk, 0)
                if f.fd.gfid not in ec._eager:
                    flushes += 1
                await asyncio.sleep(0.01)
            await f.close()
            return flushes

        flushes = c._run(stream())
        # the window was force-released at least twice in 0.8s despite
        # uninterrupted writes with a 5s idle timeout
        assert flushes >= 2, f"window never yielded ({flushes})"
        info = c._run(ec.heal_info(Loc("/hold")))
        assert info["bad"] == [] and not info["dirty"]
    finally:
        c.close()


def test_degraded_window_keeps_dirty_for_shd(vol):
    """Brick dies mid-window: post-op bumps versions on survivors only,
    dirty stays, index retains the entry until healed."""
    c, ec, base = vol
    f = c.create("/deg")
    a = _rand(STRIPE, seed=7).tobytes()
    f.write(a, 0)
    ec.set_child_up(2, False)
    b = _rand(STRIPE, seed=8).tobytes()
    f.write(b, STRIPE)
    ec.set_child_up(2, True)
    f.close()
    # brick 2 missed a write inside the window -> excluded from post-op
    info = c._run(ec.heal_info(Loc("/deg")))
    assert info["bad"] == [2] and info["dirty"]
    assert _index_entries(base, 0) != []
    report = c._run(crawl_once(c._client))
    assert [h["path"] for h in report["healed"]] == ["/deg"]
    ec.set_child_up(0, False)
    ec.set_child_up(1, False)
    assert c.read_file("/deg") == a + b
    ec.set_child_up(0, True)
    ec.set_child_up(1, True)
