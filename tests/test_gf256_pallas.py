"""Pallas kernel parity (interpret mode on CPU; real lowering exercised on TPU
by bench.py and __graft_entry__)."""

import numpy as np
import pytest

from glusterfs_tpu.ops import gf256, gf256_pallas

CONFIGS = [(4, 2), (8, 4), (16, 4)]


@pytest.mark.parametrize("k,r", CONFIGS)
@pytest.mark.parametrize("formulation", ["xor", "xor3", "mxu"])
def test_encode_parity(k, r, formulation):
    n = k + r
    rng = np.random.default_rng(k + r)
    data = rng.integers(0, 256, k * gf256.CHUNK_SIZE * 3, dtype=np.uint8)
    expect = gf256.ref_encode(data, k, n)
    got = gf256_pallas.encode(data, k, n, formulation, interpret=True)
    assert np.array_equal(got, expect)


@pytest.mark.parametrize("k,r", CONFIGS)
@pytest.mark.parametrize("formulation", ["xor", "xor3", "mxu"])
def test_decode_parity(k, r, formulation):
    n = k + r
    rng = np.random.default_rng(k * 3 + r)
    data = rng.integers(0, 256, k * gf256.CHUNK_SIZE * 2, dtype=np.uint8)
    frags = gf256.ref_encode(data, k, n)
    rows = list(range(r, r + k))
    got = gf256_pallas.decode(frags[rows], rows, k, formulation, interpret=True)
    assert np.array_equal(got, data)
