"""Pallas kernel parity (interpret mode on CPU; real lowering exercised on TPU
by bench.py and __graft_entry__)."""

import numpy as np
import pytest

from glusterfs_tpu.ops import gf256, gf256_pallas

CONFIGS = [(4, 2), (8, 4), (16, 4)]


@pytest.mark.parametrize("k,r", CONFIGS)
@pytest.mark.parametrize("formulation", ["xor", "xor3", "mxu", "fused"])
def test_encode_parity(k, r, formulation):
    n = k + r
    rng = np.random.default_rng(k + r)
    data = rng.integers(0, 256, k * gf256.CHUNK_SIZE * 3, dtype=np.uint8)
    expect = gf256.ref_encode(data, k, n)
    got = gf256_pallas.encode(data, k, n, formulation, interpret=True)
    assert np.array_equal(got, expect)


@pytest.mark.parametrize("k,r", CONFIGS)
@pytest.mark.parametrize("formulation", ["xor", "xor3", "mxu", "fused"])
def test_decode_parity(k, r, formulation):
    n = k + r
    rng = np.random.default_rng(k * 3 + r)
    data = rng.integers(0, 256, k * gf256.CHUNK_SIZE * 2, dtype=np.uint8)
    frags = gf256.ref_encode(data, k, n)
    rows = list(range(r, r + k))
    got = gf256_pallas.decode(frags[rows], rows, k, formulation, interpret=True)
    assert np.array_equal(got, data)


@pytest.mark.parametrize("k,r", [(4, 2), (8, 3)])
def test_fused_unaligned_stripe_counts(k, r):
    """Stripe counts that don't divide the kernel tile must pad+trim."""
    n = k + r
    for s in (1, 3, 127, 129):
        rng = np.random.default_rng(s)
        data = rng.integers(0, 256, k * gf256.CHUNK_SIZE * s, dtype=np.uint8)
        frags = gf256_pallas.encode(data, k, n, "fused", interpret=True)
        assert np.array_equal(frags, gf256.ref_encode(data, k, n))
        rows = list(range(r, r + k))
        out = gf256_pallas.decode(frags[rows], rows, k, "fused",
                                  interpret=True)
        assert np.array_equal(out, data)


def test_fused_all_masks_4p2():
    import itertools

    k, r = 4, 2
    n = k + r
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, k * gf256.CHUNK_SIZE * 2, dtype=np.uint8)
    frags = gf256.ref_encode(data, k, n)
    for rows in itertools.combinations(range(n), k):
        out = gf256_pallas.decode(frags[np.asarray(rows)], rows, k, "fused",
                                  interpret=True)
        assert np.array_equal(out, data), rows


# -- real-lowering parity (VERDICT r3 weak #8: interpret-only parity
# lets a Mosaic lowering bug reach bench.py before any test) ----------

def _tpu():
    try:
        import jax

        return any(d.platform in ("tpu", "axon") for d in jax.devices())
    except Exception:
        return False


@pytest.mark.skipif(not _tpu(), reason="needs a real TPU")
@pytest.mark.parametrize("k,r", CONFIGS)
def test_fused_parity_on_silicon(k, r):
    """Golden-vector parity through REAL Mosaic lowering (skip-if-no-
    tpu): the same byte-exactness the interpret tests assert, on the
    chip the production path runs on."""
    n = k + r
    rng = np.random.default_rng(97 + k)
    data = rng.integers(0, 256, k * gf256.CHUNK_SIZE * 300,
                        dtype=np.uint8)
    expect = gf256.ref_encode(data, k, n)
    got = gf256_pallas.encode(data, k, n, "fused", interpret=False)
    assert np.array_equal(got, expect)
    rows = list(range(r, r + k))
    out = gf256_pallas.decode(expect[rows], rows, k, "fused",
                              interpret=False)
    assert np.array_equal(out, data)


@pytest.mark.skipif(not _tpu(), reason="needs a real TPU")
def test_golden_vectors_on_silicon():
    """The reference-C golden vectors through real lowering."""
    import os

    path = os.path.join(os.path.dirname(__file__), "golden",
                        "ec_golden.npz")
    g = np.load(path)
    for key in g.files:
        if not key.endswith("_data"):
            continue
        tag = key[: -len("_data")]
        k, r = (int(x) for x in tag.split("p"))
        data = g[f"{tag}_data"]
        frags = g[f"{tag}_frags"]
        got = gf256_pallas.encode(data, k, k + r, "fused",
                                  interpret=False)
        assert np.array_equal(got, frags), tag
