"""Wire codec + protocol client/server: in-process TCP loopback tests and
a real multi-process cluster (brick subprocesses) running a disperse
volume over the network — the distributed end-to-end slice."""

import asyncio
import errno
import time

import numpy as np
import pytest

from glusterfs_tpu.api.glfs import Client, SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc
from glusterfs_tpu.core.iatt import Iatt, IAType
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.rpc import wire

from .harness import BRICK_VOLFILE, Cluster


# -- wire codec ------------------------------------------------------------

def test_wire_roundtrip():
    cases = [
        None, True, False, 0, 1, -5, 2 ** 40, 3.25, b"\x00\xff", "héllo",
        [1, [2, b"x"], "y"], {"a": 1, "b": [True, None]},
        Iatt(gfid=b"\x01" * 16, ia_type=IAType.REG, size=42),
        Loc("/a/b", gfid=b"\x02" * 16, parent=b"\x03" * 16),
        wire.FdHandle(7, b"\x04" * 16, "/f"),
    ]
    for v in cases:
        buf = wire.pack(9, wire.MT_CALL, v)
        xid, mtype, out = wire.unpack(buf[4:])
        assert xid == 9 and mtype == wire.MT_CALL
        if isinstance(v, Iatt):
            assert out.gfid == v.gfid and out.size == v.size
        elif isinstance(v, Loc):
            assert out.path == v.path and out.gfid == v.gfid
        elif isinstance(v, wire.FdHandle):
            assert (out.fdid, out.gfid, out.path) == (7, b"\x04" * 16, "/f")
        else:
            assert out == v
    err = FopError(errno.ENOENT, "gone")
    _, _, out = wire.unpack(wire.pack(1, wire.MT_ERROR, err)[4:])
    assert isinstance(out, FopError) and out.err == errno.ENOENT


def test_wire_rejects_unknown_types():
    with pytest.raises(wire.WireError):
        wire.pack(1, wire.MT_CALL, object())


# -- in-process TCP loopback ----------------------------------------------

CLIENT_VOLFILE = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume locks
end-volume
"""


def test_loopback_volume(tmp_path):
    async def run():
        server = await serve_brick(BRICK_VOLFILE.format(dir=tmp_path / "b"))
        g = Graph.construct(CLIENT_VOLFILE.format(port=server.port))
        c = Client(g)
        await c.mount()
        # wait for connect
        for _ in range(100):
            if g.top.connected:
                break
            await asyncio.sleep(0.05)
        assert g.top.connected
        f = await c.create("/x")
        await f.write(b"over the wire", 0)
        await f.close()
        assert await c.read_file("/x") == b"over the wire"
        await c.mkdir("/d")
        assert sorted(await c.listdir("/")) == ["d", "x"]
        ia = await c.stat("/x")
        assert ia.size == 13
        # locks work remotely (lk-owner scoped per connection)
        await g.top.inodelk("dom", Loc("/x"), "lock", "wr", 0, -1,
                            {"lk-owner": b"me"})
        await g.top.inodelk("dom", Loc("/x"), "unlock", "wr", 0, -1,
                            {"lk-owner": b"me"})
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_loopback_disconnect_notifies(tmp_path):
    async def run():
        server = await serve_brick(BRICK_VOLFILE.format(dir=tmp_path / "b"))
        g = Graph.construct(CLIENT_VOLFILE.format(port=server.port))
        c = Client(g)
        await c.mount()
        for _ in range(100):
            if g.top.connected:
                break
            await asyncio.sleep(0.05)
        await server.stop()  # brick dies
        for _ in range(100):
            if not g.top.connected:
                break
            await asyncio.sleep(0.05)
        assert not g.top.connected
        with pytest.raises(FopError) as ei:
            await c.read_file("/x")
        assert ei.value.err in (errno.ENOTCONN, errno.ENOENT)
        await c.unmount()

    asyncio.run(run())


# -- real multi-process cluster -------------------------------------------

@pytest.mark.slow
def test_multiprocess_disperse_cluster(tmp_path):
    """6 brick daemons as subprocesses; 4+2 disperse over TCP; kill a
    brick mid-flight; degraded read; heal after restart."""
    cluster = Cluster(tmp_path, 6)
    try:
        cluster.start()
        vf = cluster.client_volfile("cluster/disperse", {"redundancy": 2})
        c = SyncClient(Graph.construct(vf))
        c.mount()
        ec = c.graph.top
        # wait until all clients connected
        deadline = time.time() + 15
        while time.time() < deadline:
            if all(cl.connected for cl in ec.children):
                break
            time.sleep(0.1)
        assert all(cl.connected for cl in ec.children)
        data = np.random.default_rng(0).integers(
            0, 256, 300000, dtype=np.uint8).tobytes()
        c.write_file("/wire", data)
        assert c.read_file("/wire") == data

        # kill brick 1: ping/disconnect marks CHILD_DOWN; reads degrade
        cluster.bricks[1].kill()
        deadline = time.time() + 15
        while time.time() < deadline:
            if not ec.children[1].connected:
                break
            time.sleep(0.1)
        assert not ec.children[1].connected
        time.sleep(0.3)
        assert c.read_file("/wire") == data  # degraded read over TCP

        # write while brick 1 is dead -> divergence recorded
        data2 = data[::-1]
        c.write_file("/wire", data2)

        # restart brick 1; client auto-reconnects; heal
        cluster.bricks[1] = type(cluster.bricks[1])(str(tmp_path), "brick1")
        # reuse same brick dir: rewrite volfile with same dir, new port
        port = cluster.bricks[1].start()
        ec.children[1].reconfigure({"remote-port": port})
        deadline = time.time() + 20
        while time.time() < deadline:
            if ec.children[1].connected:
                break
            time.sleep(0.1)
        assert ec.children[1].connected
        ec.set_child_up(1, True)
        info = c._run(ec.heal_info(Loc("/wire")))
        assert 1 in info["bad"]
        res = c._run(ec.heal_file("/wire"))
        assert 1 in res["healed"]
        # read through the healed brick (drop two others)
        ec.set_child_up(4, False)
        ec.set_child_up(5, False)
        assert c.read_file("/wire") == data2
        c.close()
    finally:
        cluster.stop()
