"""Sharded data-plane tests on the 8-device virtual CPU mesh.

The reference's multi-brick fan-out/fan-in (ec_dispatch_all/_min,
reference xlators/cluster/ec/src/ec-common.c:816-900) maps to a (dp, frag)
device mesh here; these tests prove the sharded encode/decode is bit-exact
against the NumPy oracle and that degraded reconstruction works for
arbitrary surviving-fragment masks while actually sharded over devices.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from glusterfs_tpu.ops import gf256
from glusterfs_tpu.parallel import mesh_codec


@pytest.fixture(scope="module")
def mesh():
    devs = jax.devices()
    assert len(devs) >= 8, "conftest must provision 8 virtual CPU devices"
    return mesh_codec.make_mesh(devs[:8])


def _batch(rng, dp_mult: int, k: int, stripes_per: int = 2) -> np.ndarray:
    b = dp_mult * stripes_per
    return rng.integers(0, 256, (b, k * 8, 64), dtype=np.uint8)


def test_mesh_shape(mesh):
    assert mesh.devices.shape == (4, 2)
    assert mesh.axis_names == ("dp", "frag")


def test_sharded_step_parity_and_layout(mesh):
    k, r = 4, 2
    rng = np.random.default_rng(7)
    batch = _batch(rng, mesh.devices.shape[0], k)
    frags, mism = mesh_codec.run_step(k, r, batch, mesh)
    assert mism == 0
    assert frags.shape == ((k + r) * 8, batch.shape[0], 64)
    # Bit-exact vs the NumPy oracle, stripe by stripe.
    s = batch.shape[0]
    flat = batch.reshape(s * k * gf256.CHUNK_SIZE)
    want = gf256.ref_encode(flat, k, k + r)  # (n, S*512)
    got = np.asarray(frags)  # (n*8, B, 64)
    n = k + r
    got_frag = (
        got.reshape(n, 8, s, 64).transpose(0, 2, 1, 3).reshape(n, s * 512)
    )
    np.testing.assert_array_equal(got_frag, want)


def test_output_sharding_rides_mesh_axes(mesh):
    """Encode output must actually be laid out (frag, dp) — the
    scatter-to-bricks placement, not a replicated array."""
    k, r = 4, 2
    rng = np.random.default_rng(8)
    batch = _batch(rng, mesh.devices.shape[0], k)
    fn = mesh_codec.sharded_step_fn(k, r, mesh)
    frags, _ = fn(jnp.asarray(batch))
    spec = frags.sharding.spec
    assert spec == P("frag", "dp", None)
    # every device holds a distinct shard (no replication); Shard.index
    # is a tuple of slices — unhashable on some jax versions, so key by
    # its repr
    n_shards = len({str(d.index) for d in frags.addressable_shards})
    assert n_shards == 8


@pytest.mark.parametrize("k,r", [(2, 1), (4, 2), (8, 3), (8, 4)])
def test_degraded_decode_all_masks_sampled(mesh, k, r):
    """Reconstruct from every (small-config) or sampled (big-config)
    surviving-k subset; parity vs original must hold for each."""
    n = k + r
    rng = np.random.default_rng(100 * k + r)
    combos = list(itertools.combinations(range(n), k))
    if len(combos) > 12:
        sel = rng.choice(len(combos), size=12, replace=False)
        combos = [combos[i] for i in sel]
    batch = _batch(rng, mesh.devices.shape[0], k, stripes_per=1)
    s = batch.shape[0]
    flat = batch.reshape(s * k * gf256.CHUNK_SIZE)
    frags = gf256.ref_encode(flat, k, n)
    for rows in combos:
        out = mesh_codec.sharded_decode(
            k, rows, frags[np.asarray(rows)], mesh)
        np.testing.assert_array_equal(np.asarray(out).ravel(), flat)


def test_dp_axis_batch_sharding(mesh):
    """Input batches shard over dp: each dp row of the mesh holds a
    disjoint slice of the stripe batch."""
    k, r = 4, 2
    rng = np.random.default_rng(9)
    batch = _batch(rng, mesh.devices.shape[0], k)
    arr = jax.device_put(
        jnp.asarray(batch), NamedSharding(mesh, P("dp", None, None)))
    shard_rows = sorted(
        sh.index[0].start or 0 for sh in arr.addressable_shards)
    # 4 dp rows x 2 frag cols; each dp row slice appears twice (replicated
    # over frag), and the 4 slices are disjoint.
    assert len(set(shard_rows)) == 4
    fn = mesh_codec.sharded_step_fn(k, r, mesh)
    _, mism = fn(arr)
    assert int(mism) == 0


def test_uneven_mask_rows_with_gaps(mesh):
    """Surviving rows with gaps and out-of-order positions (e.g. brick 0
    and 3 dead in 4+2) decode correctly."""
    k, r = 4, 2
    n = k + r
    rng = np.random.default_rng(10)
    batch = _batch(rng, mesh.devices.shape[0], k, stripes_per=1)
    s = batch.shape[0]
    flat = batch.reshape(s * k * gf256.CHUNK_SIZE)
    frags = gf256.ref_encode(flat, k, n)
    for rows in [(1, 2, 4, 5), (0, 2, 3, 5), (2, 3, 4, 5), (0, 1, 4, 5)]:
        out = mesh_codec.sharded_decode(
            k, rows, frags[np.asarray(rows)], mesh)
        np.testing.assert_array_equal(np.asarray(out).ravel(), flat)


def test_single_stripe_decode_pads_to_dp(mesh):
    """A one-stripe degraded read (the common ec_dispatch_min case) must
    decode even though 1 doesn't divide the dp axis."""
    k, r = 4, 2
    rng = np.random.default_rng(11)
    flat = rng.integers(0, 256, k * gf256.CHUNK_SIZE, dtype=np.uint8)
    frags = gf256.ref_encode(flat, k, k + r)
    rows = (0, 2, 3, 5)
    out = mesh_codec.sharded_decode(k, rows, frags[np.asarray(rows)], mesh)
    np.testing.assert_array_equal(out, flat)


def test_sharded_decode_rejects_wrong_fragment_count(mesh):
    k = 4
    frags = np.zeros((6, 512), dtype=np.uint8)  # all n, not k
    with pytest.raises(ValueError, match="exactly 4 fragments"):
        mesh_codec.sharded_decode(k, (0, 1, 2, 3), frags, mesh)
