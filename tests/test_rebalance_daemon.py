"""Elastic scale-out: the glusterd-managed rebalance daemon (ISSUE 11).

Covers the checkpoint math (canonical walk order, resume skipping),
the torn-read-safe migration fop sequence (temp + rename commit,
internal-op cleanup unlinks, gfid stability), live throttle retune,
the rebalance task row in plain ``volume status``, the EC
traffic-origin plumb, and the acceptance satellite: SIGKILL the
daemon mid-migration, respawn, and prove it CONTINUES from the
checkpoint and converges byte-identical.
"""

import asyncio
import os
import signal
import time

import pytest

from glusterfs_tpu.cluster.dht import XA_LINKTO, DistributeLayer
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc
from glusterfs_tpu.features.trash import INTERNAL_OP
from glusterfs_tpu.mgmt.rebalanced import Rebalancer, tag_rebalance_origin


def _volfile(base, n=3) -> str:
    out = []
    for i in range(n):
        out.append(f"volume b{i}\n    type storage/posix\n"
                   f"    option directory {base}/brick{i}\nend-volume\n")
    subs = " ".join(f"b{i}" for i in range(n))
    out.append(f"volume dist\n    type cluster/distribute\n"
               f"    subvolumes {subs}\nend-volume\n")
    return "\n".join(out)


# -- walk-order / checkpoint math (pure) ------------------------------------


def test_dir_key_is_preorder_position():
    """Preorder DFS with sorted children emits directory paths exactly
    in dir_key order — the property the checkpoint skip relies on."""
    key = Rebalancer.dir_key
    preorder = ["/", "/a", "/a/b", "/a/c", "/a/c/x", "/b", "/b/a"]
    keys = [key(p) for p in preorder]
    assert keys == sorted(keys)
    assert key("/") < key("/a") < key("/a/b") < key("/b")
    # a child always sorts after its parent
    assert key("/a/c") < key("/a/c/x")


def test_resume_skip_math():
    r = Rebalancer(None, "v", checkpoint={
        "phase": "migrate", "last_dir": "/a/c",
        "counters": {"moved": 7, "scanned": 9}})
    # counters carried over, resume marker recorded
    assert r.counters["moved"] == 7 and r.counters["scanned"] == 9
    assert r.resumed_from == {"phase": "migrate", "last_dir": "/a/c"}
    # a migrate-phase checkpoint means fix-layout finished earlier
    assert r._done_before_resume("fix-layout", "/zzz")
    # migrate dirs at/before the checkpoint are done, later ones not
    assert r._done_before_resume("migrate", "/")
    assert r._done_before_resume("migrate", "/a/b")
    assert r._done_before_resume("migrate", "/a/c")
    assert not r._done_before_resume("migrate", "/a/c/x")
    assert not r._done_before_resume("migrate", "/b")
    # no checkpoint -> nothing is skipped
    r2 = Rebalancer(None, "v")
    assert not r2._done_before_resume("migrate", "/")


def test_throttle_table_shape():
    """lazy/normal/aggressive map onto (width, pause) with lazy the
    only cooperative-yield mode (dht-rebalance.c:3269 scaling)."""
    t = DistributeLayer._THROTTLE
    assert set(t) == {"lazy", "normal", "aggressive"}
    assert t["lazy"][0] < t["normal"][0] < t["aggressive"][0]
    assert t["lazy"][1] > 0 and t["normal"][1] == 0


# -- migration fop sequence (in-process graph) ------------------------------


def _misplace(c, dht):
    """Create a file whose cached subvol differs from its hashed one
    (the rename-linkto shape rebalance exists to fix)."""
    src, dst = "alpha", "beta"
    if dht.hashed_idx(src) == dht.hashed_idx(dst):
        dst = "gamma2"
        assert dht.hashed_idx(src) != dht.hashed_idx(dst)
    return src, dst


def test_migrate_temp_rename_commit_and_internal_unlinks(tmp_path):
    """The safe sequence: data lands in a hidden reserved-suffix temp,
    commits via same-child rename, cleanup unlinks carry the
    internal-op flag (features/trash must not capture them), the gfid
    survives the move, and no temp or stale linkto is left behind."""
    from glusterfs_tpu.api.glfs import Client

    async def run():
        c = Client(Graph.construct(_volfile(tmp_path)))
        await c.mount()
        try:
            dht = c.graph.top
            src, dst = _misplace(c, dht)
            body = b"move me" * 500
            await c.write_file(f"/{src}", body)
            await c.rename(f"/{src}", f"/{dst}")
            g0 = (await c.stat(f"/{dst}")).gfid
            unlink_xdata = []
            for child in dht.children:
                orig = child.unlink

                async def spy(loc, xdata=None, _orig=orig):
                    unlink_xdata.append((loc.path, dict(xdata or {})))
                    return await _orig(loc, xdata)

                child.unlink = spy
            res = await dht.rebalance("/")
            assert len(res["moved"]) == 1, res
            assert res["status"]["failed"] == 0
            # every migration cleanup unlink is an internal-engine op
            assert unlink_xdata, "migration made no cleanup unlinks"
            assert all(x.get(INTERNAL_OP) for _p, x in unlink_xdata), \
                unlink_xdata
            # byte-identical at the new home, gfid stable
            assert bytes(await c.read_file(f"/{dst}")) == body
            ia = await c.stat(f"/{dst}")
            assert ia.gfid == g0, "migration re-minted the gfid"
            di = dht.hashed_idx(dst)
            assert (tmp_path / f"brick{di}" / dst).read_bytes() == body
            # exactly one copy, no temp, no linkto marker left
            for i in range(3):
                names = os.listdir(tmp_path / f"brick{i}")
                assert not any(n.endswith(dht.MIGRATE_SUFFIX)
                               for n in names), names
            count = sum((tmp_path / f"brick{i}" / dst).exists()
                        for i in range(3))
            assert count == 1
            with pytest.raises(FopError):
                await dht.children[di].getxattr(Loc(f"/{dst}"),
                                                XA_LINKTO)
        finally:
            await c.unmount()

    asyncio.run(run())


def test_failed_linkto_removal_aborts_before_source_unlink(tmp_path):
    """A failed linkto-marker removal after the rename commit must
    abort the migration BEFORE the source unlink: the surviving marker
    routes readers at the source, so deleting it would strand the file
    unreadable forever.  Failing instead keeps the file served from
    the source, and a later pass retries the whole migration."""
    import errno

    from glusterfs_tpu.api.glfs import Client

    async def run():
        c = Client(Graph.construct(_volfile(tmp_path)))
        await c.mount()
        try:
            dht = c.graph.top
            src, dst = _misplace(c, dht)
            body = b"keep me readable" * 400
            await c.write_file(f"/{src}", body)
            await c.rename(f"/{src}", f"/{dst}")
            target = dht.children[dht.hashed_idx(dst)]
            orig = target.removexattr
            fails = {"n": 0}

            async def flaky(loc, name, xdata=None):
                if name == XA_LINKTO:
                    fails["n"] += 1
                    raise FopError(errno.EIO, "brick hiccup")
                return await orig(loc, name, xdata)

            target.removexattr = flaky
            res = await dht.rebalance("/")
            assert res["status"]["failed"] == 1, res["status"]
            assert fails["n"] == 1
            # source copy survived: still readable, byte-identical
            assert bytes(await c.read_file(f"/{dst}")) == body
            # brick heals: the next pass redoes the migration whole
            target.removexattr = orig
            res = await dht.rebalance("/")
            assert res["status"]["failed"] == 0, res["status"]
            assert len(res["moved"]) == 1
            assert bytes(await c.read_file(f"/{dst}")) == body
            with pytest.raises(FopError):
                await target.getxattr(Loc(f"/{dst}"), XA_LINKTO)
        finally:
            await c.unmount()

    asyncio.run(run())


def test_committed_but_unswept_destination_not_clobbered(tmp_path):
    """A migrator that died between its rename commit and the source
    unlink left TWO real copies — and clients have been writing to the
    committed (hashed) one since.  The next walk must finish the dead
    migrator's teardown (unlink the stale source), never re-copy the
    stale source over the committed copy."""
    from glusterfs_tpu.api.glfs import Client

    async def run():
        c = Client(Graph.construct(_volfile(tmp_path)))
        await c.mount()
        try:
            dht = c.graph.top
            # names arranged so the stale source sits at a LOWER child
            # index than the hashed destination — the order in which a
            # _locate_real scan would find the stale copy first
            src = dst = None
            for s in ("alpha", "beta", "gamma2", "delta", "omega"):
                for d in ("alpha", "beta", "gamma2", "delta", "omega"):
                    if dht.hashed_idx(s) < dht.hashed_idx(d):
                        src, dst = s, d
                        break
                if src:
                    break
            assert src, "no name pair with si < hi on this layout"
            stale = b"pre-migration bytes" * 300
            await c.write_file(f"/{src}", stale)
            await c.rename(f"/{src}", f"/{dst}")
            g0 = (await c.stat(f"/{dst}")).gfid
            si, hi = dht.hashed_idx(src), dht.hashed_idx(dst)
            # forge the post-commit crash state at the hashed child:
            # linkto replaced by a committed real copy (same gfid, the
            # rename preserves it) that a client has since rewritten
            committed = b"client wrote AFTER the commit" * 200
            hc, loc = dht.children[hi], Loc(f"/{dst}")
            await hc.unlink(loc, {INTERNAL_OP: True})
            fd, _ = await hc.create(loc, os.O_RDWR | os.O_EXCL, 0o644,
                                    {"gfid-req": g0})
            await hc.writev(fd, committed, 0)
            await hc.release(fd)
            try:
                await hc.removexattr(loc, XA_LINKTO)
            except FopError:
                pass  # marker already absent
            unlink_xdata = []
            orig_unlink = dht.children[si].unlink

            async def spy(l, xdata=None):
                unlink_xdata.append((l.path, dict(xdata or {})))
                return await orig_unlink(l, xdata)

            dht.children[si].unlink = spy
            idx, fia = await dht._locate_real(loc)
            assert idx == si, "stale source must be the scan's find"
            nbytes = await dht._migrate_file(loc, fia, si, hi)
            assert nbytes == 0, "teardown must not re-copy bytes"
            # the committed copy survived, the stale source is gone,
            # and the teardown unlink was an internal-engine op
            assert bytes(await c.read_file(f"/{dst}")) == committed
            assert not (tmp_path / f"brick{si}" / dst).exists()
            assert unlink_xdata and \
                all(x.get(INTERNAL_OP) for _p, x in unlink_xdata)
        finally:
            await c.unmount()

    asyncio.run(run())


def test_committed_check_transport_error_never_guesses(tmp_path):
    """A transport error while probing the destination for the
    committed-copy state proves nothing — the check must propagate
    (counted failed, retried later), never unlink the source on a
    guess (the only real copy) or fall through to the copy path
    (clobbering a committed one)."""
    import errno

    from glusterfs_tpu.api.glfs import Client

    async def run():
        c = Client(Graph.construct(_volfile(tmp_path)))
        await c.mount()
        try:
            dht = c.graph.top
            src, dst = _misplace(c, dht)
            body = b"the only real copy" * 300
            await c.write_file(f"/{src}", body)
            await c.rename(f"/{src}", f"/{dst}")
            si, hi = dht.hashed_idx(src), dht.hashed_idx(dst)
            hc, loc = dht.children[hi], Loc(f"/{dst}")
            orig = hc.getxattr

            async def flaky(l, name=None, xdata=None):
                if name == XA_LINKTO:
                    raise FopError(errno.ENOTCONN, "brick dropped")
                return await orig(l, name, xdata)

            hc.getxattr = flaky
            ia, _ = await dht.children[si].lookup(loc)
            with pytest.raises(FopError):
                await dht._migrate_file(loc, ia, si, hi)
            # the source copy survived the failed probe
            hc.getxattr = orig
            assert bytes(await c.read_file(f"/{dst}")) == body
            assert (tmp_path / f"brick{si}" / dst).exists()
        finally:
            await c.unmount()

    asyncio.run(run())


def test_reserved_suffix_names_refused(tmp_path):
    """User names carrying the reserved migration-temp suffix are
    refused at every namespace entry point — accepted, they would be
    hidden from every listing and later DELETED by the orphan
    sweep."""
    import errno

    from glusterfs_tpu.api.glfs import Client

    async def run():
        c = Client(Graph.construct(_volfile(tmp_path)))
        await c.mount()
        try:
            dht = c.graph.top
            sfx = dht.MIGRATE_SUFFIX
            for attempt in (
                    c.write_file(f"/user{sfx}", b"x"),
                    c.mkdir(f"/dir{sfx}"),
                    dht.symlink("t", Loc(f"/sym{sfx}")),
                    dht.mknod(Loc(f"/dev{sfx}"))):
                with pytest.raises(FopError) as ei:
                    await attempt
                assert ei.value.err == errno.EPERM
            await c.write_file("/ok", b"fine")
            with pytest.raises(FopError) as ei:
                await c.rename("/ok", f"/ok{sfx}")
            assert ei.value.err == errno.EPERM
            with pytest.raises(FopError) as ei:
                await dht.link(Loc("/ok"), Loc(f"/lnk{sfx}"))
            assert ei.value.err == errno.EPERM
            assert bytes(await c.read_file("/ok")) == b"fine"
        finally:
            await c.unmount()

    asyncio.run(run())


def test_fresh_run_sweeps_orphan_temps(tmp_path):
    """A FRESH (checkpoint-free) daemon run still reclaims
    crash-orphaned migration temps — a crashed predecessor's
    checkpoint may have been abandoned (topology change, stop before
    restart), and the hidden temps are invisible to every other
    path."""
    from glusterfs_tpu.api.glfs import Client

    async def run():
        c = Client(Graph.construct(_volfile(tmp_path)))
        await c.mount()
        try:
            from glusterfs_tpu.core.iatt import gfid_new

            dht = c.graph.top
            await c.write_file("/keep", b"serving data" * 100)
            # forge the crash leftover the way the migrator makes it:
            # a hidden reserved-suffix temp on one child
            b1 = dht.children[1]
            tloc = Loc(f"/.dead{dht.MIGRATE_SUFFIX}")
            fd, _ = await b1.create(tloc, os.O_RDWR | os.O_EXCL, 0o600,
                                    {"gfid-req": gfid_new()})
            await b1.writev(fd, b"x" * 4096, 0)
            await b1.release(fd)
            orphan = tmp_path / "brick1" / f".dead{dht.MIGRATE_SUFFIX}"
            assert orphan.exists()
            reb = Rebalancer(c, "tv", mode="full",
                             checkpoint_interval=0.01)
            assert reb.resumed_from is None  # genuinely fresh
            await reb.run()
            assert reb.phase == "done"
            assert reb.counters["temps_swept"] >= 1, reb.counters
            assert not orphan.exists()
        finally:
            await c.unmount()

    asyncio.run(run())


def test_concurrent_readers_never_torn(tmp_path):
    """Readers racing the migration see the old full bytes or the new
    full bytes — never a partial copy and never a transient ENOENT
    (the temp+rename commit plus the re-resolution retry)."""
    from glusterfs_tpu.api.glfs import Client

    async def run():
        c = Client(Graph.construct(_volfile(tmp_path)))
        await c.mount()
        try:
            dht = c.graph.top
            src, dst = _misplace(c, dht)
            body = os.urandom(256 * 1024)
            await c.write_file(f"/{src}", body)
            await c.rename(f"/{src}", f"/{dst}")
            stop = asyncio.Event()
            reads = {"n": 0}

            async def reader():
                while not stop.is_set():
                    got = await c.read_file(f"/{dst}")
                    assert bytes(got) == body, "reader saw a torn file"
                    reads["n"] += 1
                    await asyncio.sleep(0)

            tasks = [asyncio.ensure_future(reader()) for _ in range(3)]
            try:
                res = await dht.rebalance("/")
                assert len(res["moved"]) == 1
                # keep reading a beat after the commit
                await asyncio.sleep(0.05)
            finally:
                stop.set()
                await asyncio.gather(*tasks)
            assert reads["n"] > 0
            assert bytes(await c.read_file(f"/{dst}")) == body
        finally:
            await c.unmount()

    asyncio.run(run())


def test_rebalancer_throttle_retunes_live(tmp_path):
    """volume-set of cluster.rebal-throttle mid-run retunes the NEXT
    wave (the daemon reads the option per wave; here the reconfigure
    lands through the same opts object a live volfile push updates)."""
    from glusterfs_tpu.api.glfs import Client

    async def run():
        c = Client(Graph.construct(_volfile(tmp_path)))
        await c.mount()
        try:
            dht = c.graph.top
            dht.reconfigure({"rebal-throttle": "lazy"})
            # misplace many files: rename leaves the data at the old
            # hashed child behind a linkto, so most need migration
            for i in range(18):
                await c.write_file(f"/n{i:02d}", f"n{i:02d}".encode() * 50)
                await c.rename(f"/n{i:02d}", f"/m{i:02d}")
            flips = {"n": 0}
            real_migrate = dht._migrate_file

            async def spy(cloc, ia, idx, hi):
                out = await real_migrate(cloc, ia, idx, hi)
                if flips["n"] == 0:
                    # live volume-set mid-run: the NEXT wave widens
                    dht.reconfigure({"rebal-throttle": "aggressive"})
                flips["n"] += 1
                return out

            dht._migrate_file = spy
            reb = Rebalancer(c, "tv", mode="full",
                             checkpoint_interval=0.01)
            await reb.run()
            assert reb.phase == "done"
            assert reb.counters["failed"] == 0
            assert reb.counters["moved"] >= 2, reb.counters
            # the wave after the flip read the retuned mode and widened
            assert reb.throttle == "aggressive", reb.throttle
            assert reb.max_inflight > 1, reb.max_inflight
            assert reb.counters["scanned"] >= 18
        finally:
            await c.unmount()

    asyncio.run(run())


# -- EC traffic-origin plumb -------------------------------------------------


def test_ec_traffic_origin_default_and_rebalance_tag(tmp_path):
    """The daemon tags its private graph's EC layers
    traffic_origin="rebalance"; codec batches then carry that origin
    (mesh/batch family attribution), while explicit heal call sites
    keep origin="heal"."""
    from glusterfs_tpu.api.glfs import Client

    vf = []
    for i in range(3):
        vf.append(f"volume e{i}\n    type storage/posix\n"
                  f"    option directory {tmp_path}/eb{i}\nend-volume\n")
    vf.append("volume ec\n    type cluster/disperse\n"
              "    option redundancy 1\n"
              "    subvolumes e0 e1 e2\nend-volume\n")

    async def run():
        c = Client(Graph.construct("\n".join(vf)))
        await c.mount()
        try:
            ec = c.graph.top
            assert ec.traffic_origin == "serve"
            tagged = tag_rebalance_origin(c.graph)
            assert tagged >= 1
            assert ec.traffic_origin == "rebalance"

            seen = []

            class StubCodec:
                async def encode_async(self, buf, origin="serve"):
                    seen.append(origin)
                    return buf  # the plumb is under test, not the math

            real_codec, real_batching = ec.codec, ec._batching
            ec.codec, ec._batching = StubCodec(), True
            try:
                await ec._codec_encode(b"")
                await ec._codec_encode(b"", origin="heal")
            finally:
                ec.codec, ec._batching = real_codec, real_batching
            assert seen == ["rebalance", "heal"], seen
        finally:
            await c.unmount()

    asyncio.run(run())


def test_migrate_streaming_rides_delta_path_with_rebalance_origin(
        tmp_path):
    """A streamed migration copy onto a healthy systematic disperse
    destination pre-sizes the temp and stripe-aligns its windows, so
    the unaligned tail rides the PR-10 parity-delta path (no full
    RMW), and the gftpu_ec_delta_writes_total family attributes it to
    origin="rebalance" (ROADMAP item 3, narrow form)."""
    from glusterfs_tpu.api.glfs import Client
    from glusterfs_tpu.core.metrics import REGISTRY

    vf = []
    for g in range(2):
        for i in range(3):
            vf.append(f"volume e{g}{i}\n    type storage/posix\n"
                      f"    option directory {tmp_path}/b{g}{i}\n"
                      "end-volume\n")
        subs = " ".join(f"e{g}{i}" for i in range(3))
        vf.append(f"volume ec{g}\n    type cluster/disperse\n"
                  "    option redundancy 1\n"
                  "    option systematic on\n"
                  f"    subvolumes {subs}\nend-volume\n")
    vf.append("volume dist\n    type cluster/distribute\n"
              "    option rebal-migrate-window 64KB\n"
              "    subvolumes ec0 ec1\nend-volume\n")

    async def run():
        c = Client(Graph.construct("\n".join(vf)))
        await c.mount()
        try:
            dht = c.graph.top
            src, dst = _misplace(c, dht)
            stripe = dht.children[0].stripe
            # two full 64 KiB windows + an unaligned 700-byte tail:
            # the streaming path (size > window), tail not a stripe
            # multiple
            size = 2 * 64 * 1024 + 700
            assert size % stripe, "tail must be unaligned"
            body = bytes(range(256)) * (size // 256) + b"T" * (size % 256)
            await c.write_file(f"/{src}", body)
            await c.rename(f"/{src}", f"/{dst}")
            tag_rebalance_origin(c.graph)
            dec = dht.children[dht.hashed_idx(dst)]
            assert dht._delta_stripe(dec) == stripe
            rmw0 = dec.write_path["rmw"]
            delta0 = dec.delta_origin.get("rebalance", 0)
            res = await dht.rebalance("/")
            assert len(res["moved"]) == 1, res
            assert res["status"]["failed"] == 0
            # the tail took the delta plane, attributed to rebalance
            assert dec.delta_origin.get("rebalance", 0) == delta0 + 1, \
                dec.delta_origin
            # ...and NOTHING on the destination paid a full RMW: the
            # aligned windows are pure encodes over the pre-sized temp
            assert dec.write_path["rmw"] == rmw0, dec.write_path
            snap = REGISTRY.snapshot()
            by_origin = {
                s[0].get("origin"): s[1]
                for s in snap["gftpu_ec_delta_writes_total"]["samples"]
                if s[0]["layer"] == dec.name}
            assert by_origin.get("rebalance", 0) >= 1, by_origin
            assert bytes(await c.read_file(f"/{dst}")) == body
        finally:
            await c.unmount()

    asyncio.run(run())


# -- glusterd surfaces -------------------------------------------------------


def test_volume_status_tasks_rebalance_row(tmp_path):
    """An active rebalance shows in plain ``volume status`` as a task
    row beside the remove-brick one (_add_task_to_dict analog); a
    drain-mode walk reports through the remove-brick row only."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd

    d = Glusterd(str(tmp_path / "gd"))
    d.state["volumes"]["tv"] = {
        "name": "tv", "type": "distribute", "status": "started",
        "bricks": [], "options": {}}
    st = d.op_volume_status("tv")
    assert "tasks" not in st
    d.state["volumes"]["tv"]["rebalance"] = {
        "status": "started", "mode": "full", "phase": "migrate",
        "node": d.uuid, "counters": {"moved": 3},
        "throttle": "normal"}
    st = d.op_volume_status("tv")
    rows = [t for t in st["tasks"] if t["type"] == "rebalance"]
    assert rows and rows[0]["status"] == "started"
    assert rows[0]["phase"] == "migrate"
    assert rows[0]["counters"] == {"moved": 3}
    # drain mode: the remove-brick row IS the task row
    d.state["volumes"]["tv"]["rebalance"]["mode"] = "drain"
    d.state["volumes"]["tv"]["remove-brick"] = {
        "status": "started", "bricks": ["tv-brick-2"]}
    st = d.op_volume_status("tv")
    types = [t["type"] for t in st["tasks"]]
    assert types == ["remove-brick"], types


def test_registry_families_present(tmp_path):
    """The gftpu_rebalance_* families exist and label by volume."""
    from glusterfs_tpu.core.metrics import REGISTRY

    r = Rebalancer(None, "famvol")
    r.counters["moved"] = 4
    r.counters["bytes_moved"] = 4096
    r.counters["failed"] = 1
    r.phase = "migrate"
    snap = REGISTRY.snapshot()
    for fam in ("gftpu_rebalance_files_total",
                "gftpu_rebalance_bytes_total",
                "gftpu_rebalance_failures_total",
                "gftpu_rebalance_phase"):
        assert fam in snap, fam
    rows = {tuple(sorted(s[0].items())): s[1]
            for s in snap["gftpu_rebalance_files_total"]["samples"]}
    assert rows[(("result", "moved"), ("volume", "famvol"))] == 4
    phase = [s for s in snap["gftpu_rebalance_phase"]["samples"]
             if s[0].get("volume") == "famvol"]
    assert phase and phase[0][1] == 2  # migrate


# -- the acceptance satellite: SIGKILL + respawn resumes ---------------------


def test_checkpoint_resume_after_sigkill(tmp_path):
    """SIGKILL the managed daemon mid-migration, respawn through
    ``volume rebalance start``, and prove it CONTINUES from the
    checkpoint (counters carry, fix-layout is not redone, resumed_from
    recorded) and converges byte-identical."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="rv",
                             vtype="distribute", redundancy=0,
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(2)])
                await c.call("volume-start", name="rv")
                await c.call("volume-set", name="rv",
                             key="rebalance.checkpoint-interval",
                             value="0.05")
                await c.call("volume-set", name="rv",
                             key="cluster.rebal-throttle", value="lazy")
            cl = await mount_volume(d.host, d.port, "rv")
            data = {}
            try:
                for dd in range(6):
                    await cl.mkdir(f"/d{dd}")
                    for i in range(8):
                        p = f"/d{dd}/f{i}"
                        data[p] = f"{p}-x".encode() * 300
                        await cl.write_file(p, data[p])
                async with MgmtClient(d.host, d.port) as c:
                    await c.call("volume-add-brick", name="rv",
                                 bricks=[{"path": str(tmp_path / "b2")}])
                    out = await c.call("volume-rebalance", name="rv",
                                       action="start")
                    assert out["status"] == "started", out

                    def rb():
                        return d._vol("rv").get("rebalance") or {}

                    deadline = time.monotonic() + 120
                    while True:
                        r = rb()
                        ck = r.get("checkpoint") or {}
                        if r.get("phase") == "migrate" and \
                                ck.get("last_dir") and \
                                (r.get("counters") or {}).get(
                                    "moved", 0) >= 1:
                            break
                        assert r.get("status") == "started", r
                        assert time.monotonic() < deadline, r
                        await asyncio.sleep(0.02)
                    pre = dict(rb()["counters"])
                    proc = d.rebalanced["rv"]
                    os.kill(proc.pid, signal.SIGKILL)
                    proc.wait()
                    # respawn through the SAME op: a dead daemon with
                    # status=started resumes, never errors
                    out = await c.call("volume-rebalance", name="rv",
                                       action="start")
                    assert out["status"] == "resumed", out
                    deadline = time.monotonic() + 240
                    while rb().get("status") not in ("completed",
                                                     "failed"):
                        assert time.monotonic() < deadline, rb()
                        await asyncio.sleep(0.2)
                    r = rb()
                    assert r["status"] == "completed", r
                    # CONTINUED, not restarted: resume marker present,
                    # counters monotonic over the checkpoint, and the
                    # fix-layout phase was NOT rerun
                    assert r.get("resumed_from", {}).get("last_dir"), r
                    fin = r["counters"]
                    assert fin["scanned"] > pre["scanned"], (pre, fin)
                    assert fin["moved"] >= pre["moved"]
                    assert fin["dirs_fixed"] == pre["dirs_fixed"], \
                        "respawn redid fix-layout (restart, not resume)"
                for p, body in data.items():
                    assert bytes(await cl.read_file(p)) == body, p
            finally:
                await cl.unmount()
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-stop", name="rv")
        finally:
            await d.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_remove_brick_drain_rides_daemon_with_stop(tmp_path):
    """remove-brick start spawns the drain-mode daemon (status /
    checkpoints for free); stop aborts it and restores the layout."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="sv",
                             vtype="distribute", redundancy=0,
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(3)])
                await c.call("volume-start", name="sv")
            cl = await mount_volume(d.host, d.port, "sv")
            data = {}
            try:
                for i in range(16):
                    p = f"/f{i:02d}"
                    data[p] = f"{p}-body".encode() * 80
                    await cl.write_file(p, data[p])
                async with MgmtClient(d.host, d.port) as c:
                    await c.call("volume-remove-brick", name="sv",
                                 bricks=["sv-brick-2"], action="start")
                    assert (d._vol("sv").get("rebalance")
                            or {}).get("mode") == "drain"
                    deadline = time.monotonic() + 180
                    while True:
                        st = await c.call("volume-remove-brick",
                                          name="sv", bricks=[],
                                          action="status")
                        if st.get("status") in ("completed", "failed"):
                            break
                        assert time.monotonic() < deadline, st
                        await asyncio.sleep(0.3)
                    assert st["status"] == "completed", st
                    assert st.get("moved", 0) >= 1
                    leftover = [
                        x for x in os.listdir(tmp_path / "b2")
                        if not x.startswith(".glusterfs")]
                    assert not leftover, leftover
                    await c.call("volume-remove-brick", name="sv",
                                 bricks=[], action="commit")
                    # a fresh shrink can be aborted with stop
                    await c.call("volume-remove-brick", name="sv",
                                 bricks=["sv-brick-1"], action="start")
                    out = await c.call("volume-remove-brick", name="sv",
                                       bricks=[], action="stop")
                    assert out["status"] == "stopped"
                    assert "remove-brick" not in d._vol("sv")
                for p, body in data.items():
                    for _ in range(40):  # graph swap settling
                        try:
                            got = await cl.read_file(p)
                            break
                        except FopError:
                            await asyncio.sleep(0.25)
                    assert bytes(got) == body, p
            finally:
                await cl.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


# -- growth placement safety (the chaos-caught pair) ------------------------


def test_no_layout_dir_places_on_holders(tmp_path):
    """A directory with NO layout xattr that exists on only a subset
    of children (the pre-add-brick namespace of a grown single-leg
    volume) derives its split over the HOLDERS — hashing over all
    children would route creates at a child with no parent directory
    to create under (the rebalance_grow chaos scenario's ENOENT)."""
    from glusterfs_tpu.api.glfs import Client

    async def run():
        c = Client(Graph.construct(_volfile(tmp_path)))
        await c.mount()
        try:
            dht = c.graph.top
            # the dir exists ONLY on child 0, stamped by nobody — as
            # if created before the other legs were added
            await dht.children[0].mkdir(Loc("/old"), 0o755)
            for i in range(40):
                assert await dht._placed(Loc(f"/old/f{i}")) == 0
            layout, authoritative = await dht._dir_meta("/old")
            assert layout and {r[2] for r in layout} == {0}
            assert not authoritative  # a miss here proves NOTHING
            # a serving create lands (on the holder), bytes exact
            await c.write_file("/old/newfile", b"grown" * 100)
            assert bytes(await c.read_file("/old/newfile")) \
                == b"grown" * 100
            assert (tmp_path / "brick0" / "old" / "newfile").exists()
            # once fix-layout stamps ranges the holders rule retires
            dht._layouts.clear()
            await dht.fix_layout("/old")
            dht._layouts.clear()
            layout2, auth2 = await dht._dir_meta("/old")
            assert layout2 and auth2
        finally:
            await c.unmount()

    asyncio.run(run())


def test_locate_real_sees_optimize_pruned_file_and_walk_fixes_it(tmp_path):
    """A file created through a stale parent layout sits misplaced
    with no linkto; cluster.lookup-optimize (default on) prunes it
    into ENOENT for serving lookups — _locate_real still finds it and
    the migrate walk moves it home, restoring visibility."""
    from glusterfs_tpu.api.glfs import Client

    async def run():
        c = Client(Graph.construct(_volfile(tmp_path)))
        await c.mount()
        try:
            dht = c.graph.top
            assert dht.opts["lookup-optimize"]
            await c.mkdir("/d")  # stamps an authoritative layout
            name = None
            for i in range(64):
                if await dht._placed(Loc(f"/d/x{i}")) != 1:
                    name = f"x{i}"
                    break
            assert name is not None
            # plant the file on a NON-owner child, no linkto anywhere
            fd, _ = await dht.children[1].create(Loc(f"/d/{name}"), 0,
                                                 0o644, {})
            await dht.children[1].writev(fd, b"stale-routed" * 64, 0)
            owner = await dht._placed(Loc(f"/d/{name}"))
            assert owner != 1
            # serving resolution prunes it invisible...
            with pytest.raises(FopError):
                await dht._cached_idx(Loc(f"/d/{name}"))
            # ...the migrator's resolution does not
            idx, ia = await dht._locate_real(Loc(f"/d/{name}"))
            assert idx == 1 and ia.size == len(b"stale-routed") * 64
            # and one migrate pass restores serving visibility
            reb = Rebalancer(c, "v", mode="drain")  # migrate-only walk
            out = await reb.run()
            assert out["counters"]["moved"] >= 1, out
            assert out["counters"]["failed"] == 0, out
            assert bytes(await c.read_file(f"/d/{name}")) \
                == b"stale-routed" * 64
        finally:
            await c.unmount()

    asyncio.run(run())


# -- checkpoint safety against glusterd (unit) ------------------------------


def test_checkpoint_reuse_guarded_by_topology(tmp_path):
    """stop -> start continues from the stop's checkpoint ONLY under
    the same topology fingerprint: a checkpoint taken before add-brick
    would skip fix-layout for the new leg, so a grown volume restarts
    the walk instead of resuming."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd

    d = Glusterd(str(tmp_path / "gd"))
    vol = {"name": "v", "status": "created", "version": 1,
           "bricks": [{"name": "n:/b0"}, {"name": "n:/b1"}]}
    d.state["volumes"] = {"v": vol}
    ck = {"phase": "migrate", "dir": ["0", "3"],
          "counters": {"moved": 3, "scanned": 9}}
    vol["rebalance"] = {"status": "stopped", "mode": "full",
                        "checkpoint": ck,
                        "topology": d._rebal_topology(vol)}
    # same topology: the stop's checkpoint rides into the new run
    d.commit_rebalance_start("v", "full", "peer-uuid", 1.0)
    assert vol["rebalance"]["checkpoint"] == ck
    # grow the volume between stop and start ...
    vol["rebalance"]["status"] = "stopped"
    vol["bricks"].append({"name": "n:/b2"})
    # ... and the stale checkpoint must NOT steer the restarted run
    d.commit_rebalance_start("v", "full", "peer-uuid", 2.0)
    assert "checkpoint" not in vol["rebalance"]
    # drain fingerprints too: same bricks, different leaver set
    vol["rebalance"] = {"status": "stopped", "mode": "drain",
                        "checkpoint": ck,
                        "topology": d._rebal_topology(vol)}
    vol["remove-brick"] = {"bricks": ["n:/b2"]}
    d.commit_rebalance_start("v", "drain", "peer-uuid", 3.0)
    assert "checkpoint" not in vol["rebalance"]


def test_kill_rebalanced_harvests_statusfile_checkpoint(tmp_path):
    """SIGTERM stop: the daemon's final rebalance-update cannot land
    while glusterd blocks in wait(), so _kill_rebalanced harvests the
    daemon's statusfile snapshot into the volinfo (this is what keeps
    the stop-continues-from-the-stop's-checkpoint contract)."""
    import json
    import subprocess
    import sys

    from glusterfs_tpu.mgmt.glusterd import Glusterd

    d = Glusterd(str(tmp_path / "gd"))
    vol = {"name": "v", "status": "started", "version": 1,
           "bricks": [{"name": "n:/b0"}],
           "rebalance": {"status": "started", "mode": "full",
                         "node": d.uuid}}
    d.state["volumes"] = {"v": vol}
    snap = {"phase": "migrate",
            "checkpoint": {"phase": "migrate", "dir": ["0", "3"]},
            "counters": {"moved": 7, "scanned": 21}}
    with open(os.path.join(d.workdir, "rebalanced-v.json"), "w") as f:
        json.dump(snap, f)
    # stand-in daemon: alive until terminate() reaps it
    d.rebalanced["v"] = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"])
    d._kill_rebalanced("v")
    rb = vol["rebalance"]
    assert rb["checkpoint"] == snap["checkpoint"]
    assert rb["counters"]["moved"] == 7
    assert rb["phase"] == "migrate"
    # a COMPLETED record is never clobbered by a stale statusfile
    rb["status"] = "completed"
    rb["counters"] = {"moved": 8}
    d._harvest_rebal_statusfile("v")
    assert rb["counters"] == {"moved": 8}


def test_opversion_13_gates_rebalance_and_drain(tmp_path):
    """Both daemon-riding ops refuse below cluster op-version 13: a
    v12 peer has neither the rebalance-start commit nor the
    rebalance-update RPC, and remove-brick start failing mid-txn-pair
    would strand the decommission 'started' with no daemon draining
    it."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtError

    d = Glusterd(str(tmp_path / "gd"))
    d.state["volumes"] = {"v": {
        "name": "v", "status": "started", "version": 1,
        "type": "distribute",
        "bricks": [{"name": "n:/b0"}, {"name": "n:/b1"}]}}
    d.cluster_op_version = lambda: 12

    async def run():
        # the gate re-handshakes before refusing; stub the poll
        async def noop():
            return None
        d._refresh_peers = noop
        with pytest.raises(MgmtError, match="op-version >= 13"):
            await d.op_volume_rebalance("v", action="start")
        # refused BEFORE brick validation or any txn: the record
        # stays untouched
        with pytest.raises(MgmtError, match="op-version >= 13"):
            await d.op_volume_remove_brick("v", ["n:/b0"],
                                           action="start")
        assert "remove-brick" not in d.state["volumes"]["v"]
        assert "rebalance" not in d.state["volumes"]["v"]

    asyncio.run(run())


def test_fresh_spawn_drops_stale_statusfile(tmp_path):
    """A FRESH rebalance run must not inherit the previous run's
    statusfile: the daemon writes it only at its first push (after
    the mount settles), so a stop before that would harvest the OLD
    run's checkpoint into the new record — under the new record's
    own topology stamp, where the fingerprint guard cannot catch
    it."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd

    d = Glusterd(str(tmp_path / "gd"))
    vol = {"name": "v", "status": "started", "version": 1,
           "bricks": [{"name": "n:/b0"}],
           "rebalance": {"status": "started", "mode": "full",
                         "node": d.uuid}}
    d.state["volumes"] = {"v": vol}
    sf = os.path.join(d.workdir, "rebalanced-v.json")
    with open(sf, "w") as f:
        f.write('{"checkpoint": {"phase": "migrate", "dir": ["9"]}}')
    try:
        d._spawn_rebalanced(vol)  # fresh: no checkpoint in the record
        assert not os.path.exists(sf), "stale statusfile survived"
        d.rebalanced.pop("v").kill()
        # a RESUME keeps the file: the volinfo checkpoint is
        # authoritative and the snapshot belongs to this same run
        vol["rebalance"]["checkpoint"] = {"phase": "migrate",
                                          "dir": ["0"]}
        with open(sf, "w") as f:
            f.write("{}")
        d._spawn_rebalanced(vol)
        assert os.path.exists(sf)
    finally:
        p = d.rebalanced.pop("v", None)
        if p is not None:
            p.kill()
