"""FUSE bridge: real kernel mounts driven by real syscalls/programs —
the reference's ``.t`` black-box methodology (tests/basic/fuse/,
mount/fuse/src/fuse-bridge.c analog).  Tests skip cleanly where the
environment cannot mount FUSE (no /dev/fuse or no privilege)."""

import asyncio
import ctypes
import errno
import hashlib
import os
import shutil
import subprocess
import sys
import threading
import time

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.mount import fuse_proto as fp
from glusterfs_tpu.mount.fuse_bridge import FuseBridge

_libc = ctypes.CDLL(None, use_errno=True)


def _fuse_usable() -> bool:
    if not os.path.exists("/dev/fuse"):
        return False
    try:
        fd = os.open("/dev/fuse", os.O_RDWR)
    except OSError:
        return False
    os.close(fd)
    return True


needs_fuse = pytest.mark.skipif(not _fuse_usable(),
                                reason="/dev/fuse not usable here")

POSIX_VOL = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
"""


def test_fuse_struct_sizes():
    """Wire-layout sanity against the kernel ABI (uapi fuse.h)."""
    assert fp.IN_HEADER.size == 40
    assert fp.OUT_HEADER.size == 16
    assert fp.ATTR.size == 88
    assert fp.ENTRY_OUT.size + fp.ATTR.size == 128
    assert fp.ATTR_OUT.size + fp.ATTR.size == 104
    assert fp.INIT_OUT.size + fp.INIT_OUT_PAD == 64
    assert fp.SETATTR_IN.size == 88
    assert fp.WRITE_IN.size == 40 and fp.READ_IN.size == 40
    assert fp.KSTATFS.size == 80
    # dirent 8-alignment
    ent = fp.pack_dirent(1, 1, 8, b"abc")
    assert len(ent) % 8 == 0


class _LoopThread:
    """Run the bridge's asyncio loop off-thread so the test can issue
    real blocking syscalls against the mountpoint."""

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self._t = threading.Thread(target=self.loop.run_forever,
                                   daemon=True)
        self._t.start()

    def run(self, coro, timeout=30):
        return asyncio.run_coroutine_threadsafe(coro, self.loop) \
            .result(timeout)

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._t.join(timeout=5)


@pytest.fixture
def fuse_posix(tmp_path):
    """A kernel mount over a single posix brick graph."""
    mnt = tmp_path / "mnt"
    mnt.mkdir()
    lt = _LoopThread()

    async def setup():
        g = Graph.construct(POSIX_VOL.format(dir=tmp_path / "brick"))
        c = Client(g)
        await c.mount()
        b = FuseBridge(c, str(mnt), "testvol")
        b.mount()
        return c, b

    client, bridge = lt.run(setup())
    try:
        yield str(mnt)
    finally:
        try:
            lt.run(bridge.unmount())
            lt.run(client.unmount())
        finally:
            lt.stop()
            subprocess.run(["umount", "-l", str(mnt)],
                           stderr=subprocess.DEVNULL)


@needs_fuse
def test_fuse_file_lifecycle(fuse_posix):
    mnt = fuse_posix
    p = os.path.join(mnt, "f.txt")
    with open(p, "w") as f:
        f.write("line one\n")
    with open(p, "a") as f:
        f.write("line two\n")
    assert open(p).read() == "line one\nline two\n"
    st = os.stat(p)
    assert st.st_size == 18
    os.chmod(p, 0o600)
    assert os.stat(p).st_mode & 0o777 == 0o600
    os.truncate(p, 9)
    assert open(p).read() == "line one\n"
    os.unlink(p)
    assert not os.path.exists(p)


@needs_fuse
def test_fuse_namespace_ops(fuse_posix):
    mnt = fuse_posix
    os.makedirs(f"{mnt}/a/b")
    with open(f"{mnt}/a/b/deep", "w") as f:
        f.write("x" * 1000)
    os.rename(f"{mnt}/a/b", f"{mnt}/moved")
    assert open(f"{mnt}/moved/deep").read() == "x" * 1000
    os.symlink("deep", f"{mnt}/moved/ln")
    assert os.readlink(f"{mnt}/moved/ln") == "deep"
    assert open(f"{mnt}/moved/ln").read() == "x" * 1000
    os.link(f"{mnt}/moved/deep", f"{mnt}/hard")
    assert os.stat(f"{mnt}/hard").st_ino == \
        os.stat(f"{mnt}/moved/deep").st_ino
    assert sorted(os.listdir(mnt)) == ["a", "hard", "moved"]
    assert sorted(os.listdir(f"{mnt}/moved")) == ["deep", "ln"]
    sv = os.statvfs(mnt)
    assert sv.f_blocks > 0
    shutil.rmtree(f"{mnt}/a")
    os.unlink(f"{mnt}/hard")


@needs_fuse
def test_fuse_xattrs(fuse_posix):
    mnt = fuse_posix
    p = os.path.join(mnt, "x")
    open(p, "w").close()
    os.setxattr(p, "user.tag", b"hello")
    assert os.getxattr(p, "user.tag") == b"hello"
    assert b"user.tag" in b"\0".join(
        n.encode() for n in os.listxattr(p)) + b"\0"
    os.removexattr(p, "user.tag")
    with pytest.raises(OSError):
        os.getxattr(p, "user.tag")
    # setxattr(2) flag semantics survive the trip through the graph
    with pytest.raises(OSError) as ei:
        os.setxattr(p, "user.miss", b"v", os.XATTR_REPLACE)
    assert ei.value.errno == errno.ENODATA
    os.setxattr(p, "user.once", b"1", os.XATTR_CREATE)
    with pytest.raises(OSError) as ei:
        os.setxattr(p, "user.once", b"2", os.XATTR_CREATE)
    assert ei.value.errno == errno.EEXIST


@needs_fuse
def test_fuse_shell_programs(fuse_posix):
    """Black-box: real programs do I/O through the mount (the .t style)."""
    mnt = fuse_posix
    r = subprocess.run(
        ["sh", "-ec", f"""
        cd {mnt}
        mkdir -p w
        seq 1 500 > w/numbers
        cp w/numbers w/copy
        cmp w/numbers w/copy
        grep -c 250 w/numbers
        dd if=/dev/urandom of=w/rand bs=65536 count=4 2>/dev/null
        cp w/rand w/rand2 && cmp w/rand w/rand2
        rm w/rand2
        ls w | sort | tr '\\n' ' '
        """],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert "copy numbers rand" in r.stdout


@pytest.mark.slow
@needs_fuse
def test_e2e_fuse_disperse_degraded(tmp_path):
    """Mount a managed 4+2 disperse volume through the kernel via the
    gftpu-fuse daemon, write under full strength, kill a brick, and
    verify reads AND writes still work degraded through the mount
    (ec-read-policy.t / ec.t workloads, kernel edition)."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

    mnt = tmp_path / "mnt"
    mnt.mkdir()
    ready = tmp_path / "fuse.ready"

    async def admin(call, **kw):
        d = admin.d
        async with MgmtClient(d.host, d.port) as c:
            return await c.call(call, **kw)

    async def setup():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        admin.d = d
        async with MgmtClient(d.host, d.port) as c:
            bricks = [{"path": str(tmp_path / f"b{i}")} for i in range(6)]
            await c.call("volume-create", name="fv", vtype="disperse",
                         bricks=bricks, redundancy=2)
            await c.call("volume-start", name="fv")
        return d

    lt = _LoopThread()
    d = lt.run(setup())
    from tests.harness import spawn_fuse, stop_fuse

    fuse_proc = spawn_fuse(f"{d.host}:{d.port}", "fv", str(ready),
                           str(mnt))
    try:

        blob = os.urandom(1 << 20)
        with open(mnt / "big", "wb") as f:
            f.write(blob)
        assert hashlib.sha1((mnt / "big").read_bytes()).digest() == \
            hashlib.sha1(blob).digest()

        # degrade: kill one brick, then read AND write through the mount
        lt.run(admin("volume-brick", name="fv",
                     brick="fv-brick-0",
                     action="stop"))
        time.sleep(0.5)
        assert (mnt / "big").read_bytes() == blob
        blob2 = os.urandom(256 << 10)
        with open(mnt / "degraded", "wb") as f:
            f.write(blob2)
        assert (mnt / "degraded").read_bytes() == blob2

        # revive and let the self-heal surface repair the stale brick
        lt.run(admin("volume-brick", name="fv",
                     brick="fv-brick-0",
                     action="start"))
        time.sleep(1.0)
        lt.run(admin("volume-heal", name="fv", action="full"))
        assert (mnt / "degraded").read_bytes() == blob2
    finally:
        stop_fuse(fuse_proc, str(mnt))

        async def teardown():
            await admin.d.stop()
        lt.run(teardown())
        lt.stop()
