"""End-to-end observability (ISSUE 4): log-bucket latency histograms +
percentile math, wire-propagated trace spans (client -> server ->
posix), compound-chain span nesting, slow-fop span-tree logging,
live-downgrade peers ignoring the trace wire field, and the unified
metrics registry (families, monotonicity, .meta/metrics, the daemon
endpoint, the per-brick metrics_dump RPC)."""

import asyncio
import os

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core import tracing
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.metrics import (HIST_BUCKETS, LogHistogram,
                                        REGISTRY)
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.core import gflog

from .harness import BRICK_VOLFILE

CLIENT_VOLFILE = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume locks
end-volume
"""

# brick graph with a protocol/server top so capability options
# (trace-fops) are enforceable, plus io-stats for the RPC extras
SERVER_TOP_VOLFILE = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume locks
    type features/locks
    subvolumes posix
end-volume
volume stats
    type debug/io-stats
    subvolumes locks
end-volume
volume srv
    type protocol/server
    option trace-fops {trace}
    subvolumes stats
end-volume
"""

# the blob-lane monotonicity test speaks the inline wire on purpose:
# with the same-host shm lane armed (default on, op-ver 17) payload
# blobs ride the arenas and gftpu_wire_blob_stats legitimately stays
# flat — the lane's own counters are pinned in test_shm_transport.py
INLINE_CLIENT_VOLFILE = CLIENT_VOLFILE.replace(
    "end-volume", "    option shm-transport off\nend-volume")

SRV_CLIENT_VOLFILE = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume stats
end-volume
"""


async def _connect(port, volfile=CLIENT_VOLFILE):
    g = Graph.construct(volfile.format(port=port))
    c = Client(g)
    await c.mount()
    for _ in range(200):
        if g.top.connected:
            break
        await asyncio.sleep(0.05)
    assert g.top.connected
    return c, g


# -- histogram math --------------------------------------------------------

def test_histogram_percentiles_known_samples():
    """Percentile math against a known sample set: bucket i holds
    [2^(i-1), 2^i) µs and percentile() reports the bucket's UPPER
    bound in seconds."""
    h = LogHistogram()
    # 90 samples of ~3µs (bucket 2: (2,4]µs upper bound 4µs) and 10 of
    # ~1000µs (bucket 10: (512,1024]µs upper bound 1024µs)
    for _ in range(90):
        h.record(3e-6)
    for _ in range(10):
        h.record(1000e-6)
    assert h.total == 100
    assert h.percentile(50) == pytest.approx(4e-6)
    assert h.percentile(90) == pytest.approx(4e-6)
    assert h.percentile(99) == pytest.approx(1024e-6)
    # empty histogram: percentiles are 0, not a crash
    assert LogHistogram().percentile(50) == 0.0


def test_histogram_bucket_edges_and_merge():
    h = LogHistogram()
    h.record(0.0)            # sub-µs -> bucket 0
    h.record(1e-6)           # 1µs -> bit_length(1)=1 -> bucket 1
    h.record(1e6)            # absurdly slow -> clamped to last bucket
    assert h.buckets[0] == 1 and h.buckets[1] == 1
    assert h.buckets[HIST_BUCKETS - 1] == 1
    other = LogHistogram()
    other.record(3e-6)
    h.merge(other)
    assert h.total == 4 and h.buckets[2] == 1


def test_fop_stats_percentiles_surface(tmp_path):
    """p50/p90/p99 show up in layer stats -> statedump -> io-stats
    profile (the volume-profile feed)."""
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume stats
    type debug/io-stats
    subvolumes posix
end-volume
"""
    async def run():
        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        await c.write_file("/f", b"x" * 1000)
        st = g.by_name["stats"]
        prof = st.profile()
        assert "latency_p50" in prof["fops"]["writev"]
        assert prof["fops"]["writev"]["latency_p99"] >= \
            prof["fops"]["writev"]["latency_p50"] > 0
        dump = g.by_name["posix"].statedump()
        assert "latency_p50" in dump["stats"]["writev"]
        await c.unmount()

    asyncio.run(run())


def test_latency_measurement_gates_histograms(tmp_path):
    """io-stats latency-measurement off: count/avg/max keep counting,
    the histograms stop (and the option re-arms live)."""
    from glusterfs_tpu.core import layer as layer_mod

    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume stats
    type debug/io-stats
    option latency-measurement off
    subvolumes posix
end-volume
"""
    async def run():
        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        try:
            assert layer_mod.HISTOGRAMS_ENABLED is False
            await c.write_file("/f", b"x")
            st = g.by_name["posix"].stats["writev"]
            assert st.count > 0 and st.hist.total == 0
            assert "latency_p50" not in st.to_dict()
            g.by_name["stats"].reconfigure({"latency-measurement": "on"})
            assert layer_mod.HISTOGRAMS_ENABLED is True
            await c.write_file("/g", b"x")
            assert g.by_name["posix"].stats["writev"].hist.total > 0
        finally:
            layer_mod.HISTOGRAMS_ENABLED = True
            await c.unmount()

    asyncio.run(run())


def test_dark_process_survives_iostats_init(tmp_path):
    """GFTPU_NO_OBSERVABILITY darkening must WIN over io-stats init:
    latency-measurement defaults 'on', and mounting a graph with an
    io-stats layer must not re-arm histograms on a darkened process
    (the bench metrics-off pass mounts volumes mid-pass)."""
    from glusterfs_tpu.core import layer as layer_mod

    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume stats
    type debug/io-stats
    subvolumes posix
end-volume
"""
    async def run():
        tracing.DARK = True
        layer_mod.HISTOGRAMS_ENABLED = False
        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        try:
            assert layer_mod.HISTOGRAMS_ENABLED is False
            await c.write_file("/f", b"x")
            assert g.by_name["posix"].stats["writev"].hist.total == 0
        finally:
            tracing.DARK = False
            layer_mod.HISTOGRAMS_ENABLED = True
            await c.unmount()

    asyncio.run(run())


# -- trace propagation -----------------------------------------------------

def test_trace_propagation_client_server_posix(tmp_path):
    """One wire readv = ONE trace id spanning the client graph, the
    brick dispatch and storage/posix (>= 3 spans), visible in
    statedump."""
    async def run():
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        c, g = await _connect(server.port)
        try:
            await c.write_file("/x", b"payload" * 1024)
            tracing.SPANS.clear()
            assert await c.read_file("/x") == b"payload" * 1024
            spans = list(tracing.SPANS)
            readv = [s for s in spans if s[3] == "readv"]
            tids = {s[0] for s in readv}
            assert len(tids) == 1, readv
            layers = {s[2] for s in readv}
            # client graph (c0), brick graph (locks), storage (posix)
            assert {"c0", "locks", "posix"} <= layers
            assert len(readv) >= 3
            # the root is the client layer; brick spans nest deeper
            by_layer = {s[2]: s[1] for s in readv}
            assert by_layer["c0"] == 0
            assert by_layer["posix"] > by_layer["locks"] > 0
            # statedump surfaces the ring
            dumped = g.statedump()["trace_spans"]
            assert any(d["op"] == "readv" and d["layer"] == "posix"
                       for d in dumped)
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_compound_chain_single_trace(tmp_path):
    """One compound chain = one trace: the chain's outermost compound
    call is the root span and every link is a child span under the
    same id."""
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume stats
    type debug/io-stats
    subvolumes posix
end-volume
"""
    async def run():
        from glusterfs_tpu.core.layer import Loc
        from glusterfs_tpu.rpc import compound as cfop

        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        try:
            tracing.SPANS.clear()
            replies = await g.top.compound([
                ("create", (Loc("/f"), os.O_RDWR, 0o644), {}),
                ("writev", (cfop.FdRef(0), b"abc", 0), {}),
                ("flush", (cfop.FdRef(0),), {}),
                ("release", (cfop.FdRef(0),), {})])
            assert cfop.first_error(replies) is None
            spans = list(tracing.SPANS)
            roots = [s for s in spans if s[1] == 0]
            assert len(roots) == 1 and roots[0][3] == "compound"
            tid = roots[0][0]
            assert all(s[0] == tid for s in spans), spans
            link_ops = {s[3] for s in spans if s[1] > 0}
            assert {"create", "writev", "flush"} <= link_ops
        finally:
            await c.unmount()

    asyncio.run(run())


def test_slow_fop_threshold_logs_tree(tmp_path):
    """A root fop slower than diagnostics.slow-fop-threshold logs its
    full span tree (and bumps the slow-fop counter)."""
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume slow
    type debug/delay-gen
    option delay-duration 20000
    option delay-percentage 100
    option enable writev
    subvolumes posix
end-volume
volume stats
    type debug/io-stats
    option slow-fop-threshold 0.005
    subvolumes slow
end-volume
"""
    async def run():
        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        try:
            before = sum(tracing.SLOW_FOP_COUNTS.values())
            await c.write_file("/f", b"x")
            assert sum(tracing.SLOW_FOP_COUNTS.values()) > before
            # the counter is labeled {layer,op}: the slow write must
            # be attributed to a concrete layer+op pair
            assert any(op == "writev"
                       for (_, op) in tracing.SLOW_FOP_COUNTS)
            logs = "\n".join(gflog.recent_messages(50))
            assert "slow fop" in logs
            # the logged tree names the layer below (where time went)
            assert "slow.writev" in logs or "posix.writev" in logs
        finally:
            tracing.SLOW_FOP_THRESHOLD = 0.0
            await c.unmount()

    asyncio.run(run())


def test_live_downgrade_peer_ignores_trace_field(tmp_path):
    """A brick with diagnostics.trace-propagation off never advertises
    trace at SETVOLUME: the client sends bare 3-element frames, I/O
    keeps working, and brick-side spans mint their OWN ids instead of
    joining the client's."""
    async def run():
        server = await serve_brick(SERVER_TOP_VOLFILE.format(
            dir=tmp_path / "b", trace="off"))
        c, g = await _connect(server.port, SRV_CLIENT_VOLFILE)
        try:
            assert g.top._peer_trace is False
            await c.write_file("/x", b"data" * 2048)
            tracing.SPANS.clear()
            assert await c.read_file("/x") == b"data" * 2048
            readv = [s for s in list(tracing.SPANS) if s[3] == "readv"]
            client_tids = {s[0] for s in readv if s[2] == "c0"}
            brick_tids = {s[0] for s in readv if s[2] == "posix"}
            assert client_tids and brick_tids
            assert not (client_tids & brick_tids)
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_trace_enabled_peer_joins(tmp_path):
    """Counter-case to the downgrade test: with the server option on
    (the default) the brick's posix spans carry the client's id."""
    async def run():
        server = await serve_brick(SERVER_TOP_VOLFILE.format(
            dir=tmp_path / "b", trace="on"))
        c, g = await _connect(server.port, SRV_CLIENT_VOLFILE)
        try:
            assert g.top._peer_trace is True
            await c.write_file("/x", b"data" * 2048)
            tracing.SPANS.clear()
            await c.read_file("/x")
            readv = [s for s in list(tracing.SPANS) if s[3] == "readv"]
            client_tids = {s[0] for s in readv if s[2] == "c0"}
            brick_tids = {s[0] for s in readv if s[2] == "posix"}
            assert client_tids & brick_tids
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_trace_fops_toggles_live(tmp_path):
    """The client's trace-fops option is read per-call: a live
    volume-set of diagnostics.trace-propagation off stops the wire
    field without a reconnect (the compound-fops pattern)."""
    async def run():
        server = await serve_brick(SERVER_TOP_VOLFILE.format(
            dir=tmp_path / "b", trace="on"))
        c, g = await _connect(server.port, SRV_CLIENT_VOLFILE)
        try:
            await c.write_file("/x", b"live" * 2048)
            g.top.reconfigure({"trace-fops": "off"})
            tracing.SPANS.clear()
            await c.read_file("/x")
            readv = [s for s in list(tracing.SPANS) if s[3] == "readv"]
            client_tids = {s[0] for s in readv if s[2] == "c0"}
            brick_tids = {s[0] for s in readv if s[2] == "posix"}
            assert client_tids and brick_tids
            assert not (client_tids & brick_tids)  # field stopped
            g.top.reconfigure({"trace-fops": "on"})
            tracing.SPANS.clear()
            await c.read_file("/x")
            readv = [s for s in list(tracing.SPANS) if s[3] == "readv"]
            assert {s[0] for s in readv if s[2] == "c0"} & \
                {s[0] for s in readv if s[2] == "posix"}
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_span_ring_bounded():
    tracing.set_ring_size(64)
    try:
        for i in range(500):
            tracing.SPANS.append(("t", 0, "l", "op", 0.0, 0.0, False))
        assert len(tracing.SPANS) == 64
    finally:
        tracing.set_ring_size(4096)


# -- unified metrics registry ----------------------------------------------

def test_registry_families_present_and_monotonic(tmp_path):
    """The acceptance families: decode-program cache events and
    wire.blob_stats, present in the render and monotonic across wire
    traffic."""
    from glusterfs_tpu.ops import gf256

    # touch the decode-program cache so the family has real counts
    gf256.decode_program(4, (0, 1, 2, 4))
    gf256.decode_program(4, (0, 1, 2, 4))

    def family_value(snap, name, **labels):
        total = 0
        for lbl, v in snap[name]["samples"]:
            if all(lbl.get(k) == val for k, val in labels.items()):
                total += v
        return total

    async def run():
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        c, _g = await _connect(server.port, INLINE_CLIENT_VOLFILE)
        try:
            snap0 = REGISTRY.snapshot()
            assert "gftpu_wire_blob_stats" in snap0
            assert "gftpu_decode_program_cache_events_total" in snap0
            assert family_value(
                snap0, "gftpu_decode_program_cache_events_total",
                cache="decode", event="hits") >= 1
            await c.write_file("/m", b"z" * 65536)
            await c.read_file("/m")
            snap1 = REGISTRY.snapshot()
            b0 = family_value(snap0, "gftpu_wire_blob_stats",
                              counter="tx_bytes")
            b1 = family_value(snap1, "gftpu_wire_blob_stats",
                              counter="tx_bytes")
            assert b1 > b0
            text = REGISTRY.render()
            assert "# TYPE gftpu_wire_blob_stats counter" in text
            assert 'counter="tx_bytes"' in text
        finally:
            await c.unmount()
            await server.stop()

    asyncio.run(run())


def test_registry_collector_isolation():
    """A raising collector loses only its own family."""
    REGISTRY.register("gftpu_test_bad", "gauge", "boom",
                      lambda: (_ for _ in ()).throw(RuntimeError()))
    try:
        snap = REGISTRY.snapshot()
        assert "gftpu_test_bad" not in snap
        assert "gftpu_wire_blob_stats" in snap
    finally:
        REGISTRY.unregister("gftpu_test_bad")


def test_metrics_dump_rpc_and_daemon_endpoint(tmp_path):
    """metrics_dump resolves by graph walk over the wire (the `gftpu
    volume metrics` backend), and the daemon's opt-in HTTP endpoint
    serves the same text dump."""
    async def run():
        from glusterfs_tpu.daemon import serve_metrics

        server = await serve_brick(SERVER_TOP_VOLFILE.format(
            dir=tmp_path / "b", trace="on"))
        c, g = await _connect(server.port, SRV_CLIENT_VOLFILE)
        msrv = await serve_metrics("127.0.0.1", 0)
        try:
            snap = await g.top.remote("metrics_dump")
            assert "gftpu_wire_blob_stats" in snap
            assert snap["gftpu_wire_blob_stats"]["type"] == "counter"
            mport = msrv.sockets[0].getsockname()[1]
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", mport)
            writer.write(b"GET /metrics HTTP/1.0\r\n\r\n")
            await writer.drain()
            body = await reader.read()
            writer.close()
            assert b"200 OK" in body
            assert b"gftpu_wire_blob_stats" in body
        finally:
            msrv.close()
            await c.unmount()
            await server.stop()

    asyncio.run(run())


# -- satellite regressions -------------------------------------------------

def test_iostats_compound_readv_replay(tmp_path):
    """Fused read chains must not vanish from `volume profile`: an ok
    readv link's reply bytes land in read_bytes + the per-path reads
    counters (writev was handled, readv was not)."""
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume stats
    type debug/io-stats
    subvolumes posix
end-volume
"""
    async def run():
        from glusterfs_tpu.core.layer import Loc
        from glusterfs_tpu.rpc import compound as cfop

        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        try:
            await c.write_file("/f", b"0123456789")
            st = g.by_name["stats"]
            st.read_bytes = 0
            replies = await g.top.compound([
                ("lookup", (Loc("/f"),), {}),
                ("open", (Loc("/f"), os.O_RDONLY), {}),
                ("readv", (cfop.FdRef(1), 1 << 20, 0), {}),
                ("release", (cfop.FdRef(1),), {})])
            assert cfop.first_error(replies) is None
            assert st.read_bytes == 10
            rows = st.top("read")
            assert rows and rows[0]["path"] == "/f"
            assert rows[0]["read_bytes"] == 10
        finally:
            await c.unmount()

    asyncio.run(run())


def test_trace_layer_exclude_ops_reconfigure(tmp_path):
    """Live `volume set ... exclude-ops` takes effect: the excluded set
    is re-derived in reconfigure (it was frozen at init)."""
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume tr
    type debug/trace
    subvolumes posix
end-volume
"""
    async def run():
        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        try:
            tr = g.by_name["tr"]
            await c.write_file("/a", b"x")
            assert any("writev" in line for line in tr.history)
            tr.reconfigure({"exclude-ops": "writev,flush"})
            assert tr._excluded == {"writev", "flush"}
            tr.history.clear()
            await c.write_file("/b", b"x")
            assert not any("writev(" in line for line in tr.history)
        finally:
            await c.unmount()

    asyncio.run(run())


def test_iostats_dump_interval_restarts_live(tmp_path):
    """A live diagnostics.stats-dump-interval change cancels the old
    dump task and arms one on the new interval."""
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume stats
    type debug/io-stats
    subvolumes posix
end-volume
"""
    async def run():
        g = Graph.construct(vf)
        c = Client(g)
        await c.mount()
        try:
            st = g.by_name["stats"]
            assert st._dump_task is None
            st.reconfigure({"ios-dump-interval": "0.05"})
            task = st._dump_task
            assert task is not None
            for _ in range(40):  # EXPECT_WITHIN: loaded-host tolerant
                if any("stats: profile" in line
                       for line in gflog.recent_messages(50)):
                    break
                await asyncio.sleep(0.1)
            logs = "\n".join(gflog.recent_messages(50))
            assert "stats: profile" in logs
            st.reconfigure({"ios-dump-interval": "0"})
            assert st._dump_task is None
            await asyncio.sleep(0)
            assert task.cancelled() or task.done()
        finally:
            await c.unmount()

    asyncio.run(run())
