"""Grand tour: one scenario threading the major subsystems together —
a multiplexed distributed-disperse volume served through a real kernel
FUSE mount, driven by real programs, surviving brick detach and
growing live.  The closest analog of the reference's long .t flows."""

import os
import subprocess
import time

import pytest

from tests.harness import spawn_fuse, stop_fuse

needs_fuse = pytest.mark.skipif(
    not os.path.exists("/dev/fuse") or os.geteuid() != 0,
    reason="needs /dev/fuse and root")


@needs_fuse
@pytest.mark.slow
def test_grand_tour(tmp_path):
    import asyncio

    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient)

    mnt = tmp_path / "mnt"
    mnt.mkdir()

    def sh(cmd):
        r = subprocess.run(cmd, shell=True, capture_output=True,
                           text=True)
        assert r.returncode == 0, (cmd, r.stderr)
        return r.stdout

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        fuse = None
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="tour",
                             vtype="disperse",
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(3)],
                             redundancy=1)
                await c.call("volume-set", name="tour",
                             key="cluster.brick-multiplex", value="on")
                await c.call("volume-start", name="tour")
                st = await c.call("volume-status", name="tour")
                assert len({b["port"] for b in st["bricks"]}) == 1

            fuse = await asyncio.to_thread(
                spawn_fuse, f"127.0.0.1:{d.port}", "tour",
                str(tmp_path / "ready"), str(mnt))

            # real programs against the kernel mount
            await asyncio.to_thread(
                sh, f"dd if=/dev/urandom of={tmp_path}/blob bs=256K "
                    f"count=4 2>/dev/null && cp {tmp_path}/blob "
                    f"{mnt}/blob && cmp {tmp_path}/blob {mnt}/blob")
            s0 = (await asyncio.to_thread(
                sh, f"sha1sum < {mnt}/blob")).split()[0]

            # detach one mux'd brick: degraded reads keep working
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-brick", name="tour",
                             brick="tour-brick-0", action="stop")
            s1 = (await asyncio.to_thread(
                sh, f"sha1sum < {mnt}/blob")).split()[0]
            assert s1 == s0
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-brick", name="tour",
                             brick="tour-brick-0", action="start")

                # grow live into 2x(2+1) while the kernel mount serves
                await c.call("volume-add-brick", name="tour",
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(3, 6)])
            await asyncio.sleep(2)  # graph swap reaches the fuse client
            await asyncio.to_thread(
                sh, f"cmp {tmp_path}/blob {mnt}/blob")
            for i in range(8):
                await asyncio.to_thread(
                    sh, f"echo tour{i} > {mnt}/n{i} && "
                        f"grep -q tour{i} {mnt}/n{i}")
            async with MgmtClient(d.host, d.port) as c:
                st = await c.call("volume-status", name="tour")
                assert len(st["bricks"]) == 6
                assert all(b["online"] for b in st["bricks"])
        finally:
            if fuse is not None:
                await asyncio.to_thread(stop_fuse, fuse, str(mnt))
            try:
                async with MgmtClient(d.host, d.port) as c:
                    await c.call("volume-stop", name="tour")
            except Exception:
                pass
            await d.stop()

    asyncio.run(run())
