"""rchecksum: adler32 parity (numpy + jax vs zlib), the posix fop, and
AFR heal's block-skip handshake (checksum.c + afr-self-heal-data
rchecksum compare)."""

import asyncio
import os
import zlib

import numpy as np
import pytest

from glusterfs_tpu.ops import checksum as ck


def test_adler32_batch_numpy_parity():
    rng = np.random.default_rng(0)
    blocks = rng.integers(0, 256, (32, 4096), dtype=np.uint8)
    got = ck.adler32_batch_np(blocks)
    for i in range(32):
        assert got[i] == zlib.adler32(blocks[i].tobytes())


def test_adler32_batch_jax_parity():
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    for b in (512, 4096, 65536):
        blocks = rng.integers(0, 256, (8, b), dtype=np.uint8)
        got = np.asarray(ck.adler32_batch_jax(jnp.asarray(blocks)))
        for i in range(8):
            assert got[i] == zlib.adler32(blocks[i].tobytes()), b


def test_adler32_batch_native_parity():
    from glusterfs_tpu import native

    if not native.available():
        pytest.skip("no native toolchain")
    rng = np.random.default_rng(2)
    for b in (512, 4096, 65536):
        blocks = rng.integers(0, 256, (8, b), dtype=np.uint8)
        got = native.adler32_batch(blocks)
        for i in range(8):
            assert got[i] == zlib.adler32(blocks[i].tobytes()), b


def test_adler32_ladder_dispatch():
    rng = np.random.default_rng(3)
    blocks = rng.integers(0, 256, (4, 1024), dtype=np.uint8)
    want = [zlib.adler32(blocks[i].tobytes()) for i in range(4)]
    for backend in ("auto", "native", "numpy"):
        try:
            got = ck.adler32_batch(blocks, backend)
        except RuntimeError:
            continue  # rung unavailable in this environment
        assert list(got) == want, backend


def test_posix_rchecksum_fop(tmp_path):
    from glusterfs_tpu.api.glfs import Client
    from glusterfs_tpu.core.graph import Graph

    async def run():
        g = Graph.construct(
            f"volume posix\n    type storage/posix\n"
            f"    option directory {tmp_path}/b\nend-volume\n")
        c = Client(g)
        await c.mount()
        blob = os.urandom(8192)
        await c.write_file("/f", blob)
        f = await c.open("/f", os.O_RDONLY)
        out = await g.top.rchecksum(f.fd, 0, 4096)
        assert out["weak"] == zlib.adler32(blob[:4096])
        import hashlib
        assert out["strong"] == hashlib.sha256(blob[:4096]).hexdigest()
        assert out["len"] == 4096
        await f.close()
        await c.unmount()

    asyncio.run(run())


def test_afr_heal_skips_identical_blocks(tmp_path):
    """A sink that only diverged in one window gets exactly that
    window rewritten — the rchecksum handshake skips the rest."""
    from glusterfs_tpu.api.glfs import Client
    from glusterfs_tpu.core.graph import Graph

    N = 2
    vol = []
    for i in range(N):
        vol.append(f"volume b{i}\n    type storage/posix\n"
                   f"    option directory {tmp_path}/brick{i}\n"
                   f"end-volume\n")
    vol.append("volume repl\n    type cluster/replicate\n"
               "    option quorum-count 1\n"
               "    option self-heal-window-size 64K\n"
               "    subvolumes b0 b1\nend-volume\n")

    async def run():
        g = Graph.construct("\n".join(vol))
        c = Client(g)
        await c.mount()
        afr = g.top
        blob = os.urandom(512 << 10)  # 8 windows of 64K
        await c.write_file("/big", blob)
        # diverge exactly one window on b0 while b1 is down
        afr.set_child_up(1, False)
        f = await c.open("/big")
        await f.write(os.urandom(1000), 200 << 10)  # inside window 3
        await f.close()
        afr.set_child_up(1, True)
        w_before = afr.children[1].stats.get("writev")
        w_before = w_before.count if w_before else 0
        out = await afr.heal_file("/big")
        assert out["healed"] == [1]
        w_after = afr.children[1].stats["writev"].count
        # one diverged 64K window -> exactly one heal write landed
        assert w_after - w_before == 1, (w_before, w_after)
        assert await c.read_file("/big") == \
            blob[:200 << 10] + (await c.read_file("/big"))[200 << 10:]
        await c.unmount()

    asyncio.run(run())
