"""glusterd hooks (glusterd-hooks.c analog) and server quorum
(glusterd-server-quorum.c analog) behavior."""

import asyncio
import os
import stat

import pytest

from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient


def _install_hook(workdir: str, op: str, phase: str, outfile: str,
                  name: str = "S10probe.sh") -> str:
    hookdir = os.path.join(workdir, "hooks", "1", op, phase)
    os.makedirs(hookdir, exist_ok=True)
    path = os.path.join(hookdir, name)
    with open(path, "w") as f:
        f.write(f"#!/bin/sh\necho \"{name} {op} {phase} $@\" >> {outfile}\n")
    os.chmod(path, os.stat(path).st_mode | stat.S_IXUSR)
    return path


def test_hooks_run_around_volume_ops(tmp_path):
    """Pre/post hook scripts fire on create/set/delete with --volname
    and -o key=value args, in S-name order; non-executables skipped."""
    out = str(tmp_path / "hooklog")

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            for op, phase in (("create", "pre"), ("create", "post"),
                              ("set", "post"), ("delete", "pre")):
                _install_hook(d.workdir, op, phase, out)
            # ordering: a second script sorts after S10
            _install_hook(d.workdir, "create", "post", out, "S20second.sh")
            # non-executable must be skipped
            skip = os.path.join(d.workdir, "hooks", "1", "create", "post",
                                "S05noexec.sh")
            with open(skip, "w") as f:
                f.write(f"#!/bin/sh\necho NOEXEC >> {out}\n")
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="hv", vtype="distribute",
                             bricks=[{"path": str(tmp_path / "b0")}])
                await c.call("volume-set", name="hv",
                             key="performance.io-cache", value="on")
                await c.call("volume-delete", name="hv")
        finally:
            await d.stop()

    asyncio.run(run())
    with open(out) as f:
        lines = f.read().splitlines()
    assert lines[0] == "S10probe.sh create pre --volname=hv"
    assert lines[1] == "S10probe.sh create post --volname=hv"
    assert lines[2] == "S20second.sh create post --volname=hv"
    assert "S10probe.sh set post --volname=hv " \
           "-operformance.io-cache=on" in lines
    assert "S10probe.sh delete pre --volname=hv" in lines
    assert not any("NOEXEC" in l for l in lines)


@pytest.mark.slow
def test_quorum_unblocks_when_enforcement_lifted(tmp_path):
    """A quorum-fenced volume must come back when the admin disables
    enforcement (or detaches the dead peer) — not stay dark forever."""

    async def run():
        d1 = Glusterd(str(tmp_path / "gd1"))
        d1.quorum_interval = 0.3
        await d1.start()
        d2 = Glusterd(str(tmp_path / "gd2"))
        await d2.start()
        try:
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("peer-probe", host=d2.host, port=d2.port)
                await c.call("volume-create", name="uv",
                             vtype="distribute",
                             bricks=[{"node": d1.uuid,
                                      "path": str(tmp_path / "ub0")}])
                await c.call("volume-set", name="uv",
                             key="cluster.server-quorum-type",
                             value="server")
                await c.call("volume-start", name="uv")
                await d2.stop()

                async def fenced():
                    st = await c.call("volume-status", name="uv")
                    return not st["bricks"][0]["online"]

                deadline = asyncio.get_event_loop().time() + 30
                while not await fenced():
                    assert asyncio.get_event_loop().time() < deadline
                    await asyncio.sleep(0.2)
                # lift enforcement directly in the store (volume-set
                # would need the dead peer's txn-lock skip — exercised
                # elsewhere; this isolates the unblock path)
                d1.state["volumes"]["uv"]["options"][
                    "cluster.server-quorum-type"] = "none"
                deadline = asyncio.get_event_loop().time() + 30
                while await fenced():
                    assert asyncio.get_event_loop().time() < deadline, \
                        "bricks stayed fenced after enforcement lifted"
                    await asyncio.sleep(0.2)
                await c.call("volume-stop", name="uv")
        finally:
            await d1.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_server_quorum_fences_and_restores_bricks(tmp_path):
    """Two-node cluster, quorum-enforcing volume: losing the peer kills
    the local bricks; the peer coming back respawns them on the same
    port (glusterd-server-quorum.c semantics)."""

    async def brick_online(c, vol="qv"):
        st = await c.call("volume-status", name=vol)
        return all(b["online"] for b in st["bricks"]
                   if b["node"] == st["bricks"][0]["node"])

    async def wait_for(pred, timeout=30.0):
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            if await pred():
                return True
            if asyncio.get_event_loop().time() > deadline:
                return False
            await asyncio.sleep(0.2)

    async def run():
        d1 = Glusterd(str(tmp_path / "gd1"))
        d1.quorum_interval = 0.3
        await d1.start()
        d2 = Glusterd(str(tmp_path / "gd2"))
        await d2.start()
        d2_port = d2.port
        try:
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("peer-probe", host=d2.host, port=d2.port)
                await c.call("volume-create", name="qv", vtype="distribute",
                             bricks=[{"node": d1.uuid,
                                      "path": str(tmp_path / "b0")}])
                await c.call("volume-set", name="qv",
                             key="cluster.server-quorum-type",
                             value="server")
                await c.call("volume-start", name="qv")
                assert await brick_online(c)
                port0 = (await c.call(
                    "volume-status", name="qv"))["bricks"][0]["port"]

                # partition: peer glusterd goes away -> 1/2 alive < 51%
                await d2.stop()
                assert await wait_for(
                    lambda: _not(brick_online(c))), "brick not fenced"

                # peer returns on its recorded endpoint -> quorum back
                d2b = Glusterd(str(tmp_path / "gd2"), port=d2_port)
                await d2b.start()
                try:
                    async def restored():
                        st = await c.call("volume-status", name="qv")
                        b = st["bricks"][0]
                        return b["online"] and b["port"] != 0

                    assert await wait_for(restored), "brick not restored"
                    port1 = (await c.call(
                        "volume-status", name="qv"))["bricks"][0]["port"]
                    assert port1 == port0, "restore must reuse the port"
                    await c.call("volume-stop", name="qv")
                finally:
                    await d2b.stop()
        finally:
            await d1.stop()

    async def _not(coro):
        return not await coro

    asyncio.run(run())


def test_op_version_gates_new_options(tmp_path):
    """Mixed-version skew guard (glusterd op-version): options newer
    than the cluster minimum are refused until every member upgrades."""

    async def run():
        d1 = Glusterd(str(tmp_path / "g1"))
        await d1.start()
        d2 = Glusterd(str(tmp_path / "g2"))
        d2.op_version = 1  # an old build in the cluster
        await d2.start()
        try:
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("peer-probe", host=d2.host, port=d2.port)
                await c.call("volume-create", name="ov",
                             vtype="distribute",
                             bricks=[{"path": str(tmp_path / "ob")}])
                # a v2 option is refused while a v1 member exists
                try:
                    await c.call("volume-set", name="ov",
                                 key="cluster.brick-multiplex",
                                 value="on")
                    raise AssertionError("v2 option accepted at v1")
                except Exception as e:
                    assert "op-version" in str(e), e
                # v1 options still work
                await c.call("volume-set", name="ov",
                             key="performance.io-cache", value="on")
            # the old member leaves: cluster rises to v2
            d1.state["peers"] = {u: p for u, p in
                                 d1.state["peers"].items()
                                 if p["uuid"] != d2.uuid}
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("volume-set", name="ov",
                             key="cluster.brick-multiplex", value="on")
        finally:
            await d2.stop()
            await d1.stop()

    asyncio.run(run())
