"""Failure-containment plane (ISSUE 9): lock revocation
(features.locks-revocation-*), disconnect failfast, per-brick circuit
breakers, deadline-budget shedding, deterministic error-gen, and the
clear-locks operator surface."""

import asyncio
import errno
import os
import sys
import time

import pytest

from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc, walk

sys.path.insert(0, os.path.dirname(__file__))
from harness import BrickProc  # noqa: E402

LOCKS_VOL = """
volume posix
    type storage/posix
    option directory {d}
end-volume

volume locks
    type features/locks
{opts}    subvolumes posix
end-volume
"""


def _locks_graph(tmp_path, **options):
    opts = "".join(f"    option {k} {v}\n" for k, v in options.items())
    g = Graph.construct(LOCKS_VOL.format(d=tmp_path / "brick", opts=opts))
    return g


# ---------------------------------------------------------------------------
# revocation: the scenario pins of the acceptance criteria
# ---------------------------------------------------------------------------


def test_revocation_secs_inodelk_waiters_drain(tmp_path):
    """A wedged inodelk holder is revoked within revocation-secs and
    EVERY blocked waiter is granted — the queue drains to empty."""
    g = _locks_graph(tmp_path, **{"revocation-secs": "0.4"})

    async def run():
        await g.activate()
        locks = g.by_name["locks"]
        loc = Loc("/")
        await locks.inodelk("dom", loc, "lock", "wr", 0, -1,
                            {"lk-owner": b"WEDGED"})
        t0 = asyncio.get_event_loop().time()
        # several rd waiters park behind the wedged wr holder
        waiters = [asyncio.create_task(
            locks.inodelk("dom", loc, "lock", "rd", 0, -1,
                          {"lk-owner": bytes([65 + i])}))
            for i in range(3)]
        await asyncio.wait_for(asyncio.gather(*waiters), 5)
        dt = asyncio.get_event_loop().time() - t0
        assert 0.2 < dt < 2.0, dt  # within revocation-secs order
        st = locks.lock_status()
        assert st["blocked"]["inodelk"] == 0  # queue drained to empty
        assert locks.revoked_counts.get("age") == 1
        # the revoked owner's NEXT lock fop: EAGAIN + notice in xdata
        with pytest.raises(FopError) as ei:
            await locks.inodelk("dom", loc, "lock-nb", "wr", 0, -1,
                                {"lk-owner": b"WEDGED"})
        assert ei.value.err == errno.EAGAIN
        note = (ei.value.xdata or {}).get("lock-revoked")
        assert note and note["reason"] == "age" and \
            note["domain"] == "dom"
        # the notice is one-shot: the owner may take fresh locks after
        await locks.inodelk("dom", loc, "unlock", "rd", 0, -1,
                            {"lk-owner": b"A"})
        await g.fini()

    asyncio.run(run())


def test_revocation_secs_entrylk(tmp_path):
    """The entrylk twin of the revocation machinery (reference
    entrylk.c:129-173)."""
    g = _locks_graph(tmp_path, **{"revocation-secs": "0.3"})

    async def run():
        await g.activate()
        locks = g.by_name["locks"]
        loc = Loc("/")
        await locks.entrylk("d", loc, "name", "lock", "wr",
                            {"lk-owner": b"WEDGED"})
        await asyncio.wait_for(
            locks.entrylk("d", loc, "name", "lock", "wr",
                          {"lk-owner": b"B"}), 5)
        assert locks.revoked_counts.get("age") == 1
        assert locks.lock_status()["blocked"]["entrylk"] == 0
        with pytest.raises(FopError) as ei:
            await locks.entrylk("d", loc, "name", "lock-nb", "wr",
                                {"lk-owner": b"WEDGED"})
        assert ei.value.err == errno.EAGAIN
        assert ei.value.xdata["lock-revoked"]["kind"] == "entrylk"
        await g.fini()

    asyncio.run(run())


def test_revocation_max_blocked(tmp_path):
    """The queue-depth trigger: blocked queue over max-blocked revokes
    immediately, no holder aging needed."""
    g = _locks_graph(tmp_path, **{"revocation-max-blocked": "1"})

    async def run():
        await g.activate()
        locks = g.by_name["locks"]
        loc = Loc("/")
        await locks.inodelk("d", loc, "lock", "wr", 0, -1,
                            {"lk-owner": b"H"})
        waiters = [asyncio.create_task(
            locks.inodelk("d", loc, "lock", "rd", 0, -1,
                          {"lk-owner": bytes([65 + i])}))
            for i in range(2)]
        await asyncio.wait_for(asyncio.gather(*waiters), 3)
        assert locks.revoked_counts.get("max-blocked") == 1
        await g.fini()

    asyncio.run(run())


def test_revocation_clear_all_flushes_waiters(tmp_path):
    """revocation-clear-all: the blocked queue is CLEARED (EAGAIN with
    the notice) instead of granted."""
    g = _locks_graph(tmp_path, **{"revocation-secs": "0.3",
                                  "revocation-clear-all": "on"})

    async def run():
        await g.activate()
        locks = g.by_name["locks"]
        loc = Loc("/")
        await locks.inodelk("d", loc, "lock", "wr", 0, -1,
                            {"lk-owner": b"H"})
        with pytest.raises(FopError) as ei:
            await asyncio.wait_for(
                locks.inodelk("d", loc, "lock", "rd", 0, -1,
                              {"lk-owner": b"W"}), 5)
        assert ei.value.err == errno.EAGAIN
        assert ei.value.xdata["lock-revoked"]["reason"] == "age"
        assert locks.lock_status()["blocked"]["inodelk"] == 0
        await g.fini()

    asyncio.run(run())


def test_clear_locks_kinds(tmp_path):
    """Operator clear-locks: blocked / granted / all are distinct
    sweeps over the path's domains."""
    g = _locks_graph(tmp_path)

    async def run():
        await g.activate()
        locks = g.by_name["locks"]
        loc = Loc("/")
        await locks.inodelk("d", loc, "lock", "wr", 0, -1,
                            {"lk-owner": b"H"})
        w = asyncio.create_task(
            locks.inodelk("d", loc, "lock", "wr", 0, -1,
                          {"lk-owner": b"W"}))
        await asyncio.sleep(0.05)
        # blocked only: the waiter fails EAGAIN, the holder survives
        out = await locks.clear_locks("/", "blocked")
        assert out["total"] == 1 and out["cleared"]["inodelk"] == 1
        with pytest.raises(FopError):
            await asyncio.wait_for(w, 2)
        assert len(locks._inodelk) == 1  # holder still there
        # granted: the holder goes, a new non-blocking lock succeeds
        out = await locks.clear_locks("/", "granted")
        assert out["total"] == 1
        await locks.inodelk("d2", loc, "lock-nb", "wr", 0, -1,
                            {"lk-owner": b"N"})
        out = await locks.clear_locks("/", "all")
        assert out["total"] == 1
        assert locks.dump_private()["granted"] == 0
        with pytest.raises(FopError):
            await locks.clear_locks("/", "bogus")
        await g.fini()

    asyncio.run(run())


def test_release_client_reaps_scoped_owners_and_waiters(tmp_path):
    """The disconnect reap (client_t analog): a dead client's granted
    locks — wire-scoped as identity + b"/" + lk-owner — are released
    and its parked waiters evicted, WITHOUT waiting revocation-secs."""
    g = _locks_graph(tmp_path)

    async def run():
        await g.activate()
        locks = g.by_name["locks"]
        loc = Loc("/")
        ident = b"CLIENT-A"
        # wire-shaped scoped owner (protocol/server._scope_owner)
        await locks.inodelk("d", loc, "lock", "wr", 0, -1,
                            {"lk-owner": ident + b"/o1"})
        # dead client's own parked waiter (scoped too)
        w_dead = asyncio.create_task(
            locks.inodelk("d", loc, "lock", "wr", 0, -1,
                          {"lk-owner": ident + b"/o2"}))
        # an innocent bystander behind the same lock
        w_live = asyncio.create_task(
            locks.inodelk("d", loc, "lock", "wr", 0, -1,
                          {"lk-owner": b"B"}))
        await asyncio.sleep(0.05)
        n = locks.release_client(ident)
        assert n == 1, n
        # the bystander gets the lock; the dead waiter is evicted
        await asyncio.wait_for(w_live, 2)
        with pytest.raises(FopError) as ei:
            await asyncio.wait_for(w_dead, 2)
        assert ei.value.err == errno.ENOTCONN
        await g.fini()

    asyncio.run(run())


def test_release_client_over_the_wire(tmp_path):
    """End to end: client A holds a lock through a real brick and
    DISCONNECTS; client B's blocked request is granted promptly (the
    server-side reap, not revocation, frees it)."""

    async def run():
        from glusterfs_tpu.daemon import serve_brick

        server = await serve_brick(LOCKS_VOL.format(
            d=tmp_path / "brick", opts=""))
        CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume locks
end-volume
"""

        async def connect():
            g = Graph.construct(CLIENT.format(port=server.port))
            await g.activate()
            for _ in range(200):
                if g.top.connected:
                    break
                await asyncio.sleep(0.05)
            assert g.top.connected
            return g

        ga = await connect()
        gb = await connect()
        loc = Loc("/")
        await ga.top.inodelk("d", loc, "lock", "wr", 0, -1,
                             {"lk-owner": b"o"})
        blocked = asyncio.create_task(
            gb.top.inodelk("d", loc, "lock", "wr", 0, -1,
                           {"lk-owner": b"o"}))
        await asyncio.sleep(0.3)
        assert not blocked.done()
        t0 = time.perf_counter()
        await ga.fini()  # A disconnects: the brick reaps its locks
        await asyncio.wait_for(blocked, 5)
        assert time.perf_counter() - t0 < 5
        await gb.fini()
        await server.stop()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# failfast + circuit breaker (acceptance pins)
# ---------------------------------------------------------------------------

DELAY_BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume delay
    type debug/delay-gen
    option delay-duration 8000000
    option delay-percentage 100
    option enable readv
    subvolumes posix
end-volume
volume locks
    type features/locks
    subvolumes delay
end-volume
"""

CLIENT_VOL = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
{opts}    option remote-subvolume locks
end-volume
"""


async def _wire_client(port, **options):
    opts = "".join(f"    option {k} {v}\n" for k, v in options.items())
    g = Graph.construct(CLIENT_VOL.format(port=port, opts=opts))
    await g.activate()
    for _ in range(200):
        if g.top.connected:
            break
        await asyncio.sleep(0.05)
    assert g.top.connected, "client never connected"
    return g


def test_failfast_outstanding_frames_under_1s(tmp_path):
    """Killing a brick with N outstanding frames fails ALL N in under
    a second — the saved-frames unwind, not N x call-timeout."""
    b = BrickProc(str(tmp_path), "b0", DELAY_BRICK)
    b.start()

    async def run():
        g = await _wire_client(b.port)
        cl = g.top
        fd, _ = await cl.create(Loc("/f"), os.O_CREAT | os.O_RDWR,
                                0o644)
        await cl.writev(fd, b"z" * 4096, 0)
        # 16 readvs parked in the brick's 8s delay-gen
        futs = [asyncio.ensure_future(cl.readv(fd, 16, 0))
                for _ in range(16)]
        await asyncio.sleep(0.5)
        t0 = time.perf_counter()
        b.kill()
        res = await asyncio.gather(*futs, return_exceptions=True)
        dt = time.perf_counter() - t0
        assert dt < 1.0, f"outstanding frames took {dt:.2f}s to fail"
        assert all(isinstance(r, FopError) and r.err == errno.ENOTCONN
                   for r in res)
        assert cl.failfast_drops == 0  # unwind, not timeout bail
        await g.fini()

    asyncio.run(run())


def test_circuit_opens_then_half_open_probe_closes(tmp_path):
    """The breaker lifecycle: consecutive transport failures open the
    circuit at the threshold (further fops shed immediately), and
    after the reset interval a half-open probe against the recovered
    brick closes it."""
    b = BrickProc(str(tmp_path), "b0")
    b.start()

    async def run():
        g = await _wire_client(b.port, **{
            "circuit-failure-threshold": "3",
            "circuit-reset-interval": "0.5",
            "idempotent-retries": "0"})
        cl = g.top
        await cl.create(Loc("/f"), os.O_CREAT | os.O_RDWR, 0o644)
        port = b.port
        b.kill()
        # burn the transport failures (reconnect-interval keeps trying
        # in the background; fop_call fails ENOTCONN immediately)
        for _ in range(200):
            if not cl.connected:
                break
            await asyncio.sleep(0.05)
        for _ in range(3):
            with pytest.raises(FopError):
                await cl.fop_call("stat", Loc("/f"))
        assert cl._cb_state == "open", cl._cb_state
        # open circuit sheds instantly, even the error text says so
        with pytest.raises(FopError) as ei:
            await cl.fop_call("stat", Loc("/f"))
        assert "circuit open" in str(ei.value)
        # brick returns on the same port; the next fop past the reset
        # interval is the half-open probe — wait for reconnect first
        # so the probe has a transport to prove
        b2 = BrickProc(str(tmp_path), "b0")
        b2.start(port=port)
        try:
            for _ in range(300):
                if cl.connected:
                    break
                await asyncio.sleep(0.05)
            assert cl.connected
            # handshake success already closes the circuit (the
            # reconnect-driven recovery path)
            assert cl._cb_state == "closed"
            await cl.fop_call("stat", Loc("/f"))
            await g.fini()
        finally:
            b2.kill()

    asyncio.run(run())


def test_circuit_half_open_probe_path(tmp_path):
    """The probe path proper: with the transport UP but fops failing
    transport-class (error-gen ENOTCONN), the breaker opens, then a
    half-open probe against a healed brick closes it without any
    reconnect."""
    VOL = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume errs
    type debug/error-gen
    option error-no ENOTCONN
    option failure-count {count}
    option enable stat
    subvolumes posix
end-volume
volume locks
    type features/locks
    subvolumes errs
end-volume
"""
    b = BrickProc(str(tmp_path), "b0", VOL.replace("{count}", "3"))
    b.start()

    async def run():
        g = await _wire_client(b.port, **{
            "circuit-failure-threshold": "3",
            "circuit-reset-interval": "0.3",
            "idempotent-retries": "0"})
        cl = g.top
        fd, _ = await cl.create(Loc("/f"), os.O_CREAT | os.O_RDWR,
                                0o644)
        for _ in range(3):
            with pytest.raises(FopError):
                await cl.fop_call("stat", Loc("/f"))
        assert cl._cb_state == "open"
        await asyncio.sleep(0.4)  # past the reset interval
        # error budget exhausted: the half-open probe succeeds
        await cl.fop_call("stat", Loc("/f"))
        assert cl._cb_state == "closed"
        await g.fini()

    asyncio.run(run())


def test_idempotent_retry_rides_out_transport_blip(tmp_path):
    """A read-class fop retries through a transport-class failure
    (error-gen ENOTCONN burns one attempt, the retry lands);
    write-class fops never retry."""
    VOL = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume errs
    type debug/error-gen
    option error-no ENOTCONN
    option failure-count 1
    option enable stat
    subvolumes posix
end-volume
volume locks
    type features/locks
    subvolumes errs
end-volume
"""
    b = BrickProc(str(tmp_path), "b0", VOL)
    b.start()

    async def run():
        g = await _wire_client(b.port, **{"idempotent-retries": "2"})
        cl = g.top
        await cl.create(Loc("/f"), os.O_CREAT | os.O_RDWR, 0o644)
        ia = await cl.stat(Loc("/f"))  # blip absorbed by one retry
        assert ia is not None
        assert cl.retries_total == 1, cl.retries_total
        await g.fini()

    asyncio.run(run())


def test_call_timeout_failfast_bails_transport(tmp_path):
    """A data fop hitting call-timeout drops the WHOLE transport: the
    second outstanding frame fails ENOTCONN immediately instead of
    waiting out its own deadline (the frame-timeout bail)."""
    b = BrickProc(str(tmp_path), "b0", DELAY_BRICK)
    b.start()

    async def run():
        g = await _wire_client(b.port, **{"call-timeout": "1",
                                          "idempotent-retries": "0"})
        cl = g.top
        fd, _ = await cl.create(Loc("/f"), os.O_CREAT | os.O_RDWR,
                                0o644)
        await cl.writev(fd, b"z" * 4096, 0)
        t0 = time.perf_counter()
        futs = [asyncio.ensure_future(cl.readv(fd, 16, 0))
                for _ in range(8)]
        res = await asyncio.gather(*futs, return_exceptions=True)
        dt = time.perf_counter() - t0
        # one frame ate the 1s deadline; the rest failed with it —
        # NOT 8 x 1s serially
        assert dt < 3.0, f"{dt:.2f}s: frames waited serially"
        errs = {r.err for r in res if isinstance(r, FopError)}
        assert errs <= {errno.ETIMEDOUT, errno.ENOTCONN} and errs
        assert cl.failfast_drops >= 1
        await g.fini()
        b.kill()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# deadline propagation + io-threads shedding
# ---------------------------------------------------------------------------


def test_io_threads_drops_expired_deadline(tmp_path):
    """io-threads sheds work whose client budget expired before a
    worker freed up (the abandoned-call drop)."""
    VOL = """
volume posix
    type storage/posix
    option directory {d}
end-volume
volume iot
    type performance/io-threads
    subvolumes posix
end-volume
"""
    g = Graph.construct(VOL.format(d=tmp_path / "brick"))

    async def run():
        await g.activate()
        iot = g.by_name["iot"]
        from glusterfs_tpu.rpc import wire

        loop = asyncio.get_running_loop()
        tok = wire.CURRENT_DEADLINE.set(loop.time() - 0.1)  # expired
        try:
            with pytest.raises(FopError) as ei:
                await iot.stat(Loc("/"))
            assert ei.value.err == errno.ETIMEDOUT
            assert iot.deadline_dropped == 1
        finally:
            wire.CURRENT_DEADLINE.reset(tok)
        # no deadline: passes
        await iot.stat(Loc("/"))
        assert iot.deadline_dropped == 1
        await g.fini()

    asyncio.run(run())


def test_deadline_budget_rides_the_wire(tmp_path):
    """The client's remaining budget is popped server-side and armed
    as CURRENT_DEADLINE for the request's dispatch context."""
    captured = {}

    async def run():
        from glusterfs_tpu.daemon import serve_brick
        from glusterfs_tpu.rpc import wire
        from glusterfs_tpu.storage.posix import PosixLayer

        server = await serve_brick(LOCKS_VOL.format(
            d=tmp_path / "brick", opts=""))
        g = await _wire_client(server.port, **{"call-timeout": "7"})
        real = PosixLayer.stat

        async def spy(self, loc, xdata=None):
            captured["deadline"] = wire.CURRENT_DEADLINE.get()
            captured["now"] = asyncio.get_running_loop().time()
            return await real(self, loc, xdata)

        PosixLayer.stat = spy
        try:
            assert g.top._peer_deadline  # advertised at SETVOLUME
            await g.top.stat(Loc("/"))
        finally:
            PosixLayer.stat = real
        assert captured.get("deadline") is not None, \
            "deadline never armed brick-side"
        remaining = captured["deadline"] - captured["now"]
        assert 0 < remaining <= 7.5, remaining
        # lock fops are exempt (they park legitimately)
        captured.clear()
        await g.top.inodelk("d", Loc("/"), "lock-nb", "wr", 0, -1,
                            {"lk-owner": b"o"})
        await g.fini()
        await server.stop()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# deterministic error-gen
# ---------------------------------------------------------------------------


def test_error_gen_failure_count_exact(tmp_path):
    """failure-count fails exactly the first N matching fops, then
    passes — and reconfigure re-arms the budget."""
    VOL = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
volume errs
    type debug/error-gen
    option failure-count 3
    option enable stat
    option error-no ENOSPC
    subvolumes posix
end-volume
"""
    g = Graph.construct(VOL.format(dir=tmp_path / "brick"))

    async def run():
        await g.activate()
        errs = g.by_name["errs"]
        loc = Loc("/")
        for i in range(3):
            with pytest.raises(FopError) as ei:
                await errs.stat(loc)
            assert ei.value.err == errno.ENOSPC
        for _ in range(5):
            await errs.stat(loc)  # budget spent: passes forever
        assert errs.injected == 3
        # other fops never matched
        await errs.lookup(loc)
        # reconfigure re-arms in full
        errs.reconfigure({"failure-count": "2", "enable": "stat",
                          "error-no": "ENOSPC"})
        for _ in range(2):
            with pytest.raises(FopError):
                await errs.stat(loc)
        await errs.stat(loc)
        assert errs.injected == 5
        await g.fini()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# wedge view + managed clear-locks surface
# ---------------------------------------------------------------------------


def test_lock_status_wedge_view(tmp_path):
    """dump_private / lock_status show blocked counts and oldest
    holder age BEFORE revocation fires — the operator's early
    warning."""
    g = _locks_graph(tmp_path)

    async def run():
        await g.activate()
        locks = g.by_name["locks"]
        loc = Loc("/")
        await locks.inodelk("d", loc, "lock", "wr", 0, -1,
                            {"lk-owner": b"H"})
        w = asyncio.create_task(
            locks.inodelk("d", loc, "lock", "wr", 0, -1,
                          {"lk-owner": b"W"}))
        await asyncio.sleep(0.25)
        st = locks.lock_status()
        assert st["blocked"]["inodelk"] == 1
        row = st["domains"][0]
        assert row["kind"] == "inodelk" and row["blocked"] == 1
        assert row["oldest_holder_secs"] >= 0.2
        assert row["oldest_waiter_secs"] >= 0.2
        dp = locks.dump_private()
        assert dp["blocked"]["inodelk"] == 1 and dp["domains"]
        w.cancel()
        await g.fini()

    asyncio.run(run())


@pytest.mark.slow
def test_clear_locks_managed_cli_op(tmp_path):
    """`gftpu volume clear-locks VOL path kind all` end to end: the
    glusterd op fans out to real brick subprocesses and clears a wire
    client's granted lock; the holder's next lock fop carries the
    notice."""

    async def run():
        from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                                 mount_volume)

        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="clv",
                             vtype="distribute",
                             bricks=[{"path": str(tmp_path / "b0")}])
                await c.call("volume-start", name="clv")
            m = await mount_volume(d.host, d.port, "clv")
            try:
                await m.write_file("/f", b"x" * 1024)
                top = m.graph.top
                await top.inodelk("app", Loc("/f"), "lock", "wr", 0, -1,
                                  {"lk-owner": b"wedged"})
                # the wedge is visible in callpool before clearing
                st = await d.op_volume_status_deep("clv", "callpool")
                lk = st["bricks"]["clv-brick-0"]["locks"]
                assert any(r["domains"] for r in lk), lk
                out = await d.op_volume_clear_locks("clv", "/f", "all")
                assert out["total"] == 1, out
                with pytest.raises(FopError) as ei:
                    await top.inodelk("app", Loc("/f"), "lock-nb", "wr",
                                      0, -1, {"lk-owner": b"wedged"})
                assert ei.value.err == errno.EAGAIN
                assert ei.value.xdata["lock-revoked"]["reason"] == \
                    "clear-locks"
            finally:
                await m.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


def test_circuit_families_and_lock_families_registered():
    """The containment plane's registry families are present."""
    from glusterfs_tpu.core.metrics import REGISTRY

    snap = REGISTRY.snapshot()
    for fam in ("gftpu_client_circuit_state",
                "gftpu_client_retries_total",
                "gftpu_client_failfast_total",
                "gftpu_locks_revoked_total",
                "gftpu_locks_blocked",
                "gftpu_io_threads_deadline_dropped_total"):
        assert fam in snap, f"missing family {fam}"
