"""server.outstanding-rpc-limit — inbound RPC backpressure
(rpcsvc_request_outstanding, rpcsvc.c:211-250 + rpcsvc.h:38): at the
limit the brick stops reading that client's connection, so a flooding
client's queue is bounded and a second client keeps making progress.
Lock fops are exempt (rpcsvc.c:183-208)."""

import asyncio

import pytest

from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.rpc import wire

VOLFILE = """
volume posix
    type storage/posix
    option directory {dir}
end-volume

volume locks
    type features/locks
    subvolumes posix
end-volume

volume srv
    type protocol/server
    option outstanding-rpc-limit {limit}
    subvolumes locks
end-volume
"""


class RawClient:
    """Frame-level client: lets the test flood calls without awaiting
    replies (a real client's pipelining, minus its pacing)."""

    def __init__(self):
        self.xid = 0
        self.reader = None
        self.writer = None

    async def connect(self, port):
        self.reader, self.writer = await asyncio.open_connection(
            "127.0.0.1", port)
        await self.call("__handshake__", (b"rawclient", "", {}), {})

    def send(self, fop, args, kwargs):
        self.xid += 1
        self.writer.write(wire.pack(self.xid, wire.MT_CALL,
                                    [fop, args, kwargs]))
        return self.xid

    async def recv(self):
        rec = await wire.read_frame(self.reader)
        xid, mtype, payload = wire.unpack(rec)
        return xid, payload

    async def call(self, fop, args, kwargs):
        want = self.send(fop, args, kwargs)
        await self.writer.drain()
        xid, payload = await self.recv()
        assert xid == want
        return payload

    def close(self):
        self.writer.close()


@pytest.fixture
def served(tmp_path):
    """(server, gate-controlled slow writev, concurrency tracker)."""
    box = {}

    async def setup(limit):
        server = await serve_brick(
            VOLFILE.format(dir=tmp_path / "b", limit=limit))
        release = asyncio.Event()
        stats = {"active": 0, "max": 0, "served": 0}
        orig = server.top.writev

        async def slow_writev(*a, **kw):
            stats["active"] += 1
            stats["max"] = max(stats["max"], stats["active"])
            try:
                await release.wait()
                return await orig(*a, **kw)
            finally:
                stats["active"] -= 1
                stats["served"] += 1

        server.top.writev = slow_writev
        box.update(server=server, release=release, stats=stats)
        return box

    yield setup
    if "server" in box:
        asyncio.run(box["server"].stop())


def test_flood_is_bounded_and_drains(served):
    """500 pipelined writes against limit 4: at most 4 dispatch at
    once, and every call is still answered once the brick unblocks —
    backpressure, not drop."""

    from glusterfs_tpu.core.layer import Loc

    async def run():
        box = await served(4)
        a = RawClient()
        await a.connect(box["server"].port)
        fd, _ia = await a.call("create", (Loc("/f"), 2, 0o644), {})
        n = 500
        for _ in range(n):
            a.send("writev", (fd, b"x" * 64, 0), {})
        # don't drain: the socket should jam once the server stops
        # reading.  Give the server time to admit what it will.
        await asyncio.sleep(0.5)
        assert box["stats"]["max"] <= 4
        assert box["stats"]["served"] == 0  # all parked on the gate
        admitted_early = box["stats"]["active"]
        assert admitted_early <= 4
        box["release"].set()
        got = 0
        while got < n:
            xid, payload = await asyncio.wait_for(a.recv(), 30)
            if xid > 1:  # skip create reply (already consumed)
                got += 1
        assert box["stats"]["served"] == n
        assert box["stats"]["max"] <= 4
        a.close()

    asyncio.run(run())


def test_second_client_progresses_during_flood(served):
    """Fairness: client A saturates its limit; client B's lookup on the
    same brick is answered promptly — per-client throttling, not a
    global stall."""

    from glusterfs_tpu.core.layer import Loc

    async def run():
        box = await served(2)
        a = RawClient()
        await a.connect(box["server"].port)
        fd, _ = await a.call("create", (Loc("/g"), 2, 0o644), {})
        for _ in range(50):
            a.send("writev", (fd, b"y" * 64, 0), {})
        await asyncio.sleep(0.2)
        assert box["stats"]["active"] == 2  # A parked at its limit

        b = RawClient()
        await b.connect(box["server"].port)
        ia = await asyncio.wait_for(b.call("lookup", (Loc("/g"),), {}), 5)
        assert ia is not None
        box["release"].set()
        a.close()
        b.close()

    asyncio.run(run())


def test_lock_fops_exempt_from_throttle(served):
    """With the limit saturated by parked writes, lock-class fops on the
    same connection are still read and served (rpcsvc.c:183-208: lock
    fops must never be throttled or the freeing unlock could starve)."""

    from glusterfs_tpu.core.layer import Loc

    async def run():
        box = await served(2)
        a = RawClient()
        await a.connect(box["server"].port)
        fd, _ = await a.call("create", (Loc("/h"), 2, 0o644), {})
        for _ in range(2):
            a.send("writev", (fd, b"z" * 64, 0), {})
        await asyncio.sleep(0.2)
        assert box["stats"]["active"] == 2
        # lock + unlock flow through while the write limit is full
        got = await asyncio.wait_for(
            a.call("inodelk", ("dom", Loc("/h"), "lock", "wr"), {}), 5)
        assert got is not None
        await asyncio.wait_for(
            a.call("inodelk", ("dom", Loc("/h"), "unlock", "wr"), {}), 5)
        box["release"].set()
        await a.recv()
        await a.recv()
        a.close()

    asyncio.run(run())


def test_limit_zero_is_unlimited(served):
    from glusterfs_tpu.core.layer import Loc

    async def run():
        box = await served(0)
        a = RawClient()
        await a.connect(box["server"].port)
        fd, _ = await a.call("create", (Loc("/u"), 2, 0o644), {})
        for _ in range(64):
            a.send("writev", (fd, b"w" * 8, 0), {})
        await asyncio.sleep(0.5)
        assert box["stats"]["active"] == 64  # nothing held back
        box["release"].set()
        for _ in range(64):
            await asyncio.wait_for(a.recv(), 30)
        a.close()

    asyncio.run(run())
