"""Ring-pipelined decode (ppermute reduce-scatter over the frag axis):
parity vs the reference decode, multiple masks and configs, on the
virtual 8-device mesh."""

import numpy as np
import pytest

from glusterfs_tpu.ops import gf256
from glusterfs_tpu.parallel import mesh_codec, ring_codec


@pytest.fixture(scope="module")
def mesh():
    return mesh_codec.make_mesh()  # (dp, frag) over the 8 CPU devices


@pytest.mark.parametrize("k,r", [(4, 2), (8, 4)])
def test_ring_decode_parity(mesh, k, r):
    n = k + r
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, k * 512 * 64, dtype=np.uint8)
    frags = gf256.ref_encode(data, k, n)
    for rows in ((tuple(range(r, n))),          # all data fragments lost
                 tuple(range(k)),                # no loss (first k)
                 tuple(sorted(rng.choice(n, k, replace=False)))):
        out = ring_codec.ring_decode(k, rows, frags[list(rows)], mesh)
        assert np.array_equal(out, data), (k, r, rows)


def test_ring_decode_unaligned_stripes(mesh):
    """Stripe counts that do not divide the ring length are padded."""
    k, n = 4, 6
    rng = np.random.default_rng(6)
    data = rng.integers(0, 256, k * 512 * 7, dtype=np.uint8)  # 7 stripes
    frags = gf256.ref_encode(data, k, n)
    rows = (0, 2, 3, 5)
    out = ring_codec.ring_decode(k, rows, frags[list(rows)], mesh)
    assert np.array_equal(out, data)


def test_ring_matches_allgather_decode(mesh):
    """The ring formulation and the XLA-collective decode agree."""
    k, n = 4, 6
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, k * 512 * 32, dtype=np.uint8)
    frags = gf256.ref_encode(data, k, n)
    rows = (1, 2, 4, 5)
    ring = ring_codec.ring_decode(k, rows, frags[list(rows)], mesh)
    ag = mesh_codec.sharded_decode(k, rows, frags[list(rows)], mesh)
    assert np.array_equal(ring, ag)
