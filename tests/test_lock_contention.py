"""Inodelk contention upcalls (locks common.c:1374-1455
inodelk_contention_notify -> ec-common.c:2576 ec_lock_release): a
blocked locker nudges the eager-lock holder, which commits its delayed
post-op and releases instead of sitting out the hold timer.  Also the
snapshot quiesce path (contend_held_locks) built on the same signal."""

import asyncio
import os
import time

import numpy as np
import pytest

from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume

N, R = 6, 2


@pytest.mark.slow
def test_contention_upcall_releases_eager_window(tmp_path):
    data = np.random.default_rng(0).integers(
        0, 256, 1 << 18, dtype=np.uint8).tobytes()

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="cv", vtype="disperse",
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(N)],
                             redundancy=R)
                await c.call("volume-start", name="cv")
                # a long hold: without contention upcalls the second
                # client would wait out (almost) this entire timer
                await c.call("volume-set", name="cv",
                             key="disperse.eager-lock-timeout",
                             value="20")
            a = await mount_volume(d.host, d.port, "cv")
            b = await mount_volume(d.host, d.port, "cv")
            try:
                fa = await a.create("/shared")
                await fa.write(data, 0)
                # A's window is live: post-op deferred, inodelk held.
                # B's write must trigger contention -> A commits and
                # releases -> B proceeds in round-trip time, not 20s.
                t0 = time.perf_counter()
                fb = await b.open("/shared", os.O_RDWR)
                await asyncio.wait_for(fb.write(b"takeover", 0), 15)
                elapsed = time.perf_counter() - t0
                await fb.close()
                await fa.close()
                assert elapsed < 10, \
                    f"blocked {elapsed:.1f}s: contention upcall dead"
            finally:
                await a.unmount()
                await b.unmount()
        finally:
            await d.stop()

    asyncio.run(run())
