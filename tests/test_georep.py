"""Changelog journal + geo-replication: brick-side fop journal feeds a
gsyncd-style worker that converges a secondary volume, survives worker
restart, and checkpoints progress — the tests/00-geo-rep + changelog .t
analog.  Reference: xlators/features/changelog,
geo-replication/syncdaemon/primary.py:90-135."""

import asyncio
import json
import os

import pytest

from glusterfs_tpu.api.glfs import Client, SyncClient
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.mgmt.gsyncd import GeoRepWorker

PRIMARY_VOL = """
volume posix
    type storage/posix
    option directory {dir}
end-volume

volume changelog
    type features/changelog
    option rollover-time 3600
    subvolumes posix
end-volume
"""

SECONDARY_VOL = """
volume posix
    type storage/posix
    option directory {dir}
end-volume
"""


def _cl_dir(brick):
    return os.path.join(str(brick), ".glusterfs_tpu", "changelog")


def _records(brick):
    d = _cl_dir(brick)
    out = []
    for n in sorted(os.listdir(d)):
        if not n.startswith("CHANGELOG."):
            continue  # HTIME coverage marker etc.
        with open(os.path.join(d, n)) as f:
            out += [json.loads(l) for l in f.read().splitlines()]
    return out


def test_changelog_journals_mutations(tmp_path):
    g = Graph.construct(PRIMARY_VOL.format(dir=tmp_path / "b"))
    c = SyncClient(g)
    c.mount()
    try:
        c.write_file("/a", b"hello")
        c.mkdir("/d")
        c.write_file("/d/x", b"nested")
        c.rename("/a", "/b")
        c.unlink("/d/x")
        c.setxattr("/b", {"user.k": b"v"})
        recs = _records(tmp_path / "b")
        ops = [(r["type"], r["op"]) for r in recs]
        assert ("E", "create") in ops
        assert ("D", "writev") in ops
        assert ("E", "mkdir") in ops
        assert ("E", "rename") in ops
        assert ("E", "unlink") in ops
        assert ("M", "setxattr") in ops
        ren = next(r for r in recs if r["op"] == "rename")
        assert ren["path"] == "/a" and ren["path2"] == "/b"
        # internal accounting is never journaled
        c._run(g.by_name["changelog"].setxattr(
            __import__("glusterfs_tpu.core.layer",
                       fromlist=["Loc"]).Loc("/b"),
            {"trusted.ec.dirty": b"\0" * 16}))
        assert not any(r["op"] == "setxattr" and "trusted.ec" in str(r)
                       for r in _records(tmp_path / "b"))
    finally:
        c.close()


@pytest.fixture
def pair(tmp_path):
    """Mounted primary (with changelog) + secondary volumes and a
    worker factory sharing one checkpoint file."""
    gp = Graph.construct(PRIMARY_VOL.format(dir=tmp_path / "p"))
    gs = Graph.construct(SECONDARY_VOL.format(dir=tmp_path / "s"))
    state = str(tmp_path / "geo.state")

    async def setup():
        p, s = Client(gp), Client(gs)
        await p.mount()
        await s.mount()
        return p, s

    loop = asyncio.new_event_loop()
    p, s = loop.run_until_complete(setup())

    def worker():
        return GeoRepWorker(p, s, [_cl_dir(tmp_path / "p")], state)

    yield loop, p, s, worker
    loop.run_until_complete(p.unmount())
    loop.run_until_complete(s.unmount())
    loop.close()


def test_worker_converges_secondary(pair):
    loop, p, s, worker = pair

    async def run():
        w = worker()
        await p.write_file("/f1", b"one")
        await p.mkdir("/sub")
        await p.write_file("/sub/f2", b"two" * 1000)
        await w.process_once()
        assert await s.read_file("/f1") == b"one"
        assert await s.read_file("/sub/f2") == b"two" * 1000
        # mutation + rename + delete converge too
        await p.write_file("/f1", b"one-v2")
        await p.rename("/sub/f2", "/f3")
        await p.unlink("/f1")
        await w.process_once()
        assert not await s.exists("/f1")
        assert await s.read_file("/f3") == b"two" * 1000
        assert w.status()["batches"] == 2

    loop.run_until_complete(run())


def test_worker_restart_resumes_from_checkpoint(pair):
    loop, p, s, worker = pair

    async def run():
        w1 = worker()
        await p.write_file("/a", b"aa")
        await w1.process_once()
        assert await s.read_file("/a") == b"aa"
        done_cursor = dict(w1.state["cursors"])
        # worker dies; more mutations land; a NEW worker picks up from
        # the persisted cursor and converges without a full re-scan
        await p.write_file("/b", b"bb")
        await p.write_file("/a", b"aa-v2")
        w2 = worker()
        assert w2.state["cursors"] == done_cursor
        n = await w2.process_once()
        assert n >= 1
        assert await s.read_file("/a") == b"aa-v2"
        assert await s.read_file("/b") == b"bb"

    loop.run_until_complete(run())


def test_data_coalescing_one_copy_per_path(pair):
    loop, p, s, worker = pair

    async def run():
        w = worker()
        f = await p.create("/hot")
        for i in range(50):
            await f.write(bytes([i]) * 64, i * 64)
        await f.close()
        before = w.synced
        await w.process_once()
        # 50 writev records coalesce to ONE data sync
        assert w.synced - before == 1
        got = await s.read_file("/hot")
        assert got == b"".join(bytes([i]) * 64 for i in range(50))

    loop.run_until_complete(run())


@pytest.mark.slow
def test_e2e_georep_through_glusterd(tmp_path):
    """Full managed path: two volumes, georep-create/start spawns a
    gsyncd subprocess, primary mutations converge on the secondary,
    and the link survives a worker restart (VERDICT next-round #9 done
    criterion)."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="pri", vtype="distribute",
                             bricks=[{"path": str(tmp_path / "pb")}],
                             redundancy=0)
                await c.call("volume-create", name="sec", vtype="distribute",
                             bricks=[{"path": str(tmp_path / "sb")}],
                             redundancy=0)
                await c.call("volume-set", name="pri",
                             key="changelog.rollover-time", value="1")
                await c.call("volume-start", name="pri")
                await c.call("volume-start", name="sec")
            # data that PREDATES the session: no journal records exist,
            # only the initial xsync crawl can sync it
            pre = await mount_volume(d.host, d.port, "pri")
            await pre.mkdir("/old")
            await pre.write_file("/old/history", b"pre-session" * 64)
            await pre.unmount()
            async with MgmtClient(d.host, d.port) as c:
                await c.call("georep-create", name="pri",
                             secondary=f"{d.host}:{d.port}:sec")
                await c.call("georep-start", name="pri")
                st = await c.call("georep-status", name="pri")
                assert st["sessions"][0]["online"]

            pc = await mount_volume(d.host, d.port, "pri")
            sc = await mount_volume(d.host, d.port, "sec")
            try:
                await pc.write_file("/doc", b"geo" * 512)
                await pc.mkdir("/dir")
                await pc.write_file("/dir/n", b"nested")
                ok = False
                for _ in range(60):
                    try:
                        if (await sc.read_file("/doc") == b"geo" * 512 and
                                await sc.read_file("/dir/n") == b"nested"):
                            ok = True
                            break
                    except Exception:
                        pass
                    await asyncio.sleep(0.5)
                assert ok, "secondary never converged"
                # pre-session data arrived via the initial crawl
                assert await sc.read_file("/old/history") == \
                    b"pre-session" * 64

                # stop -> mutate -> start: resumes from checkpoint
                async with MgmtClient(d.host, d.port) as c:
                    await c.call("georep-stop", name="pri")
                await pc.write_file("/late", b"after-restart")
                async with MgmtClient(d.host, d.port) as c:
                    await c.call("georep-start", name="pri")
                ok = False
                for _ in range(60):
                    try:
                        if await sc.read_file("/late") == b"after-restart":
                            ok = True
                            break
                    except Exception:
                        pass
                    await asyncio.sleep(0.5)
                assert ok, "post-restart mutation never synced"

                # checkpoint: stamped now, completes once the worker
                # has replayed everything journaled before it
                async with MgmtClient(d.host, d.port) as c:
                    cp = await c.call("georep-checkpoint", name="pri")
                    assert cp["checkpoint"] > 0
                    done = False
                    for _ in range(60):
                        st = await c.call("georep-status", name="pri")
                        s = st["sessions"][0]
                        assert s["checkpoint"] == cp["checkpoint"]
                        if s["checkpoint_completed"]:
                            done = True
                            break
                        await asyncio.sleep(0.5)
                    assert done, "checkpoint never completed"
            finally:
                await pc.unmount()
                await sc.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_georep_per_brick_failover(tmp_path):
    """Monitor model (reference monitor.py:63-85,299): one worker per
    local brick, one ACTIVE per replica set.  Kill the active worker's
    brick mid-replication — a peer brick's worker takes over and the
    secondary converges on changes made after the failover."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="pri",
                             vtype="replicate",
                             bricks=[{"path": str(tmp_path / f"pb{i}")}
                                     for i in range(3)], group_size=3)
                await c.call("volume-create", name="sec",
                             vtype="distribute",
                             bricks=[{"path": str(tmp_path / "sb")}],
                             redundancy=0)
                await c.call("volume-set", name="pri",
                             key="georep.sync-interval", value="0.5")
                await c.call("volume-start", name="pri")
                await c.call("volume-start", name="sec")
                await c.call("georep-create", name="pri",
                             secondary=f"{d.host}:{d.port}:sec")
                await c.call("georep-start", name="pri")

            pc = await mount_volume(d.host, d.port, "pri")
            sc = await mount_volume(d.host, d.port, "sec")
            try:
                await pc.write_file("/before", b"pre-failover")
                for _ in range(120):
                    try:
                        if await sc.read_file("/before") == \
                                b"pre-failover":
                            break
                    except Exception:
                        pass
                    await asyncio.sleep(0.5)
                else:
                    raise AssertionError("never synced pre-failover")

                # exactly one Active worker in the replica set
                async with MgmtClient(d.host, d.port) as c:
                    st = await c.call("georep-status", name="pri")
                workers = st["sessions"][0].get("workers") or {}
                active = [n for n, w in workers.items()
                          if w["state"] == "Active"]
                assert len(active) == 1, workers
                victim = active[0]

                # kill the ACTIVE brick's process (not via glusterd
                # stop: a real crash)
                proc = d.bricks[victim]
                proc.terminate()
                proc.wait(timeout=10)

                # volume stays writable (2/3 replicas); the monitor
                # must fail replication over to a surviving brick
                await asyncio.sleep(1.0)
                await pc.write_file("/after", b"post-failover")
                for _ in range(120):
                    try:
                        if await sc.read_file("/after") == \
                                b"post-failover":
                            break
                    except Exception:
                        pass
                    await asyncio.sleep(0.5)
                else:
                    raise AssertionError("no failover: post-failover "
                                         "write never synced")
                async with MgmtClient(d.host, d.port) as c:
                    st = await c.call("georep-status", name="pri")
                workers = st["sessions"][0].get("workers") or {}
                active2 = [n for n, w in workers.items()
                           if w["state"] == "Active"]
                assert active2 and active2[0] != victim, workers
            finally:
                await pc.unmount()
                await sc.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


def test_changelog_entry_class_covers_namelink():
    """graft-lint GL01 regression: namelink (icreate's other half —
    link a name to an existing inode) journaled NOWHERE, hiding the
    new name from geo-rep forever.  It is an entry op: E class, with
    a generated wrapper like its siblings."""
    from glusterfs_tpu.core.fops import Fop
    from glusterfs_tpu.features import changelog as cl

    assert Fop.NAMELINK in cl.E_FOPS
    assert "namelink" in vars(cl.ChangelogLayer)
