"""Barrier + snapshots: the barrier quiesces mutating fops for a
consistent store capture; snapshot create/list/restore/delete round-trip
a started volume's state — the tests/basic/volume-snapshot.t analog
(store-level; the reference snapshots LVM).  Reference: barrier.c:104-256,
glusterd-snapshot.c."""

import asyncio
import os

import pytest

from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc

BARRIER_VOL = """
volume posix
    type storage/posix
    option directory {dir}
end-volume

volume barrier
    type features/barrier
    subvolumes posix
end-volume
"""


def test_barrier_holds_and_releases(tmp_path):
    g = Graph.construct(BARRIER_VOL.format(dir=tmp_path / "b"))

    async def run():
        await g.activate()
        top = g.top
        import os as _os

        # O_SYNC: plain writes pass a barrier (reference barrier.c fops
        # table); durability-acknowledged ones hold
        fd, _ = await top.create(Loc("/f"), _os.O_SYNC, 0o644)
        bar = g.by_name["barrier"]
        bar.reconfigure({"barrier": "on", "barrier-timeout": "30"})

        done = asyncio.Event()

        async def writer():
            await top.writev(fd, b"held", 0)
            done.set()

        t = asyncio.get_running_loop().create_task(writer())
        await asyncio.sleep(0.2)
        assert not done.is_set(), "barrier did not hold the write"
        # non-mutating fops pass through a barriered brick
        assert (await top.stat(Loc("/f"))).size == 0
        bar.reconfigure({"barrier": "off"})
        await asyncio.wait_for(done.wait(), 5)
        assert (await top.stat(Loc("/f"))).size == 4
        await t
        await g.fini()

    asyncio.run(run())


def test_barrier_armed_from_volfile(tmp_path):
    """A brick whose volfile already says barrier=on must gate from the
    first fop — arming is state, not an off->on reconfigure edge."""
    vol = BARRIER_VOL.replace("subvolumes posix",
                              "option barrier on\n    subvolumes posix")
    g = Graph.construct(vol.format(dir=tmp_path / "b"))

    async def run():
        await g.activate()
        top = g.top
        done = asyncio.Event()

        async def writer():
            # unlink-class fops are the barriered set (barrier.c);
            # create flows through an armed barrier
            await top.create(Loc("/f"), 0, 0o644)
            await top.unlink(Loc("/f"))
            done.set()

        t = asyncio.get_running_loop().create_task(writer())
        await asyncio.sleep(0.2)
        assert not done.is_set(), "volfile-armed barrier did not hold"
        g.by_name["barrier"].reconfigure({"barrier": "off"})
        await asyncio.wait_for(done.wait(), 5)
        await t
        await g.fini()

    asyncio.run(run())


def test_snapshot_copy_survives_directory_rename(tmp_path):
    """Path hints in gfid records go stale when a parent directory is
    renamed; snapshot_copy must refresh them from the live dev:ino
    sidecars or restore drops the children's identity (gfid + EC/AFR
    versioning xattrs)."""
    from glusterfs_tpu.storage.posix import (META_DIR, rebuild_identity,
                                             snapshot_copy)

    store = tmp_path / "b"
    g = Graph.construct(BARRIER_VOL.format(dir=store))

    async def run():
        await g.activate()
        top = g.top
        await top.mkdir(Loc("/d"), 0o755)
        fd, _ = await top.create(Loc("/d/f"), 0, 0o644)
        await top.writev(fd, b"payload", 0)
        gfid = (await top.stat(Loc("/d/f"))).gfid
        await top.rename(Loc("/d"), Loc("/e"))  # /e/f's hint says /d/f
        snap = tmp_path / "snap"
        snapshot_copy(str(store), str(snap))
        await g.fini()

        n = rebuild_identity(str(snap))
        assert n >= 3  # /, /e, /e/f all rebound — nothing dropped
        rec = snap / META_DIR / "gfid" / gfid.hex()
        assert rec.exists(), "renamed child's identity was dropped"
        assert rec.read_text().split("\n", 1)[1] == "/e/f"

    asyncio.run(run())


def test_barrier_timeout_auto_releases(tmp_path):
    g = Graph.construct(BARRIER_VOL.format(dir=tmp_path / "b"))

    async def run():
        await g.activate()
        top = g.top
        import os as _os

        fd, _ = await top.create(Loc("/t"), _os.O_SYNC, 0o644)
        bar = g.by_name["barrier"]
        bar.reconfigure({"barrier": "on", "barrier-timeout": "0.3"})
        # nobody releases: the timeout must (a wedged snapshot flow
        # cannot freeze the brick forever)
        await asyncio.wait_for(top.writev(fd, b"x", 0), 5)
        assert bar.opts["barrier"] is False
        await g.fini()

    asyncio.run(run())


@pytest.mark.slow
def test_e2e_snapshot_create_restore(tmp_path):
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                bricks = [{"path": str(tmp_path / f"b{i}")}
                          for i in range(6)]
                await c.call("volume-create", name="sv", vtype="disperse",
                             bricks=bricks, redundancy=2)
                await c.call("volume-start", name="sv")

            client = await mount_volume(d.host, d.port, "sv")
            ec = next(l for l in client.graph.by_name.values()
                      if l.type_name == "cluster/disperse")
            for _ in range(150):
                if all(ch.connected for ch in ec.children):
                    break
                await asyncio.sleep(0.1)
            await client.write_file("/keep", b"snapshot me" * 100)
            async with MgmtClient(d.host, d.port) as c:
                await c.call("snapshot-create", name="snapA", volume="sv")
                ls = await c.call("snapshot-list")
                assert "snapA" in ls["snapshots"]
                assert ls["snapshots"]["snapA"]["volume"] == "sv"
            # post-snapshot divergence to be rolled back
            await client.write_file("/keep", b"MUTATED")
            await client.write_file("/extra", b"born after snap")
            await client.unmount()

            async with MgmtClient(d.host, d.port) as c:
                # restore refuses on a started volume
                with pytest.raises(Exception):
                    await c.call("snapshot-restore", name="snapA")
                await c.call("volume-stop", name="sv")
                await c.call("snapshot-restore", name="snapA")
                await c.call("volume-start", name="sv")

            client = await mount_volume(d.host, d.port, "sv")
            ec = next(l for l in client.graph.by_name.values()
                      if l.type_name == "cluster/disperse")
            for _ in range(150):
                if all(ch.connected for ch in ec.children):
                    break
                await asyncio.sleep(0.1)
            assert await client.read_file("/keep") == b"snapshot me" * 100
            assert not await client.exists("/extra")
            await client.unmount()

            async with MgmtClient(d.host, d.port) as c:
                await c.call("snapshot-delete", name="snapA")
                ls = await c.call("snapshot-list")
                assert ls["snapshots"] == {}
        finally:
            await d.stop()

    asyncio.run(run())


def test_e2e_snapshot_clone(tmp_path):
    """snapshot clone -> a NEW independent writable volume carrying the
    snapshot-time content (glusterd-snapshot.c clone): the original and
    the clone diverge freely after the clone."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="cv", vtype="disperse",
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(3)], redundancy=1)
                await c.call("volume-start", name="cv")
            client = await mount_volume(d.host, d.port, "cv")
            await client.write_file("/base", b"at snap time")
            async with MgmtClient(d.host, d.port) as c:
                await c.call("snapshot-create", name="s1", volume="cv")
            await client.write_file("/after", b"post-snap divergence")
            await client.unmount()

            async with MgmtClient(d.host, d.port) as c:
                await c.call("snapshot-clone", clonename="cvclone",
                             snapname="s1")
                info = await c.call("volume-info")
                assert "cvclone" in info
                await c.call("volume-start", name="cvclone")
            clone = await mount_volume(d.host, d.port, "cvclone")
            assert await clone.read_file("/base") == b"at snap time"
            assert not await clone.exists("/after")
            # the clone is writable and independent
            await clone.write_file("/clone-only", b"clone write")
            await clone.unmount()
            orig = await mount_volume(d.host, d.port, "cv")
            assert not await orig.exists("/clone-only")
            assert await orig.read_file("/after") == \
                b"post-snap divergence"
            await orig.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


def test_restore_rolls_back_grown_shape(tmp_path):
    """Restoring a snapshot taken BEFORE an add-brick rolls the
    volume's shape back too — never snap-time content on old bricks
    mixed with post-snap content on new ones (two-epoch volume)."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="gv",
                             vtype="distribute",
                             bricks=[{"path": str(tmp_path / f"g{i}")}
                                     for i in range(2)], redundancy=0)
                await c.call("volume-start", name="gv")
            cl = await mount_volume(d.host, d.port, "gv")
            for i in range(8):
                await cl.write_file(f"/s{i}", b"epoch-1")
            await cl.unmount()
            async with MgmtClient(d.host, d.port) as c:
                await c.call("snapshot-create", name="pre", volume="gv")
                await c.call("volume-add-brick", name="gv",
                             bricks=[{"path": str(tmp_path / "g2"),
                                      "host": "127.0.0.1"}])
            cl = await mount_volume(d.host, d.port, "gv")
            for i in range(8):
                await cl.write_file(f"/post{i}", b"epoch-2")
            await cl.unmount()
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-stop", name="gv")
                await c.call("snapshot-restore", name="pre")
                info = await c.call("volume-info", name="gv")
                assert len(info["gv"]["bricks"]) == 2, \
                    "restore must roll the brick set back to snap time"
                await c.call("volume-start", name="gv")
            cl = await mount_volume(d.host, d.port, "gv")
            for i in range(8):
                assert await cl.read_file(f"/s{i}") == b"epoch-1"
                assert not await cl.exists(f"/post{i}")
            await cl.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


def test_clone_across_nodes(tmp_path):
    """Cloning a snapshot of a volume whose bricks span two glusterds:
    each node stages/copies ITS snapped stores, and the clone's brick
    paths land under each node's own workdir."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume

    async def run():
        d1 = Glusterd(str(tmp_path / "n1"))
        d2 = Glusterd(str(tmp_path / "n2"))
        await d1.start()
        await d2.start()
        try:
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("peer-probe", host=d2.host, port=d2.port)
                bricks = [
                    {"node": f"{d1.host}:{d1.port}",
                     "path": str(tmp_path / "x0")},
                    {"node": f"{d2.host}:{d2.port}",
                     "path": str(tmp_path / "x1")},
                ]
                await c.call("volume-create", name="xv",
                             vtype="replicate", bricks=bricks,
                             redundancy=0)
                await c.call("volume-start", name="xv")
            cl = await mount_volume(d1.host, d1.port, "xv")
            await cl.write_file("/two-node", b"spanning")
            await cl.unmount()
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("snapshot-create", name="xs", volume="xv")
                await c.call("snapshot-clone", clonename="xc",
                             snapname="xs")
            # the clone registered on BOTH nodes with per-node paths
            for d in (d1, d2):
                vi = d.state["volumes"]["xc"]
                mine = [b for b in vi["bricks"] if b["node"] == d.uuid]
                assert len(mine) == 1
                assert mine[0]["path"].startswith(d.workdir)
                assert os.path.isdir(mine[0]["path"])
            async with MgmtClient(d1.host, d1.port) as c:
                await c.call("volume-start", name="xc")
            c2 = await mount_volume(d1.host, d1.port, "xc")
            assert await c2.read_file("/two-node") == b"spanning"
            await c2.unmount()
        finally:
            await d2.stop()
            await d1.stop()

    asyncio.run(run())
