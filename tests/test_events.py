"""Events subsystem: gf_event UDP datagrams -> eventsd -> webhooks —
the libglusterfs/src/events.c + glustereventsd.py analog."""

import asyncio
import json

import pytest

from glusterfs_tpu.core import events
from glusterfs_tpu.mgmt.eventsd import EventsDaemon


@pytest.fixture
def noevents():
    yield
    events.configure(None)


def test_emit_disabled_is_noop(noevents):
    events.configure(None)
    assert events.gf_event("NOPE") is False


def test_eventsd_collects_and_serves_recent(noevents):
    async def run():
        d = EventsDaemon()
        udp, _ = await d.start()
        events.configure(f"127.0.0.1:{udp}")
        assert events.gf_event("TEST_EVENT", volume="v1", n=7)
        for _ in range(100):
            if d.received:
                break
            await asyncio.sleep(0.02)
        assert d.received == 1
        ev = d.recent[-1]
        assert ev["event"] == "TEST_EVENT"
        assert ev["volume"] == "v1" and ev["n"] == 7
        assert d._ctl_op("status", {})["received"] == 1
        assert d._ctl_op("recent", {})["events"][-1]["event"] == \
            "TEST_EVENT"
        await d.stop()

    asyncio.run(run())


def test_webhook_delivery(noevents):
    async def run():
        got = []
        hit = asyncio.Event()

        async def handler(reader, writer):
            data = await reader.read(65536)
            head, _, body = data.partition(b"\r\n\r\n")
            got.append(json.loads(body.decode()))
            writer.write(b"HTTP/1.1 200 OK\r\nContent-Length: 0\r\n\r\n")
            await writer.drain()
            writer.close()
            hit.set()

        srv = await asyncio.start_server(handler, "127.0.0.1", 0)
        hport = srv.sockets[0].getsockname()[1]
        d = EventsDaemon()
        udp, _ = await d.start()
        d._ctl_op("webhook-add",
                  {"url": f"http://127.0.0.1:{hport}/hook"})
        events.configure(f"127.0.0.1:{udp}")
        events.gf_event("WEBHOOK_ME", volume="w")
        await asyncio.wait_for(hit.wait(), 5)
        assert got[0]["event"] == "WEBHOOK_ME"
        for _ in range(100):
            st = d._ctl_op("status", {})
            url = f"http://127.0.0.1:{hport}/hook"
            if st["webhooks"][url]["delivered"] == 1:
                break
            await asyncio.sleep(0.02)
        assert st["webhooks"][url]["delivered"] == 1
        d._ctl_op("webhook-del", {"url": url})
        assert d._ctl_op("status", {})["webhooks"] == {}
        await d.stop()
        srv.close()

    asyncio.run(run())


@pytest.mark.slow
def test_glusterd_lifecycle_emits_events(tmp_path, noevents):
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

    async def run():
        ed = EventsDaemon()
        udp, _ = await ed.start()
        events.configure(f"127.0.0.1:{udp}")
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="ev", vtype="distribute",
                             bricks=[{"path": str(tmp_path / "b0")}],
                             redundancy=0)
                await c.call("volume-start", name="ev")
                await c.call("volume-stop", name="ev")
                await c.call("volume-delete", name="ev")
            for _ in range(100):
                if ed.received >= 4:
                    break
                await asyncio.sleep(0.05)
            names = [e["event"] for e in ed.recent]
            for want in ("VOLUME_CREATE", "VOLUME_START", "VOLUME_STOP",
                         "VOLUME_DELETE"):
                assert want in names, names
        finally:
            await d.stop()
            await ed.stop()

    asyncio.run(run())


def test_eventsapi_cluster_webhook_config(tmp_path, noevents,
                                          monkeypatch):
    """peer_eventsapi analog: glusterd's eventsapi op forwards webhook
    config to the node's eventsd ctl port (GFTPU_EVENTSD_CTL)."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

    async def run():
        ed = EventsDaemon()
        _, ctl = await ed.start()
        monkeypatch.setenv("GFTPU_EVENTSD_CTL", f"127.0.0.1:{ctl}")
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                out = await c.call("eventsapi", action="webhook-add",
                                   url="http://127.0.0.1:1/hook")
                assert out["ok"]
                assert "http://127.0.0.1:1/hook" in ed.webhooks
                st = await c.call("eventsapi", action="status")
                assert any("http://127.0.0.1:1/hook"
                           in n.get("webhooks", {})
                           for n in st["nodes"].values()), st
                await c.call("eventsapi", action="webhook-del",
                             url="http://127.0.0.1:1/hook")
                assert "http://127.0.0.1:1/hook" not in ed.webhooks
        finally:
            await d.stop()
            await ed.stop()

    asyncio.run(run())
