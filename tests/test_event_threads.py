"""Concurrent event plane (ISSUE 7): keyed frame-turning pools on both
transport ends.  Pins the ordering invariant (a connection's frames are
dispatched in arrival order with server.event-threads >= 4), byte
identity under 64 interleaved client connections, compound single-slot
+ single-journal-batch semantics under concurrent dispatch, live pool
grow/shrink without dropping in-flight frames, and the
gftpu_event_threads* registry families."""

import asyncio
import os
import threading
import time

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc, walk
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.protocol.client import ClientLayer
from glusterfs_tpu.protocol.server import ServerLayer
from glusterfs_tpu.rpc import compound as cfop
from glusterfs_tpu.rpc import event_pool as evt
from glusterfs_tpu.rpc.event_pool import EventPool
from glusterfs_tpu.storage.posix import PosixLayer

BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume

volume locks
    type features/locks
    subvolumes posix
end-volume

volume srv
    type protocol/server
    option event-threads {evt}
    subvolumes locks
end-volume
"""

CLIENT = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume srv
    option event-threads {cevt}
    option compound-fops on
{extra}end-volume
"""


async def _connected(tmp_path, evt_threads=4, cevt=2, extra=""):
    server = await serve_brick(
        BRICK.format(dir=tmp_path / "b", evt=evt_threads))
    g = Graph.construct(CLIENT.format(port=server.port, cevt=cevt,
                                      extra=extra))
    c = Client(g)
    await c.mount()
    for _ in range(200):
        if g.top.connected:
            break
        await asyncio.sleep(0.05)
    assert g.top.connected
    return server, c, g.top


# -- the pool itself -------------------------------------------------------

def test_pool_keyed_fifo_serialization():
    """Same-key jobs never overlap and finish FIFO; distinct keys
    proceed in parallel across the workers."""

    async def run():
        pool = EventPool(4, name="t-fifo")
        try:
            keys = {"a": object(), "b": object(), "c": object()}
            order = {k: [] for k in keys}
            active = {k: 0 for k in keys}
            violations = []
            parallel_peak = [0]
            lock = threading.Lock()

            def job(k, i):
                with lock:
                    active[k] += 1
                    if active[k] > 1:
                        violations.append((k, i))
                    parallel_peak[0] = max(parallel_peak[0],
                                           sum(active.values()))
                time.sleep(0.002)
                with lock:
                    order[k].append(i)
                    active[k] -= 1
                return (k, i)

            futs = [pool.submit(keys[k], job, k, i)
                    for i in range(20) for k in keys]
            res = await asyncio.gather(*futs)
            assert len(res) == 60
            assert not violations, f"same-key overlap: {violations}"
            for k in keys:
                assert order[k] == list(range(20)), f"{k} reordered"
            # distinct keys actually overlapped on the workers
            assert parallel_peak[0] >= 2, parallel_peak
        finally:
            pool.shutdown()

    asyncio.run(run())


def test_pool_resize_never_drops_jobs():
    """Grow/shrink mid-stream: every submitted job completes, per-key
    FIFO holds throughout, and the pool converges on the target."""

    async def run():
        pool = EventPool(2, name="t-resize")
        try:
            keys = [object() for _ in range(8)]
            order = {i: [] for i in range(8)}

            def job(ki, i):
                time.sleep(0.001)
                order[ki].append(i)
                return i

            futs = []
            for i in range(25):
                futs += [pool.submit(keys[ki], job, ki, i)
                         for ki in range(8)]
                if i == 5:
                    pool.resize(8)
                elif i == 12:
                    pool.resize(1)
                elif i == 18:
                    pool.resize(4)
            res = await asyncio.gather(*futs)
            assert len(res) == 200
            for ki in range(8):
                assert order[ki] == list(range(25))
            assert pool.size == 4
            # size 0 = inline turning: still answered, never dropped
            pool.resize(0)
            assert await pool.turn(keys[0], lambda: "inline") == "inline"
        finally:
            pool.shutdown()

        # resize to 0 WITH a queued backlog: the retiring workers must
        # drain it first — an orphaned job would wedge its connection
        def slow_id(i):
            time.sleep(0.002)
            return i

        for stopper in ("resize0", "shutdown"):
            p2 = EventPool(2, name=f"t-drain-{stopper}")
            k = object()
            futs2 = [p2.submit(k, slow_id, i) for i in range(20)]
            if stopper == "resize0":
                p2.resize(0)
            else:
                p2.shutdown()
            res2 = await asyncio.wait_for(asyncio.gather(*futs2), 30)
            assert res2 == list(range(20)), stopper
            p2.shutdown()

    asyncio.run(run())


# -- per-connection ordering through the wire ------------------------------

def test_per_connection_dispatch_order_with_4_event_threads(tmp_path):
    """16 pipelined writevs from ONE connection (no awaits between
    sends) enter the brick graph in send order even with 4 frame
    turners, and the assembled bytes are exact."""

    async def run():
        server, c, cl = await _connected(tmp_path, evt_threads=4)
        assert server.event_pool().size == 4
        posix = next(l for l in walk(server.top)
                     if isinstance(l, PosixLayer))
        arrivals = []
        real = PosixLayer.writev

        async def recording(self, fd, data, offset, *a, **kw):
            arrivals.append(offset)
            return await real(self, fd, data, offset, *a, **kw)

        chunk = 8192  # >= TURN_MIN: every frame rides the pool
        fd, _ = await cl.create(Loc("/ordered"),
                                os.O_CREAT | os.O_RDWR, 0o644)
        PosixLayer.writev = recording
        try:
            tasks = [asyncio.ensure_future(
                cl.writev(fd, bytes([i]) * chunk, i * chunk))
                for i in range(16)]
            await asyncio.gather(*tasks)
        finally:
            PosixLayer.writev = real
        assert arrivals == [i * chunk for i in range(16)], arrivals
        got = await c.read_file("/ordered")
        assert got == b"".join(bytes([i]) * chunk for i in range(16))
        del posix
        await c.unmount()
        await server.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_64_interleaved_clients_byte_identical(tmp_path):
    """64 real connections write interleaved chunks concurrently; every
    file reads back byte-identical through a fresh pass."""

    async def run():
        server = await serve_brick(
            BRICK.format(dir=tmp_path / "b", evt=4))
        clients = []
        for i in range(64):
            g = Graph.construct(CLIENT.format(port=server.port, cevt=2,
                                              extra=""))
            c = Client(g)
            await c.mount()
            clients.append((c, g))
        for _, g in clients:
            for _ in range(400):
                if g.top.connected:
                    break
                await asyncio.sleep(0.025)
            assert g.top.connected

        chunk = 8192
        payloads = [bytes([i]) * chunk + bytes([255 - i]) * chunk
                    for i in range(64)]

        async def drive(i):
            c, g = clients[i]
            cl = g.top
            fd, _ = await cl.create(Loc(f"/f{i}"),
                                    os.O_CREAT | os.O_RDWR, 0o644)
            # interleaved: both chunks in flight at once
            await asyncio.gather(
                cl.writev(fd, payloads[i][:chunk], 0),
                cl.writev(fd, payloads[i][chunk:], chunk))
            await cl.release(fd)

        await asyncio.gather(*(drive(i) for i in range(64)))
        for i in (0, 17, 42, 63):
            got = await clients[i][0].read_file(f"/f{i}")
            assert got == payloads[i], f"client {i} corrupted"
        for c, _ in clients:
            await c.unmount()
        await server.stop()

    asyncio.run(run())


# -- compound semantics under concurrent dispatch --------------------------

def test_compound_single_journal_batch_with_event_threads(tmp_path):
    """A wired chain through the 4-thread brick still lands as ONE
    posix journal append (the handle-farm transaction survives the
    concurrent plane)."""

    async def run():
        server, c, cl = await _connected(tmp_path, evt_threads=4)
        posix = next(l for l in walk(server.top)
                     if isinstance(l, PosixLayer))
        writes = []
        real_write = os.write

        def counting_write(fd, data):
            if fd == posix._xa_journal_fd:
                writes.append(bytes(data))
            return real_write(fd, data)

        import glusterfs_tpu.storage.posix as posix_mod

        posix_mod.os.write = counting_write
        try:
            replies = await cl.compound([
                ("create", (Loc("/chain"),
                            os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o644),
                 {}),
                ("writev", (cfop.FdRef(0), b"x" * 8192, 0), {}),
                ("flush", (cfop.FdRef(0),), {}),
                ("release", (cfop.FdRef(0),), {}),
            ])
        finally:
            posix_mod.os.write = real_write
        assert [st for st, _ in replies] == ["ok"] * 4
        appends = [w for w in writes if b'"' in w]
        assert len(appends) == 1, \
            f"expected one batched journal append, saw {len(appends)}"
        assert await c.read_file("/chain") == b"x" * 8192
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_compound_one_outstanding_slot_under_concurrency(tmp_path):
    """A slow in-flight chain occupies exactly ONE outstanding-rpc slot
    on its connection, while a second connection's fops proceed in
    parallel through the brick (the cross-connection concurrency the
    plane exists for)."""

    async def run():
        server, c1, cl1 = await _connected(tmp_path, evt_threads=4)
        g2 = Graph.construct(CLIENT.format(port=server.port, cevt=2,
                                          extra=""))
        c2 = Client(g2)
        await c2.mount()
        for _ in range(200):
            if g2.top.connected:
                break
            await asyncio.sleep(0.05)

        real = PosixLayer.writev

        async def slow(self, fd, data, offset, *a, **kw):
            await asyncio.sleep(0.05)
            return await real(self, fd, data, offset, *a, **kw)

        conn1 = next(cn for cn in server.connections
                     if cn.identity == cl1.identity)
        peak = [0]

        async def sample():
            while True:
                peak[0] = max(peak[0],
                              conn1.inflight + conn1.exempt_inflight)
                await asyncio.sleep(0.005)

        PosixLayer.writev = slow
        sampler = asyncio.ensure_future(sample())
        t0 = time.perf_counter()
        try:
            chain = cl1.compound([
                ("create", (Loc("/slowchain"),
                            os.O_RDWR | os.O_CREAT, 0o644), {}),
                ("writev", (cfop.FdRef(0), b"a" * 4096, 0), {}),
                ("writev", (cfop.FdRef(0), b"b" * 4096, 4096), {}),
                ("release", (cfop.FdRef(0),), {}),
            ])
            other = c2.write_file("/other", b"o" * 4096)
            replies, _ = await asyncio.gather(chain, other)
        finally:
            PosixLayer.writev = real
            sampler.cancel()
        elapsed = time.perf_counter() - t0
        assert [st for st, _ in replies] == ["ok"] * 4
        # the 4-link chain held ONE slot on its connection
        assert peak[0] == 1, f"chain occupied {peak[0]} slots"
        # both clients' slow writes overlapped (serial would be ~4x50ms
        # for the chain alone plus the other write's delay); generous
        # bound — the slot assertion above is the real pin, this one
        # only guards gross serialization on a loaded host
        assert await c2.read_file("/other") == b"o" * 4096
        assert elapsed < 2.5, elapsed
        await c1.unmount()
        await c2.unmount()
        await server.stop()

    asyncio.run(run())


# -- live reconfigure ------------------------------------------------------

def test_live_reconfigure_grows_and_shrinks_without_drops(tmp_path):
    """server.event-threads reconfigures mid-traffic: the pool follows
    the option both directions and no in-flight frame is lost."""

    async def run():
        server, c, cl = await _connected(tmp_path, evt_threads=2)
        srv = server.top
        assert isinstance(srv, ServerLayer)
        assert server.event_pool().size == 2
        chunk = 8192
        fd, _ = await cl.create(Loc("/live"),
                                os.O_CREAT | os.O_RDWR, 0o644)

        async def burst(base):
            await asyncio.gather(*(
                cl.writev(fd, bytes([base + i]) * chunk,
                          (base + i) * chunk) for i in range(8)))

        b0 = asyncio.ensure_future(burst(0))
        srv.reconfigure({"event-threads": 8})
        await burst(8)
        await b0
        assert server.event_pool().size == 8
        b1 = asyncio.ensure_future(burst(16))
        srv.reconfigure({"event-threads": 1})
        await burst(24)
        await b1
        assert server.event_pool().size == 1
        got = await c.read_file("/live")
        assert got == b"".join(bytes([i]) * chunk for i in range(32))
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_client_event_threads_reconfigure_resizes_shared_pool(tmp_path):
    """client.event-threads reconfigure applies to the process-wide
    reply pool exactly (grow AND shrink), and big replies decoded
    through it stay byte-identical."""

    async def run():
        # inline wire on purpose: the reply pool turns BIG INLINE
        # frames, and with the same-host shm lane armed (default on)
        # a 256 KiB reply is a 20-byte descriptor frame that never
        # needs the pool — the lane's path is pinned in
        # test_shm_transport.py
        server, c, cl = await _connected(
            tmp_path, evt_threads=2, cevt=2,
            extra="    option shm-transport off\n")
        payload = os.urandom(256 << 10)
        await c.write_file("/big", payload)
        assert await c.read_file("/big") == payload  # pooled decode
        pool = evt.client_pool(0)
        assert pool is not None and pool.size >= 2
        cl_layer = next(l for l in walk(c.graph.top)
                        if isinstance(l, ClientLayer))
        cl_layer.reconfigure({"event-threads": 5})
        assert evt.client_pool(0).size == 5
        assert await c.read_file("/big") == payload
        cl_layer.reconfigure({"event-threads": 2})
        assert evt.client_pool(0).size == 2
        await c.unmount()
        await server.stop()

    asyncio.run(run())


# -- observability ---------------------------------------------------------

def test_event_plane_registry_families(tmp_path):
    """gftpu_event_threads{,_busy} + per-worker frames-turned counters
    are on the unified registry and move with traffic."""

    async def run():
        server, c, cl = await _connected(tmp_path, evt_threads=3)
        await c.write_file("/fam", b"f" * 65536)
        assert await c.read_file("/fam") == b"f" * 65536
        snap = REGISTRY.snapshot()
        for fam in ("gftpu_event_threads", "gftpu_event_threads_busy",
                    "gftpu_event_frames_total"):
            assert fam in snap, f"missing family {fam}"
        # collect ALL samples named "srv": earlier tests' stopped
        # servers share the volfile name and linger in the weakset
        # (size 0, shut down) until the GC reaps them
        srv_sizes = [s[1] for s in
                     snap["gftpu_event_threads"]["samples"]
                     if s[0]["pool"] == "srv"]
        assert 3 in srv_sizes, srv_sizes
        turned = sum(s[1] for s in
                     snap["gftpu_event_frames_total"]["samples"]
                     if s[0]["pool"] == "srv")
        assert turned > 0, "no frames turned on the brick pool"
        await c.unmount()
        await server.stop()

    asyncio.run(run())


# -- fragment readv coalescing (ROADMAP item 7 satellite) ------------------

def test_ec_adjacent_readv_chain_coalesces(tmp_path):
    """Adjacent readv links of one chain merge into ONE ranged fragment
    fan-out per brick; answers byte-identical; non-adjacent chains
    decompose as before."""
    from glusterfs_tpu.utils.volspec import ec_volfile

    async def run():
        spec = ec_volfile(str(tmp_path), 6, 2)
        g = Graph.construct(spec)
        c = Client(g)
        await c.mount()
        disp = next(l for l in walk(g.top)
                    if l.type_name == "cluster/disperse")
        data = os.urandom(512 << 10)
        await c.write_file("/coal", data)

        fd = await disp.open(Loc("/coal"), os.O_RDONLY)
        base_rt = dict(disp.read_coalesced)
        win = 128 << 10
        replies = await disp.compound([
            ("readv", (fd, win, 0), {}),
            ("readv", (fd, win, win), {}),
        ])
        assert [st for st, _ in replies] == ["ok", "ok"]
        assert bytes(replies[0][1]) == data[:win]
        assert bytes(replies[1][1]) == data[win: 2 * win]
        assert disp.read_coalesced["chains"] == base_rt["chains"] + 1
        assert disp.read_coalesced["links"] == base_rt["links"] + 2

        # a hole between ranges: falls back to per-link dispatch
        replies = await disp.compound([
            ("readv", (fd, 4096, 0), {}),
            ("readv", (fd, 4096, 256 << 10), {}),
        ])
        assert [st for st, _ in replies] == ["ok", "ok"]
        assert bytes(replies[0][1]) == data[:4096]
        assert bytes(replies[1][1]) == data[256 << 10: (256 << 10) + 4096]
        assert disp.read_coalesced["chains"] == base_rt["chains"] + 1
        await disp.release(fd)
        await c.unmount()

    asyncio.run(run())
