"""Performance layers: write-behind aggregation, io-cache hits,
read-ahead, md-cache invalidation, quick-read, open-behind, nl-cache,
readdir-ahead, io-threads gating (reference tests/performance/ +
write-behind.md semantics)."""

import asyncio

import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc


def _vol(tmp_path, *layers) -> str:
    out = [f"volume posix\n    type storage/posix\n"
           f"    option directory {tmp_path}/b\nend-volume\n"]
    prev = "posix"
    for i, (ltype, opts) in enumerate(layers):
        name = f"l{i}"
        body = "".join(f"    option {k} {v}\n" for k, v in opts.items())
        out.append(f"volume {name}\n    type {ltype}\n{body}"
                   f"    subvolumes {prev}\nend-volume\n")
        prev = name
    return "\n".join(out)


def _client(tmp_path, *layers) -> SyncClient:
    c = SyncClient(Graph.construct(_vol(tmp_path, *layers)))
    c.mount()
    return c


def test_write_behind(tmp_path):
    c = _client(tmp_path, ("performance/write-behind",
                           {"window-size": "64KB"}))
    wb = c.graph.top
    posix = c.graph.by_name["posix"]
    f = c.create("/f")
    for i in range(8):
        f.write(b"A" * 1000, i * 1000)  # adjacent: coalesce, below window
    # nothing flushed yet (below window): posix saw create only
    assert posix.stats.get("writev") is None
    assert f.read(4, 0) == b"AAAA"  # read forces flush
    assert posix.stats["writev"].count == 1  # coalesced to ONE write
    f.close()
    assert c.read_file("/f") == b"A" * 8000
    c.close()


def test_write_behind_deferred_error(tmp_path):
    vf = _vol(tmp_path) + """
volume errg
    type debug/error-gen
    option failure 100
    option enable writev
    subvolumes posix
end-volume
volume wb
    type performance/write-behind
    subvolumes errg
end-volume
"""
    c = SyncClient(Graph.construct(vf))
    c.mount()
    f = c.create("/f")
    f.write(b"x", 0)  # buffered: acked
    with pytest.raises(FopError):
        f.fsync()  # flush surfaces the injected error
    c.close()


def test_io_cache(tmp_path):
    c = _client(tmp_path, ("performance/io-cache", {"page-size": "4KB"}))
    ioc = c.graph.top
    posix = c.graph.by_name["posix"]
    c.write_file("/f", b"z" * 10000)
    assert c.read_file("/f") == b"z" * 10000
    n1 = posix.stats["readv"].count
    assert c.read_file("/f") == b"z" * 10000  # cached
    assert posix.stats["readv"].count == n1
    assert ioc.hits > 0
    # write invalidates
    f = c.open("/f")
    f.write(b"y", 0)
    f.close()
    assert c.read_file("/f")[:1] == b"y"
    c.close()


def test_io_cache_cross_client_revalidation(tmp_path):
    """Cached pages older than cache-timeout are revalidated against
    the file's mtime (ioc_cache_validate): a change made BEHIND the
    cache (another client / direct brick write) becomes visible after
    the timeout instead of never."""
    import time

    c = _client(tmp_path, ("performance/io-cache",
                           {"page-size": "4KB",
                            "cache-timeout": "0.2"}))
    ioc = c.graph.top
    posix = c.graph.by_name["posix"]
    c.write_file("/f", b"old" * 2000)
    assert c.read_file("/f") == b"old" * 2000
    time.sleep(0.25)
    c.read_file("/f")  # establishes the (mtime, pages) baseline
    # mutate BEHIND the cache: straight through posix, invisible to
    # the io-cache layer's own invalidation
    from glusterfs_tpu.core.layer import FdObj
    ia = c.stat("/f")
    anon = FdObj(ia.gfid, path="/f", anonymous=True)
    time.sleep(0.05)
    c._run(posix.writev(anon, b"new" * 2000, 0))
    # within the timeout the stale page may still be served; after it,
    # revalidation sees the mtime change and refetches
    time.sleep(0.25)
    assert c.read_file("/f")[:6] == b"newnew"
    assert ioc.validations > 0
    c.close()


def test_read_ahead(tmp_path):
    c = _client(tmp_path, ("performance/read-ahead",
                           {"page-size": "4KB", "page-count": 2}))
    c.write_file("/f", bytes(range(256)) * 100)
    f = c.open("/f")
    out = b""
    for i in range(6):  # sequential reads trigger prefetch
        out += f.read(4096, i * 4096)
    f.close()
    assert out == (bytes(range(256)) * 100)[:6 * 4096]
    c.close()


def test_md_cache(tmp_path):
    c = _client(tmp_path, ("performance/md-cache", {"timeout": "60"}))
    mdc = c.graph.top
    posix = c.graph.by_name["posix"]
    c.write_file("/f", b"12345")
    # the writev postbuf was absorbed (mdc_writev_cbk analog): stats
    # after a write are served from cache without reaching the brick
    c.stat("/f")
    c.stat("/f")
    assert posix.stats.get("stat") is None  # never reached posix
    assert c.stat("/f").size == 5
    assert mdc.hits >= 2
    # write invalidates: size change visible
    f = c.open("/f")
    f.write(b"6789ab", 5)
    f.close()
    assert c.stat("/f").size == 11
    c.close()


def test_quick_read(tmp_path):
    c = _client(tmp_path, ("performance/quick-read",
                           {"max-file-size": "1KB"}))
    qr = c.graph.top
    posix = c.graph.by_name["posix"]
    c.write_file("/small", b"tiny")
    assert c.read_file("/small") == b"tiny"
    n = posix.stats["readv"].count
    assert c.read_file("/small") == b"tiny"
    assert posix.stats["readv"].count == n
    assert qr.hits >= 1
    big = b"B" * 5000
    c.write_file("/big", big)
    assert c.read_file("/big") == big  # above limit: passthrough
    c.close()


def test_open_behind(tmp_path):
    c = _client(tmp_path, ("performance/open-behind", {}))
    posix = c.graph.by_name["posix"]

    def opens():
        st = posix.stats.get("open")
        return st.count if st else 0

    c.write_file("/f", b"lazily")
    n_opens = opens()
    f = c.open("/f")  # deferred: no child open yet
    assert opens() == n_opens
    assert f.read(6, 0) == b"lazily"  # first use opens
    assert opens() == n_opens + 1
    f.close()
    c.close()


def test_nl_cache(tmp_path):
    c = _client(tmp_path, ("performance/nl-cache", {}))
    nlc = c.graph.top
    posix = c.graph.by_name["posix"]
    for _ in range(3):
        assert not c.exists("/missing")
    assert nlc.hits >= 2  # negative entries served from cache
    # creating the file invalidates the negative entry
    c.write_file("/missing", b"now here")
    assert c.exists("/missing")
    c.close()


def test_readdir_ahead(tmp_path):
    c = _client(tmp_path, ("performance/readdir-ahead", {}))
    for i in range(5):
        c.write_file(f"/f{i}", b".")
    assert c.listdir("/") == [f"f{i}" for i in range(5)]
    c.close()


def test_io_threads_gating(tmp_path):
    c = _client(tmp_path, ("performance/io-threads", {"thread-count": 2}))
    iot = c.graph.top
    c.write_file("/f", b"x" * 100)
    assert c.read_file("/f") == b"x" * 100
    assert iot.executed[1] > 0  # normal-prio fops went through the gate
    assert iot.executed[0] > 0  # lookups on the fast path
    c.close()


def test_full_perf_stack(tmp_path):
    """All perf layers stacked (volgen order) still give correct I/O."""
    c = _client(
        tmp_path,
        ("performance/write-behind", {}),
        ("performance/read-ahead", {}),
        ("performance/readdir-ahead", {}),
        ("performance/io-cache", {}),
        ("performance/quick-read", {}),
        ("performance/open-behind", {}),
        ("performance/md-cache", {}),
        ("performance/nl-cache", {}),
    )
    data = bytes(range(256)) * 300
    c.write_file("/f", data)
    assert c.read_file("/f") == data
    f = c.open("/f")
    f.write(b"PATCH", 1000)
    f.close()
    expect = data[:1000] + b"PATCH" + data[1005:]
    assert c.read_file("/f") == expect
    assert c.stat("/f").size == len(data)
    c.mkdir("/d")
    assert sorted(c.listdir("/")) == ["d", "f"]
    c.close()


def test_write_behind_bridging_write_order(tmp_path):
    """A bridging write that overlaps TWO buffered chunks must win over
    both: stale higher-offset chunk bytes must not clobber newer data on
    drain (advisor round-1 finding)."""
    c = _client(tmp_path, ("performance/write-behind",
                           {"window-size": "1MB"}))
    f = c.create("/f")
    f.write(b"A" * 10, 0)      # chunk [0,10)
    f.write(b"B" * 10, 20)     # chunk [20,30) — disjoint, older
    f.write(b"C" * 20, 5)      # bridges both: [5,25), newest
    f.close()                  # drain
    want = b"A" * 5 + b"C" * 20 + b"B" * 5
    assert c.read_file("/f") == want
    c.close()


def test_write_behind_many_overlaps_disjoint_invariant(tmp_path):
    """Random overlapping writes replayed through write-behind must equal
    a plain sequential replay (newest-wins everywhere)."""
    import random

    rnd = random.Random(3)
    shadow = bytearray(4096)
    c = _client(tmp_path, ("performance/write-behind",
                           {"window-size": "1MB"}))
    f = c.create("/f")
    for step in range(60):
        off = rnd.randrange(0, 3500)
        ln = rnd.randrange(1, 500)
        pat = bytes([step % 256]) * ln
        f.write(pat, off)
        shadow[off:off + ln] = pat
    f.close()
    got = c.read_file("/f")
    assert got == bytes(shadow[:len(got)])
    assert bytes(shadow[len(got):]).count(0) == len(shadow) - len(got)
    c.close()
