"""Runtime spine unit tests: options, volfile DSL, graph lifecycle, layer
stats, inode table (reference analogs: options.c validators, graph.y
grammar, graph.c init order, xlator stats, inode.c)."""

import asyncio

import pytest

from glusterfs_tpu.core import graph as graph_mod
from glusterfs_tpu.core.fops import Fop, FopError
from glusterfs_tpu.core.iatt import IAType, ROOT_GFID, gfid_new
from glusterfs_tpu.core.inode import InodeTable
from glusterfs_tpu.core.layer import Event, Layer, register
from glusterfs_tpu.core.options import (Option, OptionError, parse_bool,
                                        parse_size, parse_time,
                                        validate_options)


# -- options ---------------------------------------------------------------

def test_option_parsing():
    assert parse_bool("on") and parse_bool("TRUE") and not parse_bool("off")
    with pytest.raises(OptionError):
        parse_bool("maybe")
    assert parse_size("64KB") == 65536
    assert parse_size("1M") == 1 << 20
    assert parse_size(512) == 512
    assert parse_time("500ms") == 0.5
    assert parse_time("2min") == 120.0


def test_option_table_validation():
    table = (
        Option("redundancy", "int", default=2, min=1, max=3),
        Option("cpu-extensions", "enum", default="auto",
               values=("auto", "ref", "tpu")),
        Option("cache-size", "size", default="32MB"),
    )
    out = validate_options(table, {"redundancy": "3"})
    assert out["redundancy"] == 3
    assert out["cache-size"] == 32 << 20
    with pytest.raises(OptionError):
        validate_options(table, {"redundancy": "9"})
    with pytest.raises(OptionError):
        validate_options(table, {"cpu-extensions": "avx"})
    with pytest.raises(OptionError):
        validate_options(table, {"bogus": 1}, strict=True)


# -- volfile ---------------------------------------------------------------

VOLFILE = """
# client graph for test volume
volume test-posix
    type storage/posix
    option directory {d}
end-volume

volume test-top
    type debug/passthrough
    subvolumes test-posix
end-volume
"""


@register("debug/passthrough")
class Passthrough(Layer):
    """No-op layer for graph tests."""


def test_volfile_parse_roundtrip():
    specs = graph_mod.parse_volfile(VOLFILE.format(d="/tmp/x"))
    assert [s.name for s in specs] == ["test-posix", "test-top"]
    assert specs[0].type_name == "storage/posix"
    assert specs[0].options["directory"] == "/tmp/x"
    assert specs[1].subvolumes == ["test-posix"]
    text = graph_mod.emit_volfile(specs)
    again = graph_mod.parse_volfile(text)
    assert again == specs


def test_volfile_errors():
    with pytest.raises(graph_mod.VolfileError):
        graph_mod.parse_volfile("volume a\ntype t\n")  # missing end-volume
    with pytest.raises(graph_mod.VolfileError):
        graph_mod.parse_volfile("type x\n")  # outside block
    with pytest.raises(graph_mod.VolfileError):
        graph_mod.Graph.construct(
            "volume a\ntype debug/passthrough\nsubvolumes nope\nend-volume\n")


def test_graph_construct_and_lifecycle(tmp_path):
    g = graph_mod.Graph.construct(VOLFILE.format(d=tmp_path / "brick"))
    assert g.top.name == "test-top"
    assert g.by_name["test-posix"].children == []
    asyncio.run(g.activate())
    assert g.active
    assert all(l.initialized for l in g.by_name.values())
    d = g.statedump()
    assert d["top"] == "test-top"
    assert d["layers"]["test-posix"]["type"] == "storage/posix"
    asyncio.run(g.fini())
    assert not g.active


def test_layer_default_passthrough_and_stats(tmp_path):
    g = graph_mod.Graph.construct(VOLFILE.format(d=tmp_path / "brick"))
    asyncio.run(g.activate())
    from glusterfs_tpu.core.layer import Loc

    ia, _ = asyncio.run(g.top.lookup(Loc("/")))
    assert ia.gfid == ROOT_GFID
    # default passthrough recorded stats on both layers
    assert g.top.stats["lookup"].count == 1
    assert g.by_name["test-posix"].stats["lookup"].count == 1
    with pytest.raises(FopError):
        asyncio.run(g.top.lookup(Loc("/missing")))
    assert g.top.stats["lookup"].errors == 1


def test_notify_propagates_up(tmp_path):
    events = []

    @register("debug/event-sink")
    class Sink(Layer):
        def notify(self, event, source=None, data=None):
            events.append((event, source.name if source else None))

    vf = VOLFILE.format(d=tmp_path / "brick") + """
volume sink
    type debug/event-sink
    subvolumes test-top
end-volume
"""
    g = graph_mod.Graph.construct(vf)
    g.by_name["test-posix"].notify(Event.CHILD_DOWN)
    # each hop re-sources the event: the sink hears it from its child
    assert (Event.CHILD_DOWN, "test-top") in events


# -- inode table -----------------------------------------------------------

def test_inode_table():
    t = InodeTable(lru_limit=2)
    g1, g2, g3 = gfid_new(), gfid_new(), gfid_new()
    t.link(ROOT_GFID, "a", g1, IAType.REG)
    t.link(ROOT_GFID, "b", g2, IAType.DIR)
    assert t.find_dentry(ROOT_GFID, "a").gfid == g1
    assert t.get(g2).is_dir()
    # forget drops to LRU; over-limit purges oldest
    t.link(ROOT_GFID, "c", g3, IAType.REG)
    for g in (g1, g2, g3):
        t.forget(g)
    assert t.get(g1) is None  # evicted (lru_limit=2)
    assert t.get(g3) is not None
    t.unlink(ROOT_GFID, "b")
    assert t.find_dentry(ROOT_GFID, "b") is None
    # root never purged
    t.invalidate(ROOT_GFID)
    assert t.root is t.get(ROOT_GFID)


def test_fop_enum_complete():
    # the reference's 59-fop vocabulary minus RPC-internal entries
    assert len(Fop) >= 50
    assert Fop.WRITEV.value == "writev"
