"""AFR split-brain: mutual-blame detection via the pending matrix,
read/write fencing, and glfsheal-style resolution (reference
afr_selfheal_find_direction, glfs-heal.c:53,1201, heal split-brain
CLI)."""

import asyncio
import errno

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc

VOLFILE = """
volume b0
    type storage/posix
    option directory {base}/brick0
end-volume

volume b1
    type storage/posix
    option directory {base}/brick1
end-volume

volume repl
    type cluster/replicate
    option quorum-count 1
{extra}    subvolumes b0 b1
end-volume
"""


def _mk(base, **opts):
    extra = "".join(f"    option {k} {v}\n" for k, v in opts.items())
    return Graph.construct(VOLFILE.format(base=base, extra=extra))


async def _make_split_brain(c, afr, path="/f"):
    """Classic 2-replica split-brain: write to each side while the
    other is partitioned away."""
    await c.write_file(path, b"common")
    afr.set_child_up(1, False)
    await c.write_file(path, b"side-A-content")  # b0 blames b1
    afr.set_child_up(1, True)
    afr.set_child_up(0, False)
    await c.write_file(path, b"side-B!")         # b1 blames b0
    afr.set_child_up(0, True)


def test_split_brain_detected_and_fenced(tmp_path):
    async def run():
        g = _mk(tmp_path)
        c = Client(g)
        await c.mount()
        afr = g.top
        await _make_split_brain(c, afr)
        info = await afr.heal_info(Loc("/f"))
        assert info["split_brain"] is True
        assert sorted(info["accused"]) == [0, 1]  # mutual blame
        # reads refuse to pick a side
        with pytest.raises(FopError) as ei:
            await c.read_file("/f")
        assert ei.value.err == errno.EIO
        # plain heal refuses without a policy
        with pytest.raises(FopError):
            await afr.heal_file("/f")
        # writes on the known-split file are fenced too
        with pytest.raises(FopError):
            await c.write_file("/f", b"new")
        await c.unmount()

    asyncio.run(run())


def test_split_brain_resolve_bigger_file(tmp_path):
    async def run():
        g = _mk(tmp_path)
        c = Client(g)
        await c.mount()
        await _make_split_brain(c, g.top)
        out = await g.top.split_brain_resolve("/f", "bigger-file")
        assert out["source"] == 0  # side-A-content is longer
        assert await c.read_file("/f") == b"side-A-content"
        info = await g.top.heal_info(Loc("/f"))
        assert info["split_brain"] is False and not info["accused"]
        # volume is fully writable again
        await c.write_file("/f", b"post-heal")
        assert await c.read_file("/f") == b"post-heal"
        await c.unmount()

    asyncio.run(run())


def test_split_brain_resolve_latest_mtime(tmp_path):
    async def run():
        g = _mk(tmp_path)
        c = Client(g)
        await c.mount()
        await _make_split_brain(c, g.top)  # side-B written last
        out = await g.top.split_brain_resolve("/f", "latest-mtime")
        assert out["source"] == 1
        assert await c.read_file("/f") == b"side-B!"
        await c.unmount()

    asyncio.run(run())


def test_split_brain_resolve_source_brick(tmp_path):
    async def run():
        g = _mk(tmp_path)
        c = Client(g)
        await c.mount()
        await _make_split_brain(c, g.top)
        out = await g.top.split_brain_resolve("/f", "source-brick",
                                              source=1)
        assert out["source"] == 1
        assert await c.read_file("/f") == b"side-B!"
        await c.unmount()

    asyncio.run(run())


def test_favorite_child_policy_auto_heal(tmp_path):
    """cluster.favorite-child-policy size: heal_file auto-resolves
    without an operator decision (shd crawl path)."""
    async def run():
        g = _mk(tmp_path, **{"favorite-child-policy": "size"})
        c = Client(g)
        await c.mount()
        await _make_split_brain(c, g.top)
        out = await g.top.heal_file("/f")
        assert out["source"] == 0
        assert await c.read_file("/f") == b"side-A-content"
        await c.unmount()

    asyncio.run(run())


def test_stale_brick_not_split_brain(tmp_path):
    """One-sided blame is NOT split-brain: the blamed brick is just
    stale and heals automatically toward the innocent source."""
    async def run():
        g = _mk(tmp_path)
        c = Client(g)
        await c.mount()
        afr = g.top
        await c.write_file("/s", b"v1")
        afr.set_child_up(1, False)
        await c.write_file("/s", b"v2-longer")
        afr.set_child_up(1, True)
        info = await afr.heal_info(Loc("/s"))
        assert info["split_brain"] is False
        assert info["good"] == [0] and 1 in info["accused"]
        # reads keep working (served from the source)
        assert await c.read_file("/s") == b"v2-longer"
        out = await afr.heal_file("/s")
        assert out["source"] == 0 and out["healed"] == [1]
        info = await afr.heal_info(Loc("/s"))
        assert info["good"] == [0, 1] and not info["accused"]
        await c.unmount()

    asyncio.run(run())
