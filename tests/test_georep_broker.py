"""Geo-rep broker channel (reference repce.py:35-223 + resource.py):
the secondary site is reached ONLY through a spawned agent process
spoken to over its stdio pipes — the worker process holds no secondary
client.  Swap the local spawn for an ssh spawn and nothing changes."""

import asyncio
import os
import subprocess

import pytest

from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume
from glusterfs_tpu.mgmt.repce import RepceClient


def test_broker_proxies_full_secondary_surface(tmp_path):
    """Namespace + data ops through the RepceClient proxy only; results
    verified through an independent direct mount."""

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="sec", vtype="disperse",
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(3)], redundancy=1)
                await c.call("volume-start", name="sec")
            broker = RepceClient(f"{d.host}:{d.port}:sec")
            try:
                assert await broker._call("__ping__") == "pong"
                # the agent is a REAL subprocess on the other end
                assert broker._proc is not None
                assert broker._proc.returncode is None
                await broker.mkdir("/d")
                f = await broker.create("/d/f")
                await f.write(b"over the pipes", 0)
                await f.close()
                f = await broker.open("/d/f", os.O_RDONLY)
                assert await f.read(14, 0) == b"over the pipes"
                await f.close()
                await broker.symlink("f", "/d/l")
                await broker.setattr("/d/f", {"mode": 0o600})
                await broker.rename("/d/f", "/d/g")
                await broker.truncate("/d/g", 4)
                # errors round-trip as FopErrors with errnos intact
                with pytest.raises(FopError) as ei:
                    await broker.unlink("/d/nope")
                import errno as _e

                assert ei.value.err in (_e.ENOENT, _e.ESTALE)
            finally:
                await broker.close()
            # verify through a direct mount: the broker really mutated
            # the volume
            direct = await mount_volume(d.host, d.port, "sec")
            try:
                assert await direct.read_file("/d/g") == b"over"
                assert await direct.readlink("/d/l") == "f"
                assert (await direct.stat("/d/g")).mode & 0o777 == 0o600
            finally:
                await direct.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


def test_worker_process_has_no_secondary_client(tmp_path):
    """The managed gsyncd subprocess (broker transport, the default)
    spawns a repce agent; the WORKER's own connections never touch the
    secondary volume's bricks — the agent's do."""

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                for vol in ("pri", "sec"):
                    await c.call("volume-create", name=vol,
                                 vtype="disperse",
                                 bricks=[{"path":
                                          str(tmp_path / f"{vol}{i}")}
                                         for i in range(3)],
                                 redundancy=1)
                    await c.call("volume-start", name=vol)
                await c.call("georep-create", name="pri",
                             secondary=f"{d.host}:{d.port}:sec")
                await c.call("georep-start", name="pri")
            # data converges through worker -> agent -> secondary
            pc = await mount_volume(d.host, d.port, "pri")
            try:
                await pc.write_file("/geo", b"site boundary")
            finally:
                await pc.unmount()
            sc = await mount_volume(d.host, d.port, "sec")
            try:
                ok = False
                for _ in range(120):
                    try:
                        if await sc.read_file("/geo") == b"site boundary":
                            ok = True
                            break
                    except FopError:
                        pass
                    await asyncio.sleep(0.5)
                assert ok, "geo-rep never converged through the broker"
            finally:
                await sc.unmount()
            # the agent subprocess exists under the gsyncd worker
            out = subprocess.run(
                ["ps", "-eo", "pid,args"], capture_output=True, text=True
            ).stdout
            assert "glusterfs_tpu.mgmt.repce" in out, (
                "no repce agent process found — secondary reached "
                "directly?")
            async with MgmtClient(d.host, d.port) as c:
                await c.call("georep-stop", name="pri")
        finally:
            await d.stop()

    asyncio.run(run())
