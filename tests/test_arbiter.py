"""Arbiter replica: metadata-only witness brick prevents split-brain
(reference features/arbiter + tests/basic/afr/arbiter.t)."""

import asyncio
import errno
import os

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc

VOLFILE = """
volume b0
    type storage/posix
    option directory {base}/brick0
end-volume

volume b1
    type storage/posix
    option directory {base}/brick1
end-volume

volume b2p
    type storage/posix
    option directory {base}/brick2
end-volume

volume b2
    type features/arbiter
    subvolumes b2p
end-volume

volume repl
    type cluster/replicate
    option arbiter-count 1
    subvolumes b0 b1 b2
end-volume
"""


def _mk(base):
    return Graph.construct(VOLFILE.format(base=base))


def test_arbiter_stores_no_data(tmp_path):
    async def run():
        g = _mk(tmp_path)
        c = Client(g)
        await c.mount()
        await c.write_file("/f", b"payload-bytes")
        assert await c.read_file("/f") == b"payload-bytes"
        # data bricks hold the bytes, the arbiter brick holds none
        for i, expect in ((0, 13), (1, 13), (2, 0)):
            p = tmp_path / f"brick{i}" / "f"
            assert p.exists()
            assert p.stat().st_size == expect, (i, p.stat().st_size)
        await c.unmount()

    asyncio.run(run())


def test_arbiter_witness_blocks_split_brain(tmp_path):
    """The arbiter's whole point: with one data brick down the other
    data brick + arbiter form quorum and blame it; the stale brick can
    then never be written while the fresh one is down (no mutual
    blame, no split-brain)."""
    async def run():
        g = _mk(tmp_path)
        c = Client(g)
        await c.mount()
        afr = g.top
        await c.write_file("/f", b"common")
        # partition data brick 1 away; write succeeds via b0+arbiter
        afr.set_child_up(1, False)
        await c.write_file("/f", b"newer-content")
        afr.set_child_up(1, True)
        # now partition b0: the would-be split-brain write must FAIL,
        # because b1 is blamed by both b0's and the arbiter's matrices
        afr.set_child_up(0, False)
        with pytest.raises(FopError):
            await c.read_file("/f")  # b1 stale, arbiter dataless
        afr.set_child_up(0, True)
        info = await afr.heal_info(Loc("/f"))
        assert info["split_brain"] is False
        assert 1 in info["accused"]
        out = await afr.heal_file("/f")
        assert out["source"] == 0
        assert 1 in out["healed"]
        assert await c.read_file("/f") == b"newer-content"
        # arbiter copy is healed metadata-only (still 0 bytes)
        assert (tmp_path / "brick2" / "f").stat().st_size == 0
        await c.unmount()

    asyncio.run(run())


def test_arbiter_never_serves_reads(tmp_path):
    async def run():
        g = _mk(tmp_path)
        c = Client(g)
        await c.mount()
        afr = g.top
        await c.write_file("/r", b"data")
        # only the arbiter up: reads must refuse, not return zeros
        afr.set_child_up(0, False)
        afr.set_child_up(1, False)
        with pytest.raises(FopError):
            await c.read_file("/r")
        afr.set_child_up(0, True)
        afr.set_child_up(1, True)
        assert await c.read_file("/r") == b"data"
        await c.unmount()

    asyncio.run(run())


@pytest.mark.slow
def test_managed_arbiter_volume(tmp_path):
    """volume create replica 3 arbiter 1: volgen puts features/arbiter
    on the last brick and arbiter-count on the client graph."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)
    from glusterfs_tpu.core.layer import walk

    async def run():
        gd = Glusterd(str(tmp_path / "gd"))
        await gd.start()
        async with MgmtClient(gd.host, gd.port) as c:
            bricks = [{"path": str(tmp_path / f"b{i}")} for i in range(3)]
            await c.call("volume-create", name="arb", vtype="replicate",
                         bricks=bricks, group_size=3, arbiter=1)
            await c.call("volume-start", name="arb")
        cl = await mount_volume(gd.host, gd.port, "arb")
        try:
            subs = [l for l in walk(cl.graph.top)
                    if l.type_name == "protocol/client"]
            for _ in range(150):
                if all(l.connected for l in subs):
                    break
                await asyncio.sleep(0.1)
            afr = next(l for l in walk(cl.graph.top)
                       if l.type_name == "cluster/replicate")
            assert afr.arbiters == {2}
            await cl.write_file("/x", b"managed-arbiter")
            assert await cl.read_file("/x") == b"managed-arbiter"
            assert os.path.getsize(tmp_path / "b2" / "x") == 0
            assert os.path.getsize(tmp_path / "b0" / "x") == 15
        finally:
            await cl.unmount()
            await gd.stop()

    asyncio.run(run())
