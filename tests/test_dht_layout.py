"""Persisted per-directory DHT layouts (reference dht-layout.c:20-94,
dht-selfheal.c): mkdir writes each subvol's hash range into a
``trusted.glusterfs.dht`` xattr, lookups place names by the PERSISTED
ranges (not a derived split), and ``rebalance fix-layout`` rewrites
ranges — optionally weighted — over the current child set, so
add-brick directs new creates at the new brick without
lookup-everywhere."""

import asyncio
import struct

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc
from glusterfs_tpu.cluster.dht import (XA_LAYOUT, _LAYOUT_FMT,
                                       DistributeLayer, dm_hash)


def _volfile(tmp_path, n):
    out = []
    for i in range(n):
        out.append(f"""
volume b{i}
    type storage/posix
    option directory {tmp_path}/brick{i}
end-volume
""")
    subs = " ".join(f"b{i}" for i in range(n))
    out.append(f"volume top\n    type cluster/distribute\n"
               f"    subvolumes {subs}\nend-volume\n")
    return "\n".join(out)


def _mount(tmp_path, n):
    g = Graph.construct(_volfile(tmp_path, n))
    c = Client(g)
    return c, g.top


async def _names_on(child, path):
    """Directory listing straight off one child ([] when the child has
    no copy of the directory at all — a just-added brick)."""
    from glusterfs_tpu.core.fops import FopError

    try:
        fd = await child.opendir(Loc(path))
        return [n for n, _ in await child.readdir(fd)]
    except FopError:
        return []


def test_mkdir_persists_ranges(tmp_path):
    async def run():
        c, dht = _mount(tmp_path, 3)
        await c.mount()
        await c.mkdir("/d")
        covered = []
        for i in range(3):
            out = await dht.children[i].getxattr(Loc("/d"), XA_LAYOUT)
            _v, _r, start, stop = struct.unpack(_LAYOUT_FMT,
                                                out[XA_LAYOUT])
            covered.append((start, stop, i))
        covered.sort()
        assert covered[0][0] == 0
        assert covered[-1][1] == (1 << 32) - 1
        for a, b in zip(covered, covered[1:]):
            assert a[1] + 1 == b[0], "ranges must tile the hash space"
        await c.unmount()

    asyncio.run(run())


def test_addbrick_respects_persisted_layout_until_fix(tmp_path):
    """Grow 2 -> 3 children: names in an OLD directory keep landing per
    the persisted 2-way layout (never on the new brick, no fan-out
    lookups); after fix-layout new creates use 3-way ranges and hit the
    new brick directly."""

    async def run():
        c2, dht2 = _mount(tmp_path, 2)
        await c2.mount()
        await c2.mkdir("/old")
        await c2.write_file("/old/seed", b"x")
        await c2.unmount()

        # "add-brick": same backends + one fresh brick, new graph
        c3, dht3 = _mount(tmp_path, 3)
        await c3.mount()
        # old dir still places by the persisted 2-way layout
        for j in range(40):
            await c3.write_file(f"/old/pre{j}", b"y")
        b2_files = await _names_on(dht3.children[2], "/old")
        assert b2_files == [], (
            f"new brick got files before fix-layout: {b2_files}")

        fixed = await dht3.fix_layout("/")
        assert fixed["fixed"] >= 2  # / and /old at least
        # fresh names owned by the NEW ranges land on the new brick,
        # chosen by reading the persisted layout (deterministic)
        out = await dht3.children[2].getxattr(Loc("/old"), XA_LAYOUT)
        _v, _r, start, stop = struct.unpack(_LAYOUT_FMT, out[XA_LAYOUT])
        assert stop > start
        landed, elsewhere = None, []
        for j in range(400):
            n = f"post{j}"
            if start <= dm_hash(n) <= stop:
                landed = landed or n
            elif len(elsewhere) < 10:
                elsewhere.append(n)
        assert landed is not None and len(elsewhere) == 10
        await c3.write_file(f"/old/{landed}", b"w")
        names = await _names_on(dht3.children[2], "/old")
        assert landed in names, "fix-layout range not honored"
        # VERDICT done criterion: after fix-layout, creates are DIRECT
        # — names owned by b0/b1 must not fan a single lookup onto the
        # new brick (the layout commit is current -> misses are
        # authoritative, lookup-optimize skips the everywhere pass)
        base = dht3.children[2].stats.get("lookup")
        base_n = base.count if base else 0
        for n in elsewhere:
            await c3.write_file(f"/old/{n}", b"z")
        after = dht3.children[2].stats.get("lookup")
        after_n = after.count if after else 0
        assert after_n == base_n, (
            "creates under a current layout must not fan out "
            f"lookups to the new brick ({after_n - base_n} extra)")
        # everything readable afterwards, incl. pre-fix files
        assert await c3.read_file("/old/seed") == b"x"
        assert await c3.read_file("/old/pre0") == b"y"
        assert await c3.read_file(f"/old/{landed}") == b"w"
        await c3.unmount()

    asyncio.run(run())


def test_weighted_fix_layout(tmp_path):
    """Weighted ranges: a child with weight 3 owns ~3x the hash span of
    a weight-1 child (the capability derived layouts cannot express)."""

    async def run():
        c, dht = _mount(tmp_path, 2)
        await c.mount()
        await c.mkdir("/w")
        await dht.fix_layout("/w", {"b0": 1.0, "b1": 3.0})
        spans = {}
        for i in range(2):
            out = await dht.children[i].getxattr(Loc("/w"), XA_LAYOUT)
            _v, _r, start, stop = struct.unpack(_LAYOUT_FMT,
                                                out[XA_LAYOUT])
            spans[i] = stop - start + 1
        ratio = spans[1] / spans[0]
        assert 2.5 < ratio < 3.5, f"weight ratio off: {ratio}"
        # placement follows the weighted ranges
        dht._layouts.clear()
        hits = {0: 0, 1: 0}
        for j in range(60):
            idx = await dht._placed(Loc(f"/w/f{j}"))
            hits[idx] += 1
        assert hits[1] > hits[0], hits
        await c.unmount()

    asyncio.run(run())


def test_decommission_then_fix_layout_clears_stale_ranges(tmp_path):
    """Decommission + fix-layout must remove the leaver's persisted
    range (else the union overlaps forever and every dir degrades to
    the anomalous-layout fallback), and the reconfigure invalidates
    cached authoritative layouts so existing files stay findable."""

    async def run():
        c, dht = _mount(tmp_path, 3)
        await c.mount()
        await c.mkdir("/d")
        for j in range(30):
            await c.write_file(f"/d/f{j}", b"x")
        # decommission b2 (remove-brick start analog)
        dht.reconfigure({"decommissioned": "b2"})
        # every file still findable right away (no stale authoritative
        # cache raising ENOENT)
        for j in range(30):
            assert await c.read_file(f"/d/f{j}") == b"x"
        await dht.rebalance("/")  # drain b2
        await dht.fix_layout("/")
        # the leaver carries no layout record anymore; the union of the
        # stayers is clean and authoritative
        with pytest.raises(FopError):
            await dht.children[2].getxattr(Loc("/d"), XA_LAYOUT)
        dht._layouts.clear()
        layout, authoritative = await dht._dir_meta("/d")
        assert layout is not None and authoritative
        for j in range(30):
            assert await c.read_file(f"/d/f{j}") == b"x"
        await c.unmount()

    asyncio.run(run())


def test_anomalous_layout_falls_back_derived(tmp_path):
    """Holes in the persisted union (half-written layout) must not
    misroute: the layer logs and falls back to the derived split."""

    async def run():
        c, dht = _mount(tmp_path, 2)
        await c.mount()
        await c.mkdir("/broken")
        # wipe one child's range: union no longer tiles the space
        await dht.children[0].removexattr(Loc("/broken"), XA_LAYOUT)
        dht._layouts.clear()
        assert await dht._dir_layout("/broken") is None
        # files still create and resolve
        await c.write_file("/broken/f", b"ok")
        assert await c.read_file("/broken/f") == b"ok"
        await c.unmount()

    asyncio.run(run())
