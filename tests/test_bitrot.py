"""Bit-rot detection: signer checksums quiescent objects, scrubber
catches silent on-disk corruption (content changed, mtime not),
quarantines the object brick-side, and the heal machinery rebuilds it —
the tests/bitrot/*.t analog.  Reference: bit-rot-stub.c:29-40,
bit-rot.c (signer), bit-rot-scrub.c (scrubber)."""

import asyncio
import errno
import json
import os

import numpy as np
import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import FdObj, Loc
from glusterfs_tpu.features.bit_rot_stub import XA_BAD, XA_SIG
from glusterfs_tpu.mgmt.bitd import BrickBitd
from glusterfs_tpu.mgmt.shd import crawl_once
from glusterfs_tpu.utils.volspec import ec_volfile

K, R = 4, 2
N = K + R
STRIPE = K * 512

BRICK_LAYERS = [("features/bit-rot-stub", {}), ("features/locks", {}),
                ("features/index", {})]


def _rand(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def _corrupt_preserving_mtime(path, offset=0, nbytes=16):
    """Silent disk corruption: bytes change, mtime does not."""
    st = os.stat(path)
    with open(path, "r+b") as f:
        f.seek(offset)
        old = f.read(nbytes)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in old))
    os.utime(path, (st.st_atime, st.st_mtime))


@pytest.fixture
def vol(tmp_path):
    g = Graph.construct(
        ec_volfile(tmp_path, N, R, brick_layers=BRICK_LAYERS))
    c = SyncClient(g)
    c.mount()
    yield c, g.top, tmp_path
    c.close()


def test_signer_signs_quiescent_only(vol):
    c, ec, base = vol
    c.write_file("/s", _rand(STRIPE, seed=1).tobytes())
    brick0 = ec.children[0]
    hot = BrickBitd(brick0, quiesce=3600)
    assert c._run(hot.sign_pass()) == 0  # too recent: not signed
    quiet = BrickBitd(brick0, quiesce=0)
    assert c._run(quiet.sign_pass()) == 1
    x = c._run(brick0.getxattr(Loc("/s"), XA_SIG))
    sig = json.loads(x[XA_SIG].decode())
    assert "sha256" in sig and sig["ts"] > 0
    # already signed: second pass is a no-op
    assert c._run(quiet.sign_pass()) == 0
    # clean scrub finds nothing
    assert c._run(quiet.scrub_pass()) == []


def test_scrub_quarantines_and_heal_recovers(vol):
    """Corrupt one EC fragment on disk: the scrubber catches it, the
    stub fences reads on that brick, EC serves from the others, the shd
    rebuilds the fragment, and the quarantine lifts."""
    c, ec, base = vol
    data = _rand(2 * STRIPE, seed=2).tobytes()
    c.write_file("/f", data)
    bitds = [BrickBitd(ch, quiesce=0) for ch in ec.children]
    for b in bitds:
        assert c._run(b.sign_pass()) == 1

    _corrupt_preserving_mtime(base / "brick0" / "f")
    # unmodified-but-different content -> corruption, quarantined
    assert c._run(bitds[0].scrub_pass()) == ["/f"]
    assert c._run(bitds[1].scrub_pass()) == []  # other bricks clean
    gfid = c.stat("/f").gfid
    bad_fd = FdObj(gfid, path="/f", anonymous=True)
    with pytest.raises(FopError):
        c._run(ec.children[0].readv(bad_fd, 512, 0))
    # plain writes are fenced too: only heal rebuilds may touch (and
    # unquarantine) a bad object
    with pytest.raises(FopError):
        c._run(ec.children[0].writev(bad_fd, b"x" * 512, 0))
    # the volume still serves correct data (EC rides the other bricks)
    assert c.read_file("/f") == data
    # the scrub marks fed the heal path: index entry + direction
    info = c._run(ec.heal_info(Loc("/f")))
    assert info["bad"] == [0]
    report = c._run(crawl_once(c._client))
    assert [h["path"] for h in report["healed"]] == ["/f"]
    # quarantine lifted by the rewrite; brick 0 serves again
    assert c._run(ec.children[0].readv(bad_fd, 512, 0))
    ec.set_child_up(4, False)
    ec.set_child_up(5, False)
    assert c.read_file("/f") == data  # brick 0 must participate
    ec.set_child_up(4, True)
    ec.set_child_up(5, True)
    info = c._run(ec.heal_info(Loc("/f")))
    assert info["bad"] == [] and not info["dirty"]


def test_quarantine_survives_brick_restart(tmp_path):
    g = Graph.construct(
        ec_volfile(tmp_path, N, R, brick_layers=BRICK_LAYERS))
    c = SyncClient(g)
    c.mount()
    data = _rand(STRIPE, seed=3).tobytes()
    c.write_file("/p", data)
    bitd = BrickBitd(g.top.children[2], quiesce=0)
    c._run(bitd.sign_pass())
    _corrupt_preserving_mtime(tmp_path / "brick2" / "p")
    assert c._run(bitd.scrub_pass()) == ["/p"]
    gfid = c.stat("/p").gfid
    c.close()
    # "restart" the brick stacks: a fresh graph over the same dirs
    g2 = Graph.construct(
        ec_volfile(tmp_path, N, R, brick_layers=BRICK_LAYERS))
    c2 = SyncClient(g2)
    c2.mount()
    try:
        bad_fd = FdObj(gfid, path="/p", anonymous=True)
        with pytest.raises(FopError):
            c2._run(g2.top.children[2].readv(bad_fd, 512, 0))
        assert c2.read_file("/p") == data
    finally:
        c2.close()


@pytest.mark.slow
def test_e2e_bitrot_detect_and_autoheal(tmp_path):
    """Full managed loop: bitd signs and scrubs over the brick RPC,
    corruption quarantines + feeds the index, the shd rebuilds, heal
    info drains — no operator action after 'bitrot enable'."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                bricks = [{"path": str(tmp_path / f"b{i}")}
                          for i in range(6)]
                await c.call("volume-create", name="bv", vtype="disperse",
                             bricks=bricks, redundancy=2)
                for k, v in (("features.bitrot", "on"),
                             ("bitrot.signer-quiesce", "0"),
                             ("bitrot.scrub-interval", "0.5"),
                             ("cluster.heal-timeout", "1")):
                    await c.call("volume-set", name="bv", key=k, value=v)
                await c.call("volume-start", name="bv")
                st = await c.call("volume-bitrot", name="bv",
                                  action="status")
                assert st["online"]

            client = await mount_volume(d.host, d.port, "bv")
            try:
                ec = next(l for l in client.graph.by_name.values()
                          if l.type_name == "cluster/disperse")
                for _ in range(150):
                    if all(ch.connected for ch in ec.children):
                        break
                    await asyncio.sleep(0.1)
                data = os.urandom(2 * 2048)
                await client.write_file("/doc", data)

                async with MgmtClient(d.host, d.port) as c:
                    signed = False
                    for _ in range(60):
                        st = await c.call("volume-bitrot", name="bv",
                                          action="scrub-status")
                        per = st.get("bricks", {})
                        if sum(b.get("signed", 0)
                               for b in per.values()) >= 6:
                            signed = True
                            break
                        await asyncio.sleep(0.5)
                    assert signed, f"bitd never signed: {st}"

                _corrupt_preserving_mtime(tmp_path / "b3" / "doc")
                async with MgmtClient(d.host, d.port) as c:
                    caught = False
                    for _ in range(60):
                        st = await c.call("volume-bitrot", name="bv",
                                          action="scrub-status")
                        per = st.get("bricks", {})
                        if any(b.get("corrupted")
                               for b in per.values()):
                            caught = True
                            break
                        await asyncio.sleep(0.5)
                    assert caught, f"corruption never detected: {st}"

                    healed = False
                    for _ in range(60):
                        info = await c.call("volume-heal", name="bv",
                                            action="info")
                        if info["count"] == 0:
                            healed = True
                            break
                        await asyncio.sleep(0.5)
                    assert healed, f"heal info never drained: {info}"
                assert await client.read_file("/doc") == data
            finally:
                await client.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


def test_scrub_token_bucket():
    """throttle-tbf analog: pacing, never-starve for oversized takes,
    and rate<=0 disabling."""
    import asyncio
    import time

    from glusterfs_tpu.mgmt.bitd import TokenBucket

    async def run():
        tb = TokenBucket(1 << 20)  # 1 MiB/s
        t0 = time.monotonic()
        for _ in range(3):
            await tb.take(1 << 20)
        dt = time.monotonic() - t0
        assert 1.5 <= dt <= 6.0, dt
        # an object bigger than a full second's budget must not
        # deadlock: the first take proceeds on the full bucket (debt),
        # the next waits the debt off — long-run rate preserved
        big = TokenBucket(1 << 20)
        t0 = time.monotonic()
        await big.take(2 << 20)  # immediate (bucket full)
        assert time.monotonic() - t0 < 0.5
        t0 = time.monotonic()
        await big.take(2 << 20)  # ~3s: 1 MiB debt + refill to full
        assert 1.5 <= time.monotonic() - t0 <= 8.0
        # disabled bucket never sleeps
        off = TokenBucket(0)
        t0 = time.monotonic()
        for _ in range(50):
            await off.take(1 << 30)
        assert time.monotonic() - t0 < 0.1

    asyncio.run(run())


def test_quarantine_fences_content_long_tail(vol):
    """graft-lint GL01 regression: a quarantined object's CONTENT is
    evidence — truncate/ftruncate/fallocate/discard/zerofill/put and
    copy_file_range were slipping past the quarantine that already
    fenced readv/writev/xorv."""
    c, ec, base = vol
    data = _rand(2 * STRIPE, seed=7).tobytes()
    c.write_file("/q", data)
    bitds = [BrickBitd(ch, quiesce=0) for ch in ec.children]
    for b in bitds:
        assert c._run(b.sign_pass()) == 1
    _corrupt_preserving_mtime(base / "brick0" / "q")
    assert c._run(bitds[0].scrub_pass()) == ["/q"]
    gfid = c.stat("/q").gfid
    brick0 = ec.children[0]
    bad_fd = FdObj(gfid, path="/q", anonymous=True)
    bad_loc = Loc("/q", gfid=gfid)

    async def drive():
        for denied in (brick0.truncate(bad_loc, 4),
                       brick0.ftruncate(bad_fd, 4),
                       brick0.fallocate(bad_fd, 0, 0, 4),
                       brick0.discard(bad_fd, 0, 4),
                       brick0.zerofill(bad_fd, 0, 4),
                       brick0.put(bad_loc, b"clobber"),
                       brick0.copy_file_range(bad_fd, 0, bad_fd, 4, 4)):
            with pytest.raises(FopError) as ei:
                await denied
            assert ei.value.err == errno.EIO
    c._run(drive())
    # the volume still serves correct data around the quarantine
    assert c.read_file("/q") == data
