"""Upcall cache invalidation: the brick tracks which clients touched an
inode and pushes MT_EVENT invalidations to the OTHERS on mutation;
md-cache drops its entry without waiting out the TTL — the
tests/basic/md-cache + upcall-cache-invalidate.t analog.
Reference: upcall.c:48-207, mdc_invalidate."""

import asyncio
import time

import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc
from glusterfs_tpu.rpc.wire import CURRENT_CLIENT

from .harness import BrickProc

UPCALL_BRICK = """
volume posix
    type storage/posix
    option directory {dir}
end-volume

volume locks
    type features/locks
    subvolumes posix
end-volume

volume upcall
    type features/upcall
    subvolumes locks
end-volume
"""


def test_upcall_layer_tracks_and_notifies(tmp_path):
    """In-process: interest registration + other-client invalidation +
    originator exclusion (upcall_client_cache_invalidate)."""
    g = Graph.construct(UPCALL_BRICK.format(dir=tmp_path / "b"))
    events = []
    up = g.by_name["upcall"]
    up.set_upcall_sink(lambda targets, payload:
                       events.append((sorted(targets), payload)))

    async def run():
        await g.activate()
        A, B = b"client-A", b"client-B"
        CURRENT_CLIENT.set(A)
        fd, ia = await g.top.create(Loc("/f"), 0, 0o644)
        await g.top.writev(fd, b"hello", 0)
        # only A has touched it: no one else to invalidate
        assert events == []
        CURRENT_CLIENT.set(B)
        await g.top.stat(Loc("/f"))          # B registers interest
        CURRENT_CLIENT.set(A)
        await g.top.writev(fd, b"world", 0)  # A mutates -> B invalidated
        assert len(events) == 1
        targets, payload = events[0]
        assert targets == [B]
        assert payload["gfid"] == ia.gfid
        assert payload["event"] == "cache-invalidation"
        # B mutates -> A invalidated (A wrote + created: registered)
        CURRENT_CLIENT.set(B)
        await g.top.truncate(Loc("/f"), 1)
        assert sorted(events[-1][0]) == [A]
        CURRENT_CLIENT.set(None)
        await g.fini()

    asyncio.run(run())


def test_release_client_drops_registrations(tmp_path):
    g = Graph.construct(UPCALL_BRICK.format(dir=tmp_path / "b"))
    events = []
    up = g.by_name["upcall"]
    up.set_upcall_sink(lambda t, p: events.append(t))

    async def run():
        await g.activate()
        CURRENT_CLIENT.set(b"B")
        await g.top.create(Loc("/x"), 0, 0o644)
        up.release_client(b"B")              # B disconnected
        CURRENT_CLIENT.set(b"A")
        await g.top.truncate(Loc("/x"), 0)
        assert events == []                  # no stale push to dead B
        CURRENT_CLIENT.set(None)
        await g.fini()

    asyncio.run(run())


CLIENT_VOLFILE = """
volume client0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume upcall
end-volume

volume mdc
    type performance/md-cache
    option timeout 3600
    subvolumes client0
end-volume
"""


def test_two_graphs_itable_invalidation(tmp_path):
    """The second-front-door scenario (ISSUE 6): client graphs A and B
    on one volume; A deletes and recreates a path (new gfid), and B —
    whose api-level itable still maps the old dentry — must revalidate
    from the pushed invalidation, NOT a remount.  Without the Client
    upcall sink, B keeps resolving the dead gfid and every fop on the
    path fails ESTALE/ENOENT forever."""
    import asyncio

    from glusterfs_tpu.api.glfs import Client
    from glusterfs_tpu.daemon import serve_brick

    async def run():
        server = await serve_brick(
            UPCALL_BRICK.format(dir=tmp_path / "b"))
        vf = CLIENT_VOLFILE.format(port=server.port)
        ca, cb = Client(Graph.construct(vf)), Client(Graph.construct(vf))
        await ca.mount()
        await cb.mount()
        try:
            for c in (ca, cb):
                prot = c.graph.by_name["client0"]
                for _ in range(200):
                    if prot.connected:
                        break
                    await asyncio.sleep(0.05)
                assert prot.connected
            await ca.write_file("/shared", b"one")
            assert await cb.read_file("/shared") == b"one"
            old_gfid = (await cb.stat("/shared")).gfid
            inv0 = cb.upcall_sink.invalidations
            # A replaces the object: the path now names a NEW gfid
            await ca.unlink("/shared")
            await ca.write_file("/shared", b"two!")
            for _ in range(100):  # the push, not a TTL
                if cb.upcall_sink.invalidations > inv0:
                    break
                await asyncio.sleep(0.05)
            assert cb.upcall_sink.invalidations > inv0, \
                "no invalidation reached B's api-level sink"
            # B re-resolves: fresh gfid, fresh bytes — no remount
            assert await cb.read_file("/shared") == b"two!"
            assert (await cb.stat("/shared")).gfid != old_gfid
        finally:
            await ca.unmount()
            await cb.unmount()
            await server.stop()

    asyncio.run(run())


@pytest.mark.slow
def test_two_clients_invalidate_over_wire(tmp_path):
    """Client A writes; client B's cached stat invalidates through the
    pushed MT_EVENT, NOT via TTL (timeout is one hour) — VERDICT
    next-round #6 done criterion."""
    brick = BrickProc(str(tmp_path), "brick0", volfile_tmpl=UPCALL_BRICK)
    port = brick.start()
    try:
        ca = SyncClient(Graph.construct(CLIENT_VOLFILE.format(port=port)))
        cb = SyncClient(Graph.construct(CLIENT_VOLFILE.format(port=port)))
        ca.mount()
        cb.mount()
        try:
            for c in (ca, cb):
                deadline = time.time() + 10
                prot = c.graph.by_name["client0"]
                while time.time() < deadline and not prot.connected:
                    time.sleep(0.05)
                assert prot.connected
            mdc_b = cb.graph.by_name["mdc"]

            f = ca.create("/shared")
            f.write(b"v1", 0)
            f.close()

            # B looks it up and caches the iatt under the gfid
            ia0 = cb._run(cb.graph.top.lookup(Loc("/shared")))[0]
            gloc = Loc("/shared", gfid=ia0.gfid)
            assert cb._run(cb.graph.top.stat(gloc)).size == 2
            hits0 = mdc_b.hits
            assert cb._run(cb.graph.top.stat(gloc)).size == 2
            assert mdc_b.hits == hits0 + 1  # served from cache

            # A extends the file; the push must reach B without TTL
            f = ca.open("/shared")
            f.write(b"longer-contents", 0)
            f.close()
            deadline = time.time() + 5
            while time.time() < deadline and mdc_b.invalidations == 0:
                time.sleep(0.05)
            assert mdc_b.invalidations >= 1, "no upcall arrived"
            # B's next stat refetches: sees the new size immediately
            assert cb._run(cb.graph.top.stat(gloc)).size == 15
        finally:
            ca.close()
            cb.close()
    finally:
        brick.kill()
