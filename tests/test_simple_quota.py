"""features/simple-quota: namespace limits, EDQUOT enforcement, delta
accounting, persisted usage re-seed (simple-quota.c behaviors)."""

import asyncio
import errno
import json

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc
from glusterfs_tpu.features.simple_quota import V_USAGE, XA_LIMIT


def _spec(tmp_path) -> str:
    return f"""
volume posix
    type storage/posix
    option directory {tmp_path}/brick
end-volume
volume squota
    type features/simple-quota
    option flush-interval 0
    subvolumes posix
end-volume
"""


def test_simple_quota_enforce_and_account(tmp_path):
    async def run():
        g = Graph.construct(_spec(tmp_path))
        c = Client(g)
        await c.mount()
        top = g.top
        await top.mkdir(Loc("/proj"), 0o755)
        await top.setxattr(Loc("/proj"), {XA_LIMIT: b"4096"})
        # under the limit: fine
        await c.write_file("/proj/a", b"x" * 1024)
        xa = await top.getxattr(Loc("/proj"), V_USAGE)
        usage = json.loads(xa[V_USAGE])
        assert usage == {"used": 1024, "limit": 4096}
        # exceeding the namespace limit: EDQUOT
        with pytest.raises(FopError) as ei:
            await c.write_file("/proj/b", b"y" * 4096)
        assert ei.value.err == errno.EDQUOT
        # other namespaces are unlimited
        await top.mkdir(Loc("/free"), 0o755)
        await c.write_file("/free/big", b"z" * 65536)
        # freeing space re-admits writes
        await top.unlink(Loc("/proj/a"))
        await c.write_file("/proj/c", b"w" * 4000)
        # truncate shrink is credited
        await top.truncate(Loc("/proj/c"), 100)
        usage = json.loads((await top.getxattr(
            Loc("/proj"), V_USAGE))[V_USAGE])
        assert usage["used"] == 100
        # usage query from a path INSIDE the namespace resolves to it
        inner = json.loads((await top.getxattr(
            Loc("/proj/c"), V_USAGE))[V_USAGE])
        assert inner["limit"] == 4096
        # limit 0 clears
        await top.setxattr(Loc("/proj"), {XA_LIMIT: b"0"})
        with pytest.raises(FopError):
            await top.getxattr(Loc("/proj"), V_USAGE)
        await c.unmount()

    asyncio.run(run())


def test_simple_quota_reseeds_from_xattr(tmp_path):
    async def run():
        g = Graph.construct(_spec(tmp_path))
        c = Client(g)
        await c.mount()
        await g.top.mkdir(Loc("/ns"), 0o755)
        await g.top.setxattr(Loc("/ns"), {XA_LIMIT: b"2048"})
        await c.write_file("/ns/f", b"d" * 1500)
        await c.unmount()
        # fresh graph over the same brick: limit + usage come back from
        # the persisted xattrs, and enforcement still holds
        g2 = Graph.construct(_spec(tmp_path))
        c2 = Client(g2)
        await c2.mount()
        usage = json.loads((await g2.top.getxattr(
            Loc("/ns"), V_USAGE))[V_USAGE])
        assert usage == {"used": 1500, "limit": 2048}
        with pytest.raises(FopError) as ei:
            await c2.write_file("/ns/g", b"e" * 1000)
        assert ei.value.err == errno.EDQUOT
        await c2.unmount()

    asyncio.run(run())


def test_simple_quota_rejects_nested_limit(tmp_path):
    async def run():
        g = Graph.construct(_spec(tmp_path))
        c = Client(g)
        await c.mount()
        await g.top.mkdir(Loc("/a"), 0o755)
        await g.top.mkdir(Loc("/a/b"), 0o755)
        with pytest.raises(FopError) as ei:
            await g.top.setxattr(Loc("/a/b"), {XA_LIMIT: b"1"})
        assert ei.value.err == errno.EINVAL
        await c.unmount()

    asyncio.run(run())


def test_volgen_wires_simple_quota(tmp_path):
    from glusterfs_tpu.mgmt import volgen

    vi = {
        "name": "sv", "type": "disperse", "redundancy": 2,
        "bricks": [{"index": i, "host": "h", "port": 1,
                    "path": str(tmp_path / f"b{i}"),
                    "name": f"sv-brick-{i}", "node": "x"}
                   for i in range(6)],
        "options": {"features.simple-quota": "on"},
    }
    text = volgen.build_brick_volfile(vi, vi["bricks"][0])
    assert "type features/simple-quota" in text
    assert "option usage-scale 4" in text  # 6 bricks - 2 redundancy
