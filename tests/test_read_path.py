"""Zero-copy read pipeline (ISSUE 3): scatter-gather wire replies,
read-ahead chain fusion + adaptive windows, EC fan-out fast path,
open-behind anon-fd hygiene, client strict-locks, and the volgen keys
that arm it all."""

import asyncio
import errno
import os

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import FdObj, Layer, Loc, register, walk
from glusterfs_tpu.daemon import serve_brick
from glusterfs_tpu.rpc import wire

from .harness import BRICK_VOLFILE

CLIENT_VOLFILE = """
volume c0
    type protocol/client
    option remote-host 127.0.0.1
    option remote-port {port}
    option remote-subvolume {sub}
{opts}end-volume
"""


async def _wait_connected(layer, timeout=10.0):
    for _ in range(int(timeout / 0.05)):
        if layer.connected:
            return True
        await asyncio.sleep(0.05)
    return layer.connected


# -- wire layer --------------------------------------------------------


def test_sgbuf_semantics():
    sg = wire.SGBuf([b"abc", memoryview(b"defg"), b""])
    assert len(sg) == 7
    assert bytes(sg) == b"abcdefg"
    assert sg.tobytes() == b"abcdefg"
    assert sg == b"abcdefg"
    assert sg == wire.SGBuf([b"abcd", b"efg"])
    assert not sg == b"abcdefX"
    assert wire.as_single_buffer(sg) == b"abcdefg"
    one = wire.SGBuf([b"solo"])
    assert wire.as_single_buffer(one) == b"solo"
    # single-segment as_single_buffer stays a view, not a copy
    assert isinstance(wire.as_single_buffer(one), memoryview)


def test_sg_vector_rides_one_frame_as_blobs():
    """An sg dict's segments ride the frame as separate trailing blob
    buffers (one gathered writelines), and decode back to views into
    the received frame — no join on either side."""
    segs = [b"A" * 8000, b"B" * 5000]
    payload = {wire.SG_KEY: [wire.Blob(s) for s in segs]}
    before = dict(wire.blob_stats)
    frames = wire.pack_frames(7, wire.MT_REPLY, payload)
    assert len(frames) == 3  # prefix + one buffer per segment
    assert wire.blob_stats["tx_blobs"] == before["tx_blobs"] + 2
    xid, mtype, out = wire.unpack(b"".join(frames)[4:])
    assert xid == 7
    got = out[wire.SG_KEY]
    assert [bytes(g) for g in got] == segs
    assert all(isinstance(g, memoryview) for g in got)


# -- wire end-to-end: server sg replies --------------------------------


@register("test/sg-source")
class SgSourceLayer(Layer):
    """Serves readv as a 2-segment SGBuf (the brick-side stand-in for
    any multi-buffer reply source)."""

    async def readv(self, fd, size, offset, xdata=None):
        data = await self.children[0].readv(fd, size, offset, xdata)
        data = bytes(data)
        mid = len(data) // 2
        return wire.SGBuf([data[:mid], data[mid:]])


SG_BRICK = BRICK_VOLFILE + """
volume sgsrc
    type test/sg-source
    subvolumes locks
end-volume
"""


def _sg_client(port, sub="sgsrc", sg="on"):
    g = Graph.construct(CLIENT_VOLFILE.format(
        port=port, sub=sub,
        opts=f"    option sg-replies {sg}\n"))
    return g


def test_wire_sg_readv_reply(tmp_path):
    """A brick-side multi-buffer readv reply crosses the wire as a blob
    vector and lands client-side as an SGBuf of frame views; a client
    that didn't advertise sg gets plain joined bytes."""
    async def run():
        server = await serve_brick(SG_BRICK.format(dir=tmp_path / "b"))
        payload = bytes(range(256)) * 64
        g = _sg_client(server.port)
        c = Client(g)
        await c.mount()
        cl = g.top
        assert await _wait_connected(cl)
        await c.write_file("/f", payload)
        f = await c.open("/f", os.O_RDONLY)
        data = await c.graph.top.readv(f.fd, 1 << 20, 0)
        assert isinstance(data, wire.SGBuf)
        assert len(data.segments) == 2
        assert data == payload
        assert await c.read_file("/f") == payload  # API edge: bytes
        await f.close()
        await c.unmount()

        # sg off: same bytes, single joined buffer (old-peer behavior)
        g2 = _sg_client(server.port, sg="off")
        c2 = Client(g2)
        await c2.mount()
        assert await _wait_connected(g2.top)
        f2 = await c2.open("/f", os.O_RDONLY)
        data2 = await c2.graph.top.readv(f2.fd, 1 << 20, 0)
        assert not isinstance(data2, wire.SGBuf)
        assert bytes(data2) == payload
        await f2.close()
        await c2.unmount()
        await server.stop()

    asyncio.run(run())


# -- client-side pipeline: io-cache / read-ahead sg serving ------------


def _vol(tmp_path, *layers) -> str:
    out = [f"volume posix\n    type storage/posix\n"
           f"    option directory {tmp_path}/b\nend-volume\n"]
    prev = "posix"
    for i, (ltype, opts) in enumerate(layers):
        name = f"l{i}"
        body = "".join(f"    option {k} {v}\n" for k, v in opts.items())
        out.append(f"volume {name}\n    type {ltype}\n{body}"
                   f"    subvolumes {prev}\nend-volume\n")
        prev = name
    return "\n".join(out)


def test_io_cache_serves_sg_page_views(tmp_path):
    """A multi-page cache hit is served as an SGBuf of page views —
    byte-identical to the page bytes, no join inside the layer."""
    async def run():
        g = Graph.construct(_vol(
            tmp_path, ("performance/io-cache", {"page-size": "4KB"})))
        c = Client(g)
        await c.mount()
        payload = bytes(range(256)) * 100  # 25600B: 7 pages
        await c.write_file("/f", payload)
        await c.read_file("/f")  # fill
        f = await c.open("/f", os.O_RDONLY)
        data = await g.top.readv(f.fd, len(payload), 0)
        assert isinstance(data, wire.SGBuf)
        assert len(data.segments) >= 2
        assert data == payload
        # an unaligned window straddling pages is sliced correctly
        part = await g.top.readv(f.fd, 9000, 1000)
        assert bytes(part) == payload[1000:10000]
        await f.close()
        await c.unmount()

    asyncio.run(run())


def test_read_ahead_adaptive_window(tmp_path):
    """The look-ahead window starts at one page, doubles per sustained
    sequential prefetch up to page-count, and a seek resets it."""
    async def run():
        g = Graph.construct(_vol(
            tmp_path, ("performance/read-ahead",
                       {"page-size": "4KB", "page-count": "8"})))
        c = Client(g)
        await c.mount()
        ra = g.top
        payload = bytes(range(256)) * 1024  # 256 KiB
        await c.write_file("/f", payload)
        f = await c.open("/f", os.O_RDONLY)
        ctx = None
        for i in range(6):
            got = await ra.readv(f.fd, 4096, i * 4096)
            assert bytes(got) == payload[i * 4096:(i + 1) * 4096]
            ctx = f.fd.ctx_get(ra)
        assert ctx.window > 1  # doubled under sequential load
        grown = ctx.window
        await ra.readv(f.fd, 4096, 200000)  # far seek
        assert f.fd.ctx_get(ra).window == 1 < grown  # ramp restarted
        await f.close()
        await c.unmount()

    asyncio.run(run())


def test_read_ahead_chain_fuses_demand_and_window(tmp_path):
    """With compound-fops on, the demand readv and its look-ahead
    window ride ONE wire frame: a sequential stream costs fewer round
    trips than the unfused task path, with identical bytes."""
    async def run():
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        payload = bytes(range(256)) * 512  # 128 KiB

        async def stream(ra_opts):
            g = Graph.construct(
                CLIENT_VOLFILE.format(
                    port=server.port, sub="locks",
                    opts="    option compound-fops on\n")
                + f"""
volume ra
    type performance/read-ahead
    option page-size 4KB
    option page-count 4
{ra_opts}    subvolumes c0
end-volume
""")
            c = Client(g)
            await c.mount()
            cl = next(l for l in walk(g.top)
                      if l.type_name == "protocol/client")
            assert await _wait_connected(cl)
            if not os.path.exists(tmp_path / "b" / "f"):
                await c.write_file("/f", payload)
            f = await c.open("/f", os.O_RDONLY)
            base = cl.rpc_roundtrips
            out = b""
            for i in range(16):
                got = await g.top.readv(f.fd, 4096, i * 4096)
                out += bytes(got)
            rts = cl.rpc_roundtrips - base
            await f.close()
            await c.unmount()
            return out, rts

        fused_out, fused_rts = await stream(
            "    option compound-fops on\n")
        plain_out, plain_rts = await stream("")
        assert fused_out == plain_out == payload[:16 * 4096]
        assert fused_rts < plain_rts, (fused_rts, plain_rts)
        await server.stop()

    asyncio.run(run())


def test_read_ahead_chain_survives_release_race(tmp_path):
    """release() cancels the in-flight demand+window chain task; a
    reader parked on it must still get its bytes (direct fallback),
    not a spurious CancelledError."""

    @register("test/slow-compound")
    class SlowCompound(Layer):
        async def compound(self, links, xdata=None):
            await asyncio.sleep(0.2)
            from glusterfs_tpu.rpc import compound as cfop

            return await cfop.decompose(self.children[0], links, xdata)

    async def run():
        g = Graph.construct(_vol(
            tmp_path,
            ("test/slow-compound", {}),
            ("performance/read-ahead",
             {"page-size": "4KB", "compound-fops": "on"})))
        c = Client(g)
        await c.mount()
        ra = g.top
        payload = bytes(range(256)) * 64
        await c.write_file("/f", payload)
        f = await c.open("/f", os.O_RDONLY)
        reader = asyncio.create_task(ra.readv(f.fd, 4096, 0))
        await asyncio.sleep(0.05)  # chain is parked in slow-compound
        await ra.release(f.fd)     # cancels the chain task
        got = await reader
        assert bytes(got) == payload[:4096]
        await c.unmount()

    asyncio.run(run())


# -- open-behind / read-ahead interaction ------------------------------


def test_open_behind_retires_anon_standin_on_materialize(tmp_path):
    """The anonymous stand-in fd (and its downstream read-ahead window,
    including any in-flight prefetch) is released when the deferred
    open materializes — prefetches issued pre-open never race the real
    fd's view of the file."""
    async def run():
        g = Graph.construct(_vol(
            tmp_path,
            ("performance/read-ahead", {"page-size": "4KB"}),
            ("performance/open-behind", {})))
        c = Client(g)
        await c.mount()
        ob = g.top
        ra = g.by_name["l0"]
        payload = bytes(range(256)) * 64
        await c.write_file("/f", payload)
        f = await c.open("/f", os.O_RDONLY)
        await g.top.readv(f.fd, 4096, 0)  # anon-routed, arms read-ahead
        ctx = f.fd.ctx_get(ob)
        anon = ctx.anon_fd
        assert anon is not None and anon.ctx_get(ra) is not None
        await g.top.fsync(f.fd, 0)  # forces the real open
        assert ctx.real_fd is not None
        assert ctx.anon_fd is None  # stand-in retired...
        assert anon.ctx_get(ra) is None  # ...and its ra window released
        got = await g.top.readv(f.fd, 4096, 0)  # now rides the real fd
        assert bytes(got) == payload[:4096]
        await f.close()
        await c.unmount()

    asyncio.run(run())


def test_open_behind_releases_anon_standin_on_close(tmp_path):
    """A lazy open/read/close pass must not leak the stand-in's
    downstream state (read-ahead pages + running prefetch task)."""
    async def run():
        g = Graph.construct(_vol(
            tmp_path,
            ("performance/read-ahead", {"page-size": "4KB"}),
            ("performance/open-behind", {})))
        c = Client(g)
        await c.mount()
        ob = g.top
        ra = g.by_name["l0"]
        await c.write_file("/f", bytes(range(256)) * 64)
        f = await c.open("/f", os.O_RDONLY)
        await g.top.readv(f.fd, 4096, 0)
        anon = f.fd.ctx_get(ob).anon_fd
        assert anon is not None and anon.ctx_get(ra) is not None
        await f.close()
        assert anon.ctx_get(ra) is None  # released, task cancelled
        await c.unmount()

    asyncio.run(run())


# -- client strict-locks -----------------------------------------------


def test_strict_locks_refuses_anon_bypass(tmp_path):
    """client.strict-locks (reference client.c:2438): an fd that holds
    posix locks and lost its server-side handle fails I/O with EBADFD
    instead of silently riding an anonymous fd past the lock."""
    async def run():
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        g = Graph.construct(CLIENT_VOLFILE.format(
            port=server.port, sub="locks",
            opts="    option strict-locks on\n"))
        c = Client(g)
        await c.mount()
        cl = g.top
        assert await _wait_connected(cl)
        await c.write_file("/lk", b"locked")
        f = await c.open("/lk", os.O_RDWR)
        await cl.lk(f.fd, "setlk",
                    {"type": "wr", "start": 0, "len": 0},
                    xdata={"lk-owner": b"me"})
        assert cl._fd_holds_locks(f.fd)
        # simulate a reconnect whose re-open failed: the handle is gone
        f.fd.ctx_del(cl)
        with pytest.raises(FopError) as ei:
            await cl.readv(f.fd, 6, 0)
        assert ei.value.err == errno.EBADFD
        # unlock drops the record; the anon route is then allowed again
        await cl.lk(f.fd, "setlk",
                    {"type": "unlck", "start": 0, "len": 0},
                    xdata={"lk-owner": b"me"})
        assert not cl._fd_holds_locks(f.fd)
        assert bytes(await cl.readv(f.fd, 6, 0)) == b"locked"
        await c.unmount()
        await server.stop()

    asyncio.run(run())


def test_strict_locks_off_allows_anon(tmp_path):
    async def run():
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        g = Graph.construct(CLIENT_VOLFILE.format(
            port=server.port, sub="locks", opts=""))
        c = Client(g)
        await c.mount()
        cl = g.top
        assert await _wait_connected(cl)
        await c.write_file("/lk", b"locked")
        f = await c.open("/lk", os.O_RDWR)
        await cl.lk(f.fd, "setlk",
                    {"type": "wr", "start": 0, "len": 0},
                    xdata={"lk-owner": b"me"})
        f.fd.ctx_del(cl)
        assert bytes(await cl.readv(f.fd, 6, 0)) == b"locked"
        await c.unmount()
        await server.stop()

    asyncio.run(run())


# -- EC fan-out --------------------------------------------------------


def _ec_client(tmp_path, n, r, options=None):
    from glusterfs_tpu.utils.volspec import ec_volfile

    g = Graph.construct(ec_volfile(str(tmp_path), n, r,
                                   options=options))
    return Client(g)


def test_ec_systematic_fanout_fast_path(tmp_path):
    """Healthy systematic reads take the zero-staging reassembly lane
    (fragment buffers straight into the output); the answer is
    byte-identical to the staged decode."""
    from glusterfs_tpu.cluster.ec import DisperseLayer

    async def run():
        c = _ec_client(tmp_path, 6, 2,
                       {"systematic": "on", "cpu-extensions": "ref"})
        await c.mount()
        ec = next(l for l in walk(c.graph.top)
                  if isinstance(l, DisperseLayer))
        payload = bytes(range(256)) * 300
        await c.write_file("/s", payload + b"odd")
        assert ec.read_fanout["fast"] == 0
        got = await c.read_file("/s")
        assert got == payload + b"odd"
        assert ec.read_fanout["fast"] > 0
        assert ec.read_fanout["staged"] == 0
        # staged reference: force the decode path on the same fragments
        f = await c.open("/s", os.O_RDONLY)
        fast = ec.read_fanout["fast"]
        orig = ec.codec.reassemble
        ec.codec.reassemble = lambda *a, **kw: None
        try:
            staged = await f.read(1 << 20, 0)
        finally:
            ec.codec.reassemble = orig
        await f.close()
        assert staged == payload + b"odd"
        assert ec.read_fanout["staged"] > 0
        assert ec.read_fanout["fast"] == fast
        await c.unmount()

    asyncio.run(run())


def test_ec_systematic_degraded_read_mask_identical(tmp_path):
    """With data bricks down (read-mask path) the staged reconstruct
    serves the same bytes the fast path served healthy."""
    from glusterfs_tpu.cluster.ec import DisperseLayer

    async def run():
        c = _ec_client(tmp_path, 6, 2,
                       {"systematic": "on", "cpu-extensions": "ref"})
        await c.mount()
        ec = next(l for l in walk(c.graph.top)
                  if isinstance(l, DisperseLayer))
        payload = bytes(range(251)) * 300  # prime-ish pattern
        await c.write_file("/d", payload)
        healthy = await c.read_file("/d")
        assert ec.read_fanout["fast"] > 0
        # operator read-mask excludes two DATA fragments: reads must
        # reconstruct from the remaining data + parity (staged path)
        ec._read_mask = {1, 2, 4, 5}
        degraded = await c.read_file("/d")
        assert degraded == healthy == payload
        assert ec.read_fanout["staged"] > 0  # reconstruction ran
        ec._read_mask = None
        await c.unmount()

    asyncio.run(run())


def test_ec_nonsystematic_stays_staged(tmp_path):
    async def run():
        from glusterfs_tpu.cluster.ec import DisperseLayer

        c = _ec_client(tmp_path, 4, 2, {"cpu-extensions": "ref"})
        await c.mount()
        ec = next(l for l in walk(c.graph.top)
                  if isinstance(l, DisperseLayer))
        payload = b"nonsys" * 1000
        await c.write_file("/n", payload)
        assert await c.read_file("/n") == payload
        assert ec.read_fanout["fast"] == 0
        assert ec.read_fanout["staged"] > 0
        await c.unmount()

    asyncio.run(run())


def test_shard_over_ec_read_roundtrip(tmp_path):
    """features/shard pads child readv results; EC now returns views —
    shard must own the buffer before .ljust (review regression)."""
    from glusterfs_tpu.utils.volspec import ec_volfile

    async def run():
        g = Graph.construct(ec_volfile(
            str(tmp_path), 6, 2, options={"cpu-extensions": "ref"}) + """
volume sh
    type features/shard
    option block-size 64KB
    subvolumes disp
end-volume
""")
        c = Client(g)
        await c.mount()
        payload = bytes(range(256)) * 700  # ~175KB: 3 shards
        await c.write_file("/s", payload)
        assert await c.read_file("/s") == payload
        await c.unmount()

    asyncio.run(run())


def test_codec_reassemble_matches_decode():
    """Oracle: reassemble == staged systematic decode on random
    fragments, including short (sparse-tail) buffers."""
    import numpy as np

    from glusterfs_tpu.ops.codec import Codec

    rng = np.random.default_rng(3)
    codec = Codec(4, 2, "ref", systematic=True)
    data = rng.integers(0, 256, 4 * 512 * 5, dtype=np.uint8)
    frags = codec.encode(data)
    bufs = [frags[i].tobytes() for i in range(4)]
    out = codec.reassemble(bufs, [0, 1, 2, 3], frags.shape[1])
    assert out is not None
    np.testing.assert_array_equal(out, data)
    # short buffer zero-fills exactly like the staging array did
    short = [bufs[0], bufs[1][: 512 * 3], bufs[2], bufs[3][:100]]
    staged = np.zeros((4, frags.shape[1]), dtype=np.uint8)
    for j, b in enumerate(short):
        staged[j, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    want = codec.decode(staged, [0, 1, 2, 3])
    got = codec.reassemble(short, [0, 1, 2, 3], frags.shape[1])
    np.testing.assert_array_equal(got, want)
    # non-qualifying row sets refuse (parity row present)
    assert codec.reassemble(bufs, [0, 1, 2, 4], frags.shape[1]) is None
    assert Codec(4, 2, "ref").reassemble(
        bufs, [0, 1, 2, 3], frags.shape[1]) is None


# -- ranged read_file (object-gateway satellite, ISSUE 6) --------------


def test_ranged_read_file_one_roundtrip(tmp_path):
    """A ranged ``read_file(path, offset=, size=)`` window inside the
    file is ONE fused chain — lookup+open+readv(window)+release in a
    single wire round trip — and the payload comes back RAW (a frame
    view / SGBuf, not joined bytes): the gateway's ranged GET hands the
    segments straight to the socket."""
    async def run():
        server = await serve_brick(
            BRICK_VOLFILE.format(dir=tmp_path / "b"))
        payload = bytes(range(256)) * 256  # 64 KiB
        g = Graph.construct(CLIENT_VOLFILE.format(
            port=server.port, sub="locks",
            opts="    option compound-fops on\n"))
        c = Client(g)
        await c.mount()
        cl = g.top
        assert await _wait_connected(cl)
        await c.write_file("/f", payload)
        base = cl.rpc_roundtrips
        data = await c.read_file("/f", offset=1000, size=5000)
        assert cl.rpc_roundtrips - base == 1, \
            "in-window ranged read_file must be one chain frame"
        assert not isinstance(data, bytes), \
            "ranged window must stay raw (join is the caller's call)"
        assert bytes(data) == payload[1000:6000]
        # EOF truncation, still one round trip
        base = cl.rpc_roundtrips
        data = await c.read_file("/f", offset=len(payload) - 100,
                                 size=4096)
        assert cl.rpc_roundtrips - base == 1
        assert bytes(data) == payload[-100:]
        # degenerate windows
        assert await c.read_file("/f", offset=0, size=0) == b""
        # open-ended tail (no size): windowed loop to EOF, still raw
        tail = await c.read_file("/f", offset=len(payload) - 300)
        assert bytes(tail) == payload[-300:]
        # whole-file default keeps returning owned bytes
        whole = await c.read_file("/f")
        assert isinstance(whole, bytes) and whole == payload
        # without compound the ranged contract holds (open+readv path)
        g2 = Graph.construct(CLIENT_VOLFILE.format(
            port=server.port, sub="locks", opts=""))
        c2 = Client(g2)
        await c2.mount()
        assert await _wait_connected(g2.top)
        d2 = await c2.read_file("/f", offset=4096, size=4096)
        assert bytes(d2) == payload[4096:8192]
        await c2.unmount()
        await c.unmount()
        await server.stop()

    asyncio.run(run())


# -- volgen keys -------------------------------------------------------


def test_volgen_read_pipeline_keys():
    """network.zero-copy-reads lands on both transport ends,
    cluster.use-compound-fops arms read-ahead, client.strict-locks and
    performance.read-ahead-adaptive map, and disperse volumes get
    stripe-aligned page sizes on the page-granular read layers."""
    from glusterfs_tpu.mgmt import volgen

    volinfo = {
        "name": "zv", "type": "disperse", "redundancy": 2,
        "group-size": 8,
        "bricks": [{"name": f"zv-brick-{i}", "host": "127.0.0.1",
                    "path": f"/tmp/zvb{i}", "index": i, "port": 0}
                   for i in range(8)],
        "options": {"cluster.use-compound-fops": "on",
                    "network.zero-copy-reads": "on",
                    "client.strict-locks": "on",
                    "performance.read-ahead-adaptive": "off"},
    }
    cvol = volgen.build_client_volfile(volinfo)
    bvol = volgen.build_brick_volfile(volinfo, volinfo["bricks"][0])
    client_stanza = cvol.split("volume zv-client-0")[1] \
                        .split("end-volume")[0]
    ra_stanza = cvol.split("volume zv-read-ahead")[1] \
                    .split("end-volume")[0]
    ioc_stanza = cvol.split("volume zv-io-cache")[1] \
                     .split("end-volume")[0]
    srv_stanza = bvol.split("volume zv-brick-0-server")[1] \
                     .split("end-volume")[0]
    assert "sg-replies on" in client_stanza
    assert "sg-replies on" in srv_stanza
    assert "strict-locks on" in client_stanza
    assert "compound-fops on" in ra_stanza
    assert "adaptive-window off" in ra_stanza
    # k=6 -> stripe 3072; largest multiple <= 128KB is 129024
    assert "page-size 129024" in ra_stanza
    assert "page-size 129024" in ioc_stanza
    for key in ("network.zero-copy-reads", "client.strict-locks",
                "performance.read-ahead-adaptive"):
        assert volgen.OPTION_MIN_OPVERSION[key] == 6
    # a power-of-two geometry keeps the 128KB default exactly
    volinfo4 = dict(volinfo, options={}, redundancy=2)
    volinfo4["group-size"] = 6
    cvol4 = volgen.build_client_volfile(volinfo4)
    ra4 = cvol4.split(f"volume zv-read-ahead")[1].split("end-volume")[0]
    assert "page-size 131072" in ra4
