"""debug/* layers: error-gen fault injection, delay-gen, trace history,
io-stats profile — and an EC volume surviving injected brick errors
(the reference's error-gen-driven .t scenarios)."""

import asyncio

import pytest

from glusterfs_tpu.api.glfs import SyncClient
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc


def test_error_gen_injects(tmp_path):
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume errg
    type debug/error-gen
    option failure 100
    option error-no ENOTCONN
    option enable writev,readv
    subvolumes posix
end-volume
"""
    c = SyncClient(Graph.construct(vf))
    c.mount()
    c.mkdir("/d")  # mkdir not in enable list -> passes
    f = c.create("/f")
    with pytest.raises(FopError) as ei:
        f.write(b"x", 0)
    assert ei.value.err == 107  # ENOTCONN
    # reconfigure to 0% -> heals
    c.graph.by_name["errg"].reconfigure({"failure": 0})
    f.write(b"x", 0)
    f.close()
    c.close()


def test_delay_gen(tmp_path):
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume slow
    type debug/delay-gen
    option delay-duration 30000
    option delay-percentage 100
    option enable writev
    subvolumes posix
end-volume
"""
    import time

    c = SyncClient(Graph.construct(vf))
    c.mount()
    t0 = time.perf_counter()
    c.write_file("/f", b"x")
    assert time.perf_counter() - t0 >= 0.03
    c.close()


def test_trace_and_iostats(tmp_path):
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume tr
    type debug/trace
    subvolumes posix
end-volume
volume stats
    type debug/io-stats
    subvolumes tr
end-volume
"""
    c = SyncClient(Graph.construct(vf))
    c.mount()
    c.write_file("/f", b"hello")
    assert c.read_file("/f") == b"hello"
    tr = c.graph.by_name["tr"]
    assert any("writev" in line for line in tr.history)
    st = c.graph.by_name["stats"]
    prof = st.profile()
    assert prof["write_bytes"] == 5 and prof["read_bytes"] == 5
    assert prof["fops"]["writev"]["count"] >= 1
    c.close()


def test_iostats_volume_top(tmp_path):
    """`volume top` backend: per-path ranked open/read/write counters
    (io-stats ios_stat_list)."""
    vf = f"""
volume posix
    type storage/posix
    option directory {tmp_path}/b
end-volume
volume stats
    type debug/io-stats
    subvolumes posix
end-volume
"""
    c = SyncClient(Graph.construct(vf))
    c.mount()
    c.write_file("/hot", b"x" * 100)
    for _ in range(5):
        assert c.read_file("/hot")
    c.write_file("/cold", b"y")
    st = c.graph.by_name["stats"]
    top_read = st.top("read")
    assert top_read and top_read[0]["path"] == "/hot"
    assert top_read[0]["reads"] == 5
    top_open = st.top("open", count=1)
    assert len(top_open) == 1 and top_open[0]["path"] == "/hot"
    assert st.top("write-bytes")[0]["write_bytes"] == 100
    try:
        st.top("bogus")
        raise AssertionError("bad metric accepted")
    except ValueError:
        pass
    c.close()


def test_ec_with_flaky_brick(tmp_path):
    """One brick fails 100% of writes: EC rides through on quorum and
    heal_info flags the brick (error-gen as the brick-failure harness)."""
    bricks = []
    for i in range(6):
        bricks.append(f"""
volume p{i}
    type storage/posix
    option directory {tmp_path}/b{i}
end-volume
""")
    # brick 2 wrapped in error-gen
    vf = "".join(bricks) + """
volume flaky
    type debug/error-gen
    option failure 100
    option enable writev,xattrop,setxattr,create,mknod
    subvolumes p2
end-volume
volume disp
    type cluster/disperse
    option redundancy 2
    subvolumes p0 p1 flaky p3 p4 p5
end-volume
"""
    c = SyncClient(Graph.construct(vf))
    c.mount()
    data = bytes(range(256)) * 16
    c.write_file("/f", data)
    assert c.read_file("/f") == data
    ec = c.graph.top
    info = c._run(ec.heal_info(Loc("/f")))
    assert 2 in info["bad"]
    # let the brick recover, heal, verify
    c.graph.by_name["flaky"].reconfigure({"failure": 0})
    res = c._run(ec.heal_file("/f"))
    assert 2 in res["healed"]
    assert c._run(ec.heal_info(Loc("/f")))["bad"] == []
    c.close()


def test_sink_terminates_graph(tmp_path):
    """debug/sink answers everything without a backend (sink.c)."""
    vf = """
volume devnull
    type debug/sink
end-volume
"""
    c = SyncClient(Graph.construct(vf))
    c.mount()
    f = c.create("/anything")
    assert f.write(b"swallowed", 0) == 9
    f.close()
    assert c.stat("/whatever") is not None
    c.mkdir("/dir")
    c.unlink("/anything")
    c.close()
