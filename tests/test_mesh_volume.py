"""A SERVED volume on the device mesh: ``cpu-extensions=mesh`` routes
the EC layer's codec through the sharded (dp, frag) data plane
(parallel/mesh_codec) — write/read parity, degraded reads, heal, and
the batching window all run on the 8-device virtual mesh the conftest
provisions (VERDICT r2 #4: the mesh must be a reachable backend of a
real volume, not a sidecar demo)."""

import asyncio
import os

import numpy as np
import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc
from glusterfs_tpu.utils.volspec import ec_volfile

K, R = 4, 2
N = K + R
STRIPE = K * 512


@pytest.fixture
def vol(tmp_path):
    g = Graph.construct(ec_volfile(tmp_path, N, R, options={
        "cpu-extensions": "mesh", "stripe-cache": "on",
        "stripe-cache-min-batch": 0}))
    c = Client(g)
    asyncio.run(c.mount())
    yield c, g.top
    asyncio.run(c.unmount())


def _rand(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def test_mesh_backend_selected(vol):
    c, ec = vol
    assert ec.codec.backend == "mesh"
    import jax

    assert len(jax.devices()) == 8  # the virtual mesh is really there


def test_mesh_volume_roundtrip_and_degraded(vol):
    c, ec = vol

    async def run():
        for i, size in enumerate((1, STRIPE, 3 * STRIPE + 77, 1 << 18)):
            data = _rand(size, seed=i).tobytes()
            await c.write_file(f"/m{i}", data)
            assert await c.read_file(f"/m{i}") == data
        assert ec.codec.launches > 0, "mesh codec never launched"
        # degraded: drop R children, reads reconstruct via mesh decode
        ec.set_child_up(0, False)
        ec.set_child_up(3, False)
        for i, size in enumerate((1, STRIPE, 3 * STRIPE + 77, 1 << 18)):
            assert await c.read_file(f"/m{i}") == \
                _rand(size, seed=i).tobytes()
        ec.set_child_up(0, True)
        ec.set_child_up(3, True)

    asyncio.run(run())


def test_mesh_volume_heal(vol):
    c, ec = vol

    async def run():
        data = _rand(6 * STRIPE, seed=9).tobytes()
        await c.write_file("/h", data)
        ec.set_child_up(2, False)
        patch = _rand(STRIPE, seed=10).tobytes()
        f = await c.open("/h")
        await f.write(patch, 0)
        await f.close()
        ec.set_child_up(2, True)
        healed = await ec.heal_file("/h")
        assert 2 in healed["healed"]
        ec.set_child_up(4, False)
        ec.set_child_up(5, False)
        assert await c.read_file("/h") == patch + data[STRIPE:]
        ec.set_child_up(4, True)
        ec.set_child_up(5, True)

    asyncio.run(run())


def test_mesh_ring_decode_threshold(vol, monkeypatch):
    """Past the memory threshold the mesh decode rides the ring
    pipeline (ppermute reduce) instead of the all-gather plane."""
    from glusterfs_tpu.ops import codec as codec_mod
    from glusterfs_tpu.parallel import ring_codec

    c, ec = vol
    called = {}
    orig = ring_codec.ring_decode

    def spy(k, rows, frags, mesh=None):
        called["ring"] = True
        return orig(k, rows, frags, mesh)

    monkeypatch.setattr(ring_codec, "ring_decode", spy)
    monkeypatch.setattr(codec_mod, "MESH_RING_DECODE_BYTES", 4 * STRIPE)

    async def run():
        data = _rand(64 * STRIPE, seed=11).tobytes()
        await c.write_file("/big", data)
        ec.set_child_up(0, False)  # force reconstruction
        assert await c.read_file("/big") == data
        ec.set_child_up(0, True)

    asyncio.run(run())
    assert called.get("ring"), "large mesh decode did not take the ring"
