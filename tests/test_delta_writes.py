"""Parity-delta sub-stripe writes (ISSUE 10) — the EC write plane's
linear-code delta update: a sub-stripe write on a healthy systematic
volume ships only the overwritten data-fragment bytes plus m parity
deltas applied by the brick-side ``xorv`` fop, skipping the reference's
full read-modify-write (ec-inode-write.c:2141 analog).  Pins:

* the acceptance fop-count pin — touched-data writev + R parity xorv,
  ZERO readv on untouched data bricks, and the
  ``gftpu_ec_delta_writes_total`` family increments;
* the property test — random unaligned write sequences (interleaved
  parallel batches included) through delta-on vs delta-off stacks give
  byte-identical files AND byte-identical fragments + trusted.ec.*
  counters on every brick;
* the fallback matrix — degraded, non-systematic, EOF-crossing and
  zerofill-edge writes keep the RMW path; a live-downgraded brick
  (EOPNOTSUPP xorv) parks the layer on RMW with no divergence;
* the xorv hazard pins — posix read-xor-write semantics (double-apply
  self-cancels), journal batching, write-class / never-retried, and
  the SETVOLUME capability gate;
* the write-behind satellite — pressure drains cut at stripe
  boundaries so streamed writes hit the aligned path.
"""

import asyncio
import errno
import os

import numpy as np
import pytest

from glusterfs_tpu.api.glfs import Client, SyncClient
from glusterfs_tpu.core.fops import Fop, FopError, WRITE_FOPS
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import Loc
from glusterfs_tpu.core.metrics import REGISTRY
from glusterfs_tpu.ops import gf256
from glusterfs_tpu.utils.volspec import ec_volfile

K, R = 4, 2
N = K + R
STRIPE = K * 512


def _rand(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8)


def _mount(tmp_path, delta="on", systematic="on", options=None):
    g = Graph.construct(ec_volfile(
        str(tmp_path), N, R,
        options={"systematic": systematic, "delta-writes": delta,
                 **(options or {})}))
    c = SyncClient(g)
    c.mount()
    return c, g.top


def _counts(ec, op):
    return [ch.stats[op].count if op in ch.stats else 0
            for ch in ec.children]


# -- the acceptance pin ------------------------------------------------


def test_sub_stripe_write_fop_counts_and_family(tmp_path):
    """A healthy systematic 4+2 sub-stripe write provably skips the
    k-fragment decode: touched data bricks see one readv + one writev,
    parity bricks see one xorv each, untouched data bricks see NOTHING
    — and the registry family increments."""
    c, ec = _mount(tmp_path)
    try:
        data = _rand(4 * STRIPE, seed=1).tobytes()
        c.write_file("/f", data)

        def fam():
            snap = REGISTRY.snapshot()
            return {s[0]["layer"]: s[1]
                    for s in snap["gftpu_ec_delta_writes_total"]["samples"]}

        before = {op: _counts(ec, op) for op in ("readv", "writev",
                                                 "xorv")}
        fam_before = fam().get(ec.name, 0)
        f = c.open("/f")
        # 700 bytes at 1000: chunks 1-3 of stripe 0 — data brick 0 and
        # no other stripe are touched
        f.write(b"Q" * 700, 1000)
        f.close()
        d = {op: [a - b for a, b in zip(_counts(ec, op), before[op])]
             for op in ("readv", "writev", "xorv")}
        assert d["readv"] == [0, 1, 1, 1, 0, 0], d
        assert d["writev"] == [0, 1, 1, 1, 0, 0], d
        assert d["xorv"] == [0, 0, 0, 0, 1, 1], d
        assert ec.write_path["delta"] == 1
        assert ec.write_path["rmw"] == 0
        assert fam().get(ec.name, 0) == fam_before + 1
        assert ec.delta_saved["read"] > 0
        assert ec.delta_saved["write"] > 0
        exp = bytearray(data)
        exp[1000:1700] = b"Q" * 700
        assert c.read_file("/f") == bytes(exp)
    finally:
        c.close()


def test_delta_fragments_match_oracle(tmp_path):
    """The delta wave lands EXACTLY the systematic codeword on every
    brick (the linearity claim, byte-for-byte)."""
    c, ec = _mount(tmp_path)
    try:
        data = _rand(2 * STRIPE, seed=2)
        c.write_file("/f", data.tobytes())
        f = c.open("/f")
        f.write(b"Z" * 1234, 333)
        f.close()
        assert ec.write_path["delta"] == 1
    finally:
        c.close()
    exp = data.copy()
    exp[333:333 + 1234] = np.frombuffer(b"Z" * 1234, dtype=np.uint8)
    oracle = gf256.ref_encode(exp, K, N, systematic=True)
    for i in range(N):
        frag = open(os.path.join(str(tmp_path), f"brick{i}", "f"),
                    "rb").read()
        assert frag == oracle[i].tobytes(), f"brick {i}"


# -- the property test -------------------------------------------------


def _gen_ops(seed, size, n_ops=24):
    """Deterministic mixed write sequence: unaligned interior writes,
    aligned writes, EOF-extending writes, and parallel batches over
    DISJOINT stripe ranges (order-independent, so both stacks converge
    to the same bytes)."""
    rng = np.random.default_rng(seed)
    ops = []
    for i in range(n_ops):
        kind = rng.integers(0, 10)
        if kind < 6:  # unaligned interior
            off = int(rng.integers(1, size - 9000))
            ln = int(rng.integers(1, 8000))
            ops.append(("w", off, ln))
        elif kind < 7:  # stripe-aligned
            off = int(rng.integers(0, (size - 2 * STRIPE) // STRIPE)) * STRIPE
            ops.append(("w", int(off), STRIPE))
        elif kind < 8:  # EOF-crossing extend
            ops.append(("w", size - int(rng.integers(1, 500)),
                        int(rng.integers(1, 3000))))
        else:  # parallel batch over disjoint aligned spans
            batch = []
            for b in range(3):
                span = 4 * STRIPE
                off = b * (size // 3) + int(rng.integers(1, STRIPE))
                ln = int(rng.integers(1, 2000))
                batch.append((off, ln))
            ops.append(("p", batch))
    return ops


async def _apply_ops(base, delta_on, ops, size, seed=99):
    rng = np.random.default_rng(seed)
    init = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
    c = Client(Graph.construct(ec_volfile(
        base, N, R, options={"systematic": "on",
                             "delta-writes": "on" if delta_on
                             else "off"})))
    await c.mount()
    try:
        ec = c.graph.top
        await c.write_file("/f", init)
        f = await c.open("/f")
        payload = rng.integers(0, 256, 16384, dtype=np.uint8).tobytes()
        for op in ops:
            if op[0] == "w":
                _t, off, ln = op
                await f.write(payload[:ln], off)
            else:
                await asyncio.gather(*(f.write(payload[:ln], off)
                                       for off, ln in op[1]))
        await f.close()
        data = bytes(await c.read_file("/f"))
        xattrs = {}
        for i, ch in enumerate(ec.children):
            x = await ch.getxattr(Loc("/f"), None)
            xattrs[i] = {k: v for k, v in x.items()
                         if k.startswith("trusted.ec.")}
        return data, xattrs, dict(ec.write_path)
    finally:
        await c.unmount()


def test_property_delta_vs_rmw_stacks(tmp_path):
    """Random write sequences through delta-on vs delta-off stacks:
    byte-identical files, byte-identical FRAGMENTS, and identical
    trusted.ec.{version,size,dirty} on every brick."""
    for seed in (5, 6):
        size = 8 * STRIPE
        ops = _gen_ops(seed, size)
        base_on = str(tmp_path / f"on{seed}")
        base_off = str(tmp_path / f"off{seed}")
        data_on, xa_on, wp_on = asyncio.run(
            _apply_ops(base_on, True, ops, size))
        data_off, xa_off, wp_off = asyncio.run(
            _apply_ops(base_off, False, ops, size))
        assert data_on == data_off, f"seed {seed}: file bytes diverged"
        assert xa_on == xa_off, f"seed {seed}: xattr counters diverged"
        assert wp_on["delta"] > 0, "delta stack never took the path"
        assert wp_off["delta"] == 0, "delta-off stack took the path"
        # fragments byte-identical on disk
        for i in range(N):
            a = open(os.path.join(base_on, f"brick{i}", "f"),
                     "rb").read()
            b = open(os.path.join(base_off, f"brick{i}", "f"),
                     "rb").read()
            assert a == b, f"seed {seed}: brick {i} fragment diverged"


# -- fallback matrix ---------------------------------------------------


def test_degraded_falls_back_to_rmw(tmp_path):
    c, ec = _mount(tmp_path)
    try:
        data = _rand(4 * STRIPE, seed=3).tobytes()
        c.write_file("/g", data)
        ec.set_child_up(0, False)
        f = c.open("/g")
        f.write(b"D" * 700, 1000)
        f.close()
        assert ec.write_path["delta"] == 0
        assert ec.write_path["rmw"] == 1
        exp = bytearray(data)
        exp[1000:1700] = b"D" * 700
        assert c.read_file("/g") == bytes(exp)
        ec.set_child_up(0, True)
    finally:
        c.close()


def test_non_systematic_never_delta(tmp_path):
    c, ec = _mount(tmp_path, systematic="off")
    try:
        c.write_file("/h", _rand(2 * STRIPE, seed=4).tobytes())
        f = c.open("/h")
        f.write(b"x" * 100, 50)
        f.close()
        assert ec.write_path["delta"] == 0
        assert ec.write_path["rmw"] == 1
        assert _counts(ec, "xorv") == [0] * N
    finally:
        c.close()


def test_eof_crossing_falls_back(tmp_path):
    c, ec = _mount(tmp_path)
    try:
        data = _rand(STRIPE, seed=5).tobytes()
        c.write_file("/e", data)
        f = c.open("/e")
        f.write(b"y" * 1000, STRIPE - 100)  # extends past true size
        f.close()
        assert ec.write_path["delta"] == 0
        assert c.stat("/e").size == STRIPE + 900
        assert c.read_file("/e") == data[:STRIPE - 100] + b"y" * 1000
    finally:
        c.close()


def test_delta_writes_off_by_key(tmp_path):
    c, ec = _mount(tmp_path, delta="off")
    try:
        c.write_file("/k", _rand(2 * STRIPE, seed=6).tobytes())
        f = c.open("/k")
        f.write(b"k" * 600, 700)
        f.close()
        assert ec.write_path["delta"] == 0
        assert ec.write_path["rmw"] == 1
    finally:
        c.close()


def test_zerofill_edges_keep_rmw(tmp_path):
    """Allocation-class edges stay on the proven RMW shape (the
    fallback matrix's zerofill row)."""
    c, ec = _mount(tmp_path)
    try:
        data = _rand(4 * STRIPE, seed=7).tobytes()
        c.write_file("/z", data)
        f = c.open("/z")
        c._run(ec.zerofill(f.fd, STRIPE // 2, STRIPE))
        f.close()
        assert ec.write_path["delta"] == 0
        exp = bytearray(data)
        exp[STRIPE // 2: STRIPE // 2 + STRIPE] = b"\0" * STRIPE
        assert c.read_file("/z") == bytes(exp)
    finally:
        c.close()


def test_live_downgrade_eopnotsupp_parks_layer(tmp_path):
    """A parity brick answering EOPNOTSUPP to xorv (live-downgraded
    peer) converts the write to full RMW in the SAME window with no
    divergence, and parks the layer on RMW for later writes."""
    c, ec = _mount(tmp_path)
    try:
        data = _rand(2 * STRIPE, seed=8).tobytes()
        c.write_file("/d", data)

        async def refuse(*a, **kw):
            raise FopError(errno.EOPNOTSUPP, "no xorv here")

        ec.children[4].xorv = refuse  # instance shadow on one parity
        f = c.open("/d")
        f.write(b"W" * 500, 600)
        f.close()
        assert ec._xorv_ok is False
        assert ec.write_path["delta"] == 0
        assert ec.write_path["rmw"] == 1
        # nothing diverged: the redo rewrote every fragment
        info = c._run(ec.heal_info(Loc("/d")))
        assert info["bad"] == [] and not info["dirty"]
        exp = bytearray(data)
        exp[600:1100] = b"W" * 500
        assert c.read_file("/d") == bytes(exp)
        # later writes skip the delta attempt entirely
        f = c.open("/d")
        f.write(b"V" * 500, 600)
        f.close()
        assert ec.write_path["rmw"] == 2
        exp[600:1100] = b"V" * 500
        # the operator toggling the key re-arms the probe
        ec.reconfigure({"delta-writes": "on", "systematic": "on",
                        "redundancy": R})
        assert ec._xorv_ok is True
    finally:
        c.close()
    oracle = gf256.ref_encode(np.frombuffer(bytes(exp), dtype=np.uint8),
                              K, N, systematic=True)
    for i in range(N):
        frag = open(os.path.join(str(tmp_path), f"brick{i}", "d"),
                    "rb").read()
        assert frag == oracle[i].tobytes(), f"brick {i}"


# -- xorv fop pins ------------------------------------------------------


def test_posix_xorv_semantics(tmp_path):
    """Read-xor-write at an offset: applies a delta in place, a
    DOUBLE-apply self-cancels (the no-blind-retry hazard made
    visible), and past-EOF bytes XOR against zeros."""
    vol = (f"volume posix\n    type storage/posix\n"
           f"    option directory {tmp_path}/b\nend-volume\n")
    c = SyncClient(Graph.construct(vol))
    c.mount()
    try:
        posix = c.graph.top
        c.write_file("/f", bytes(range(64)))
        f = c.open("/f")
        delta = bytes(0x55 for _ in range(16))
        c._run(posix.xorv(f.fd, delta, 8))
        got = c.read_file("/f")
        exp = bytearray(range(64))
        for i in range(16):
            exp[8 + i] ^= 0x55
        assert got == bytes(exp)
        # double-apply self-cancels — exactly why xorv must never be
        # blindly retried
        c._run(posix.xorv(f.fd, delta, 8))
        assert c.read_file("/f") == bytes(range(64))
        # past EOF: 0 ⊕ d = d (a delta on a sparse tail degenerates
        # to a plain write)
        c._run(posix.xorv(f.fd, b"\xaa\xbb", 100))
        got = c.read_file("/f")
        assert got[100:102] == b"\xaa\xbb"
        assert got[64:100] == b"\0" * 36
        f.close()
    finally:
        c.close()


def test_posix_xorv_journal_batched(tmp_path):
    """The pre-xattrop marker's sidecar append coalesces with the xorv
    into ONE journal write (the compound journal_batch machinery)."""
    vol = (f"volume posix\n    type storage/posix\n"
           f"    option directory {tmp_path}/b\nend-volume\n")
    c = SyncClient(Graph.construct(vol))
    c.mount()
    try:
        posix = c.graph.top
        c.write_file("/f", b"\0" * 1024)
        f = c.open("/f")
        writes = []
        orig = os.write

        def counting_write(fd, buf):
            writes.append(len(buf))
            return orig(fd, buf)

        import glusterfs_tpu.storage.posix as posix_mod

        posix_mod.os.write = counting_write
        try:
            c._run(posix.xorv(
                f.fd, b"\x11" * 64, 0,
                {"pre-xattrop": {"trusted.ec.dirty":
                                 b"\0\0\0\0\0\0\0\x01" + b"\0" * 8}}))
        finally:
            posix_mod.os.write = orig
        # one coalesced journal append for the whole op (the data path
        # uses pwrite, not write)
        assert len(writes) == 1, writes
        f.close()
    finally:
        c.close()


def test_xorv_class_pins():
    """xorv is write-class (EC/AFR accounting, read-only rejection,
    barrier gating) and NEVER in the idempotent-retry allowlist."""
    from glusterfs_tpu.protocol.client import ClientLayer

    assert Fop.XORV in WRITE_FOPS
    assert "xorv" not in ClientLayer._IDEMPOTENT_FOPS
    assert "xorv" not in ClientLayer._LOCK_FOPS


def test_client_capability_gate(tmp_path):
    """A connected client whose peer did not advertise xorv fails the
    fop EOPNOTSUPP locally — zero round trips against a pre-12 brick."""
    from glusterfs_tpu.core.layer import FdObj
    from glusterfs_tpu.protocol.client import ClientLayer

    cl = ClientLayer("c0", {"remote-host": "127.0.0.1",
                            "remote-port": 1,
                            "remote-subvolume": "x"})
    cl.connected = True  # pretend: handshake done, no xorv advertised
    rt_before = cl.rpc_roundtrips
    with pytest.raises(FopError) as ei:
        asyncio.run(cl.xorv(FdObj(b"\0" * 16, anonymous=True),
                            b"\x01", 0))
    assert ei.value.err == errno.EOPNOTSUPP
    assert cl.rpc_roundtrips == rt_before  # nothing hit the wire


def test_read_only_rejects_xorv(tmp_path):
    """WRITE_FOPS membership is live: features/read-only refuses it."""
    vol = (f"volume posix\n    type storage/posix\n"
           f"    option directory {tmp_path}/b\nend-volume\n"
           f"volume ro\n    type features/read-only\n"
           f"    subvolumes posix\nend-volume\n")
    c = SyncClient(Graph.construct(vol))
    c.mount()
    try:
        from glusterfs_tpu.core.layer import FdObj

        with pytest.raises(FopError) as ei:
            c._run(c.graph.top.xorv(
                FdObj(b"\0" * 16, anonymous=True), b"\x01", 0))
        assert ei.value.err == errno.EROFS
    finally:
        c.close()


# -- write-behind satellite --------------------------------------------


def test_wb_stripe_aligned_cut_points(tmp_path):
    """Streamed sub-stripe chunks below a stripe-size window: every
    PRESSURE drain the child sees ENDS on a stripe boundary (and,
    for this aligned-start stream, starts on one too — an
    unaligned-start stream keeps its one intrinsic head partial);
    the final close drains the sub-stripe tail."""
    vol = (f"volume posix\n    type storage/posix\n"
           f"    option directory {tmp_path}/b\nend-volume\n"
           f"volume wb\n    type performance/write-behind\n"
           f"    option window-size 4096\n"
           f"    option stripe-size {STRIPE}\n"
           f"    subvolumes posix\nend-volume\n")
    c = SyncClient(Graph.construct(vol))
    c.mount()
    try:
        posix = c.graph.by_name["posix"]
        writes = []
        orig = posix.writev

        async def recording(fd, data, offset, xdata=None):
            writes.append((int(offset), len(data)))
            return await orig(fd, data, offset, xdata)

        posix.writev = recording
        f = c.create("/f")
        # stream 3000-byte chunks (the gateway chunked-PUT shape):
        # window 4096 forces pressure drains mid-stream
        for i in range(4):
            f.write(b"c" * 3000, i * 3000)
        pressure = list(writes)
        f.close()  # release drains the tail fully
        assert pressure, "window never hit pressure"
        for off, ln in pressure:
            assert off % STRIPE == 0 and ln % STRIPE == 0, \
                (pressure, "unaligned pressure drain")
        assert c.read_file("/f") == b"c" * 12000
    finally:
        c.close()


def test_wb_stripe_cut_points_unit(tmp_path):
    """Unit-level pin on the cut machinery: a partial drain emits only
    whole stripes and retains the tail; an all-sub-stripe window still
    flushes fully (bounded window invariant)."""
    from glusterfs_tpu.performance.write_behind import WriteBehindLayer

    class Rec:
        def __init__(self):
            self.writes = []
            self.type_name = "rec"
            self.name = "rec"
            self.children = []
            self.parents = []

        async def writev(self, fd, data, offset, xdata=None):
            self.writes.append((offset, len(data)))
            return None

    rec = Rec()
    wb = WriteBehindLayer("wb", {"stripe-size": STRIPE},
                          children=[rec])

    from glusterfs_tpu.core.layer import FdObj

    async def run():
        fd = FdObj(b"\0" * 16)
        ctx = wb._ctx(fd)
        wb._absorb(ctx, b"a" * (2 * STRIPE + 300), 0)
        await wb._drain(fd, ctx, partial=True)
        assert rec.writes == [(0, 2 * STRIPE)], rec.writes
        assert ctx.chunks == [(2 * STRIPE, bytearray(b"a" * 300))]
        assert ctx.bytes == 300
        # extend the retained tail and force a FULL drain
        wb._absorb(ctx, b"b" * 100, 2 * STRIPE + 300)
        await wb._drain(fd, ctx)
        assert rec.writes[-1] == (2 * STRIPE, 400)
        assert ctx.chunks == []
        # all-sub-stripe window: partial drain must still flush
        wb._absorb(ctx, b"c" * 100, 0)
        await wb._drain(fd, ctx, partial=True)
        assert rec.writes[-1] == (0, 100)
        assert ctx.chunks == []
        assert wb.window_bytes == 0

    asyncio.run(run())


def test_volgen_wires_wb_stripe_size():
    """A disperse client graph carries the EC stripe into
    write-behind's cut points (and the delta-writes key maps)."""
    from glusterfs_tpu.mgmt import volgen

    volinfo = {
        "name": "dv", "type": "disperse", "redundancy": 2,
        "bricks": [{"name": f"dv-brick-{i}", "host": "h", "index": i,
                    "path": f"/b{i}"} for i in range(6)],
        "options": {},
    }
    vf = volgen.build_client_volfile(volinfo)
    assert "option stripe-size 2048" in vf
    assert volgen.OPTION_MAP["cluster.delta-writes"] == \
        ("cluster/disperse", "delta-writes")
    assert volgen.OPTION_MIN_OPVERSION["cluster.delta-writes"] == 12


# -- mgmt satellite -----------------------------------------------------


def test_changelog_graph_disables_delta():
    """A changelog-armed (geo-rep) disperse graph keeps RMW: gsyncd's
    one-Active-worker-per-group election assumes every brick journals
    the same logical ops, which a delta wave's untouched data bricks
    would break.  An explicit operator key still wins."""
    from glusterfs_tpu.mgmt import volgen

    volinfo = {
        "name": "gv", "type": "disperse", "redundancy": 2,
        "bricks": [{"name": f"gv-brick-{i}", "host": "h", "index": i,
                    "path": f"/b{i}"} for i in range(6)],
        "options": {"changelog.changelog": "on"},
    }
    vf = volgen.build_client_volfile(volinfo)
    assert "option delta-writes off" in vf
    volinfo["options"]["cluster.delta-writes"] = "on"
    vf = volgen.build_client_volfile(volinfo)
    assert "option delta-writes on" in vf
    # xorv journals as a data op wherever it does land
    from glusterfs_tpu.features.changelog import D_FOPS

    assert Fop.XORV in D_FOPS


def test_mesh_codec_on_systematic_volume_gated_by_opversion(tmp_path):
    """The mesh-codec-vs-systematic exclusion is LIFTED at cluster
    op-version >= 14 (the mesh tier's parity-rows-only systematic
    encode, ISSUE 12): volume set accepts the key on a systematic
    volume now — and still refuses while any member would be pre-14
    (pinned by forcing the stored op-version down)."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="sv",
                             vtype="disperse", redundancy=2,
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(6)])
                res = await c.call("volume-set", name="sv",
                                   key="cluster.mesh-codec",
                                   value="on")
                assert res["ok"]
            # a pre-14 member keeps the old refusal (its BatchingCodec
            # has no systematic mesh tier): MgmtError rides the wire
            # as FopError(EINVAL)
            d.op_version = 13
            async with MgmtClient(d.host, d.port) as c:
                with pytest.raises(OSError, match="op-version >= 14"):
                    await c.call("volume-set", name="sv",
                                 key="cluster.mesh-codec", value="on")
        finally:
            await d.stop()

    asyncio.run(run())


def test_opversion_floor_for_delta_writes():
    # the delta plane shipped at 12; later rounds may raise the build's
    # op-version but must never lower it below the xorv capability
    import glusterfs_tpu

    assert glusterfs_tpu.OP_VERSION >= 12


def test_delta_over_wire_managed(tmp_path):
    """End to end over real TCP: a managed volume (systematic by
    default now) serves an unaligned write through the delta path —
    xorv crosses the wire under the SETVOLUME capability — and the
    file reads back exact."""
    from glusterfs_tpu.core.layer import walk
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    data = _rand(4 * STRIPE, seed=31).tobytes()

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="dw",
                             vtype="disperse", redundancy=2,
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(6)])
                await c.call("volume-start", name="dw")
            cl = await mount_volume(d.host, d.port, "dw")
            try:
                ec = next(l for l in walk(cl.graph.top)
                          if l.type_name == "cluster/disperse")
                assert ec.opts["systematic"] is True  # the new default
                await cl.write_file("/x", data)
                f = await cl.open("/x")
                await f.write(b"Q" * 700, 1000)
                await f.close()
                assert ec.write_path["delta"] == 1, ec.write_path
                exp = bytearray(data)
                exp[1000:1700] = b"Q" * 700
                assert bytes(await cl.read_file("/x")) == bytes(exp)
            finally:
                await cl.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


def test_volume_create_systematic_default(tmp_path):
    """New disperse volumes default to the systematic layout at
    cluster op-version >= 12; the explicit opt-out key holds; replicate
    volumes are untouched."""
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="dflt",
                             vtype="disperse", redundancy=2,
                             bricks=[{"path": str(tmp_path / f"a{i}")}
                                     for i in range(6)])
                await c.call("volume-create", name="optout",
                             vtype="disperse", redundancy=2,
                             systematic=0,
                             bricks=[{"path": str(tmp_path / f"b{i}")}
                                     for i in range(6)])
                info = await c.call("volume-info", name="dflt")
                assert info["dflt"].get("systematic") == 1
                info = await c.call("volume-info", name="optout")
                assert not info["optout"].get("systematic")
        finally:
            await d.stop()

    asyncio.run(run())
