"""Live reconfigure: `volume set` reaches running bricks (in-place
xlator.reconfigure or same-port respawn on shape change) and mounted
clients (volfile-modified push -> option apply or graph swap) WITHOUT
remount — the reference's graph.c:980-1089 volfile compare + switch.
VERDICT next-round #10 done criterion."""

import asyncio

import pytest

from glusterfs_tpu.core.graph import Graph

EC_VOLFILE = """
volume b0
    type storage/posix
    option directory {dir}
end-volume

volume top
    type debug/io-stats
    subvolumes b0
end-volume
"""


def test_apply_volfile_reconfigures_in_place(tmp_path):
    g = Graph.construct(EC_VOLFILE.format(dir=tmp_path / "b"))
    newtext = EC_VOLFILE.format(dir=tmp_path / "b").replace(
        "    type debug/io-stats",
        "    type debug/io-stats\n    option latency-measurement on")
    top = g.top
    assert g.apply_volfile(newtext) is True
    assert g.top is top  # same objects, options applied
    assert g.by_name["top"].opts["latency-measurement"] is True


def test_apply_volfile_rejects_topology_change(tmp_path):
    g = Graph.construct(EC_VOLFILE.format(dir=tmp_path / "b"))
    changed = EC_VOLFILE.format(dir=tmp_path / "b") + """
volume extra
    type performance/io-cache
    subvolumes top
end-volume
"""
    assert g.apply_volfile(changed) is False


@pytest.mark.slow
def test_e2e_volume_set_applies_live(tmp_path):
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                bricks = [{"path": str(tmp_path / f"b{i}")}
                          for i in range(6)]
                await c.call("volume-create", name="lv", vtype="disperse",
                             bricks=bricks, redundancy=2)
                await c.call("volume-start", name="lv")

            client = await mount_volume(d.host, d.port, "lv")
            try:
                ec = next(l for l in client.graph.by_name.values()
                          if l.type_name == "cluster/disperse")
                for _ in range(150):
                    if all(ch.connected for ch in ec.children):
                        break
                    await asyncio.sleep(0.1)
                assert ec.opts["read-policy"] == "round-robin"
                await client.write_file("/live", b"before-reconfigure")

                # 1) client-side option: reaches the mounted graph with
                # no remount, same layer objects
                async with MgmtClient(d.host, d.port) as c:
                    r = await c.call("volume-set", name="lv",
                                     key="disperse.read-policy",
                                     value="first-k")
                ok = False
                for _ in range(100):
                    if ec.opts["read-policy"] == "first-k":
                        ok = True
                        break
                    await asyncio.sleep(0.1)
                assert ok, "client never saw the option change"
                assert client.graph.by_name[ec.name] is ec  # no swap

                # 2) brick-side option: live reconfigure on running
                # brick daemons (no respawn)
                async with MgmtClient(d.host, d.port) as c:
                    r = await c.call("volume-set", name="lv",
                                     key="performance.io-thread-count",
                                     value="4")
                assert r["applied"] == ["reconfigured"]

                # 3) topology change: enabling a perf layer swaps the
                # client graph; existing mount keeps working
                f = await client.open("/live")  # fd across the swap
                async with MgmtClient(d.host, d.port) as c:
                    await c.call("volume-set", name="lv",
                                 key="performance.io-cache", value="on")
                ok = False
                for _ in range(150):
                    if any(l.type_name == "performance/io-cache"
                           for l in client.graph.by_name.values()):
                        ok = True
                        break
                    await asyncio.sleep(0.1)
                assert ok, "graph never swapped in io-cache"
                # the pre-swap fd and the path both still serve
                assert await f.read(100, 0) == b"before-reconfigure"
                await f.close()
                assert await client.read_file("/live") == \
                    b"before-reconfigure"
                await client.write_file("/after", b"post-swap write")
                assert await client.read_file("/after") == b"post-swap write"

                # 4) brick shape change: feature toggle respawns bricks
                # and enforcement starts without volume restart
                async with MgmtClient(d.host, d.port) as c:
                    r = await c.call("volume-set", name="lv",
                                     key="features.read-only", value="on")
                assert r["applied"] == ["respawned"]
                ec2 = next(l for l in client.graph.by_name.values()
                           if l.type_name == "cluster/disperse")
                for _ in range(150):  # client reconnects to same ports
                    if all(ch.connected for ch in ec2.children):
                        break
                    await asyncio.sleep(0.1)
                with pytest.raises(Exception):
                    await client.write_file("/denied", b"x")
                assert await client.read_file("/live") == \
                    b"before-reconfigure"
            finally:
                await client.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


def test_option_map_integrity():
    """Every `volume set` key lands on a REAL declared option of a
    registered layer type (glusterd-volume-set.c keeps the same
    contract via its option tables): a key pointing at a typo'd or
    removed option would store silently and configure nothing."""
    import importlib
    import pkgutil

    import glusterfs_tpu
    from glusterfs_tpu.core.layer import _REGISTRY
    from glusterfs_tpu.mgmt import volgen

    for pkg in ("cluster", "features", "performance", "protocol",
                "storage", "debug", "system", "meta"):
        p = importlib.import_module(f"glusterfs_tpu.{pkg}")
        for m in pkgutil.iter_modules(p.__path__):
            importlib.import_module(f"glusterfs_tpu.{pkg}.{m.name}")

    # pseudo-targets consumed by daemons, not graph layers
    pseudo = {"__ssl__", "mgmt/glusterd", "mgmt/shd", "mgmt/gsyncd",
              "mgmt/bitd", "mgmt/gateway", "mgmt/rebalanced"}
    # both-end transport keys must exist on BOTH protocol layers
    for key, (ltype, opt) in volgen.OPTION_MAP.items():
        if ltype == "__transport__":
            for t in ("protocol/client", "protocol/server"):
                cls = _REGISTRY[t]
                assert any(o.name == opt for o in cls.OPTIONS), \
                    f"{key}: {t} lacks option {opt!r}"
    pseudo.add("__transport__")
    # the compound key must exist on every fusion end it arms
    for key, (ltype, opt) in volgen.OPTION_MAP.items():
        if ltype == "__compound__":
            from glusterfs_tpu.core.layer import lookup_type

            for t in ("protocol/client", "protocol/server",
                      "performance/write-behind",
                      "performance/read-ahead"):
                cls = lookup_type(t)
                assert any(o.name == opt for o in cls.OPTIONS), \
                    f"{key}: {t} lacks option {opt!r}"
    pseudo.add("__compound__")
    # the scatter-gather key must exist on both transport ends
    for key, (ltype, opt) in volgen.OPTION_MAP.items():
        if ltype == "__sg__":
            for t in ("protocol/client", "protocol/server"):
                cls = _REGISTRY[t]
                assert any(o.name == opt for o in cls.OPTIONS), \
                    f"{key}: {t} lacks option {opt!r}"
    pseudo.add("__sg__")
    # the trace-propagation key must exist on both transport ends
    for key, (ltype, opt) in volgen.OPTION_MAP.items():
        if ltype == "__trace__":
            for t in ("protocol/client", "protocol/server"):
                cls = _REGISTRY[t]
                assert any(o.name == opt for o in cls.OPTIONS), \
                    f"{key}: {t} lacks option {opt!r}"
    pseudo.add("__trace__")
    # the shm bulk-lane key must exist on both transport ends
    for key, (ltype, opt) in volgen.OPTION_MAP.items():
        if ltype == "__shm__":
            for t in ("protocol/client", "protocol/server"):
                cls = _REGISTRY[t]
                assert any(o.name == opt for o in cls.OPTIONS), \
                    f"{key}: {t} lacks option {opt!r}"
    pseudo.add("__shm__")
    missing = []
    for key, (ltype, opt) in volgen.OPTION_MAP.items():
        if ltype in pseudo:
            continue
        cls = _REGISTRY.get(ltype)
        if cls is None:
            missing.append(f"{key} -> unknown layer {ltype}")
            continue
        if opt in ("__enable__", "__passthrough__"):
            continue  # presence keys: insert/omit the layer
        if not any(o.name == opt for o in getattr(cls, "OPTIONS", ())):
            missing.append(f"{key} -> {ltype} has no option {opt!r}")
    assert not missing, missing
    # every op-version-gated key must exist (typo guard on _V3_KEYS)
    for k in volgen.OPTION_MIN_OPVERSION:
        assert k in volgen.OPTION_MAP, f"gated ghost key {k!r}"
    # breadth floor: the operable long tail must not silently shrink
    assert len(volgen.OPTION_MAP) >= 220, len(volgen.OPTION_MAP)
    # the operator-facing table is generated output, not prose: pin it
    import os
    doc = os.path.join(os.path.dirname(__file__), "..", "docs",
                       "volume_options.md")
    with open(doc) as f:
        assert f.read() == volgen.options_doc(), \
            "docs/volume_options.md drifted: regenerate with " \
            "volgen.options_doc()" 


def test_new_long_tail_options_apply_live(tmp_path):
    """Sampled new keys reach running layers through `volume set`."""
    import asyncio

    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="ov",
                             vtype="distribute",
                             bricks=[{"path": str(tmp_path / "b0")}],
                             redundancy=0)
                await c.call("volume-start", name="ov")
                for key, val in (
                        ("performance.cache-timeout", "3"),
                        ("performance.flush-behind", "off"),
                        ("features.locks-lock-timeout", "7"),
                        ("diagnostics.count-fop-hits", "on"),
                        ("cluster.lookup-optimize", "off"),
                        ("performance.lazy-open", "off")):
                    await c.call("volume-set", name="ov", key=key,
                                 value=val)
                info = await c.call("volume-info", name="ov")
                opts = info["ov"]["options"]
                assert opts["features.locks-lock-timeout"] == "7"
            # the client graph generated from the options carries them
            cl = await mount_volume(d.host, d.port, "ov")
            try:
                from glusterfs_tpu.core.layer import walk
                vals = {}
                for layer in walk(cl.graph.top):
                    if layer.type_name == "performance/io-cache":
                        vals["ct"] = layer.opts["cache-timeout"]
                    if layer.type_name == "performance/open-behind":
                        vals["lo"] = layer.opts["lazy-open"]
                assert vals.get("ct") == 3.0, vals
                assert vals.get("lo") is False, vals
            finally:
                await cl.unmount()
        finally:
            await d.stop()

    asyncio.run(run())


def test_debug_fault_injection_via_volume_set(tmp_path):
    """debug.error-gen inserted live through `volume set` (the
    reference volgen inserts error-gen the same way): writes start
    failing with the configured errno, and disabling restores I/O."""
    import asyncio
    import errno as errno_mod

    from glusterfs_tpu.core.fops import FopError
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-create", name="fv",
                             vtype="distribute",
                             bricks=[{"path": str(tmp_path / "b0")}],
                             redundancy=0)
                await c.call("volume-start", name="fv")
            cl = await mount_volume(d.host, d.port, "fv")
            await cl.write_file("/ok", b"fine")
            await cl.unmount()
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-set", name="fv",
                             key="debug.error-fops", value="writev")
                await c.call("volume-set", name="fv",
                             key="debug.error-failure", value="100")
                await c.call("volume-set", name="fv",
                             key="debug.error-number", value="ENOSPC")
                await c.call("volume-set", name="fv",
                             key="debug.error-gen", value="on")
            cl = await mount_volume(d.host, d.port, "fv")
            try:
                await cl.write_file("/boom", b"x" * 8192)
                raise AssertionError("write should have failed")
            except FopError as e:
                assert e.err == errno_mod.ENOSPC, e
            await cl.unmount()
            async with MgmtClient(d.host, d.port) as c:
                await c.call("volume-set", name="fv",
                             key="debug.error-gen", value="off")
            cl = await mount_volume(d.host, d.port, "fv")
            await cl.write_file("/fine-again", b"y")
            assert await cl.read_file("/fine-again") == b"y"
            await cl.unmount()
        finally:
            await d.stop()

    asyncio.run(run())
