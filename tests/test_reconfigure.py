"""Live reconfigure: `volume set` reaches running bricks (in-place
xlator.reconfigure or same-port respawn on shape change) and mounted
clients (volfile-modified push -> option apply or graph swap) WITHOUT
remount — the reference's graph.c:980-1089 volfile compare + switch.
VERDICT next-round #10 done criterion."""

import asyncio

import pytest

from glusterfs_tpu.core.graph import Graph

EC_VOLFILE = """
volume b0
    type storage/posix
    option directory {dir}
end-volume

volume top
    type debug/io-stats
    subvolumes b0
end-volume
"""


def test_apply_volfile_reconfigures_in_place(tmp_path):
    g = Graph.construct(EC_VOLFILE.format(dir=tmp_path / "b"))
    newtext = EC_VOLFILE.format(dir=tmp_path / "b").replace(
        "    type debug/io-stats",
        "    type debug/io-stats\n    option latency-measurement on")
    top = g.top
    assert g.apply_volfile(newtext) is True
    assert g.top is top  # same objects, options applied
    assert g.by_name["top"].opts["latency-measurement"] is True


def test_apply_volfile_rejects_topology_change(tmp_path):
    g = Graph.construct(EC_VOLFILE.format(dir=tmp_path / "b"))
    changed = EC_VOLFILE.format(dir=tmp_path / "b") + """
volume extra
    type performance/io-cache
    subvolumes top
end-volume
"""
    assert g.apply_volfile(changed) is False


@pytest.mark.slow
def test_e2e_volume_set_applies_live(tmp_path):
    from glusterfs_tpu.mgmt.glusterd import Glusterd, MgmtClient, mount_volume

    async def run():
        d = Glusterd(str(tmp_path / "gd"))
        await d.start()
        try:
            async with MgmtClient(d.host, d.port) as c:
                bricks = [{"path": str(tmp_path / f"b{i}")}
                          for i in range(6)]
                await c.call("volume-create", name="lv", vtype="disperse",
                             bricks=bricks, redundancy=2)
                await c.call("volume-start", name="lv")

            client = await mount_volume(d.host, d.port, "lv")
            try:
                ec = next(l for l in client.graph.by_name.values()
                          if l.type_name == "cluster/disperse")
                for _ in range(150):
                    if all(ch.connected for ch in ec.children):
                        break
                    await asyncio.sleep(0.1)
                assert ec.opts["read-policy"] == "round-robin"
                await client.write_file("/live", b"before-reconfigure")

                # 1) client-side option: reaches the mounted graph with
                # no remount, same layer objects
                async with MgmtClient(d.host, d.port) as c:
                    r = await c.call("volume-set", name="lv",
                                     key="disperse.read-policy",
                                     value="first-k")
                ok = False
                for _ in range(100):
                    if ec.opts["read-policy"] == "first-k":
                        ok = True
                        break
                    await asyncio.sleep(0.1)
                assert ok, "client never saw the option change"
                assert client.graph.by_name[ec.name] is ec  # no swap

                # 2) brick-side option: live reconfigure on running
                # brick daemons (no respawn)
                async with MgmtClient(d.host, d.port) as c:
                    r = await c.call("volume-set", name="lv",
                                     key="performance.io-thread-count",
                                     value="4")
                assert r["applied"] == ["reconfigured"]

                # 3) topology change: enabling a perf layer swaps the
                # client graph; existing mount keeps working
                f = await client.open("/live")  # fd across the swap
                async with MgmtClient(d.host, d.port) as c:
                    await c.call("volume-set", name="lv",
                                 key="performance.io-cache", value="on")
                ok = False
                for _ in range(150):
                    if any(l.type_name == "performance/io-cache"
                           for l in client.graph.by_name.values()):
                        ok = True
                        break
                    await asyncio.sleep(0.1)
                assert ok, "graph never swapped in io-cache"
                # the pre-swap fd and the path both still serve
                assert await f.read(100, 0) == b"before-reconfigure"
                await f.close()
                assert await client.read_file("/live") == \
                    b"before-reconfigure"
                await client.write_file("/after", b"post-swap write")
                assert await client.read_file("/after") == b"post-swap write"

                # 4) brick shape change: feature toggle respawns bricks
                # and enforcement starts without volume restart
                async with MgmtClient(d.host, d.port) as c:
                    r = await c.call("volume-set", name="lv",
                                     key="features.read-only", value="on")
                assert r["applied"] == ["respawned"]
                ec2 = next(l for l in client.graph.by_name.values()
                           if l.type_name == "cluster/disperse")
                for _ in range(150):  # client reconnects to same ports
                    if all(ch.connected for ch in ec2.children):
                        break
                    await asyncio.sleep(0.1)
                with pytest.raises(Exception):
                    await client.write_file("/denied", b"x")
                assert await client.read_file("/live") == \
                    b"before-reconfigure"
            finally:
                await client.unmount()
        finally:
            await d.stop()

    asyncio.run(run())
