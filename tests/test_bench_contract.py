"""The bench reporting contract (VERDICT r4 #1): the driver captures only
a ~2KB stdout tail, so round 4's grown result line recorded parsed:null —
the final stdout line must stay a compact parseable headline while the
full detail dict goes to BENCH_DETAIL.json."""

import importlib.util
import json
import os

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(HERE, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fat_result():
    # A result dict at least as large as round 4's (which broke the
    # driver's tail window): padded with sweep/volume rows.
    result = {
        "metric": "ec_encode_4p2_1MiB_stripes",
        "value": 111071.7,
        "unit": "MiB/s",
        "vs_baseline": 19.01,
        "decode_MiB_s": 98858.9,
        "decode_vs_baseline": 11.28,
        "backend": "xor-cse",
        "device": "TPU v5 lite0",
        "sweep": {f"{k}+{r}": {"encode_MiB_s": 1.0, "decode_MiB_s": 2.0}
                  for k in range(2, 17) for r in range(1, 5)},
        "headline_pass_MiB_s": {
            t: {"min": 1.0, "median": 2.0, "max": 3.0}
            for t in ("encode", "decode")},
        "regressions": [{"row": f"sweep.row{i}", "prev": 2.0, "now": 1.0,
                         "drop_pct": 50.0} for i in range(10)],
    }
    result.update({f"volume_row_{i}_MiB_s": float(i) for i in range(40)})
    assert len(json.dumps(result)) > 2048  # would overflow the tail window
    return result


def test_headline_line_is_compact_and_parseable(tmp_path):
    bench = _load_bench()
    detail = tmp_path / "BENCH_DETAIL.json"
    line = bench.emit(_fat_result(), detail_path=str(detail))
    # the contract: one line, < 1KB, json-parseable, required keys present
    assert "\n" not in line
    assert len(line) < 1024
    parsed = json.loads(line)
    for key in ("metric", "value", "unit", "vs_baseline", "decode_MiB_s",
                "decode_vs_baseline", "backend", "regressions",
                "detail_file"):
        assert key in parsed, key
    assert parsed["detail_file"] == "BENCH_DETAIL.json"
    # full detail survives on disk byte-complete
    with open(detail) as f:
        on_disk = json.load(f)
    assert on_disk == _fat_result()


def test_headline_stays_compact_with_huge_detail(tmp_path):
    bench = _load_bench()
    result = _fat_result()
    result["sweep"].update(
        {f"pad{i}": {"encode_MiB_s": i} for i in range(500)})
    line = bench.emit(result, detail_path=str(tmp_path / "d.json"))
    assert len(line) < 1024


def test_prev_bench_skips_null_parsed_rounds(tmp_path):
    """r4's BENCH_r04.json has parsed:null — the gate must fall back to
    the newest round that actually parsed rather than going blind.
    Isolated in tmp_path (no git, no BENCH_DETAIL.json) so the detail-
    file branch cannot shadow the fallback under test."""
    import shutil

    shutil.copy(os.path.join(HERE, "bench.py"), tmp_path / "bench.py")
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        {"parsed": {"value": 101.5, "metric": "m"}}))
    (tmp_path / "BENCH_r04.json").write_text(json.dumps(
        {"parsed": None, "tail": "truncated..."}))
    spec = importlib.util.spec_from_file_location(
        "bench_tmp", str(tmp_path / "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    prev = mod._prev_bench()
    assert prev == {"value": 101.5, "metric": "m"}


def test_prev_bench_prefers_committed_detail_over_worktree():
    """The gate baseline is the COMMITTED detail record: a dev run that
    overwrites the working-tree BENCH_DETAIL.json must not re-baseline
    the gate to itself (slow-drift masking)."""
    import subprocess

    bench = _load_bench()
    committed = subprocess.run(
        ["git", "-C", HERE, "show", "HEAD:BENCH_DETAIL.json"],
        capture_output=True).stdout
    prev = bench._prev_bench()
    assert prev is not None and "value" in prev
    if committed:
        assert prev == json.loads(committed)
    else:
        # detail not committed yet: fallback must come from BENCH_r*
        assert prev.get("metric") is not None
