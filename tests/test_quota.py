"""Quota: marker-persistent accounting, disperse scaling, quotad
aggregation, and the managed enable/limit/list lifecycle (reference
tests/basic/quota.t workloads; quota.c + marker + quotad analogs)."""

import asyncio
import errno
import json

import pytest

from glusterfs_tpu.api.glfs import Client
from glusterfs_tpu.core.fops import FopError
from glusterfs_tpu.core.graph import Graph
from glusterfs_tpu.core.layer import walk

QUOTA_VOLFILE = """
volume posix
    type storage/posix
    option directory {dir}
end-volume

volume quota
    type features/quota
    option limits {limits}
    option usage-scale {scale}
    subvolumes posix
end-volume
"""


def _graph(tmp_path, limits, scale=1):
    return Graph.construct(QUOTA_VOLFILE.format(
        dir=tmp_path / "b", limits=json.dumps(limits,
                                              separators=(",", ":")),
        scale=scale))


def test_quota_enforced_and_persisted(tmp_path):
    """EDQUOT past the limit; usage survives a layer restart via the
    marker xattr (no re-crawl)."""
    async def run():
        g = _graph(tmp_path, {"/d": 4096})
        c = Client(g)
        await c.mount()
        await c.mkdir("/d")
        await c.write_file("/d/a", b"x" * 3000)
        with pytest.raises(FopError) as ei:
            await c.write_file("/d/b", b"x" * 2000)
        assert ei.value.err == errno.EDQUOT
        # under the limit still works
        await c.write_file("/d/c", b"x" * 500)
        await c.unmount()

        # a fresh graph (brick restart) seeds usage from the xattr
        g2 = _graph(tmp_path, {"/d": 4096})
        c2 = Client(g2)
        await c2.mount()
        ql = next(l for l in walk(g2.top)
                  if l.type_name == "features/quota")
        assert ql._usage.get("/d", 0) == 3500  # seeded, not re-crawled
        with pytest.raises(FopError):
            await c2.write_file("/d/more", b"x" * 1000)
        await c2.unmount()

    asyncio.run(run())


def test_quota_scale(tmp_path):
    """usage-scale maps backend (fragment) bytes to logical: a K=4
    disperse brick holding 1000 backend bytes reports 4000 logical."""
    async def run():
        g = _graph(tmp_path, {"/": 4096}, scale=4)
        c = Client(g)
        await c.mount()
        await c.write_file("/f", b"x" * 1000)  # 4000 logical
        ql = next(l for l in walk(g.top)
                  if l.type_name == "features/quota")
        usage = await ql.quota_usage()
        assert usage["/"]["used"] == 4000
        with pytest.raises(FopError) as ei:
            await c.write_file("/g", b"x" * 100)  # +400 logical > 4096
        assert ei.value.err == errno.EDQUOT
        await c.unmount()

    asyncio.run(run())


def test_quota_unlink_releases(tmp_path):
    async def run():
        g = _graph(tmp_path, {"/": 2048})
        c = Client(g)
        await c.mount()
        await c.write_file("/a", b"x" * 2000)
        with pytest.raises(FopError):
            await c.write_file("/b", b"x" * 2000)
        await c.unlink("/a")
        await c.write_file("/b", b"x" * 2000)  # space released
        await c.unmount()

    asyncio.run(run())


@pytest.mark.slow
def test_managed_quota_lifecycle(tmp_path):
    """volume quota enable -> limit-usage -> EDQUOT through a disperse
    client -> quotad aggregation via 'quota list' -> remove lifts it."""
    from glusterfs_tpu.mgmt.glusterd import (Glusterd, MgmtClient,
                                             mount_volume)

    async def run():
        gd = Glusterd(str(tmp_path / "gd"))
        await gd.start()
        async with MgmtClient(gd.host, gd.port) as c:
            bricks = [{"path": str(tmp_path / f"b{i}")} for i in range(6)]
            await c.call("volume-create", name="qv", vtype="disperse",
                         bricks=bricks, redundancy=2)
            await c.call("volume-start", name="qv")
            await c.call("volume-quota", name="qv", action="enable")
            await c.call("volume-quota", name="qv", action="limit-usage",
                         path="/lim", limit=1 << 20)
        cl = await mount_volume(gd.host, gd.port, "qv")
        try:
            subs = [l for l in walk(cl.graph.top)
                    if l.type_name == "protocol/client"]
            for _ in range(100):
                if all(l.connected for l in subs):
                    break
                await asyncio.sleep(0.1)
            await cl.mkdir("/lim")
            await cl.write_file("/lim/ok", b"x" * (256 << 10))
            with pytest.raises(FopError) as ei:
                await cl.write_file("/lim/big", b"x" * (900 << 10))
            assert ei.value.err == errno.EDQUOT
            # aggregated listing reflects logical usage near 256KiB
            async with MgmtClient(gd.host, gd.port) as c:
                for _ in range(50):
                    lst = await c.call("volume-quota", name="qv",
                                       action="list")
                    if "/lim" in lst and lst["/lim"]["used"] > 0:
                        break
                    await asyncio.sleep(0.2)
            assert "/lim" in lst, lst
            used = lst["/lim"]["used"]
            assert (200 << 10) <= used <= (400 << 10), used
            assert lst["/lim"]["limit"] == 1 << 20
            # removing the limit lifts enforcement
            async with MgmtClient(gd.host, gd.port) as c:
                await c.call("volume-quota", name="qv", action="remove",
                             path="/lim")
            await cl.write_file("/lim/big", b"x" * (900 << 10))
        finally:
            await cl.unmount()
            await gd.stop()

    asyncio.run(run())


def test_quotad_group_aggregation():
    """sum over DHT groups of max within a replica/disperse group
    (quotad-aggregator semantics for distributed-replicate shapes)."""
    from glusterfs_tpu.mgmt.quotad import Quotad

    class Fake:
        connected = True

        def __init__(self, name, usage):
            self.name = name
            self._u = usage

        async def remote(self, method):
            assert method == "quota_usage"
            return self._u

    # 2x2 distributed-replicate: group 0 holds 100 (both replicas),
    # group 1 holds 40 (one replica trails at 35)
    layers = [Fake("a", {"/d": {"used": 100, "limit": 1000}}),
              Fake("b", {"/d": {"used": 100, "limit": 1000}}),
              Fake("c", {"/d": {"used": 35, "limit": 1000}}),
              Fake("d", {"/d": {"used": 40, "limit": 1000}})]
    qd = Quotad(layers, {"a": 0, "b": 0, "c": 1, "d": 1})
    agg = asyncio.run(qd.poll_once())
    assert agg["/d"]["used"] == 140
    assert agg["/d"]["available"] == 860
